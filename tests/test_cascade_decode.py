"""Trunk-aware cascade DECODE + fully-fused cascade prefill (PR 17).

Parity contracts pinned here:
- ops/lse.merge_partials algebraic properties: all-masked partial sets
  are NaN-free (the all-zero-row convention), the merge is associative
  (pairwise == 3-way to float tolerance), and dtypes pass through;
- ops/flash_decode.flash_decode_trunk (and the _mq sibling) matches the
  flat split-K kernel at every trunk extent — the trunk-split dedup is
  a pure HBM-traffic lever, never an arithmetic change — including the
  nt == 0 passthrough, GQA/MQA grouping, and ALiBi (bitwise on the
  chip; exact-to-1-ulp under the CPU interpreter, see
  _assert_ulp_close);
- the fully-fused cascade prefill kernel (suffix leg inside the Pallas
  kernel, no HBM round-trip for partials) is BITWISE the PR-16 two-leg
  path at every trunk extent of the cascade matrix;
- generate-level: greedy_decode_fused_shared(decode_trunk=N) and the
  speculative sibling are BITWISE their decode_trunk=0 selves;
- engine routing: cascade_decode_supported gates, decode_trunk_for LCP
  reuse, CascadeStats decode counters (dispatches + analytic deduped
  trunk bytes), and the --no-cascade-decode static-config mirror;
- scheduler: decode_floor's decode_trunk_frac discount with defaults
  byte-identical to the old model.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.engine import generate
from lir_tpu.models import decoder
from lir_tpu.models.registry import ModelConfig
from lir_tpu.ops.cascade_prefill import cascade_attention
from lir_tpu.ops.flash_decode import (flash_decode, flash_decode_mq,
                                      flash_decode_mq_trunk,
                                      flash_decode_trunk, pick_split)
from lir_tpu.ops.lse import merge_partials


def _tiny_cfg(**kw) -> ModelConfig:
    base = dict(name="cascdec-tiny", vocab_size=128, hidden_size=32,
                n_layers=2, n_heads=4, n_kv_heads=2, intermediate_size=64,
                max_seq_len=512)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture()
def fused_decode_interpret():
    old = decoder.FUSED_DECODE_INTERPRET_ON_CPU
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True
    yield
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = old


# ---------------------------------------------------------------------------
# Satellite: merge_partials property tests
# ---------------------------------------------------------------------------

class TestMergePartialsProperties:
    def _partials(self, seed, S, shape=(2, 3), hd=8, dtype=np.float32):
        rng = np.random.default_rng(seed)
        o = rng.normal(size=shape + (S, hd)).astype(dtype)
        m = rng.normal(size=shape + (S,)).astype(dtype)
        l = (np.abs(rng.normal(size=shape + (S,))) + 0.1).astype(dtype)
        return jnp.asarray(o), jnp.asarray(m), jnp.asarray(l)

    def test_all_masked_partials_nan_free(self):
        """EVERY partition empty (m = -inf, l = 0): the 1e-30 floor
        engages and the convention is an all-zero row — never NaN/inf,
        for any partition count including one."""
        for S in (1, 2, 5):
            o = jnp.zeros((2, 3, S, 8), jnp.float32)
            m = jnp.full((2, 3, S), -np.inf, jnp.float32)
            l = jnp.zeros((2, 3, S), jnp.float32)
            got = np.asarray(merge_partials(o, m, l, axis=2))
            assert np.isfinite(got).all(), S
            np.testing.assert_array_equal(got, np.zeros_like(got))

    def test_mixed_masked_rows_nan_free(self):
        """Some rows fully masked, others partially: finite everywhere,
        and the live rows ignore their empty partitions exactly."""
        o, m, l = self._partials(0, S=4)
        m = np.array(m)
        l = np.array(l)
        m[0, 0, :], l[0, 0, :] = -np.inf, 0.0        # dead row
        m[1, 2, 1], l[1, 2, 1] = -np.inf, 0.0        # one empty split
        full = merge_partials(o, jnp.asarray(m), jnp.asarray(l), axis=2)
        assert np.isfinite(np.asarray(full)).all()
        live = merge_partials(o[1, 2, [0, 2, 3]][None, None],
                              jnp.asarray(m[1, 2, [0, 2, 3]])[None, None],
                              jnp.asarray(l[1, 2, [0, 2, 3]])[None, None],
                              axis=2)
        np.testing.assert_allclose(np.asarray(full)[1, 2],
                                   np.asarray(live)[0, 0], rtol=1e-6)

    def test_pairwise_merge_associative_vs_three_way(self):
        """Merging partials {1,2} into a single combined partial (the
        running-max recombination every flash kernel uses), then merging
        with {3}, equals the flat 3-way merge: the reduction is
        associative, which is WHY the trunk/suffix split can recombine
        in any grouping without drift."""
        o, m, l = self._partials(1, S=3)
        three = merge_partials(o, m, l, axis=2)
        # Fold partials 0 and 1 into one combined partial triple.
        m2, l2, o2 = m[..., :2], l[..., :2], o[..., :2, :]
        m12 = m2.max(axis=-1)
        w = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - m12[..., None]), 0.0)
        l12 = (w * l2).sum(axis=-1)
        o12 = (w[..., None] * o2).sum(axis=-2)
        pair = merge_partials(
            jnp.stack([o12, o[..., 2, :]], axis=-2),
            jnp.stack([m12, m[..., 2]], axis=-1),
            jnp.stack([l12, l[..., 2]], axis=-1), axis=2)
        np.testing.assert_allclose(np.asarray(pair), np.asarray(three),
                                   rtol=2e-6, atol=1e-7)

    def test_associativity_with_empty_partition(self):
        """Associativity holds when one of the folded partials is empty
        (m = -inf carries weight exactly 0 through the fold)."""
        o, m, l = self._partials(2, S=3)
        m = np.asarray(m).copy()
        l = np.asarray(l).copy()
        m[..., 1] = -np.inf
        l[..., 1] = 0.0
        m, l = jnp.asarray(m), jnp.asarray(l)
        three = merge_partials(o, m, l, axis=2)
        m2, l2, o2 = m[..., :2], l[..., :2], o[..., :2, :]
        m12 = m2.max(axis=-1)
        w = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - m12[..., None]), 0.0)
        l12 = (w * l2).sum(axis=-1)
        o12 = (w[..., None] * o2).sum(axis=-2)
        pair = merge_partials(
            jnp.stack([o12, o[..., 2, :]], axis=-2),
            jnp.stack([m12, m[..., 2]], axis=-1),
            jnp.stack([l12, l[..., 2]], axis=-1), axis=2)
        np.testing.assert_allclose(np.asarray(pair), np.asarray(three),
                                   rtol=2e-6, atol=1e-7)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_dtype_preservation(self, dtype):
        """The merge emits the partials' own dtype — the kernels hand it
        float32 accumulators and must get float32 back (a silent
        down-cast here would corrupt every split path)."""
        o, m, l = self._partials(3, S=4)
        o, m, l = o.astype(dtype), m.astype(dtype), l.astype(dtype)
        got = merge_partials(o, m, l, axis=2)
        assert got.dtype == dtype
        assert got.shape == o.shape[:2] + (o.shape[-1],)


# ---------------------------------------------------------------------------
# Tentpole (a): trunk-aware flash-decode splits vs the flat kernel
# ---------------------------------------------------------------------------

def _assert_ulp_close(got, flat):
    """Identical arithmetic per partial — bitwise on the chip where the
    Pallas lowering fixes the tiling. Under the CPU interpreter XLA
    re-vectorizes the trunk leg's batched shapes (B*S*G rows in one
    GEMM vs the flat kernel's per-row grid), and its SIMD-vs-scalar
    ``exp`` tails can differ by 1 ulp on some inputs — so the CPU pin
    is exact-to-1-ulp, not exact-to-the-bit."""
    got, flat = np.asarray(got), np.asarray(flat)
    np.testing.assert_allclose(got, flat, rtol=3e-6, atol=3e-8)

def _decode_case(seed, B=3, H=4, K=2, hd=16, T=256, S=None, shared=None):
    """A decode-step cache state with realistic ragged masks; queries
    (B, H, hd) or (B, S, H, hd) when S is given (the verify window).
    The leading ``shared`` cache slots hold row 0's K/V in EVERY row —
    the shared-trunk precondition the trunk kernels dedup against (a
    cascade/shared dispatch broadcast or prefilled the trunk into every
    row, so those slots are bitwise-identical across the batch)."""
    rng = np.random.default_rng(seed)
    qshape = (B, H, hd) if S is None else (B, S, H, hd)
    q = jnp.asarray(rng.normal(size=qshape), jnp.float32)
    k = rng.normal(size=(K, T, B, hd)).astype(np.float32)
    v = rng.normal(size=(K, T, B, hd)).astype(np.float32)
    shared = T if shared is None else shared
    k[:, :shared] = k[:, :shared, :1]
    v[:, :shared] = v[:, :shared, :1]
    k, v = jnp.asarray(k), jnp.asarray(v)
    mask = np.zeros((B, T), np.int32)
    fill = [T - 16, T - 40, T][:B] + [T] * max(0, B - 3)
    for r in range(B):
        mask[r, :fill[r]] = 1
    key_pos = np.maximum(np.cumsum(mask, -1) - 1, 0)
    if S is None:
        q_pos = np.asarray([mask[r].sum() - 1 for r in range(B)], np.int32)
    else:
        last = np.asarray([mask[r].sum() - 1 for r in range(B)], np.int32)
        q_pos = last[:, None] - np.arange(S - 1, -1, -1, np.int32)[None]
    return (q, k, v, jnp.asarray(q_pos), jnp.asarray(mask),
            jnp.asarray(key_pos))


class TestTrunkDecodeBitwise:
    @pytest.mark.parametrize("trunk", [0, 64, 100, 128, 200, 255])
    def test_single_query_bitwise_flat(self, trunk):
        """flash_decode_trunk == flash_decode at every trunk extent:
        whole splits inside the trunk batch into the shared GEMM,
        partial trailing splits stay per-row, and the merge is the same
        reduction over the same partial values (see _assert_ulp_close
        for the CPU-interpreter bar)."""
        case = _decode_case(0, T=256, shared=trunk)
        flat = flash_decode(*case, interpret=True)
        got = flash_decode_trunk(*case, trunk_len=trunk, interpret=True)
        _assert_ulp_close(got, flat)

    def test_multi_trunk_splits(self):
        """A trunk spanning several whole splits (T=384 -> split 128,
        trunk 256 -> nt=2) still matches bitwise."""
        case = _decode_case(1, T=384, shared=256)
        assert pick_split(384) == 128
        flat = flash_decode(*case, interpret=True)
        got = flash_decode_trunk(*case, trunk_len=256, interpret=True)
        _assert_ulp_close(got, flat)

    def test_trunk_caps_at_cache_edge(self):
        """trunk_len >= T clamps to T-1: at least the final split always
        stays per-row (the rows' own tails differ)."""
        case = _decode_case(2, T=256)
        flat = flash_decode(*case, interpret=True)
        got = flash_decode_trunk(*case, trunk_len=10_000, interpret=True)
        _assert_ulp_close(got, flat)

    def test_mqa_and_alibi_bitwise(self):
        q, k, v, q_pos, mask, key_pos = _decode_case(3, H=4, K=1, T=256,
                                                     shared=128)
        slopes = decoder.alibi_slopes(4)
        flat = flash_decode(q, k, v, q_pos, mask, key_pos,
                            alibi_slopes=slopes, interpret=True)
        got = flash_decode_trunk(q, k, v, q_pos, mask, key_pos,
                                 alibi_slopes=slopes, trunk_len=128,
                                 interpret=True)
        _assert_ulp_close(got, flat)

    @pytest.mark.parametrize("trunk", [0, 128, 200])
    def test_multi_query_bitwise_flat(self, trunk):
        """The _mq sibling (speculative verify windows): same parity
        contract, every query in the window."""
        case = _decode_case(4, T=256, S=3, shared=trunk)
        flat = flash_decode_mq(*case, interpret=True)
        got = flash_decode_mq_trunk(*case, trunk_len=trunk, interpret=True)
        _assert_ulp_close(got, flat)

    def test_multi_query_alibi_bitwise(self):
        q, k, v, q_pos, mask, key_pos = _decode_case(5, T=256, S=4,
                                                     shared=128)
        slopes = decoder.alibi_slopes(4)
        flat = flash_decode_mq(q, k, v, q_pos, mask, key_pos,
                               alibi_slopes=slopes, interpret=True)
        got = flash_decode_mq_trunk(q, k, v, q_pos, mask, key_pos,
                                    alibi_slopes=slopes, trunk_len=128,
                                    interpret=True)
        _assert_ulp_close(got, flat)


# ---------------------------------------------------------------------------
# Tentpole (b): fully-fused cascade prefill vs the PR-16 two-leg path
# ---------------------------------------------------------------------------

def _prefill_case(Tt, R=8, seed=0, B=2, H=4, K=2, hd=16):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, R, H, hd)), jnp.float32)
    sk = jnp.asarray(rng.normal(size=(B, R, K, hd)), jnp.float32)
    sv = jnp.asarray(rng.normal(size=(B, R, K, hd)), jnp.float32)
    tk = jnp.asarray(rng.normal(size=(K, Tt, hd)), jnp.float32)
    tv = jnp.asarray(rng.normal(size=(K, Tt, hd)), jnp.float32)
    mask = np.ones((B, R), np.int32)
    mask[0, R // 2:] = 0
    if B > 2:
        mask[2, :] = 0
    q_pos = Tt + np.maximum(np.cumsum(mask, -1) - 1, 0)
    return q, sk, sv, tk, tv, jnp.asarray(mask), jnp.asarray(q_pos)


class TestFusedSuffixBitwise:
    @pytest.mark.parametrize("Tt", [16, 32, 48, 64, 100, 128])
    @pytest.mark.parametrize("R,B,K", [(8, 2, 2), (5, 3, 1), (8, 3, 4)])
    def test_fused_equals_two_leg(self, Tt, R, B, K):
        """The single-kernel cascade (suffix leg fused into the Pallas
        kernel, no HBM round-trip for partials) is BITWISE the two-leg
        path at every trunk extent of the cascade matrix, under GQA /
        MQA, masked remainder rows, and fully-masked rows."""
        case = _prefill_case(Tt, R=R, B=B, K=K, seed=Tt + R)
        two_leg = cascade_attention(*case, fused_suffix=False,
                                    interpret=True)
        fused = cascade_attention(*case, fused_suffix=True, interpret=True)
        np.testing.assert_array_equal(np.asarray(fused),
                                      np.asarray(two_leg))

    def test_fused_alibi_bitwise(self):
        q, sk, sv, tk, tv, mask, q_pos = _prefill_case(48, seed=9, K=4)
        slopes = decoder.alibi_slopes(4)
        two_leg = cascade_attention(q, sk, sv, tk, tv, mask, q_pos,
                                    alibi_slopes=slopes,
                                    fused_suffix=False, interpret=True)
        fused = cascade_attention(q, sk, sv, tk, tv, mask, q_pos,
                                  alibi_slopes=slopes, fused_suffix=True,
                                  interpret=True)
        np.testing.assert_array_equal(np.asarray(fused),
                                      np.asarray(two_leg))

    def test_int8_qk_routes_two_leg(self):
        """int8 QK^T keeps the two-leg lowering (the int8 prefix kernel
        has no fused sibling): fused_suffix=True with int8_qk is the
        int8 two-leg path verbatim."""
        case = _prefill_case(64, seed=10)
        a = cascade_attention(*case, int8_qk=True, fused_suffix=True,
                              interpret=True)
        b = cascade_attention(*case, int8_qk=True, fused_suffix=False,
                              interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Generate-level: decode_trunk threading is invisible to outputs
# ---------------------------------------------------------------------------

def _assert_trees_bitwise(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _trunk_shared_args(seed, B=3, S=128, trunk=96, SA=4, SB=8, V=128):
    """Shared-args tuple whose rows lead with a ``trunk``-token LCP, in
    a bucket big enough that the decode cache (S + sfx + new) spans
    multiple key splits — so the trunk leg actually engages."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(3, V, (B, S)).astype(np.int32)
    prefix[:, :trunk] = prefix[0, :trunk]
    pm = np.ones((B, S), np.int32)
    pm[0, S - 6:] = 0
    sa = jnp.asarray(rng.integers(3, V, (B, SA)), jnp.int32)
    sam = np.ones((B, SA), np.int32)
    sam[1, 2:] = 0
    sb = jnp.asarray(rng.integers(3, V, (B, SB)), jnp.int32)
    sbm = np.ones((B, SB), np.int32)
    sbm[B - 1, 5:] = 0
    yes = jnp.asarray([5, 6, 7][:B], jnp.int32)
    no = jnp.asarray([9, 10, 11][:B], jnp.int32)
    d_ids = jnp.arange(10, 30, dtype=jnp.int32)
    d_vals = jnp.arange(0.0, 20.0, dtype=jnp.float32)
    return (jnp.asarray(prefix), jnp.asarray(pm), sa, jnp.asarray(sam),
            sb, jnp.asarray(sbm), yes, no, d_ids, d_vals)


class TestGenerateDecodeTrunk:
    def test_sequential_bitwise(self, fused_decode_interpret):
        """greedy_decode_fused_shared with decode_trunk engaged is
        BITWISE its flat self — every payload leaf."""
        cfg = _tiny_cfg()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        args = _trunk_shared_args(0)
        flat = generate.greedy_decode_fused_shared(
            params, cfg, *args, max_new_a=3, max_new_b=5)
        trunked = generate.greedy_decode_fused_shared(
            params, cfg, *args, max_new_a=3, max_new_b=5, decode_trunk=96)
        _assert_trees_bitwise(flat, trunked)

    def test_cascade_dispatch_bitwise(self, fused_decode_interpret):
        """The cascade prefill dispatch threads its own trunk into the
        decode tail (decode_trunk=trunk_len) — still bitwise vs the
        dense+flat shared path at the argmax bar's float fields too,
        when the model's cascade_decode static flag is OFF (trunk
        zeroed in the decoder gate)."""
        cfg = _tiny_cfg(name="cascdec-gate-off", cascade_decode=False)
        params = decoder.init_params(cfg, jax.random.PRNGKey(1),
                                     dtype=jnp.float32)
        old = decoder.CASCADE_INTERPRET_ON_CPU
        decoder.CASCADE_INTERPRET_ON_CPU = True
        try:
            args = _trunk_shared_args(1)
            on = generate.greedy_decode_fused_shared_cascade(
                params, cfg, *args, max_new_a=2, max_new_b=3, trunk_len=96)
            cfg_on = dataclasses.replace(cfg, name="cascdec-gate-on",
                                         cascade_decode=True)
            on2 = generate.greedy_decode_fused_shared_cascade(
                params, cfg_on, *args, max_new_a=2, max_new_b=3,
                trunk_len=96)
        finally:
            decoder.CASCADE_INTERPRET_ON_CPU = old
        _assert_trees_bitwise(on, on2)

    def test_spec_bitwise(self, fused_decode_interpret):
        """The speculative verify window rides flash_decode_mq_trunk:
        spec decode with decode_trunk engaged is bitwise flat spec."""
        cfg = _tiny_cfg(name="cascdec-spec")
        params = decoder.init_params(cfg, jax.random.PRNGKey(2),
                                     dtype=jnp.float32)
        args = _trunk_shared_args(2, SA=4, SB=8)
        B, Ta, Tb, k = 3, 3, 4, 2
        width = 128 + 8 + max(Ta, Tb)
        ctx = np.zeros((B, width), np.int32)
        lens = np.full((B,), 100, np.int32)
        ctx[:, :100] = np.asarray(args[0])[:, :100]
        si = (jnp.asarray(ctx), jnp.asarray(lens),
              jnp.zeros((B, Ta), jnp.int32), jnp.zeros((B,), jnp.int32),
              jnp.asarray(ctx), jnp.asarray(lens),
              jnp.zeros((B, Tb), jnp.int32), jnp.zeros((B,), jnp.int32))
        flat = generate.greedy_decode_fused_shared_spec(
            params, cfg, *args, *si, max_new_a=Ta, max_new_b=Tb, spec_k=k)
        trunked = generate.greedy_decode_fused_shared_spec(
            params, cfg, *args, *si, max_new_a=Ta, max_new_b=Tb, spec_k=k,
            decode_trunk=96)
        _assert_trees_bitwise(flat, trunked)


# ---------------------------------------------------------------------------
# Engine routing, counters, config mirror
# ---------------------------------------------------------------------------

def _fake_engine(rt=None, cfg_kw=None):
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine

    cfg = _tiny_cfg(vocab_size=FakeTokenizer.VOCAB, **(cfg_kw or {}))
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    rt = rt or RuntimeConfig(batch_size=4)
    return ScoringEngine(params, cfg, FakeTokenizer(), rt)


def _trunk_rows(B=4, trunk=96, tail=8, seed=0):
    rng = np.random.default_rng(seed)
    head = [int(x) for x in rng.integers(3, 200, trunk)]
    return [head + [int(x) for x in rng.integers(3, 200, tail - (r % 3))]
            for r in range(B)]


class TestEngineDecodeTrunk:
    def test_gates(self, fused_decode_interpret):
        from lir_tpu.config import RuntimeConfig

        eng = _fake_engine()
        assert eng.cascade_decode_supported()
        assert eng.decode_trunk_for(_trunk_rows(), 4, 128) == 96
        off = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            cascade_decode=False))
        assert not off.cascade_decode_supported()
        assert off.decode_trunk_for(_trunk_rows(), 4, 128) == 0
        # the static model flag mirrors the runtime opt-out, so stale
        # executables can never serve the other mode
        assert off.cfg.cascade_decode is False
        assert eng.cfg.cascade_decode is True

    def test_gate_needs_fused_decode_kernels(self):
        eng = _fake_engine()          # hook not armed, CPU backend
        assert not eng.cascade_decode_supported()
        assert eng.decode_trunk_for(_trunk_rows(), 4, 128) == 0

    def test_fused_suffix_flag_mirrors(self):
        from lir_tpu.config import RuntimeConfig

        eng = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            cascade_fused_suffix=False))
        assert eng.cfg.cascade_fused_suffix is False

    def test_trunk_reuses_lcp_discipline(self, fused_decode_interpret):
        """decode_trunk_for is the SAME quantized-LCP ladder the cascade
        prefill keys on: quantum snap, min_rows, bucket clamp."""
        eng = _fake_engine()
        rows = _trunk_rows(trunk=39)
        assert eng.decode_trunk_for(rows, 4, 64) == 32    # snap to 32
        assert eng.decode_trunk_for(rows, 1, 64) == 0     # min_rows
        ident = [list(range(3, 131))] * 4
        t = eng.decode_trunk_for(ident, 4, 128)
        assert 0 < t < 128                                # bucket clamp

    def test_dispatch_counters_and_parity(self, fused_decode_interpret):
        """A shared dispatch over a 96-token trunk in a 128 bucket: ON
        counts a cascade-decode dispatch with nonzero analytic deduped
        trunk bytes; OFF counts nothing; payloads match at the PR-7
        argmax bar (the executables differ, the arithmetic does not)."""
        from lir_tpu.config import RuntimeConfig

        rows = _trunk_rows()
        bins = [r + [5, 6] for r in rows]
        conf = [r + [7, 8] for r in rows]
        t1 = np.asarray([5] * 4, np.int32)
        t2 = np.asarray([9] * 4, np.int32)

        def dispatch(eng):
            return eng.decode_fused_shared(
                [""] * 4, [""] * 4, t1, t2, new_tokens=3, conf_tokens=4,
                pretokenized_a=bins, pretokenized_b=conf, bucket=128,
                sfx_buckets_ab=(8, 8), reuse_cache=True, n_real=4)

        on = _fake_engine()
        f_on = dispatch(on)
        assert on.cascade_stats.cascade_decode_dispatches == 1
        assert on.cascade_stats.trunk_bytes_deduped > 0
        off = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            cascade_decode=False))
        f_off = dispatch(off)
        assert off.cascade_stats.cascade_decode_dispatches == 0
        assert off.cascade_stats.trunk_bytes_deduped == 0
        for a, b in zip(f_on, f_off):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                x, y = np.asarray(x), np.asarray(y)
                if np.issubdtype(x.dtype, np.floating):
                    np.testing.assert_allclose(x, y, atol=5e-5)
                else:
                    np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# compile_plan keying
# ---------------------------------------------------------------------------

class TestCompilePlanDecodeTrunk:
    def test_spec_label_and_keying(self):
        from lir_tpu.engine import compile_plan as cp

        flat = cp.shared_spec(128, 4, 8, 8, 3, 4, False, False)
        trunked = cp.shared_spec(128, 4, 8, 8, 3, 4, False, False,
                                 decode_trunk=96)
        assert flat.decode_trunk == 0
        assert trunked.decode_trunk == 96
        assert flat != trunked
        assert "/dtrunk96" in trunked.label
        assert "dtrunk" not in flat.label
        paged = cp.shared_paged_spec(128, 4, 64, 8, 8, 3, 4, False, False,
                                     decode_trunk=96)
        assert paged.decode_trunk == 96 and "/dtrunk96" in paged.label


# ---------------------------------------------------------------------------
# Pricing + the analytic dedup counter
# ---------------------------------------------------------------------------

class TestSchedulerDecodeTrunk:
    def test_decode_floor_defaults_byte_identical(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.decode_floor(4, 4, 12)
        assert sched.decode_floor(4, 4, 12, decode_trunk_frac=0.0) == base
        assert sched.bucket_cost(4, 64, 4, 12,
                                 decode_trunk_frac=0.0) == (
            sched.bucket_cost(4, 64, 4, 12))

    def test_decode_floor_trunk_discount(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.decode_floor(4, 4, 12)
        half = sched.decode_floor(4, 4, 12, decode_trunk_frac=0.5)
        full = sched.decode_floor(4, 4, 12, decode_trunk_frac=1.0)
        assert base > half > full > 0
        # deduped-row fraction: (slots-1)/slots; KV share caps the lever
        assert full == pytest.approx(
            base * (1 - sched.CASCADE_DECODE_KV_SHARE * 3 / 4))
        # one slot has nothing to dedup
        single = sched.decode_floor(1, 4, 12)
        assert sched.decode_floor(1, 4, 12, decode_trunk_frac=1.0) == single
        # frac clamps at 1
        assert sched.decode_floor(4, 4, 12, decode_trunk_frac=3.0) == full

    def test_bucket_cost_passthrough(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.bucket_cost(4, 64, 4, 12)
        disc = sched.bucket_cost(4, 64, 4, 12, decode_trunk_frac=0.75)
        assert disc < base
        assert base - disc == pytest.approx(
            sched.decode_floor(4, 4, 12)
            - sched.decode_floor(4, 4, 12, decode_trunk_frac=0.75))


class TestBytesSavedAnalytic:
    def test_guards_and_ladder_mirror(self):
        from lir_tpu.utils.profiling import cascade_decode_bytes_saved

        cfg = _tiny_cfg(name="cascdec-bytes")
        assert cascade_decode_bytes_saved(cfg, 1, 96, 256, 3) == 0.0
        assert cascade_decode_bytes_saved(cfg, 4, 0, 256, 3) == 0.0
        assert cascade_decode_bytes_saved(cfg, 4, 96, 256, 0) == 0.0
        # trunk shorter than one split: kernel falls back flat, counter
        # reports zero (it mirrors the ladder, not an idealized bound)
        assert cascade_decode_bytes_saved(cfg, 4, 64, 256, 3) == 0.0
        # T=256 -> split 128, trunk 200 -> nt=1: per row-step bytes are
        # 2 (K+V) * n_kv * 128 * hd * 4B * n_layers
        hd = cfg.hidden_size // cfg.n_heads
        per = 2 * cfg.n_kv_heads * 128 * hd * 4 * cfg.n_layers
        got = cascade_decode_bytes_saved(cfg, 4, 200, 256, 3)
        assert got == per * 3 * 3
        # linear in deduped rows and steps
        assert cascade_decode_bytes_saved(cfg, 7, 200, 256, 3) == 2 * got
        assert cascade_decode_bytes_saved(cfg, 4, 200, 256, 6) == 2 * got
