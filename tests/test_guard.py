"""Guard layer tests (lir_tpu/guard): watchdog stall detection, numerics
quarantine, and multihost liveness.

Pins the robustness tentpole's contracts:
- watch_call runs a callable on a watched thread: results and
  exceptions (BaseException included) propagate; a call that outlives
  its deadline is abandoned and raises DispatchStalled; on_tick runs on
  the caller's thread while the call is in flight;
- DispatchWatchdog calibrates seconds-per-bucket_cost-unit from
  observed dispatches and enforces floor + multiple * predicted; the
  first (uncalibrated) dispatch is observe-only;
- an injected HANG in a sweep dispatch is detected within its deadline
  and fed into the EXISTING recovery ladder: the sweep completes with
  rows bitwise identical to a clean run, long before the hang releases;
- injected NaN corruption quarantines exactly the corrupt rows as
  error:numerics while their neighbors score bitwise identical to a
  fault-free sweep, and GuardStats counters match the injected counts —
  offline and serve;
- _parse_confidence rejects out-of-range integers (satellite 2);
- the multihost liveness barrier raises HostDesyncError within its
  timeout instead of hanging on a dead peer, and degrades to the
  identity single-process.
"""

import time

import jax
import numpy as np
import pytest

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RetryConfig, RuntimeConfig, ServeConfig
from lir_tpu.data.prompts import LegalPrompt
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import _parse_confidence, run_perturbation_sweep
from lir_tpu.guard import numerics
from lir_tpu.guard.watchdog import (DispatchStalled, DispatchWatchdog,
                                    dump_thread_stacks, watch_call)
from lir_tpu.parallel import multihost
from lir_tpu.serve import ScoringServer, ServeRequest
from lir_tpu.utils.profiling import GuardStats


# ---------------------------------------------------------------------------
# watch_call: the watched executor primitive
# ---------------------------------------------------------------------------

def test_watch_call_returns_result_and_ticks():
    ticks = []
    out = watch_call(lambda: (time.sleep(0.15), 42)[1], deadline_s=10.0,
                     on_tick=lambda: ticks.append(1), tick_s=0.02)
    assert out == 42
    assert len(ticks) >= 2      # ticks fired while the call ran


def test_watch_call_propagates_exceptions_and_base_exceptions():
    with pytest.raises(ValueError, match="boom"):
        watch_call(lambda: (_ for _ in ()).throw(ValueError("boom")),
                   deadline_s=5.0)

    def preempt():
        raise faults.InjectedPreemption("kill")

    # BaseException must unwind through the watched thread exactly as it
    # would inline — recovery code catching Exception cannot survive it.
    with pytest.raises(faults.InjectedPreemption):
        watch_call(preempt, deadline_s=5.0)


def test_watch_call_deadline_abandons_and_raises_stalled():
    t0 = time.monotonic()
    with pytest.raises(DispatchStalled, match="watchdog deadline"):
        watch_call(lambda: time.sleep(30), deadline_s=0.2, label="hungcall",
                   tick_s=0.02)
    # Detected within ~one deadline, not after the 30s sleep.
    assert time.monotonic() - t0 < 5.0


def test_watch_call_none_deadline_waits_out_the_call():
    out = watch_call(lambda: (time.sleep(0.1), "done")[1], deadline_s=None,
                     tick_s=0.02)
    assert out == "done"


def test_dump_thread_stacks_includes_this_thread():
    text = dump_thread_stacks()
    assert "test_dump_thread_stacks_includes_this_thread" in text


# ---------------------------------------------------------------------------
# DispatchWatchdog: calibration + deadline policy
# ---------------------------------------------------------------------------

def test_watchdog_uncalibrated_is_observe_only_then_enforces():
    wd = DispatchWatchdog(multiple=2.0, floor_s=0.05)
    assert wd.enabled and not wd.calibrated
    assert wd.deadline_for(100) is None          # observe-only
    assert wd.watch(lambda: "first") == "first"  # runs inline, calibrates
    assert wd.calibrated
    d = wd.deadline_for(100)
    assert d is not None and d >= wd.floor_s
    # Stats: the inline observe-only call is not counted as watched.
    assert wd.stats.watched == {}


def test_watchdog_disabled_by_nonpositive_multiple():
    wd = DispatchWatchdog(multiple=0.0, floor_s=0.05)
    assert not wd.enabled
    assert wd.watch(lambda: "x") == "x"
    assert wd.deadline_for(10) is None


def test_watchdog_stall_counts_per_site():
    wd = DispatchWatchdog(multiple=1.0, floor_s=0.1, tick_s=0.02)
    wd.observe(cost=10, elapsed=0.01)            # calibrate: fast device
    with pytest.raises(DispatchStalled):
        wd.watch(lambda: time.sleep(30), cost=10, site="sweep")
    assert wd.stats.stalls == {"sweep": 1}
    assert wd.stats.stall_dumps == 1
    assert wd.stats.watched == {"sweep": 1}


def test_watchdog_deadline_scales_with_cost():
    wd = DispatchWatchdog(multiple=10.0, floor_s=1.0)
    wd.observe(cost=100, elapsed=0.5)            # 5 ms per unit
    small, big = wd.deadline_for(100), wd.deadline_for(1000)
    assert big > small > wd.floor_s


# ---------------------------------------------------------------------------
# Numerics guard: the validation boundary
# ---------------------------------------------------------------------------

def test_numerics_check_values_accepts_sane_rows():
    assert numerics.check_values(0.4, 0.3, 55.0, [-1.2, -0.001], 85) is None
    assert numerics.check_values(0.0, 1.0, 0.0, [], None) is None
    # Float slop at the boundary is rounding, not corruption.
    assert numerics.check_values(1.0 + 5e-5, 0.0, 100.0) is None


@pytest.mark.parametrize("kw,frag", [
    (dict(token_1_prob=float("nan"), token_2_prob=0.1), "not finite"),
    (dict(token_1_prob=float("inf"), token_2_prob=0.1), "not finite"),
    (dict(token_1_prob=1.5, token_2_prob=0.1), "outside [0,1]"),
    (dict(token_1_prob=-0.2, token_2_prob=0.1), "outside [0,1]"),
    (dict(token_1_prob=0.7, token_2_prob=0.7), "> 1"),
    (dict(token_1_prob=0.4, token_2_prob=0.3,
          weighted_confidence=float("nan")), "not finite"),
    (dict(token_1_prob=0.4, token_2_prob=0.3,
          weighted_confidence=250.0), "outside [0,100]"),
    (dict(token_1_prob=0.4, token_2_prob=0.3,
          logprob_values=[-1.0, float("nan")]), "NaN"),
    (dict(token_1_prob=0.4, token_2_prob=0.3,
          logprob_values=[0.5]), "positive"),
    (dict(token_1_prob=0.4, token_2_prob=0.3,
          confidence_value=250), "outside [0,100]"),
])
def test_numerics_check_values_rejects_corruption(kw, frag):
    reason = numerics.check_values(**kw)
    assert reason is not None and frag in reason


def test_numerics_check_payload_reads_the_logprob_map():
    ok = dict(token_1_prob=0.5, token_2_prob=0.2, weighted_confidence=50.0,
              log_probabilities='{"7": -0.5, "9": -2.25}',
              confidence_value=None)
    assert numerics.check_payload(ok) is None
    bad = dict(ok, log_probabilities='{"7": NaN}')
    assert "NaN" in numerics.check_payload(bad)


# ---------------------------------------------------------------------------
# Satellite 2: _parse_confidence rejects out-of-range integers
# ---------------------------------------------------------------------------

def test_parse_confidence_rejects_out_of_range_values():
    assert _parse_confidence("confidence: 250") is None   # the bug case
    assert _parse_confidence("in the year 1987 .") is None
    assert _parse_confidence("confidence: 100") == 100
    assert _parse_confidence("confidence: 0") == 0
    assert _parse_confidence("I am 85% sure") == 85
    # First-integer semantics preserved: an out-of-range FIRST integer
    # rejects the row (the reference reads only the first integer; we
    # never silently substitute a later one).
    assert _parse_confidence("policy 250 , confidence 80") is None
    # The truncation guard still composes with the range check.
    assert _parse_confidence("about 85", complete=False) is None


# ---------------------------------------------------------------------------
# End-to-end: injected hang + injected NaN on the fake backend
# ---------------------------------------------------------------------------

def _tiny_engine(batch=2, seed=5, **rt_kw):
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="guard-t", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=128)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(batch_size=batch, max_seq_len=128,
                                       **rt_kw))


def _tiny_grid(n_cells, seed=3):
    rng = np.random.default_rng(seed)
    words = "coverage policy flood water damage claim".split()

    def text():
        return " ".join(rng.choice(words) for _ in range(8)) + " ?"

    lp = (LegalPrompt(main=text(), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number from 0 to 100 ."),)
    return lp, ([text() for _ in range(n_cells - 1)],)


def _values(r):
    return (r.token_1_prob, r.token_2_prob, r.confidence_value,
            r.weighted_confidence, r.model_response,
            r.model_confidence_response, r.log_probabilities)


def test_sweep_watchdog_detects_hang_and_ladder_recovers(tmp_path):
    """An injected stall (sleep far past the deadline) is abandoned by
    the watchdog within its deadline and fed into the sweep's recovery
    ladder — rows bitwise identical to a clean run, wall time nowhere
    near the hang duration."""
    lp, perts = _tiny_grid(6)
    # One engine for both runs: the clean sweep calibrates the watchdog
    # (deadline ~ floor + 2x observed dispatch seconds), so the chaos
    # sweep's deadlines are tight without hand-tuning.
    engine = _tiny_engine(watchdog_multiple=2.0, watchdog_floor_s=0.2)
    clean = run_perturbation_sweep(engine, "g", lp, perts,
                                   tmp_path / "clean.csv",
                                   checkpoint_every=100)
    assert engine.watchdog.calibrated

    hang_s = 60.0
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule.hang_at(1, seconds=hang_s)})
    faults.wrap_engine(engine, plan)
    t0 = time.monotonic()
    rows = run_perturbation_sweep(engine, "g", lp, perts,
                                  tmp_path / "chaos.csv",
                                  checkpoint_every=100)
    elapsed = time.monotonic() - t0
    assert plan.stats.injected_total == 1
    assert engine.guard_stats.stalls.get("sweep", 0) >= 1   # watchdog fired
    assert engine.fault_stats.recovered_dispatches >= 1     # ladder recovered
    # Recovered within ~one deadline, not by waiting out the hang.
    assert elapsed < hang_s / 2, f"sweep waited out the hang ({elapsed:.1f}s)"
    by_key = {r.rephrased_main: _values(r) for r in clean}
    assert len(rows) == 6
    for r in rows:
        assert _values(r) == by_key[r.rephrased_main]       # bitwise


def test_sweep_nan_rows_quarantined_neighbors_bitwise(tmp_path):
    """Injected NaN corruption (SDC stand-in) quarantines exactly the
    corrupt rows as error:numerics; every clean row is bitwise identical
    to a fault-free sweep; GuardStats counters match the injection."""
    lp, perts = _tiny_grid(6, seed=9)
    clean = run_perturbation_sweep(_tiny_engine(), "g", lp, perts,
                                   tmp_path / "clean.csv",
                                   checkpoint_every=100)

    engine = _tiny_engine()
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule.nan_at(1, rows=(0,))})
    faults.wrap_engine(engine, plan)
    rows = run_perturbation_sweep(engine, "g", lp, perts,
                                  tmp_path / "chaos.csv",
                                  checkpoint_every=100)
    assert plan.stats.injected_total == 1
    assert len(rows) == 6                                   # zero lost
    quarantined = [r for r in rows
                   if r.model_response == numerics.NUMERICS_ERROR]
    assert len(quarantined) == 1                # exactly the corrupt row
    assert engine.guard_stats.quarantined == {"sweep": 1}
    assert engine.guard_stats.checked["sweep"] == 6
    q = quarantined[0]
    assert q.token_1_prob is None and q.token_2_prob is None
    assert q.confidence_value is None and q.weighted_confidence is None
    assert numerics.NUMERICS_ERROR in q.model_confidence_response
    import math
    assert math.isnan(q.odds_ratio)             # schema None-safety
    by_key = {r.rephrased_main: _values(r) for r in clean}
    for r in rows:
        if r is q:
            continue
        assert _values(r) == by_key[r.rephrased_main]       # bitwise


_FAST_RETRY = RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5)

_SERVE_CFG = ServeConfig(queue_depth=32, classes=(("t", 600.0),),
                         default_class="t", linger_s=0.0,
                         max_consecutive_failures=3, retry=_FAST_RETRY)


def _req(i, rid=None):
    body = f"clause {i} covers hail damage under policy {i * 3}"
    return ServeRequest(binary_prompt=f"{body} Answer Yes or No .",
                        confidence_prompt=f"{body} Number 0 to 100 .",
                        klass="t", request_id=rid or str(i))


def test_serve_nan_payload_quarantined_neighbors_ok():
    server = ScoringServer(_tiny_engine(batch=4), "g", _SERVE_CFG)
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule.nan_at(0, rows=(0,))})
    faults.wrap_server(server, plan)
    futs = [server.submit(_req(i)) for i in range(4)]
    server.start()
    try:
        res = [f.result(timeout=60) for f in futs]
    finally:
        server.stop()
    by_id = {r.request_id: r for r in res}
    bad = by_id["0"]                    # row 0 of the first dispatch
    assert bad.status == "error"
    assert numerics.NUMERICS_ERROR in bad.note
    assert all(by_id[str(i)].status == "ok" for i in range(1, 4))
    g = server.engine.guard_stats
    assert g.quarantined == {"serve": 1}
    assert plan.stats.injected_total == 1
    assert server.healthy               # row-local corruption, no breaker


def test_serve_watchdog_detects_hang_and_recovers():
    engine = _tiny_engine(batch=2, watchdog_multiple=3.0,
                          watchdog_floor_s=0.3)
    server = ScoringServer(engine, "g", _SERVE_CFG)
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule.hang_at(1, seconds=60.0)})
    faults.wrap_server(server, plan)
    server.start()
    try:
        # Dispatch 0: clean — calibrates the watchdog.
        warm = server.submit(_req(0)).result(timeout=60)
        assert warm.status == "ok"
        # Dispatch 1: hangs; the watchdog must abandon it and the
        # retry/ladder must score the rows long before the 60s release.
        t0 = time.monotonic()
        r = server.submit(_req(1)).result(timeout=60)
        elapsed = time.monotonic() - t0
    finally:
        server.stop()
    assert r.status == "ok"
    assert elapsed < 30.0, f"serve waited out the hang ({elapsed:.1f}s)"
    assert engine.guard_stats.stalls.get("serve", 0) >= 1
    assert server.faults.recovered_dispatches >= 1
    assert server.healthy


# ---------------------------------------------------------------------------
# Multihost liveness: timeout-bounded barrier + heartbeat
# ---------------------------------------------------------------------------

def test_multihost_single_process_is_identity():
    # No distributed runtime: every liveness helper degrades to the
    # identity, so sweep drivers call them unconditionally.
    assert not multihost.is_multiprocess()
    beat = multihost.liveness_barrier("t", timeout_s=0.1, payload=7)
    assert beat.shape == (1, 2) and int(beat[0, 1]) == 7
    multihost.barrier("t", timeout_s=0.1)       # no-op, no error


def test_multihost_dead_peer_raises_desync_within_timeout(monkeypatch):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)

    def parked(*a, **k):            # a dead peer: the collective never
        time.sleep(60)              # completes on the survivor

    monkeypatch.setattr(multihost_utils, "process_allgather", parked)
    monkeypatch.setattr(multihost_utils, "sync_global_devices", parked)
    stats = GuardStats()
    t0 = time.monotonic()
    with pytest.raises(multihost.HostDesyncError, match="presumed dead"):
        multihost.liveness_barrier("shard-done", timeout_s=0.3,
                                   payload=12, stats=stats)
    assert time.monotonic() - t0 < 10.0     # fail fast, not in 60s
    assert stats.barrier_timeouts == 1


def test_multihost_heartbeat_gathers_progress(monkeypatch):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    # A live pod: echo both hosts' beats back.
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x: np.stack([np.asarray([[0, 40]], np.int64),
                            np.asarray(x)]))
    beats = multihost.heartbeat("t", payload=41, timeout_s=1.0)
    assert beats.shape == (2, 2)
    assert beats.tolist() == [[0, 40], [1, 41]]
