"""Online serving layer tests (lir_tpu/serve + the retry/bucket_cost
satellites).

Pins the contracts the serving tentpole rides on:
- admission control: FIFO under capacity, deadline-aware shedding at the
  bound (the least-urgent request is the one shed);
- deadline expiry returns PARTIAL confidence-free results without
  failing the rest of the batch;
- the content-addressed dedup cache returns bitwise-identical results to
  a fresh score;
- continuous-batch per-request results equal the offline sweep's for the
  same cells (the dispatch path is the sweep's own, bit for bit);
- repeated device errors drain the queue and flip the health flag;
- retry_with_exponential_backoff's full jitter stays inside the delay
  envelope and the max-elapsed cap bounds total retry time.
"""

import random

import jax
import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RetryConfig, RuntimeConfig, ServeConfig
from lir_tpu.engine import compile_plan
from lir_tpu.engine import scheduler as sched_mod
from lir_tpu.serve import (ResultCache, ScoringServer, ServeFuture,
                           ServeRequest, content_key)
from lir_tpu.serve.queue import Pending, RequestQueue
from lir_tpu.utils.profiling import ServeStats
from lir_tpu.utils.retry import retry_with_exponential_backoff


# ---------------------------------------------------------------------------
# RequestQueue: admission control + deadline-aware shedding (pure host)
# ---------------------------------------------------------------------------

def _pending(deadline: float, rid: str) -> Pending:
    return Pending(
        request=ServeRequest(binary_prompt="b", confidence_prompt="c",
                             request_id=rid),
        future=ServeFuture(), t_submit=0.0, t_deadline=deadline)


def test_queue_admission_and_shed_ordering():
    stats = ServeStats()
    q = RequestQueue(2, stats, clock=lambda: 0.0)
    a, b = _pending(10.0, "a"), _pending(5.0, "b")
    assert q.offer(a) and q.offer(b)

    # Full queue + a LESS urgent newcomer: the newcomer is shed.
    c = _pending(20.0, "c")
    assert not q.offer(c)
    assert c.future.result(0).status == "shed"

    # Full queue + a MORE urgent newcomer: the latest-deadline queued
    # request (a) is evicted instead.
    d = _pending(1.0, "d")
    assert q.offer(d)
    assert a.future.result(0).status == "shed"
    assert not b.future.done() and not d.future.done()

    # FIFO among survivors; the books balance.
    assert [p.request.request_id for p in q.drain()] == ["b", "d"]
    assert stats.shed == 2
    assert stats.admitted == 3
    assert stats.queue_depth_peak == 2


def test_queue_concurrent_shed_keeps_most_urgent_set():
    """The latest-deadline-shed invariant under CONCURRENT submitters
    (it was only pinned single-threaded before): with every offer
    serialized through the queue lock, the greedy policy keeps exactly
    the maxlen most-urgent requests seen so far — so after N threads
    race 200 distinct-deadline offers into a depth-16 queue, the
    survivors must be precisely the 16 earliest deadlines, every loser
    must hold a resolved shed future, and the books must balance."""
    import threading

    depth, n_threads, per_thread = 16, 8, 25
    stats = ServeStats()
    q = RequestQueue(depth, stats, clock=lambda: 0.0)
    # Distinct deadlines, dealt round-robin so every thread holds a mix
    # of urgent and lazy requests (maximizing eviction interleavings).
    deadlines = [float(d) for d in
                 np.random.default_rng(0).permutation(
                     n_threads * per_thread)]
    pendings = [_pending(d, str(i)) for i, d in enumerate(deadlines)]
    start = threading.Barrier(n_threads)

    def submitter(tid):
        start.wait()
        for p in pendings[tid::n_threads]:
            q.offer(p)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    survivors = q.drain()
    assert len(survivors) == depth
    want = sorted(deadlines)[:depth]
    assert sorted(p.t_deadline for p in survivors) == want
    # Every non-survivor was resolved shed — no future leaks.
    kept = {id(p) for p in survivors}
    for p in pendings:
        if id(p) in kept:
            assert not p.future.done()
        else:
            assert p.future.result(0).status == "shed"
    assert stats.shed == len(pendings) - depth
    # admitted counts every entry that EVER joined the queue (evicted
    # ones included), so it must at least cover the survivors and never
    # exceed the offers.
    assert depth <= stats.admitted <= len(pendings)


def test_queue_flush_resolves_everything():
    q = RequestQueue(8, ServeStats(), clock=lambda: 0.0)
    ps = [_pending(9.0, str(i)) for i in range(3)]
    for p in ps:
        q.offer(p)
    assert q.flush("error", "drained") == 3
    assert all(p.future.result(0).status == "error" for p in ps)
    assert len(q) == 0


# ---------------------------------------------------------------------------
# ResultCache: content addressing + LRU bound
# ---------------------------------------------------------------------------

def test_result_cache_lru_and_keying():
    stats = ServeStats()
    cache = ResultCache(2, stats)
    r1 = ServeRequest(binary_prompt="p1 bin", confidence_prompt="p1 conf")
    r2 = ServeRequest(binary_prompt="p2 bin", confidence_prompt="p2 conf")
    r3 = ServeRequest(binary_prompt="p1 bin", confidence_prompt="p1 conf",
                      targets=("Covered", "Not"))
    k1, k2, k3 = (content_key("eng", r) for r in (r1, r2, r3))
    assert len({k1, k2, k3}) == 3            # prompts AND targets key
    assert content_key("other-engine", r1) != k1

    cache.put(k1, {"v": 1})
    cache.put(k2, {"v": 2})
    assert cache.get(k1) == {"v": 1}         # k1 now most-recent
    cache.put(k3, {"v": 3})                  # evicts k2 (LRU)
    assert cache.get(k2) is None
    assert cache.get(k1) == {"v": 1} and cache.get(k3) == {"v": 3}
    assert stats.dedup_hits == 3 and stats.dedup_misses == 1

    disabled = ResultCache(0, ServeStats())
    disabled.put(k1, {"v": 1})
    assert disabled.get(k1) is None and len(disabled) == 0


# ---------------------------------------------------------------------------
# Retry satellite: full jitter + max-elapsed cap
# ---------------------------------------------------------------------------

def test_retry_max_elapsed_cap_is_deterministic():
    calls, waits, t = [], [], [0.0]
    cfg = RetryConfig(max_retries=10, initial_delay=4.0, max_delay=300.0,
                      backoff_factor=2.0, jitter=(1.0, 1.0),
                      max_elapsed=5.0)

    def always_fails():
        calls.append(1)
        raise ValueError("nope")

    def sleep(s):
        waits.append(s)
        t[0] += s

    with pytest.raises(ValueError):
        retry_with_exponential_backoff(
            always_fails, (ValueError,), cfg, sleep=sleep,
            log=lambda s: None, clock=lambda: t[0])
    # First retry slept 4 s (inside the cap); the second would sleep 8 s,
    # crossing the 5 s cap -> the failure re-raises without sleeping.
    assert waits == [4.0]
    assert len(calls) == 2
    assert t[0] <= cfg.max_elapsed


def test_retry_full_jitter_stays_inside_the_envelope():
    random.seed(0)
    waits, t = [], [0.0]
    cfg = RetryConfig(max_retries=6, initial_delay=1.0, max_delay=4.0,
                      backoff_factor=2.0, full_jitter=True,
                      max_elapsed=1000.0)

    def always_fails():
        raise ValueError("nope")

    def sleep(s):
        waits.append(s)
        t[0] += s

    with pytest.raises(ValueError):
        retry_with_exponential_backoff(
            always_fails, (ValueError,), cfg, sleep=sleep,
            log=lambda s: None, clock=lambda: t[0])
    assert len(waits) == 6
    caps = [1.0, 2.0, 4.0, 4.0, 4.0, 4.0]    # delay doubles, capped at 4
    assert all(0.0 <= w <= c for w, c in zip(waits, caps))


# ---------------------------------------------------------------------------
# bucket_cost satellite: one price model for planner and batcher
# ---------------------------------------------------------------------------

def test_bucket_cost_matches_the_planner_rule():
    # The helper IS the planner's keep-the-tail price: padded
    # power-of-two batch x (prefill edge + fixed decode scan).
    assert sched_mod.bucket_cost(3, 64, 8, 12) == 4 * (64 + 12)
    assert sched_mod.bucket_cost(8, 64, 8, 12) == 8 * (64 + 12)
    assert sched_mod.bucket_cost(9, 64, 8, 12) == 8 * (64 + 12)  # capped
    # Promotion fires exactly when riding the next bucket is cheaper.
    B, edge, nxt, dc = 8, 64, 96, 12
    for n in range(1, B + 1):
        promote = n * nxt < sched_mod.bucket_cost(n, edge, B, dc)
        assert promote == (n * nxt < sched_mod._tail_batch(n, B)
                           * (edge + dc))


def test_serve_batches_and_ladder_specs():
    assert compile_plan.serve_batches(32) == (1, 2, 4, 8, 16, 32)
    assert compile_plan.serve_batches(1) == (1,)
    # The serve boot precompile warms every (edge, sfx, padded batch)
    # shared executable in both handoff variants.
    engine = _tiny_setup()()
    specs = compile_plan.sweep_specs_for_ladder(
        engine, sfx_buckets=(8,), batches=(1, 2, 4))
    # Sequential + speculative sibling per (edge, sfx, batch, handoff).
    seq = [s for s in specs if not s.spec_k]
    assert len(seq) == len(engine.buckets) * 1 * 3 * 2
    assert len(specs) == 2 * len(seq)
    assert {s.batch for s in specs} == {1, 2, 4}
    assert {s.bucket for s in specs} == set(engine.buckets)


def test_online_promotion_rides_the_next_buckets_dispatch():
    """An underfull ripe bucket with work waiting above it promotes —
    the offline slot-refill rule run incrementally. A lone bucket never
    promotes into an empty queue (nothing to ride)."""
    from lir_tpu.serve.batcher import ContinuousBatcher

    engine = _tiny_setup()()          # buckets: ladder up to 256
    stats = ServeStats()
    b = ContinuousBatcher(engine, stats, linger_s=0.0, pad_full=True)
    small, big = engine.buckets[0], engine.buckets[1]

    def pend(bucket, rid):
        p = _pending(600.0, rid)
        p.bucket = bucket
        return p

    # 2 rows at the small edge + 2 at the next: promotion merges them
    # into ONE full dispatch at the bigger edge.
    for i in range(2):
        b.admit(pend(small, f"s{i}"))
        b.admit(pend(big, f"b{i}"))
    edge, rows = b.next_dispatch(now=10.0)
    assert edge == big and len(rows) == 4
    assert stats.promoted == 2
    # Lone underfull bucket, empty ladder above: dispatches in place.
    b2 = ContinuousBatcher(engine, stats, linger_s=0.0, pad_full=True)
    b2.admit(pend(small, "alone"))
    edge2, rows2 = b2.next_dispatch(now=10.0)
    assert edge2 == small and len(rows2) == 1


# ---------------------------------------------------------------------------
# Server-level: scoring parity, dedup, deadlines, health
# ---------------------------------------------------------------------------

def _tiny_setup(batch_size=4, seed=2):
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="serve-t", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    rt = RuntimeConfig(batch_size=batch_size, max_seq_len=256)

    def engine():
        return ScoringEngine(params, cfg, FakeTokenizer(), rt)

    return engine


def _grid(n_cells, words_each=12, seed=5):
    """Uniform-length cells (every prompt the same token count) so the
    offline planner and the online batcher form IDENTICAL dispatch
    shapes — the precondition for bitwise equality across the paths."""
    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer "
             "premium exclusion endorsement").split()

    def text():
        return " ".join(rng.choice(words) for _ in range(words_each)) + " ?"

    lp = (LegalPrompt(main=text(), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    return lp, ([text() for _ in range(n_cells - 1)],)


def _request_for(cell, rid):
    return ServeRequest(binary_prompt=cell.binary_prompt,
                        confidence_prompt=cell.confidence_prompt,
                        targets=cell.target_tokens, klass="t",
                        request_id=rid)


_SERVE_CFG = ServeConfig(queue_depth=64, classes=(("t", 600.0),),
                         default_class="t", linger_s=0.01)


def test_continuous_batching_matches_offline_sweep_bitwise(tmp_path):
    """The acceptance pin: per-request serve results equal the offline
    sweep's for the same cells, bit for bit. Same cells, same batch
    size, same bucket/suffix snapping, same handoff chain -> the serve
    path dispatches the sweep's own executables on identical inputs."""
    from lir_tpu.engine import grid as grid_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep

    make_engine = _tiny_setup(batch_size=4)
    lp, perts = _grid(12)

    rows = run_perturbation_sweep(
        make_engine(), "serve-t", lp, perts, tmp_path / "off.xlsx",
        checkpoint_every=100)
    by_prompt = {r.rephrased_main: r for r in rows}
    assert len(by_prompt) == 12

    cells = grid_mod.build_grid("serve-t", lp, perts)
    server = ScoringServer(make_engine(), "serve-t", _SERVE_CFG)
    futures = [(c, server.submit(_request_for(c, str(i))))
               for i, c in enumerate(cells)]
    server.start()
    try:
        for cell, fut in futures:
            res = fut.result(timeout=300)
            off = by_prompt[cell.rephrased_main]
            assert res.status == "ok" and not res.cached
            # Bitwise: exact float equality, not allclose.
            assert res.token_1_prob == off.token_1_prob
            assert res.token_2_prob == off.token_2_prob
            assert res.weighted_confidence == off.weighted_confidence
            assert res.confidence_value == off.confidence_value
            assert res.model_response == off.model_response
            assert (res.model_confidence_response
                    == off.model_confidence_response)
            assert res.log_probabilities == off.log_probabilities
    finally:
        server.stop()
    assert server.stats.completed == 12
    assert server.stats.shed == 0 and server.stats.expired == 0


def test_dedup_cache_hit_is_bitwise_identical_to_fresh_score():
    make_engine = _tiny_setup()
    lp, perts = _grid(4, seed=9)
    from lir_tpu.engine import grid as grid_mod

    cells = grid_mod.build_grid("serve-t", lp, perts)
    server = ScoringServer(make_engine(), "serve-t", _SERVE_CFG).start()
    try:
        fresh = [server.submit(_request_for(c, str(i))).result(timeout=300)
                 for i, c in enumerate(cells)]
        assert all(r.status == "ok" and not r.cached for r in fresh)
        dispatches_after_fresh = server.stats.dispatches
        hits = [server.submit(_request_for(c, f"again{i}"))
                .result(timeout=60) for i, c in enumerate(cells)]
    finally:
        server.stop()
    for a, b in zip(fresh, hits):
        assert b.cached and b.status == "ok"
        assert b.token_1_prob == a.token_1_prob
        assert b.token_2_prob == a.token_2_prob
        assert b.weighted_confidence == a.weighted_confidence
        assert b.log_probabilities == a.log_probabilities
        assert b.model_response == a.model_response
    assert server.stats.dedup_hits == len(cells)
    # A hit never touched the device: dispatch count didn't grow.
    assert server.stats.dispatches == dispatches_after_fresh


def test_deadline_expired_rows_return_partial_without_failing_batch():
    make_engine = _tiny_setup()
    lp, perts = _grid(4, seed=3)
    from lir_tpu.engine import grid as grid_mod

    cells = grid_mod.build_grid("serve-t", lp, perts)
    server = ScoringServer(make_engine(), "serve-t", _SERVE_CFG)
    # Submit BEFORE start: the expired row sits queued past its deadline
    # while the live rows ride the same bucket.
    doomed = server.submit(ServeRequest(
        binary_prompt=cells[0].binary_prompt,
        confidence_prompt=cells[0].confidence_prompt,
        deadline_s=0.0, request_id="doomed"))
    live = [server.submit(_request_for(c, str(i)))
            for i, c in enumerate(cells[1:])]
    server.start()
    try:
        d = doomed.result(timeout=300)
        results = [f.result(timeout=300) for f in live]
    finally:
        server.stop()
    # Partial, confidence-free result — not an exception, not a dropped
    # request, and the batch it would have ridden still completed.
    assert d.status == "deadline_exceeded"
    assert d.token_1_prob is None and d.token_2_prob is None
    assert d.confidence_value is None and d.weighted_confidence is None
    assert all(r.status == "ok" for r in results)
    assert server.stats.expired == 1
    assert server.stats.completed == len(results)


def test_expired_request_resolves_partial_during_watched_dispatch():
    """Satellite pin (guard layer): deadline enforcement actually
    CANCELS. A request whose deadline passes while its dispatch is on
    the device resolves its partial result immediately — the watched
    executor's tick callback — instead of waiting out the device call.
    Pre-guard behavior was to block until the dispatch returned, which
    made deadlines advisory whenever the device was slow or hung."""
    import time as _time

    make_engine = _tiny_setup()
    lp, perts = _grid(4, seed=7)
    from lir_tpu.engine import grid as grid_mod

    cells = grid_mod.build_grid("serve-t", lp, perts)
    server = ScoringServer(make_engine(), "serve-t", _SERVE_CFG)
    real_score = server.batcher.score
    slow_s = 1.5

    def slow_score(bucket, rows):
        _time.sleep(slow_s)         # a slow (not hung) device call
        return real_score(bucket, rows)

    server.batcher.score = slow_score
    doomed = server.submit(ServeRequest(
        binary_prompt=cells[0].binary_prompt,
        confidence_prompt=cells[0].confidence_prompt,
        deadline_s=0.2, request_id="doomed"))
    live = [server.submit(_request_for(c, str(i)))
            for i, c in enumerate(cells)]
    server.start()
    try:
        t0 = _time.monotonic()
        d = doomed.result(timeout=60)
        waited = _time.monotonic() - t0
        results = [f.result(timeout=300) for f in live]
    finally:
        server.stop()
    assert d.status == "deadline_exceeded"
    assert d.token_1_prob is None and d.weighted_confidence is None
    assert "mid-dispatch" in d.note
    # The whole point: resolved BEFORE the device call finished.
    assert waited < slow_s, (
        f"expired request waited out the {slow_s}s dispatch "
        f"({waited:.2f}s)")
    # Its batch still completed for every live neighbor, and the late
    # payload for the cancelled row was dropped, not double-resolved.
    assert all(r.status == "ok" for r in results)
    eng_stats = server.engine.guard_stats
    assert eng_stats.inflight_cancelled >= 1
    assert server.stats.expired >= 1


def test_repeated_device_errors_drain_queue_and_flip_health():
    make_engine = _tiny_setup()
    cfg = ServeConfig(
        queue_depth=16, classes=(("t", 600.0),), default_class="t",
        linger_s=0.0, max_consecutive_failures=1,
        retry=RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=1.0))
    server = ScoringServer(make_engine(), "serve-t", cfg)
    boom = RuntimeError("device on fire")

    def exploding_score(bucket, rows):
        raise boom

    server.batcher.score = exploding_score
    lp, perts = _grid(4, seed=4)
    from lir_tpu.engine import grid as grid_mod

    cells = grid_mod.build_grid("serve-t", lp, perts)
    futures = [server.submit(_request_for(c, str(i)))
               for i, c in enumerate(cells)]
    server.start()
    try:
        results = [f.result(timeout=60) for f in futures]
    finally:
        server.stop()
    assert all(r.status == "error" for r in results)
    assert not server.healthy
    assert server.stats.errors == len(cells)
    # Post-trip submits shed immediately instead of queueing.
    shed = server.submit(_request_for(cells[0], "post")).result(timeout=5)
    assert shed.status == "shed" and "unhealthy" in shed.note
