"""Chunked weight streaming vs monolithic load: BITWISE per family.

The fleet layer (models/weights.py) ships converted param trees
host->device in chunks with a double-buffered in-flight window instead
of one monolithic device_put per leaf. The contract this file pins: for
EVERY architecture family converter (gpt2, llama, falcon, bloom, opt,
t5), the streamed tree is bitwise-identical — same bytes, same dtypes,
same structure — to the tree the converter produced, including
quantized (int8 payload + fp32 scale) trees. Chunk sizes are set tiny
so every large leaf actually takes the multi-chunk concatenate path.

Tiny HF models are built locally from configs (no network, no
weights on disk) exactly like tests/test_model_parity.py does.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lir_tpu.models import quant, weights
from lir_tpu.models.loader import (config_from_hf, convert_decoder,
                                   convert_t5, t5_config_from_hf)

TINY = dict(vocab=128, hidden=32, layers=2, heads=4)

# Small enough that 32x32 fp32 leaves (4 KB) split into several chunks
# AND per-layer stacked leaves (L=2) split along the stack axis.
CHUNK = 1024


def _hf_tiny(family):
    import torch  # noqa: F401 — state_dict tensors
    import transformers as tf

    torch.manual_seed(0)
    v, d, l, h = TINY["vocab"], TINY["hidden"], TINY["layers"], TINY["heads"]
    if family == "gpt2":
        return tf.GPT2LMHeadModel(tf.GPT2Config(
            vocab_size=v, n_embd=d, n_layer=l, n_head=h, n_positions=128))
    if family == "llama":
        return tf.LlamaForCausalLM(tf.LlamaConfig(
            vocab_size=v, hidden_size=d, num_hidden_layers=l,
            num_attention_heads=h, num_key_value_heads=h,
            intermediate_size=2 * d, max_position_embeddings=128,
            tie_word_embeddings=False))
    if family == "falcon":
        return tf.FalconForCausalLM(tf.FalconConfig(
            vocab_size=v, hidden_size=d, num_hidden_layers=l,
            num_attention_heads=h, multi_query=True, new_decoder_arch=False,
            parallel_attn=True, bias=False, alibi=False))
    if family == "bloom":
        return tf.BloomForCausalLM(tf.BloomConfig(
            vocab_size=v, hidden_size=d, n_layer=l, n_head=h))
    if family == "opt":
        return tf.OPTForCausalLM(tf.OPTConfig(
            vocab_size=v, hidden_size=d, num_hidden_layers=l,
            num_attention_heads=h, ffn_dim=4 * d, word_embed_proj_dim=d,
            max_position_embeddings=128, do_layer_norm_before=True))
    raise KeyError(family)


def _converted(family):
    if family == "t5":
        import transformers as tf

        hf = tf.T5ForConditionalGeneration(tf.T5Config(
            vocab_size=TINY["vocab"], d_model=TINY["hidden"], d_kv=8,
            d_ff=64, num_layers=TINY["layers"], num_heads=TINY["heads"],
            feed_forward_proj="gated-gelu", tie_word_embeddings=False,
            decoder_start_token_id=0)).eval()
        cfg = t5_config_from_hf(hf.config)
        return convert_t5(hf.state_dict(), cfg), cfg
    hf = _hf_tiny(family).eval()
    cfg, fam = config_from_hf(hf.config)
    return convert_decoder(hf.state_dict(), cfg, fam), cfg


def _assert_tree_bitwise(monolithic, streamed):
    is_qt = lambda x: isinstance(x, quant.QuantTensor)  # noqa: E731
    mono = jax.tree_util.tree_flatten_with_path(monolithic, is_leaf=is_qt)[0]
    stream = jax.tree.leaves(streamed, is_leaf=is_qt)
    assert len(mono) == len(stream)
    for (path, a), b in zip(mono, stream):
        if isinstance(a, quant.QuantTensor):
            assert isinstance(b, quant.QuantTensor), path
            assert a.dynamic == b.dynamic, path
            pairs = [(a.q, b.q), (a.scale, b.scale)]
        else:
            pairs = [(a, b)]
        for x, y in pairs:
            assert x.dtype == y.dtype, path
            assert x.shape == y.shape, path
            # Bitwise: compare raw bytes, so NaN payloads and signed
            # zeros cannot hide behind float equality.
            np.testing.assert_array_equal(
                np.asarray(x).view(np.uint8), np.asarray(y).view(np.uint8),
                err_msg=str(path))


FAMILIES = ["gpt2", "llama", "falcon", "bloom", "opt", "t5"]


@pytest.mark.parametrize("family", FAMILIES)
def test_streamed_load_bitwise_per_family(family):
    params, _cfg = _converted(family)
    staged = weights.host_stage(params)
    streamed = weights.stream_params(staged, chunk_bytes=CHUNK)
    _assert_tree_bitwise(params, streamed)


@pytest.mark.parametrize("family,dynamic",
                         [("llama", False), ("llama", True),
                          ("bloom", False), ("t5", False)])
def test_streamed_load_bitwise_quantized(family, dynamic):
    """int8 trees: payload bytes AND fp32 scales survive the chunked
    path bit-for-bit, with the dynamic flag preserved."""
    params, _cfg = _converted(family)
    qfn = (quant.quantize_encdec_params if family == "t5"
           else quant.quantize_decoder_params)
    qparams = qfn(params, dynamic=dynamic)
    staged = weights.host_stage(qparams)
    streamed = weights.stream_params(staged, chunk_bytes=CHUNK)
    _assert_tree_bitwise(qparams, streamed)
    assert weights.tree_bytes(streamed) == weights.tree_bytes(qparams)


def test_chunking_actually_chunks():
    """The chunk path must actually engage at this test's sizes (a
    regression here would quietly turn every case above into the
    monolithic path and prove nothing)."""
    params, _cfg = _converted("llama")
    big = [l for l in jax.tree.leaves(params)
           if weights.leaf_bytes(l) > CHUNK and l.shape[0] > 1]
    assert big, "no leaf large enough to chunk — shrink CHUNK"


def test_stream_reports_bytes():
    from lir_tpu.utils.profiling import FleetStats

    params, _cfg = _converted("gpt2")
    stats = FleetStats()
    weights.stream_params(weights.host_stage(params), chunk_bytes=CHUNK,
                          stats=stats)
    assert stats.weight_bytes_streamed == weights.tree_bytes(params)
