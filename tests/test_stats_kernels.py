"""Unit tests for the stats kernels vs scipy/sklearn ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as scipy_stats
from sklearn.metrics import cohen_kappa_score

from lir_tpu.stats import (
    aggregate_kappa,
    average_ranks,
    bootstrap_correlation,
    bootstrap_correlation_matrix,
    bootstrap_mean_ci,
    cohen_kappa,
    interpret_kappa,
    masked_pearson_matrix,
    masked_spearman_matrix,
    normal_approx_mc_difference,
    normality_tests,
    pairwise_agreement_stats,
    pearson,
    permutation_test_difference,
    self_kappa_bootstrap,
    spearman,
    truncated_normal_mc_fit,
    within_group_kappa,
)


KEY = jax.random.PRNGKey(42)


class TestCore:
    def test_pearson_matches_scipy(self, rng):
        x = rng.normal(size=200)
        y = 0.6 * x + rng.normal(size=200)
        expected = scipy_stats.pearsonr(x, y)[0]
        got = float(pearson(jnp.asarray(x), jnp.asarray(y)))
        assert abs(got - expected) < 1e-6

    def test_spearman_matches_scipy_with_ties(self, rng):
        x = rng.integers(0, 10, size=100).astype(float)  # heavy ties
        y = rng.integers(0, 10, size=100).astype(float)
        expected = scipy_stats.spearmanr(x, y)[0]
        got = float(spearman(jnp.asarray(x), jnp.asarray(y)))
        assert abs(got - expected) < 1e-6

    def test_average_ranks_matches_scipy(self, rng):
        x = rng.integers(0, 5, size=50).astype(float)
        expected = scipy_stats.rankdata(x, method="average")
        got = np.asarray(average_ranks(jnp.asarray(x)))
        np.testing.assert_allclose(got, expected)


@pytest.mark.slow
class TestBootstrap:
    def test_bootstrap_correlation_brackets_estimate(self, rng):
        x = rng.normal(size=100)
        y = 0.7 * x + 0.3 * rng.normal(size=100)
        res = bootstrap_correlation(x, y, KEY, n_boot=1000)
        assert res.ci_lower < res.estimate < res.ci_upper
        assert 0 < res.standard_error < 0.2
        expected = scipy_stats.pearsonr(x, y)
        assert abs(res.estimate - expected[0]) < 1e-12
        assert abs(res.p_value - expected[1]) < 1e-12

    def test_bootstrap_deterministic_for_fixed_key(self, rng):
        x, y = rng.normal(size=50), rng.normal(size=50)
        a = bootstrap_correlation(x, y, KEY, n_boot=200)
        b = bootstrap_correlation(x, y, KEY, n_boot=200)
        np.testing.assert_array_equal(a.samples, b.samples)

    def test_bootstrap_mean_ci(self, rng):
        v = rng.normal(loc=5.0, size=400)
        res = bootstrap_mean_ci(v, KEY, n_boot=1000)
        # CI should bracket the true mean and be close to analytic width
        assert res.ci_lower < 5.0 < res.ci_upper
        assert abs(res.estimate - v.mean()) < 1e-12

    def test_permutation_test_null(self, rng):
        a = rng.normal(size=60)
        b = rng.normal(size=60)
        res = permutation_test_difference(a, b, KEY, n_perm=2000)
        assert res["p_value"] > 0.01  # same distribution: should not reject

    def test_permutation_test_signal(self, rng):
        a = rng.normal(loc=1.0, size=60)
        b = rng.normal(loc=0.0, size=60)
        res = permutation_test_difference(a, b, KEY, n_perm=2000)
        assert res["p_value"] < 0.01

    def test_normal_approx_mc_difference(self):
        res = normal_approx_mc_difference(0.8, 0.05, 0.5, 0.05, KEY, n_draws=10_000)
        assert res["p_value"] < 0.01
        assert res["ci_lower"] > 0


@pytest.mark.slow
class TestKappa:
    def test_cohen_kappa_matches_sklearn(self, rng):
        for _ in range(5):
            a = rng.integers(0, 2, size=80)
            b = rng.integers(0, 2, size=80)
            expected = cohen_kappa_score(a, b)
            got = float(cohen_kappa(jnp.asarray(a), jnp.asarray(b)))
            assert abs(got - expected) < 1e-6

    def test_cohen_kappa_constant_identical_is_nan(self):
        a = jnp.ones(10, dtype=jnp.int32)
        assert np.isnan(float(cohen_kappa(a, a)))

    def test_within_group_kappa_matches_pair_loop(self, rng):
        decisions = rng.integers(0, 2, size=300)
        groups = rng.integers(0, 5, size=300)
        got = within_group_kappa(decisions, groups)
        # Brute-force O(n^2) loop, as the reference computes it
        agree = total = 0
        for g in np.unique(groups):
            d = decisions[groups == g]
            for i in range(len(d)):
                for j in range(i + 1, len(d)):
                    total += 1
                    agree += int(d[i] == d[j])
        observed = agree / total
        p1 = decisions.mean()
        expected_agreement = p1 * p1 + (1 - p1) * (1 - p1)
        kappa = (observed - expected_agreement) / (1 - expected_agreement)
        assert abs(got["observed_agreement"] - observed) < 1e-12
        assert abs(got["kappa"] - kappa) < 1e-12

    def test_aggregate_kappa_matches_loop(self, rng):
        binary = rng.integers(0, 2, size=(40, 6))
        got = aggregate_kappa(binary, KEY, n_boot=200)
        # reference formulation
        import itertools

        rates = []
        for row in binary:
            agree = sum(
                1
                for i, j in itertools.combinations(range(len(row)), 2)
                if row[i] == row[j]
            )
            rates.append(agree / (len(row) * (len(row) - 1) / 2))
        observed = np.mean(rates)
        p1 = binary.mean()
        chance = p1 * p1 + (1 - p1) * (1 - p1)
        kappa = (observed - chance) / (1 - chance)
        assert abs(got["aggregate_kappa"] - kappa) < 1e-6
        assert got["kappa_ci_lower"] <= got["aggregate_kappa"] <= got["kappa_ci_upper"]

    def test_self_kappa_near_zero_for_random(self, rng):
        d = rng.integers(0, 2, size=500)
        got = self_kappa_bootstrap(d, KEY, n_boot=300)
        assert abs(got["self_kappa"]) < 0.15  # independent resamples ~ chance

    def test_interpret_bands(self):
        assert "Poor" in interpret_kappa(-0.1)
        assert "Slight" in interpret_kappa(0.1)
        assert "Fair" in interpret_kappa(0.3)
        assert "Moderate" in interpret_kappa(0.5)
        assert "Substantial" in interpret_kappa(0.7)
        assert "perfect" in interpret_kappa(0.9)


@pytest.mark.slow
class TestAgreement:
    def test_pairwise_agreement_matches_loop(self, rng):
        vals = rng.uniform(0, 100, size=50)
        got = pairwise_agreement_stats(vals, scale=100.0)
        pair_vals = [
            (100 - abs(vals[i] - vals[j])) / 100
            for i in range(len(vals))
            for j in range(i + 1, len(vals))
        ]
        assert abs(got["mean_agreement"] - np.mean(pair_vals)) < 1e-6
        assert abs(got["std_agreement"] - np.std(pair_vals)) < 1e-6
        assert got["n_pairs"] == len(pair_vals)


@pytest.mark.slow
class TestCorrelationMatrix:
    def test_masked_pearson_matches_pandas(self, rng):
        import pandas as pd

        x = rng.normal(size=(30, 5))
        x[rng.uniform(size=x.shape) < 0.1] = np.nan  # pairwise-complete case
        expected = pd.DataFrame(x).corr(method="pearson").values
        got = np.asarray(masked_pearson_matrix(jnp.asarray(x)))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-8)

    def test_masked_spearman_matches_pandas_dense(self, rng):
        import pandas as pd

        x = rng.normal(size=(30, 4))
        expected = pd.DataFrame(x).corr(method="spearman").values
        got = np.asarray(masked_spearman_matrix(jnp.asarray(x)))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-8)

    def test_masked_spearman_matches_pandas_with_nan(self, rng):
        """Pairwise-complete spearman must re-rank within each joint subset
        (pandas semantics), not rank whole columns first."""
        import pandas as pd

        x = rng.normal(size=(40, 5))
        x[rng.uniform(size=x.shape) < 0.3] = np.nan
        expected = pd.DataFrame(x).corr(method="spearman").values
        got = np.asarray(masked_spearman_matrix(jnp.asarray(x)))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)

    def test_bootstrap_correlation_matrix_sane(self, rng):
        x = rng.normal(size=(50, 6))
        res = bootstrap_correlation_matrix(x, KEY, n_bootstrap=200)
        assert res["mean_ci"][0] <= res["mean_correlation"] <= res["mean_ci"][1]
        assert res["correlation_matrix"].shape == (6, 6)


@pytest.mark.slow
class TestFitsAndNormality:
    def test_truncnorm_fit_recovers_moments(self):
        rng = np.random.default_rng(0)
        true = np.clip(rng.normal(0.6, 0.25, size=5000), 0, 1)
        res, sample = truncated_normal_mc_fit(true, KEY, n_simulations=50_000)
        assert res["Mean Relative Error"] < 0.01
        assert res["Std Relative Error"] < 0.02
        assert sample.size == 50_000
        # a truncated normal fit to truncated-normal data should be adequate
        assert res["KS p-value"] > 0.01

    def test_truncnorm_fit_all_extreme(self):
        res = truncated_normal_mc_fit(np.array([0.0, 1.0, 1.0]), KEY)
        assert "Failed" in res[0]["Model Fit"] if isinstance(res, tuple) else True

    def test_normality_gaussian_passes(self):
        rng = np.random.default_rng(1)
        res = normality_tests(rng.normal(size=800))
        assert res["KS p-value"] > 0.05
        assert res["AD Normal (stat<crit)"]

    def test_normality_bimodal_fails(self):
        rng = np.random.default_rng(2)
        data = np.concatenate([rng.normal(-3, 0.3, 400), rng.normal(3, 0.3, 400)])
        res = normality_tests(data)
        assert res["KS p-value"] < 0.05
        assert not res["AD Normal (stat<crit)"]
