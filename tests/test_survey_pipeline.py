"""Parity tests for the survey subsystem (C31-C43) against independent
reference-style (row-loop pandas/scipy) reimplementations, evaluated on the
committed reference data (D2/D3) — the free regression fixtures of
SURVEY.md §4.
"""

import jax
import numpy as np
import pandas as pd
import pytest
from scipy import stats as scipy_stats

from lir_tpu.survey import (
    agreement_metrics,
    apply_exclusions,
    bootstrap_agreement_metrics,
    canonical_question_mapping,
    extract_question_text,
    human_averages_from_detailed,
    human_correlations_with_pvalues,
    human_cross_prompt_correlations,
    human_llm_correlation,
    human_responses_by_question,
    llm_correlations_with_pvalues,
    llm_cross_prompt_correlations,
    llm_responses_by_question,
    load_survey,
    match_survey_to_llm_questions,
    model_group_tensors,
    pearson_pvalues,
    relative_prob_series,
    survey_detailed,
)
from lir_tpu.survey.loader import group_question_ids

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

KEY = jax.random.PRNGKey(42)


@pytest.fixture(scope="module")
def survey(reference_data_dir):
    return load_survey(f"{reference_data_dir}/word_meaning_survey_results.csv")


@pytest.fixture(scope="module")
def clean(survey):
    df, cols = survey
    return apply_exclusions(df, cols)


@pytest.fixture(scope="module")
def instruct_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")


@pytest.fixture(scope="module")
def base_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")


@pytest.fixture(scope="module")
def matches(reference_data_dir, instruct_df):
    mapping = extract_question_text(
        f"{reference_data_dir}/word_meaning_survey_results.csv"
    )
    return match_survey_to_llm_questions(instruct_df, mapping)


class TestLoaderAndExclusions:
    def test_load_shape(self, survey):
        df, cols = survey
        # D3: 507 respondent rows, 55 slider columns (5 groups x 11).
        assert len(df) == 507
        assert len(cols) == 55

    def test_exclusions_match_reference_row_loop(self, survey):
        """Vectorized exclusions == the reference's row-by-row loops
        (survey_analysis_consolidated.py:36-85)."""
        df, cols = survey
        ours, stats = apply_exclusions(df, cols)

        # Independent reimplementation with explicit Python loops.
        ref = df.copy()
        median = ref["Duration (in seconds)"].median()
        ref = ref[ref["Duration (in seconds)"] >= 0.2 * median]
        identical = []
        for idx, row in ref.iterrows():
            answered = [c for c in cols if pd.notna(row[c]) and not c.endswith("_8")]
            if len(answered) > 1 and len({row[c] for c in answered}) == 1:
                identical.append(idx)
        ref = ref.drop(identical)
        attention = []
        for idx, row in ref.iterrows():
            for g in range(1, 6):
                col = f"Q{g}_8"
                if col in ref.columns and pd.notna(row[col]) and row[col] != 100:
                    attention.append(idx)
                    break
        ref = ref.drop(attention)

        assert stats["identical_excluded"] == len(identical)
        assert stats["attention_failed"] == len(attention)
        assert stats["final_count"] == len(ref)
        # Same surviving respondents (compare a stable identifier column).
        assert list(ours["ResponseId"]) == list(ref["ResponseId"])

    def test_matching_covers_all_50(self, matches):
        assert len(matches) == 50
        assert set(matches.values()) == {
            q for g in range(1, 6) for q in group_question_ids(g)
        }

    def test_canonical_mapping_agrees_with_qualtrics_headers(self, matches):
        canonical = canonical_question_mapping()
        assert canonical == matches

    def test_survey_detailed_schema(self, clean, survey):
        _, cols = survey
        clean_df, _ = clean
        payload = survey_detailed(clean_df, cols)
        by_q = payload["results"]["by_question"]
        assert len(by_q) == 50
        q = by_q["Q1_1"]
        direct = clean_df["Q1_1"].dropna().to_numpy(dtype=float)
        assert q["mean_response"] == pytest.approx(direct.mean())
        assert q["std_response"] == pytest.approx(direct.std())
        assert 0.0 <= q["proportion_yes"] <= 1.0
        assert q["n_responses"] == direct.size


class TestConsolidated:
    def test_human_llm_correlation_point_estimate(self, clean, survey, instruct_df, matches):
        clean_df, _ = clean
        _, cols = survey
        h_stats = human_responses_by_question(clean_df, cols)
        l_stats = llm_responses_by_question(instruct_df)
        res = human_llm_correlation(h_stats, l_stats, matches, KEY, n_bootstrap=50)

        h = [h_stats[q]["mean"] / 100.0 for p, q in matches.items()]
        m = [l_stats[p]["mean"] for p, q in matches.items()]
        expected_r, expected_p = scipy_stats.pearsonr(h, m)
        assert res["correlation"] == pytest.approx(expected_r)
        assert res["p_value"] == pytest.approx(expected_p)
        assert res["n_questions"] == 50

    def test_llm_mean_uses_nan_skipping(self, instruct_df):
        """The reference's np.mean(Series) dispatches to pandas' skipna mean."""
        stats = llm_responses_by_question(instruct_df)
        for prompt, s in stats.items():
            direct = instruct_df.loc[
                instruct_df["prompt"] == prompt, "relative_prob"
            ]
            assert s["mean"] == pytest.approx(direct.mean(), nan_ok=True)

    def test_human_cross_prompt_base_mean(self, clean):
        """Kernel pair means == pandas .corr() pooled means
        (survey_analysis_consolidated.py:352-412)."""
        clean_df, _ = clean
        res = human_cross_prompt_correlations(clean_df, KEY, n_bootstrap=10)

        all_corrs = []
        for g in range(1, 6):
            gq = group_question_ids(g)
            gdf = clean_df[clean_df[f"Q{g}_1"].notna()]
            rows, ids = [], []
            for idx in gdf.index:
                vals = [gdf.loc[idx, q] / 100.0 for q in gq]
                if sum(pd.notna(v) for v in vals) >= 5:
                    rows.append(vals)
                    ids.append(idx)
            mat = pd.DataFrame(rows, index=ids, columns=gq).T
            corr = mat.corr(method="pearson")
            for i in range(len(corr)):
                for j in range(i + 1, len(corr)):
                    v = corr.iloc[i, j]
                    if not np.isnan(v):
                        all_corrs.append(v)

        assert res["n_pairs"] == len(all_corrs)
        assert res["mean_correlation"] == pytest.approx(np.mean(all_corrs), abs=1e-6)

    def test_llm_cross_prompt_base_mean(self, instruct_df, matches):
        res = llm_cross_prompt_correlations(instruct_df, matches, KEY, n_bootstrap=10)

        prompt_to_group = {
            p: int(q.split("_")[0][1:]) for p, q in matches.items()
        }
        all_corrs = []
        for g in range(1, 6):
            prompts = [p for p, gg in prompt_to_group.items() if gg == g]
            data = instruct_df[instruct_df["prompt"].isin(prompts)]
            pivot = data.pivot_table(
                index="prompt", columns="model", values="relative_prob"
            )
            corr = pivot.corr(method="pearson")
            for i in range(len(corr)):
                for j in range(i + 1, len(corr)):
                    v = corr.iloc[i, j]
                    if not np.isnan(v):
                        all_corrs.append(v)

        assert res["n_pairs"] == len(all_corrs)
        assert res["mean_correlation"] == pytest.approx(np.mean(all_corrs), abs=1e-6)


class TestHumanLLMAgreement:
    @pytest.fixture(scope="class")
    def human_avgs(self, clean, survey):
        clean_df, _ = clean
        _, cols = survey
        detailed = survey_detailed(clean_df, cols)
        return human_averages_from_detailed(detailed, canonical_question_mapping())

    def test_point_metrics_vs_direct(self, human_avgs, instruct_df):
        model = instruct_df["model"].unique()[0]
        mdf = instruct_df[instruct_df["model"] == model]
        res = agreement_metrics(mdf, model, human_avgs)
        assert res is not None

        rel = dict(zip(mdf["prompt"], mdf["relative_prob"]))
        pairs = [
            (human_avgs[q], rel[q])
            for q in human_avgs
            if q in rel and np.isfinite(rel[q])
        ]
        h, m = map(np.asarray, zip(*pairs))
        assert res["n_questions"] == len(pairs)
        assert res["mae"] == pytest.approx(np.abs(h - m).mean())
        assert res["rmse"] == pytest.approx(np.sqrt(((h - m) ** 2).mean()))
        r, p = scipy_stats.pearsonr(h, m)
        assert res["pearson_r"] == pytest.approx(r)

    def test_relative_prob_from_yes_no(self, base_df):
        rel = relative_prob_series(base_df)
        row = base_df.iloc[0]
        total = row["yes_prob"] + row["no_prob"]
        expected = row["yes_prob"] / total if total > 0 else 0.5
        assert rel.iloc[0] == pytest.approx(expected)

    def test_bootstrap_full_sample_equals_point(self, human_avgs, instruct_df):
        """A bootstrap metric evaluated on every question (identity-like
        resample covering all indices) equals the direct metric."""
        model = instruct_df["model"].unique()[0]
        mdf = instruct_df[instruct_df["model"] == model]
        point = agreement_metrics(mdf, model, human_avgs)
        boot = bootstrap_agreement_metrics(
            mdf, human_avgs, KEY, n_bootstrap=400, min_successful=10
        )
        assert boot is not None
        # Bootstrap mean approximates the point value.
        assert boot["mae_mean"] == pytest.approx(point["mae"], abs=0.05)
        assert boot["mae_ci_lower"] <= point["mae"] <= boot["mae_ci_upper"]


class TestPvalues:
    def test_pearson_pvalues_match_scipy(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=20)
        y = 0.5 * x + rng.normal(size=20)
        r, p = scipy_stats.pearsonr(x, y)
        ours = pearson_pvalues(np.asarray([r]), np.asarray([20]))[0]
        assert ours == pytest.approx(p, rel=1e-6)

    def test_llm_pairs_match_scipy(self, instruct_df, base_df):
        rows = llm_correlations_with_pvalues(instruct_df, base_df)
        assert len(rows) > 100
        # Spot-check three pairs against a direct scipy computation.
        combined = pd.concat(
            [
                base_df.assign(_rel=relative_prob_series(base_df)),
                instruct_df.assign(_rel=relative_prob_series(instruct_df)),
            ],
            ignore_index=True,
        )
        for row in rows[:3]:
            a = combined[combined["model"] == row["model1"]]
            b = combined[combined["model"] == row["model2"]]
            da = dict(zip(a["prompt"], a["_rel"]))
            db = dict(zip(b["prompt"], b["_rel"]))
            common = [
                q
                for q in set(da) & set(db)
                if np.isfinite(da[q]) and np.isfinite(db[q])
            ]
            r, p = scipy_stats.pearsonr(
                [da[q] for q in common], [db[q] for q in common]
            )
            assert row["correlation"] == pytest.approx(r, abs=1e-6)
            assert row["p_value"] == pytest.approx(p, rel=1e-5, abs=1e-12)
            assert row["n_questions"] == len(common)

    def test_human_pairs_subset(self, clean):
        clean_df, _ = clean
        rows = human_correlations_with_pvalues(clean_df)
        assert len(rows) > 1000
        sample = rows[0]
        g = sample["group"]
        gq = group_question_ids(g)
        gdf = clean_df[clean_df[f"Q{g}_1"].notna()]
        r1 = gdf.iloc[sample["rater1_idx"]]
        r2 = gdf.iloc[sample["rater2_idx"]]
        v1, v2 = [], []
        for q in gq:
            if pd.notna(r1[q]) and pd.notna(r2[q]):
                v1.append(r1[q])
                v2.append(r2[q])
        r, p = scipy_stats.pearsonr(v1, v2)
        assert sample["correlation"] == pytest.approx(r, abs=1e-6)
        assert sample["n_questions"] == len(v1)


class TestSimulated:
    def test_group_tensor_gate(self, base_df, clean, survey):
        clean_df, _ = clean
        _, cols = survey
        detailed = survey_detailed(clean_df, cols)
        mapping = canonical_question_mapping()
        model = base_df["model"].unique()[0]
        means, stds, vals, usable = model_group_tensors(
            base_df[base_df["model"] == model], mapping, detailed
        )
        assert means.shape == (5, 10)
        # A usable group has >= 8 matched questions and no NaN model values.
        for gi in range(5):
            matched = np.isfinite(vals[gi]).sum()
            if usable[gi]:
                assert matched >= 8


class TestGoldenPins:
    """Headline numbers pinned from the committed reference data — the
    ≤1% deviation gate made executable (BASELINE.md north star). All are
    deterministic point estimates (no bootstrap randomness)."""

    def test_exclusion_pins(self, clean):
        _, stats = clean
        assert stats["duration_excluded"] == 0
        assert stats["identical_excluded"] == 5
        assert stats["attention_failed"] == 56
        assert stats["final_count"] == 446

    def test_human_llm_correlation_pin(self, clean, survey, instruct_df, matches):
        clean_df, _ = clean
        _, cols = survey
        h_stats = human_responses_by_question(clean_df, cols)
        l_stats = llm_responses_by_question(instruct_df)
        res = human_llm_correlation(h_stats, l_stats, matches, KEY, n_bootstrap=10)
        assert res["correlation"] == pytest.approx(0.48526, abs=1e-4)
        assert res["p_value"] == pytest.approx(3.545e-4, rel=1e-2)

    def test_cross_prompt_pins(self, clean, instruct_df, matches):
        clean_df, _ = clean
        human = human_cross_prompt_correlations(clean_df, KEY, n_bootstrap=2)
        assert human["n_pairs"] == 19595
        assert human["mean_correlation"] == pytest.approx(0.32270, abs=1e-4)
        llm = llm_cross_prompt_correlations(instruct_df, matches, KEY, n_bootstrap=2)
        assert llm["n_pairs"] == 140
        assert llm["mean_correlation"] == pytest.approx(0.029167, abs=1e-4)
