"""Golden-parity tests: recompute headline statistics from the committed
reference data CSVs (D1/D2) and pin the values.

The reference ships its experiment outputs as data/*.csv, which makes them
free end-to-end regression fixtures (SURVEY.md §4): if our kernels reproduce
these numbers from the same inputs, the downstream analysis layer is faithful.
Pins were computed with the kernels under test and cross-checked against
pandas/sklearn formulations where one exists.
"""

import jax
import numpy as np
import pandas as pd
import pytest

from lir_tpu.stats import (
    aggregate_kappa,
    bootstrap_correlation_matrix,
    masked_pearson_matrix,
    within_group_kappa,
)

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

KEY = jax.random.PRNGKey(42)


@pytest.fixture(scope="module")
def instruct_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")


@pytest.fixture(scope="module")
def base_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")


def test_instruct_csv_shape(instruct_df):
    # D2: 500 rows, 10 models, 50 prompts (SURVEY.md §2.4)
    assert instruct_df.shape[0] == 500
    assert instruct_df["model"].nunique() == 10
    assert instruct_df["prompt"].nunique() == 50


def test_base_csv_shape(base_df):
    # D1: 882 rows, 18 models, 49 prompts
    assert base_df.shape[0] == 882
    assert base_df["model"].nunique() == 18
    assert base_df["prompt"].nunique() == 49


def test_aggregate_kappa_golden(instruct_df):
    """Pooled kappa across instruct models, with the model filter of
    model_comparison_graph.py:724-726 (drop opt-iml + Mistral)."""
    df = instruct_df[
        ~instruct_df["model"].str.contains("opt-iml|Mistral", case=False)
    ]
    pivot = df.pivot_table(index="prompt", columns="model", values="relative_prob")
    binary = (pivot.dropna() > 0.5).astype(int).values
    res = aggregate_kappa(binary, KEY, n_boot=1000)
    # Point estimate is deterministic (no resampling); pin tightly.
    assert res["n_models"] == 8
    assert abs(res["aggregate_kappa"] - (-0.094987)) < 1e-4
    assert abs(res["observed_agreement"] - 0.472619) < 1e-4
    assert abs(res["chance_agreement"] - 0.518368) < 1e-4
    # CI brackets the estimate; the negative kappa (= systematic disagreement)
    # is the paper's headline inter-model finding.
    assert res["kappa_ci_upper"] < 0


def test_mean_pairwise_correlation_golden(instruct_df):
    """Mean pairwise inter-model Pearson r ~= 0.05 — the 'models are
    unreliable' headline (model_comparison_graph.py correlation suite)."""
    df = instruct_df[
        ~instruct_df["model"].str.contains("opt-iml|Mistral", case=False)
    ]
    pivot = df.pivot_table(index="prompt", columns="model", values="relative_prob")
    res = bootstrap_correlation_matrix(pivot.values, KEY, n_bootstrap=200)
    assert abs(res["mean_correlation"] - 0.050819) < 1e-4
    # cross-check vs pandas' own pairwise-complete corr
    expected = pd.DataFrame(pivot.values).corr().values
    np.testing.assert_allclose(
        res["correlation_matrix"], expected, rtol=1e-4, atol=1e-6
    )


def test_within_group_kappa_on_base_data(base_df):
    """Within-prompt kappa over the base-vs-instruct CSV: same-prompt
    decisions across models vs pooled chance agreement."""
    df = base_df.copy()
    denom = df["yes_prob"] + df["no_prob"]
    df["relative_prob"] = np.where(denom > 0, df["yes_prob"] / denom, np.nan)
    df = df[np.isfinite(df["relative_prob"])]
    decisions = (df["relative_prob"] > 0.5).astype(int).values
    groups = pd.factorize(df["prompt"])[0]
    res = within_group_kappa(decisions, groups)
    # deterministic closed form — pin to recomputed value
    assert np.isfinite(res["kappa"])
    brute_p1 = decisions.mean()
    expected_chance = brute_p1**2 + (1 - brute_p1) ** 2
    assert abs(res["expected_agreement"] - expected_chance) < 1e-12
    # models agree within a prompt barely above chance
    assert -0.5 < res["kappa"] < 0.5
