"""Sharding-rule validation for every real 7B-class preset on the virtual
8-device mesh (stage 4 of SURVEY.md §7): the spec tree must match each
family's param tree exactly, place without error, and degrade gracefully
where head counts don't divide the mesh (falcon-7b: 71 heads, MQA)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lir_tpu.config import MeshConfig
from lir_tpu.models import decoder, registry
from lir_tpu.parallel import sharding

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)

PRESETS = {
    "pythia-6.9b": registry.gptneox(),
    "llama2-7b": registry.llama2_7b(),
    "mistral-7b": registry.mistral_7b(),
    "qwen-7b": registry.qwen_7b(),
    "baichuan2-7b": registry.baichuan2_7b(),
    "falcon-7b": registry.falcon_7b(),
    "bloom-7b1": registry.bloom_7b1(),
    "opt-iml-1.3b": registry.opt(),
    "gpt2-small": registry.gpt2(),
}


def _shrunk(cfg):
    """Keep every divisibility-relevant dimension (heads, kv heads, vocab
    parity mod 8, intermediate mod 8) but shrink layers/hidden so param
    placement is instant."""
    head_dim = max(8, cfg.head_dim // 16)
    return dataclasses.replace(
        cfg,
        n_layers=2,
        hidden_size=cfg.n_heads * head_dim if cfg.hidden_size % cfg.n_heads == 0
        else cfg.hidden_size // 16,
        head_dim=head_dim,
        intermediate_size=max(16, cfg.intermediate_size // 16),
        vocab_size=max(128, cfg.vocab_size // 64 // 8 * 8),
        max_seq_len=128,
    )


@pytest.fixture(scope="module")
def mesh():
    return sharding.build_mesh(MeshConfig(data=1, model=8))


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_spec_tree_matches_and_places(name, mesh):
    cfg = _shrunk(PRESETS[name])
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    specs = sharding.decoder_param_specs(cfg, mesh)

    # Same tree structure.
    assert (jax.tree.structure(jax.tree.map(lambda _: 0, params))
            == jax.tree.structure(
                jax.tree.map(lambda _: 0, specs,
                             is_leaf=lambda x: isinstance(x, P))))

    sharded = sharding.shard_params(params, cfg, mesh)
    # Placement executes and a sharded forward runs.
    toks = jnp.asarray(
        np.random.default_rng(0).integers(3, cfg.vocab_size, (2, 16)),
        jnp.int32)
    logits = decoder.forward(sharded, cfg, toks)
    assert bool(jnp.isfinite(logits).all())


def test_falcon_mqa_degrades_to_replicated_attention(mesh):
    """71 q heads / 1 kv head don't divide 8: attention specs must be
    replicated, MLP still sharded."""
    cfg = _shrunk(PRESETS["falcon-7b"])
    specs = sharding.decoder_param_specs(cfg, mesh)
    assert specs["layers"]["wq"] == P(None, None, None)
    assert specs["layers"]["w_up"] == P(None, None, "model")


def test_divisible_presets_shard_attention(mesh):
    cfg = _shrunk(PRESETS["llama2-7b"])
    specs = sharding.decoder_param_specs(cfg, mesh)
    assert specs["layers"]["wq"] == P(None, None, "model")
    assert specs["layers"]["wo"] == P(None, "model", None)
