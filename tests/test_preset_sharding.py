"""Sharding-rule validation for every real 7B-class preset on the virtual
8-device mesh (stage 4 of SURVEY.md §7): the spec tree must match each
family's param tree exactly, place without error, and degrade gracefully
where head counts don't divide the mesh (falcon-7b: 71 heads, MQA)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lir_tpu.config import MeshConfig
from lir_tpu.models import decoder, registry
from lir_tpu.parallel import sharding

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)

PRESETS = {
    "pythia-6.9b": registry.gptneox(),
    "llama2-7b": registry.llama2_7b(),
    "mistral-7b": registry.mistral_7b(),
    "qwen-7b": registry.qwen_7b(),
    "baichuan2-7b": registry.baichuan2_7b(),
    "falcon-7b": registry.falcon_7b(),
    "bloom-7b1": registry.bloom_7b1(),
    "opt-iml-1.3b": registry.opt(),
    "gpt2-small": registry.gpt2(),
}


def _shrunk(cfg):
    """Keep every divisibility-relevant dimension (heads, kv heads, vocab
    parity mod 8, intermediate mod 8) but shrink layers/hidden so param
    placement is instant."""
    head_dim = max(8, cfg.head_dim // 16)
    return dataclasses.replace(
        cfg,
        n_layers=2,
        hidden_size=cfg.n_heads * head_dim if cfg.hidden_size % cfg.n_heads == 0
        else cfg.hidden_size // 16,
        head_dim=head_dim,
        intermediate_size=max(16, cfg.intermediate_size // 16),
        vocab_size=max(128, cfg.vocab_size // 64 // 8 * 8),
        max_seq_len=128,
    )


@pytest.fixture(scope="module")
def mesh():
    return sharding.build_mesh(MeshConfig(data=1, model=8))


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_spec_tree_matches_and_places(name, mesh):
    cfg = _shrunk(PRESETS[name])
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    specs = sharding.decoder_param_specs(cfg, mesh)

    # Same tree structure.
    assert (jax.tree.structure(jax.tree.map(lambda _: 0, params))
            == jax.tree.structure(
                jax.tree.map(lambda _: 0, specs,
                             is_leaf=lambda x: isinstance(x, P))))

    sharded = sharding.shard_params(params, cfg, mesh)
    # Placement executes and a sharded forward runs.
    toks = jnp.asarray(
        np.random.default_rng(0).integers(3, cfg.vocab_size, (2, 16)),
        jnp.int32)
    logits = decoder.forward(sharded, cfg, toks)
    assert bool(jnp.isfinite(logits).all())


def test_falcon_mqa_degrades_to_replicated_attention(mesh):
    """71 q heads / 1 kv head don't divide 8: attention specs must be
    replicated, MLP still sharded."""
    cfg = _shrunk(PRESETS["falcon-7b"])
    specs = sharding.decoder_param_specs(cfg, mesh)
    assert specs["layers"]["wq"] == P(None, None, None)
    assert specs["layers"]["w_up"] == P(None, None, "model")


def test_divisible_presets_shard_attention(mesh):
    cfg = _shrunk(PRESETS["llama2-7b"])
    specs = sharding.decoder_param_specs(cfg, mesh)
    assert specs["layers"]["wq"] == P(None, None, "model")
    assert specs["layers"]["wo"] == P(None, "model", None)


@pytest.mark.parametrize("name", ["llama2-7b", "falcon-7b"])
def test_int8_tree_shards_and_matches_dense(name, mesh):
    """VERDICT r1 #6: QuantTensor trees place on the mesh (payload on the
    dense weight's spec, scale on the derived output-axis spec) and the
    sharded int8 forward matches the unsharded int8 forward exactly."""
    from lir_tpu.models import quant

    cfg = _shrunk(PRESETS[name])
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_decoder_params(params)
    sharded = sharding.shard_params(qparams, cfg, mesh)

    # Scale sharding follows the payload's output axis.
    wq_spec = sharding.decoder_param_specs(cfg, mesh)["layers"]["wq"]
    assert sharding.quant_scale_spec(wq_spec) == P(*wq_spec[:-2], wq_spec[-1])

    toks = jnp.asarray(
        np.random.default_rng(1).integers(3, cfg.vocab_size, (2, 16)),
        jnp.int32)
    logits_sharded = decoder.forward(sharded, cfg, toks)
    logits_local = decoder.forward(qparams, cfg, toks)
    np.testing.assert_allclose(np.asarray(logits_sharded),
                               np.asarray(logits_local), atol=1e-4, rtol=1e-4)


def test_int8_fused_decode_on_mesh(mesh):
    """The production scorer (greedy_decode_fused) runs on a sharded int8
    tree with batch over 'data'."""
    from lir_tpu.engine import generate, score
    from lir_tpu.models import quant

    cfg = _shrunk(PRESETS["llama2-7b"])
    params = quant.quantize_decoder_params(
        decoder.init_params(cfg, jax.random.PRNGKey(0)))
    dp_mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    params = sharding.shard_params(params, cfg, dp_mesh)

    B = 4
    toks = jnp.asarray(
        np.random.default_rng(2).integers(3, cfg.vocab_size, (B, 16)),
        jnp.int32)
    bs = sharding.batch_sharding(dp_mesh)
    toks = jax.device_put(toks, bs)
    mask = jax.device_put(jnp.ones_like(toks), bs)
    yes = jnp.full((B,), 1, jnp.int32)
    no = jnp.full((B,), 2, jnp.int32)
    fused = generate.greedy_decode_fused(
        params, cfg, toks, mask, yes, no,
        jnp.arange(4, dtype=jnp.int32), jnp.arange(4, dtype=jnp.float32),
        max_new_tokens=4)
    res = score.readout_from_fused(fused, yes, no)
    assert res.yes_prob.shape == (B,)
    assert bool(jnp.all(jnp.isfinite(res.yes_prob)))


def test_full_feature_matrix_on_mesh(mesh):
    """The complete production fast path composed: tensor-parallel sharding
    x dynamic int8 weights (s8 x s8 dots) x int8 KV cache, through the
    fused scorer on the dp x tp mesh, vs the same unsharded bf16-cache
    weight-only model."""
    import dataclasses
    from lir_tpu.engine import generate, score
    from lir_tpu.models import quant

    cfg = _shrunk(PRESETS["llama2-7b"])
    cfg_fast = dataclasses.replace(cfg, kv_cache_int8=True)
    dense_q = quant.quantize_decoder_params(
        decoder.init_params(cfg, jax.random.PRNGKey(0)))
    dyn_q = quant.quantize_decoder_params(
        decoder.init_params(cfg, jax.random.PRNGKey(0)), dynamic=True)
    dp_mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    dyn_sharded = sharding.shard_params(dyn_q, cfg_fast, dp_mesh)
    assert dyn_sharded["layers"]["wq"].dynamic

    B = 4
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (B, 16)), jnp.int32)
    mask = jnp.ones_like(toks)
    yes = jnp.full((B,), 1, jnp.int32)
    no = jnp.full((B,), 2, jnp.int32)
    digits = jnp.arange(10, 110, dtype=jnp.int32)
    vals = jnp.arange(0, 100, dtype=jnp.float32)

    ref = generate.greedy_decode_fused(
        dense_q, cfg, toks, mask, yes, no, digits, vals, max_new_tokens=4)
    bs = sharding.batch_sharding(dp_mesh)
    fast = generate.greedy_decode_fused(
        dyn_sharded, cfg_fast, jax.device_put(toks, bs),
        jax.device_put(mask, bs), yes, no, digits, vals, max_new_tokens=4)
    r_ref = score.readout_from_fused(ref, yes, no)
    r_fast = score.readout_from_fused(fast, yes, no)
    assert np.isfinite(np.asarray(r_fast.yes_prob)).all()
    # Three stacked approximations (activation quant, cache quant, sharded
    # reductions) against weight-only int8: readout agreement within 5e-2.
    np.testing.assert_allclose(np.asarray(r_fast.yes_prob),
                               np.asarray(r_ref.yes_prob), atol=5e-2)


# ---------------------------------------------------------------------------
# Encoder-decoder (T5) sharding — closes the r2 "--mesh silently ignored
# for enc-dec" gap (compare_instruct_models.py:145-166,471-475 parity)
# ---------------------------------------------------------------------------

def _tiny_t5():
    from lir_tpu.models import encdec
    cfg = registry.t5_v1_1("small")
    cfg = dataclasses.replace(cfg, name="t5-shard-test", vocab_size=256,
                              hidden_size=64, n_layers=2, n_heads=4,
                              head_dim=16, intermediate_size=128)
    params = encdec.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_t5_sharded_forward_matches_single_device():
    from lir_tpu.models import encdec
    cfg, params = _tiny_t5()
    mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    sharded = sharding.shard_params(params, cfg, mesh)
    # Attention + MLP really shard (4 divides 4 heads / 128 ff).
    wq = sharded["encoder"]["wq"]
    assert wq.sharding.shard_shape(wq.shape)[-1] == wq.shape[-1] // 4
    co = sharded["decoder"]["co"]
    assert co.sharding.shard_shape(co.shape)[1] == co.shape[1] // 4

    rng = np.random.default_rng(3)
    enc = jnp.asarray(rng.integers(0, 256, (4, 10)), jnp.int32)
    dec = jnp.asarray(rng.integers(0, 256, (4, 3)), jnp.int32)
    ref = encdec.forward(params, cfg, enc, jnp.ones_like(enc), dec)
    bs = sharding.batch_sharding(mesh)
    out = encdec.forward(sharded, cfg, jax.device_put(enc, bs),
                         jax.device_put(jnp.ones_like(enc), bs),
                         jax.device_put(dec, bs))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_t5_int8_sharded_greedy_decode_matches():
    """int8 QuantTensor trees compose with the enc-dec specs; the full T5
    scoring decode path agrees with the unsharded int8 run."""
    from lir_tpu.engine import generate
    from lir_tpu.models import quant
    cfg, params = _tiny_t5()
    qparams = quant.quantize_encdec_params(params)
    mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    sharded = sharding.shard_params(qparams, cfg, mesh)
    rng = np.random.default_rng(4)
    enc = jnp.asarray(rng.integers(0, 256, (4, 8)), jnp.int32)
    mask = jnp.ones_like(enc)
    ref_gen, ref_logits = generate.t5_greedy_decode(qparams, cfg, enc, mask,
                                                    max_new_tokens=4)
    bs = sharding.batch_sharding(mesh)
    gen, logits = generate.t5_greedy_decode(
        sharded, cfg, jax.device_put(enc, bs), jax.device_put(mask, bs),
        max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(gen), np.asarray(ref_gen))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=1e-4, rtol=1e-4)
