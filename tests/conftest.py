"""Test configuration: force JAX onto 8 virtual CPU devices.

This exercises the same Mesh/pjit code paths as a v5e-8 slice without TPU
hardware (SURVEY.md §4). The environment may pre-import jax with a TPU
plugin selected (JAX_PLATFORMS=axon via sitecustomize), so env vars alone
are too late — we must override via jax.config before any backend
initializes, or the first `jax.devices()` call tries to reach real TPU
hardware and stalls the whole test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Zero-egress container: stop transformers/huggingface_hub from attempting
# (and retry-looping on) network fetches.
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402
from pathlib import Path  # noqa: E402

# Shared tiny-checkpoint builders (tools/tiny_checkpoints.py) back both the
# oracle capture tools and the checkpoint-based differentials.
_TOOLS = str(Path(__file__).resolve().parent.parent / "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/data"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy tests (virtual-mesh TP/PP/seq-parallel, "
        "executed-reference differentials, torch differentials at size) — "
        "excluded from the fast inner loop")
    config.addinivalue_line(
        "markers", "fast: auto-applied complement of slow; "
        "`pytest -m fast` is the inner loop (measured 163s on the 1-core build container)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow") is None:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(scope="session")
def reference_data_dir():
    """Golden reference CSVs; skip golden-parity tests when not mounted."""
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference data not available")
    return REFERENCE_DATA


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
