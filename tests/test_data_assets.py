"""Data-asset integrity: the experiment stimuli match the reference study."""

import pandas as pd

from lir_tpu.data import (
    LEGAL_PROMPTS,
    QUALTRICS_TO_QUESTION,
    QUESTION_TO_QUALTRICS,
    WORD_MEANING_QUESTIONS,
    format_base_prompt,
    format_instruct_prompt,
)
from lir_tpu.data.prompts import ATTENTION_CHECK_COLUMNS


def test_counts():
    assert len(LEGAL_PROMPTS) == 5
    assert len(WORD_MEANING_QUESTIONS) == 50
    assert len(QUESTION_TO_QUALTRICS) == 50
    assert len(QUALTRICS_TO_QUESTION) == 50
    assert len(ATTENTION_CHECK_COLUMNS) == 5


def test_qualtrics_mapping_shape():
    # 5 groups x 10 substantive sliders, attention column (x_8) never mapped.
    ids = set(QUESTION_TO_QUALTRICS.values())
    assert len(ids) == 50
    for q_id in ids:
        group, col = q_id[1:].split("_")
        assert 1 <= int(group) <= 5
        assert int(col) != 8
        assert 1 <= int(col) <= 11
    assert QUESTION_TO_QUALTRICS['Is a "screenshot" a "photograph"?'] == "Q1_1"
    assert QUESTION_TO_QUALTRICS['Is "streaming" a video "broadcasting" that video?'] == "Q1_9"
    assert QUESTION_TO_QUALTRICS['Is a "mask" a form of "clothing"?'] == "Q5_11"


def test_target_tokens():
    firsts = [p.target_tokens for p in LEGAL_PROMPTS]
    assert firsts[0] == ("Covered", "Not")
    assert firsts[1] == ("Ultimate", "First")
    assert firsts[2] == ("Existing", "Future")
    assert firsts[3] == ("Monthly", "Payment")
    assert firsts[4] == ("Covered", "Not")


def test_prompt_formatting():
    q = WORD_MEANING_QUESTIONS[0]
    base = format_base_prompt(q)
    instr = format_instruct_prompt(q)
    assert base.endswith("\nAnswer:")
    assert q in base and q in instr
    assert base.count("Question:") == 3  # 2 few-shot + 1 target
    assert "soup" in base and "tweet" in base


def test_questions_match_reference_csv(reference_data_dir):
    """Questions must cover the committed golden CSV's prompt set."""
    df = pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")
    assert set(df["prompt"].unique()) <= set(WORD_MEANING_QUESTIONS)
    assert len(set(df["prompt"].unique())) == 50
