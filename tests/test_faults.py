"""Fault-injection harness + self-healing dispatch tests (lir_tpu/faults).

Pins the robustness tentpole's contracts:
- FaultPlan schedules are deterministic and seeded (same seed -> same
  injections, at exact call indices, bounded by max_failures);
- the circuit breaker walks closed -> open -> half_open -> closed with
  lazy cooldown promotion, and every transition is recorded;
- the degradation ladder isolates poison rows by bisection without
  punishing their neighbors;
- retry_with_exponential_backoff never swallows KeyboardInterrupt /
  SystemExit, even under a broad retry_on tuple;
- SweepManifest tolerates (and truncates) a torn trailing line — the
  exact crash it exists to survive;
- the sweep's dispatch recovery outlives transient device faults with
  bitwise-identical rows, and a preempted sweep resumes with zero lost
  and zero duplicated rows;
- the serve breaker recovers to healthy via the half-open probe, the
  serve ladder isolates poison requests, and the shutdown checkpoint
  hands every pending request to a fresh server.
"""

import json
import time

import jax
import numpy as np
import pytest

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RetryConfig, RuntimeConfig, ServeConfig
from lir_tpu.data.prompts import LegalPrompt
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_perturbation_sweep
from lir_tpu.serve import ScoringServer, ServeRequest
from lir_tpu.utils.manifest import SweepManifest
from lir_tpu.utils.profiling import FaultStats
from lir_tpu.utils.retry import retry_with_exponential_backoff


# ---------------------------------------------------------------------------
# FaultPlan: deterministic seeded schedules
# ---------------------------------------------------------------------------

def test_fault_plan_explicit_schedule_and_bounds():
    plan = faults.FaultPlan(seed=0, schedules={
        "dispatch": faults.SiteSchedule(fail_calls=(1, 3),
                                        max_failures=1)})
    hits = []
    for i in range(5):
        try:
            plan.check("dispatch")
            hits.append("ok")
        except faults.InjectedFault:
            hits.append("fault")
    # Call 1 fails; call 3 would, but max_failures=1 already spent.
    assert hits == ["ok", "fault", "ok", "ok", "ok"]
    assert plan.injected("dispatch") == 1
    assert plan.calls("dispatch") == 5
    assert plan.stats.injected == {"dispatch": 1}
    # An unscheduled site never fails but still counts calls.
    plan.check("tokenize")
    assert plan.calls("tokenize") == 1


def test_fault_plan_rate_is_seed_deterministic():
    def draws(seed):
        plan = faults.FaultPlan(seed=seed, schedules={
            "dispatch": faults.SiteSchedule(rate=0.3)})
        out = []
        for _ in range(50):
            try:
                plan.check("dispatch")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    a, b = draws(7), draws(7)
    assert a == b                       # same seed -> same schedule
    assert 0 < sum(a) < 50              # rate actually fires sometimes


def test_fault_plan_preemption_is_base_exception():
    plan = faults.FaultPlan(schedules={
        "preempt": faults.SiteSchedule.kill_at(0)})
    with pytest.raises(faults.InjectedPreemption):
        plan.check("preempt")
    assert not issubclass(faults.InjectedPreemption, Exception)
    assert plan.stats.preemptions == 1


def test_fault_plan_wrap_indexes_by_site_not_wrapper():
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule(fail_calls=(2,))})
    f = plan.wrap("dispatch", lambda: "a")
    g = plan.wrap("dispatch", lambda: "b")
    assert f() == "a"           # call 0
    assert g() == "b"           # call 1 — shared site counter
    with pytest.raises(faults.InjectedFault):
        f()                     # call 2


def test_replica_kill_and_lag_schedule_kinds():
    """The elastic chaos kinds: replica_kill raises InjectedReplicaKill
    (an ordinary Exception — the router is the recovery layer under
    test and must survive it); replica_lag delays the call and lets it
    COMPLETE (the straggler whose late payload must lose the race)."""
    plan = faults.FaultPlan(schedules={
        "replica": faults.SiteSchedule.replica_kill_at(1, "r1")})
    f = plan.wrap("replica", lambda: "ok")
    assert f() == "ok"
    with pytest.raises(faults.InjectedReplicaKill) as exc:
        f()
    assert exc.value.replica_id == "r1"
    assert isinstance(exc.value, Exception)   # NOT a BaseException kill
    assert plan.stats.injected == {"replica": 1}

    lag = faults.FaultPlan(schedules={
        "replica": faults.SiteSchedule.replica_lag_at(0, 0.02)})
    g = lag.wrap("replica", lambda: "late")
    t0 = time.monotonic()
    assert g() == "late"          # delayed, then completed
    assert time.monotonic() - t0 >= 0.02
    assert g() == "late"          # schedule exhausted -> instant
    assert lag.stats.injected == {"replica": 1}


def test_migration_stall_and_corrupt_schedule_kinds():
    """The disaggregation chaos kinds (serve/migrate.py seam):
    migration_stall sleeps then raises at the migrator's wire hop;
    migration_corrupt flips the export's chunk bytes UNDER its
    checksums and lets the transfer proceed — detection is the
    import-side verify's job. Both are counter-indexed at the
    'migrate' site like every other kind."""

    class _Migrator:
        def transfer(self, export):
            return export

    class _Export:
        def __init__(self):
            import numpy as np

            self.chunks = [(np.zeros((2, 2, 4), np.float32), 2)]
            self.checksums = [0]

    stall = faults.FaultPlan(schedules={
        "migrate": faults.SiteSchedule.migration_stall_at(
            1, seconds=0.02)})
    m = faults.wrap_migrator(_Migrator(), stall)
    e = _Export()
    assert m.transfer(e) is e            # call 0: clean
    t0 = time.monotonic()
    with pytest.raises(faults.InjectedFault, match="migration stall"):
        m.transfer(e)                    # call 1: sleeps then raises
    assert time.monotonic() - t0 >= 0.02
    assert stall.stats.injected == {"migrate": 1}

    corrupt = faults.FaultPlan(seed=9, schedules={
        "migrate": faults.SiteSchedule.migration_corrupt_at(0)})
    m2 = faults.wrap_migrator(_Migrator(), corrupt)
    e2 = _Export()
    before = e2.chunks[0][0].copy()
    assert m2.transfer(e2) is e2         # completes, mutated in place
    assert not (e2.chunks[0][0] == before).all()
    assert e2.checksums == [0]           # checksums left stale
    assert corrupt.stats.injected == {"migrate": 1}
    # the new kinds/site are registered
    assert "migration_stall" in faults.KINDS
    assert "migration_corrupt" in faults.KINDS
    assert "migrate" in faults.SITES


# ---------------------------------------------------------------------------
# CircuitBreaker lifecycle
# ---------------------------------------------------------------------------

def test_breaker_lifecycle_closed_open_half_open_closed():
    t = [0.0]
    stats = FaultStats()
    b = faults.CircuitBreaker(failure_threshold=2, cooldown_s=5.0,
                              clock=lambda: t[0], stats=stats)
    assert b.state == faults.CLOSED and b.allow()
    assert not b.record_failure()           # 1 of 2
    assert b.record_failure()               # opens
    assert b.state == faults.OPEN and not b.allow()
    t[0] += 4.9
    assert b.state == faults.OPEN           # cooldown not elapsed
    t[0] += 0.2
    assert b.state == faults.HALF_OPEN and b.allow()
    # Probe fails -> straight back to OPEN for another cooldown.
    assert b.record_failure()
    assert b.state == faults.OPEN
    t[0] += 5.1
    assert b.state == faults.HALF_OPEN
    b.record_success()                      # probe succeeds -> CLOSED
    assert b.state == faults.CLOSED
    assert b.consecutive_failures == 0
    assert stats.transitions == [
        (faults.CLOSED, faults.OPEN),
        (faults.OPEN, faults.HALF_OPEN),
        (faults.HALF_OPEN, faults.OPEN),
        (faults.OPEN, faults.HALF_OPEN),
        (faults.HALF_OPEN, faults.CLOSED)]
    assert stats.breaker_opens == 2
    assert stats.breaker_probes == 2
    assert stats.breaker_closes == 1


def test_breaker_cooldown_is_monotonic_not_wall_clock():
    """The cooldown must be timed on time.monotonic, never time.time:
    a wall-clock step (NTP correction, operator clock change) must not
    hold a per-replica breaker open past its cooldown or promote it
    early. Pinned by faking BOTH clocks: the breaker runs on an
    injected monotonic stand-in while the wall clock jumps around it —
    only monotonic elapsed time may move the state."""
    import time as _time

    # The default clock IS time.monotonic — the contract itself.
    assert faults.CircuitBreaker().clock is _time.monotonic

    mono = [100.0]
    wall = [1_700_000_000.0]
    b = faults.CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                              clock=lambda: mono[0])
    assert b.record_failure() and b.state == faults.OPEN

    # Wall clock leaps a day FORWARD; monotonic barely moves: a
    # wall-clocked breaker would promote immediately — ours must not.
    wall[0] += 86_400.0
    mono[0] += 0.5
    assert b.state == faults.OPEN

    # Wall clock steps BACKWARD an hour; monotonic crosses the
    # cooldown: a wall-clocked breaker would stay open ~an hour — ours
    # promotes on schedule.
    wall[0] -= 3_600.0
    mono[0] += 5.0
    assert b.state == faults.HALF_OPEN
    b.record_success()
    assert b.state == faults.CLOSED
    del wall  # the wall clock never entered a single comparison


def test_breaker_trip_forces_open_then_ordinary_recovery():
    """trip() (the router's replica-kill path) opens the breaker NOW
    regardless of the failure count, and recovery still runs the
    ordinary open -> half_open -> closed probe."""
    t = [0.0]
    stats = FaultStats()
    b = faults.CircuitBreaker(failure_threshold=3, cooldown_s=2.0,
                              clock=lambda: t[0], stats=stats)
    b.trip()
    assert b.state == faults.OPEN and not b.allow()
    b.trip()                                # idempotent while open
    assert stats.breaker_opens == 1
    t[0] += 2.1
    assert b.state == faults.HALF_OPEN
    b.record_success()
    assert b.state == faults.CLOSED


def test_breaker_success_resets_consecutive_count():
    b = faults.CircuitBreaker(failure_threshold=3, cooldown_s=1.0,
                              clock=lambda: 0.0)
    b.record_failure()
    b.record_failure()
    b.record_success()
    assert b.consecutive_failures == 0
    assert not b.record_failure()       # 1 of 3 again, stays CLOSED
    assert b.state == faults.CLOSED


# ---------------------------------------------------------------------------
# Degradation ladder: bisection isolates poison
# ---------------------------------------------------------------------------

def test_degrade_dispatch_isolates_poison_rows():
    poison = {3, 6}
    calls = []

    def score(rows):
        calls.append(list(rows))
        if any(r in poison for r in rows):
            raise RuntimeError("poison")
        return [{"row": r} for r in rows]

    rows = list(range(8))
    out = faults.degrade_dispatch(score, rows)
    for i, payload in enumerate(out):
        if i in poison:
            assert payload is None
        else:
            assert payload == {"row": i}
    # First call retries the whole batch (the AOT->lazy retry).
    assert calls[0] == rows


def test_degrade_dispatch_full_batch_retry_can_recover():
    """A transient full-batch failure (already retried upstream) that
    clears by the ladder's first re-call recovers every row."""
    state = {"failed": False}

    def score(rows):
        if not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient")
        return [{"row": r} for r in rows]

    out = faults.degrade_dispatch(score, [1, 2, 3])
    assert out == [{"row": 1}, {"row": 2}, {"row": 3}]


def test_degrade_dispatch_propagates_shutdown_signals():
    def score(rows):
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        faults.degrade_dispatch(score, [1, 2])


# ---------------------------------------------------------------------------
# Retry satellite: shutdown signals are never swallowed
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sig", [KeyboardInterrupt, SystemExit])
def test_retry_never_swallows_shutdown_signals(sig):
    calls, waits = [], []

    def fn():
        calls.append(1)
        raise sig()

    with pytest.raises(sig):
        retry_with_exponential_backoff(
            fn, retry_on=(BaseException,),
            config=RetryConfig(max_retries=5, initial_delay=60.0),
            sleep=waits.append, log=lambda m: None)
    assert len(calls) == 1          # no retry
    assert waits == []              # and no 60 s backoff sleep


# ---------------------------------------------------------------------------
# Manifest satellite: torn-tail tolerance
# ---------------------------------------------------------------------------

def test_manifest_torn_tail_is_skipped_and_truncated(tmp_path):
    path = tmp_path / "m.jsonl"
    m = SweepManifest(path, ("model", "reph"))
    m.mark_done_many([{"model": "m", "reph": f"r{i}"} for i in range(3)])
    faults.tear_jsonl_tail(path, '{"model": "m", "re')

    # The exact crash this file exists to survive must not kill resume.
    m2 = SweepManifest(path, ("model", "reph"))
    assert len(m2) == 3
    # The next append truncates the torn fragment first.
    m2.mark_done({"model": "m", "reph": "r3"})
    lines = [l for l in path.read_text().splitlines() if l.strip()]
    assert [json.loads(l)["reph"] for l in lines] == ["r0", "r1", "r2",
                                                      "r3"]
    assert len(SweepManifest(path, ("model", "reph"))) == 4


def test_manifest_torn_tail_with_valid_json_missing_keys(tmp_path):
    """A torn line can still parse as JSON (cut between fields) — the
    key check catches it."""
    path = tmp_path / "m.jsonl"
    m = SweepManifest(path, ("model", "reph"))
    m.mark_done({"model": "m", "reph": "r0"})
    faults.tear_jsonl_tail(path, '{"model": "m"}')
    m2 = SweepManifest(path, ("model", "reph"))
    assert len(m2) == 1
    m2.mark_done({"model": "m", "reph": "r1"})
    assert len(SweepManifest(path, ("model", "reph"))) == 2


def test_manifest_mid_file_corruption_still_raises(tmp_path):
    path = tmp_path / "m.jsonl"
    path.write_text('not json\n{"model": "m", "reph": "r0"}\n')
    with pytest.raises(json.JSONDecodeError):
        SweepManifest(path, ("model", "reph"))


def test_manifest_seed_from_results_with_column_map(tmp_path):
    import pandas as pd

    csv = tmp_path / "results.csv"
    pd.DataFrame({"Model": ["m"], "Original Main Part": ["o"],
                  "Rephrased Main Part": ["r"]}).to_csv(csv, index=False)
    m = SweepManifest.from_existing_results(
        tmp_path / "m.jsonl", csv, ("model", "original_main",
                                    "rephrased_main"),
        column_map={"model": "Model", "original_main":
                    "Original Main Part",
                    "rephrased_main": "Rephrased Main Part"})
    assert m.is_done({"model": "m", "original_main": "o",
                      "rephrased_main": "r"})


# ---------------------------------------------------------------------------
# Sweep: transient-fault recovery + preemption resume
# ---------------------------------------------------------------------------

def _tiny_engine(batch=2, seed=5):
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="faults-t", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=128)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(batch_size=batch, max_seq_len=128))


def _tiny_grid(n_cells, seed=3):
    rng = np.random.default_rng(seed)
    words = "coverage policy flood water damage claim".split()

    def text():
        return " ".join(rng.choice(words) for _ in range(8)) + " ?"

    lp = (LegalPrompt(main=text(), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number from 0 to 100 ."),)
    return lp, ([text() for _ in range(n_cells - 1)],)


def _values(r):
    return (r.token_1_prob, r.token_2_prob, r.confidence_value,
            r.weighted_confidence, r.model_response,
            r.model_confidence_response, r.log_probabilities)


def test_sweep_recovers_transient_fault_bitwise(tmp_path):
    lp, perts = _tiny_grid(6)
    clean = run_perturbation_sweep(_tiny_engine(), "f", lp, perts,
                                   tmp_path / "clean.csv",
                                   checkpoint_every=100)

    engine = _tiny_engine()
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule(fail_calls=(0, 2))})
    faults.wrap_engine(engine, plan)
    rows = run_perturbation_sweep(engine, "f", lp, perts,
                                  tmp_path / "chaos.csv",
                                  checkpoint_every=100)
    assert engine.fault_stats.recovered_dispatches >= 1
    assert plan.stats.injected_total == 2
    by_key = {r.rephrased_main: _values(r) for r in clean}
    assert len(rows) == 6
    for r in rows:
        assert _values(r) == by_key[r.rephrased_main]   # bitwise


def test_sweep_preemption_resume_zero_lost_zero_dup(tmp_path):
    from lir_tpu.data import schemas
    from lir_tpu.engine import grid as grid_mod

    lp, perts = _tiny_grid(6, seed=9)
    clean = run_perturbation_sweep(_tiny_engine(), "f", lp, perts,
                                   tmp_path / "clean.csv",
                                   checkpoint_every=2)

    out = tmp_path / "chaos.csv"
    plan = faults.FaultPlan(schedules={
        "manifest_write": faults.SiteSchedule.kill_at(1)})
    manifest = SweepManifest(out.with_suffix(".manifest.jsonl"),
                             grid_mod.RESUME_KEY_FIELDS)
    manifest.mark_done_many = plan.wrap("manifest_write",
                                        manifest.mark_done_many)
    with pytest.raises(faults.InjectedPreemption):
        run_perturbation_sweep(_tiny_engine(), "f", lp, perts, out,
                               manifest=manifest, checkpoint_every=2)
    # The kill landed AFTER the checkpoint's results-append, BEFORE its
    # manifest mark — the torn window — and left a torn manifest line.
    faults.tear_jsonl_tail(out.with_suffix(".manifest.jsonl"))

    run_perturbation_sweep(_tiny_engine(), "f", lp, perts, out,
                           checkpoint_every=2)
    df = schemas.read_results_frame(out)
    keys = list(df["Rephrased Main Part"])
    assert len(keys) == 6                       # zero lost
    assert len(set(keys)) == 6                  # zero duplicated
    by_key = {r.rephrased_main: r.token_1_prob for r in clean}
    for _, row in df.iterrows():
        assert float(row["Token_1_Prob"]) == pytest.approx(
            by_key[row["Rephrased Main Part"]], abs=0, rel=1e-12)


# ---------------------------------------------------------------------------
# Serve: breaker recovery, ladder isolation, checkpoint resume
# ---------------------------------------------------------------------------

_FAST_RETRY = RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5)


def _serve_cfg(**kw):
    base = dict(queue_depth=32, classes=(("t", 600.0),),
                default_class="t", linger_s=0.0,
                max_consecutive_failures=1, breaker_cooldown_s=0.15,
                retry=_FAST_RETRY)
    base.update(kw)
    return ServeConfig(**base)


def _req(i, rid=None):
    body = f"clause {i} covers hail damage under policy {i * 3}"
    return ServeRequest(binary_prompt=f"{body} Answer Yes or No .",
                        confidence_prompt=f"{body} Number 0 to 100 .",
                        klass="t", request_id=rid or str(i))


def test_server_breaker_opens_then_recovers_via_probe():
    server = ScoringServer(_tiny_engine(batch=2), "f",
                           _serve_cfg(degrade_ladder=False))
    # Outage: exactly one dispatch's retries (2 attempts), then healthy.
    plan = faults.FaultPlan(schedules={
        "dispatch": faults.SiteSchedule(rate=1.0, max_failures=2)})
    faults.wrap_server(server, plan)
    server.start()
    try:
        r = server.submit(_req(0)).result(timeout=60)
        assert r.status == "error"
        deadline = time.monotonic() + 10
        while server.healthy and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not server.healthy               # breaker OPEN
        shed = server.submit(_req(1)).result(timeout=5)
        assert shed.status == "shed" and "unhealthy" in shed.note
        time.sleep(0.2)                         # cooldown -> half-open
        probe = server.submit(_req(2)).result(timeout=60)
        assert probe.status == "ok"             # probe served
        assert server.healthy                   # breaker CLOSED again
        ok = server.submit(_req(3)).result(timeout=60)
        assert ok.status == "ok"
    finally:
        server.stop()
    trans = server.faults.transitions
    assert (faults.CLOSED, faults.OPEN) in trans
    assert (faults.OPEN, faults.HALF_OPEN) in trans
    assert (faults.HALF_OPEN, faults.CLOSED) in trans


def test_server_ladder_isolates_poison_request():
    server = ScoringServer(_tiny_engine(batch=4), "f",
                           _serve_cfg(max_consecutive_failures=3))
    real_score = server.batcher.score

    def poisoned(bucket, rows):
        if any(p.request.request_id == "poison" for p in rows):
            raise RuntimeError("poison row crash")
        return real_score(bucket, rows)

    server.batcher.score = poisoned
    futs = [server.submit(_req(i)) for i in range(3)]
    bad = server.submit(_req(7, "poison"))
    server.start()
    try:
        results = [f.result(timeout=60) for f in futs]
        poison_res = bad.result(timeout=60)
    finally:
        server.stop()
    assert all(r.status == "ok" for r in results)   # neighbors survive
    assert poison_res.status == "error"
    assert "degradation ladder" in poison_res.note
    assert server.faults.degraded_rows == 1
    assert server.faults.recovered_dispatches >= 1
    assert server.healthy                           # no breaker trip


def test_server_shutdown_checkpoint_resume_zero_lost(tmp_path):
    ckpt = tmp_path / "state.json"
    server = ScoringServer(_tiny_engine(), "f", _serve_cfg())
    futs = [server.submit(_req(i)) for i in range(5)]
    n = server.shutdown_checkpoint(ckpt)    # never started: all pending
    assert n == 5
    assert not any(f.done() for f in futs)  # neither served nor lost

    fresh = ScoringServer(_tiny_engine(), "f", _serve_cfg()).start()
    try:
        resumed = fresh.resume_from_checkpoint(ckpt)
        results = [f.result(timeout=60) for f in resumed]
    finally:
        fresh.stop()
    assert sorted(r.request_id for r in results) == [str(i)
                                                     for i in range(5)]
    assert all(r.status == "ok" for r in results)


def test_serve_request_record_roundtrip():
    r = ServeRequest(binary_prompt="b", confidence_prompt="c",
                     targets=("Covered", "Not"), klass="interactive",
                     deadline_s=2.5, request_id="x1")
    rec = r.to_record()
    assert json.loads(json.dumps(rec)) == rec       # JSON-safe
    assert ServeRequest.from_record(rec) == r
