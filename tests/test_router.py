"""Elastic multi-replica router tests (serve/router.py).

Pins the failover tentpole's contracts:

- placement follows the live signals: queue depth, router-side breaker
  state, weight residency, and the SLO term (oldest queued-row wait vs
  remaining deadline);
- an erroring replica's request fails over to a survivor and resolves
  exactly once; the breaker opens after the configured threshold and
  recovers through open -> half_open -> closed;
- a KILLED replica's in-flight requests are re-admitted to survivors,
  and a zombie's late payload is dropped — never double-resolved;
- hedged requests resolve first-payload-wins, the loser is dropped;
- the router's content-addressed dedup answers repeats without
  touching any replica;
- with real engines, the winning payload is bitwise the payload any
  replica would have produced (replica-independence — the paper's
  results cannot depend on which replica scored a row).
"""

import threading

import jax

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RouterConfig, RuntimeConfig, ServeConfig
from lir_tpu.faults import CLOSED, HALF_OPEN, OPEN
from lir_tpu.serve import (ReplicaRouter, ScoringServer, ServeFuture,
                           ServeRequest, ServeResult)
from lir_tpu.serve.queue import STATUS_ERROR, STATUS_OK, STATUS_SHED


def _req(i, rid=None, deadline_s=None, klass="t"):
    body = f"clause {i} covers wind damage under policy {i * 7}"
    return ServeRequest(
        binary_prompt=f"{body} Answer Yes or No .",
        confidence_prompt=f"{body} Give a number from 0 to 100 .",
        klass=klass, deadline_s=deadline_s, request_id=rid or str(i))


def _ok(request, marker=0.5):
    return ServeResult(
        request_id=request.request_id, status=STATUS_OK,
        model_response="Yes", model_confidence_response="80",
        token_1_prob=marker, token_2_prob=1 - marker,
        log_probabilities="{}", confidence_value=80,
        weighted_confidence=80.0)


class FakeReplica:
    """Duck-typed replica server: depth signal + scripted submit
    behavior (a callable returning a ServeResult to resolve with, or
    None to leave the future pending)."""

    def __init__(self, depth=0, behavior=None):
        self.config = ServeConfig(classes=(("t", 600.0),),
                                  default_class="t")
        self.queue_depth = depth
        self.wait = 0.0
        self.behavior = behavior or _ok
        self.submitted = []

    def oldest_wait(self, now):
        return self.wait

    def submit(self, request):
        fut = ServeFuture()
        self.submitted.append((request, fut))
        res = self.behavior(request)
        if res is not None:
            fut.resolve(res)
        return fut


def _router(replicas, clock=None, **cfg_kw):
    cfg = RouterConfig(**cfg_kw)
    kw = {} if clock is None else {"clock": clock}
    return ReplicaRouter(replicas, config=cfg, **kw)


# ---------------------------------------------------------------------------
# ServeFuture callbacks (the router seam)
# ---------------------------------------------------------------------------

def test_future_callbacks_fire_once_first_resolution_wins():
    fut = ServeFuture()
    got = []
    fut.add_done_callback(lambda r: got.append(r.status))
    fut.resolve(ServeResult(request_id="a", status=STATUS_OK))
    fut.resolve(ServeResult(request_id="a", status=STATUS_ERROR))
    assert got == ["ok"]
    # Registered after resolution: fires immediately with the winner.
    fut.add_done_callback(lambda r: got.append(r.status))
    assert got == ["ok", "ok"]
    assert fut.result(0).status == STATUS_OK


# ---------------------------------------------------------------------------
# Placement
# ---------------------------------------------------------------------------

def test_routes_to_least_loaded_replica():
    shallow, deep = FakeReplica(depth=1), FakeReplica(depth=50)
    router = _router([("shallow", shallow), ("deep", deep)])
    for i in range(4):
        assert router.submit(_req(i)).result(1).status == STATUS_OK
    assert len(deep.submitted) == 0
    assert router.stats.per_replica == {"shallow": 4}
    assert router.stats.routed == 4
    assert router.stats.completed == 4


def test_residency_is_a_routing_signal():
    # b is DEEPER but holds the model's weights — within the bonus, it
    # wins; without a model id, depth decides.
    a, b = FakeReplica(depth=1), FakeReplica(depth=5)
    router = _router([("a", a), ("b", b)], residency_bonus=8.0)
    router.handle("b").seed_resident(["m1"])
    assert router.submit(_req(0, "r0"), model_id="m1") \
        .result(1).status == STATUS_OK
    assert len(b.submitted) == 1 and len(a.submitted) == 0
    assert router.stats.routed_resident == 1
    assert router.submit(_req(1, "r1")).result(1).status == STATUS_OK
    assert len(a.submitted) == 1


def test_page_residency_and_pressure_fold_into_pick():
    """The _pick satellite fix: the residency signal is not weights
    alone — cluster prefix-tree match length (pages a replica already
    holds for the prompt) and hbm_pressure fold in, so a decode replica
    already holding the prompt's pages wins placement over an
    equally-loaded cold one, and a squeezed page-holder loses it
    again."""
    from lir_tpu.config import MigrationConfig

    a, b = FakeReplica(depth=3), FakeReplica(depth=3)
    router = ReplicaRouter(
        [("a", a), ("b", b)], config=RouterConfig(),
        migrate=MigrationConfig(page_bonus=1.0))
    # b holds 4 of the prompt's pages (cluster-index match): b wins
    # despite equal depth.
    picked = router._pick("", set(), page_match={"b": 4})
    assert picked.replica_id == "b"
    # pressure pushes the page-holder back out: 4 pages of bonus lose
    # to a full-ledger squeeze at pressure_weight 6.
    b.hbm_pressure = 1.0
    picked = router._pick("", set(), page_match={"b": 4})
    assert picked.replica_id == "a"


def test_page_residency_routes_real_traffic_to_the_holder():
    """End-to-end placement: after one request warms a replica's radix
    tree, a second request sharing the trunk routes to THAT replica
    (listener events -> cluster index -> _pick bonus), not round-robin."""
    import numpy as np

    servers = [_tiny_server(seed=2) for _ in range(2)]
    for s in servers:
        s.start()
    from lir_tpu.config import MigrationConfig

    router = ReplicaRouter(
        [("a", servers[0]), ("b", servers[1])],
        config=RouterConfig(cache_entries=0),
        migrate=MigrationConfig(page_bonus=2.0))
    try:
        rng = np.random.default_rng(3)
        words = "coverage policy flood water damage claim".split()
        trunk = " ".join(rng.choice(words) for _ in range(50))

        def req(i):
            body = f"{trunk} case {i}"
            return ServeRequest(
                binary_prompt=f"{body} Answer Yes or No .",
                confidence_prompt=f"{body} Give a number from 0 "
                                  f"to 100 .",
                klass="t", request_id=str(i))

        assert router.submit(req(0)).result(120).status == STATUS_OK
        holder = next(iter(router.stats.per_replica))
        for i in range(1, 4):
            assert router.submit(req(i)).result(120).status == STATUS_OK
        assert router.stats.per_replica == {holder: 4}, \
            router.stats.per_replica
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_slo_term_avoids_stale_backlogs_for_tight_deadlines():
    # Equal depths, but a's oldest queued row has waited 30s: a
    # deadline-tight request must land on b.
    a, b = FakeReplica(depth=3), FakeReplica(depth=3)
    a.wait = 30.0
    router = _router([("a", a), ("b", b)], slo_wait_weight=4.0)
    assert router.submit(_req(0, deadline_s=1.0)) \
        .result(1).status == STATUS_OK
    assert len(b.submitted) == 1 and len(a.submitted) == 0


def test_no_replica_available_sheds():
    a = FakeReplica()
    router = _router([("a", a)])
    router.kill_replica("a")
    res = router.submit(_req(0)).result(1)
    assert res.status == STATUS_SHED
    assert router.stats.no_replica_sheds == 1


# ---------------------------------------------------------------------------
# Failover + breaker
# ---------------------------------------------------------------------------

def _err(request):
    return ServeResult(request_id=request.request_id,
                       status=STATUS_ERROR, note="device error")


def test_error_fails_over_and_resolves_exactly_once():
    bad = FakeReplica(depth=0, behavior=_err)
    good = FakeReplica(depth=10)
    router = _router([("bad", bad), ("good", good)])
    res = router.submit(_req(0)).result(1)
    assert res.status == STATUS_OK
    assert router.stats.failovers == 1
    assert router.stats.replica_errors == 1
    assert router.stats.completed == 1
    assert len(bad.submitted) == 1 and len(good.submitted) == 1


def test_breaker_opens_avoids_then_recovers():
    now = {"t": 0.0}
    clock = lambda: now["t"]  # noqa: E731
    flaky = FakeReplica(depth=0, behavior=_err)
    good = FakeReplica(depth=10)
    router = _router([("flaky", flaky), ("good", good)], clock=clock,
                     replica_failure_threshold=1,
                     replica_cooldown_s=5.0)
    assert router.submit(_req(0)).result(1).status == STATUS_OK
    assert router.breaker_of("flaky").state == OPEN
    # While open, traffic avoids the flaky replica entirely.
    assert router.submit(_req(1)).result(1).status == STATUS_OK
    assert len(flaky.submitted) == 1
    # Cooldown elapses -> half-open; the replica recovered -> the next
    # routed probe closes the breaker.
    now["t"] = 6.0
    flaky.behavior = _ok
    flaky.queue_depth = 0
    assert router.breaker_of("flaky").state == HALF_OPEN
    assert router.submit(_req(2)).result(1).status == STATUS_OK
    assert len(flaky.submitted) == 2
    assert router.breaker_of("flaky").state == CLOSED


def test_all_replicas_error_resolves_error():
    a = FakeReplica(behavior=_err)
    b = FakeReplica(behavior=_err)
    router = _router([("a", a), ("b", b)])
    res = router.submit(_req(0)).result(1)
    assert res.status == STATUS_ERROR
    assert router.stats.errors == 1
    # Both were tried exactly once: failover never loops.
    assert len(a.submitted) == 1 and len(b.submitted) == 1


# ---------------------------------------------------------------------------
# Kill / zombie / hedge
# ---------------------------------------------------------------------------

def test_kill_readmits_inflight_and_drops_zombie_payload():
    hang = FakeReplica(depth=0, behavior=lambda r: None)  # never answers
    good = FakeReplica(depth=10)
    router = _router([("hang", hang), ("good", good)])
    fut = router.submit(_req(0, "x"))
    assert not fut.done()
    assert router.kill_replica("hang") == 1
    res = fut.result(1)
    assert res.status == STATUS_OK
    assert router.stats.re_admitted == 1
    assert router.stats.kills == 1
    assert router.breaker_of("hang").state == OPEN
    # The zombie replica answers LATE with a divergent-looking payload:
    # dropped, counted, and the client's result is unchanged.
    _, zombie_fut = hang.submitted[0]
    zombie_fut.resolve(_ok(_req(0, "x"), marker=0.999))
    assert router.stats.zombie_payloads == 1
    assert fut.result(0).token_1_prob == res.token_1_prob


def test_revive_places_probe_after_cooldown():
    now = {"t": 0.0}
    clock = lambda: now["t"]  # noqa: E731
    a = FakeReplica(depth=0, behavior=lambda r: None)
    b = FakeReplica(depth=10)
    router = _router([("a", a), ("b", b)], clock=clock,
                     replica_cooldown_s=2.0)
    router.submit(_req(0))
    router.kill_replica("a")
    router.revive_replica("a")
    a.behavior = _ok
    # Before the cooldown the breaker is still open -> b serves.
    assert router.submit(_req(1)).result(1).status == STATUS_OK
    assert len(b.submitted) >= 1
    # After the cooldown the half-open probe lands on a (depth 0) and
    # closes its breaker.
    now["t"] = 3.0
    assert router.submit(_req(2)).result(1).status == STATUS_OK
    assert router.breaker_of("a").state == CLOSED
    assert router.stats.revives == 1


def test_hedge_first_payload_wins_and_loser_is_dropped():
    slow = FakeReplica(depth=0, behavior=lambda r: None)
    fast = FakeReplica(depth=10)
    router = _router([("slow", slow), ("fast", fast)], hedge_s=100.0)
    fut = router.submit(_req(0, "h", deadline_s=1.0))
    assert not fut.done()
    router._tick()      # the whisker check (no thread in tests)
    res = fut.result(1)
    assert res.status == STATUS_OK
    assert router.stats.hedged == 1
    assert router.stats.hedge_wins == 1
    # The straggler completes late: hedge loss, not a second result.
    _, late = slow.submitted[0]
    late.resolve(_ok(_req(0, "h"), marker=0.123))
    assert router.stats.hedge_losses == 1
    assert fut.result(0).token_1_prob == res.token_1_prob
    # A request is hedged at most once.
    router._tick()
    assert router.stats.hedged == 1


def test_dedup_answers_repeats_without_touching_replicas():
    a = FakeReplica()
    router = _router([("a", a)])
    r1 = router.submit(_req(0, "d0")).result(1)
    assert r1.status == STATUS_OK and not r1.cached
    r2 = router.submit(_req(0, "d0-again")).result(1)
    assert r2.status == STATUS_OK and r2.cached
    assert r2.token_1_prob == r1.token_1_prob
    assert router.stats.dedup_hits == 1
    assert len(a.submitted) == 1


def test_concurrent_submits_resolve_exactly_once_each():
    replicas = [(f"r{i}", FakeReplica(depth=i)) for i in range(3)]
    router = _router(replicas)
    futs = {}
    lock = threading.Lock()

    def client(tid):
        for j in range(20):
            rid = f"c{tid}-{j}"
            f = router.submit(_req(1000 + tid * 100 + j, rid))
            with lock:
                futs[rid] = f

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(futs) == 80
    for rid, f in futs.items():
        assert f.result(1).request_id == rid
    assert router.stats.completed == 80


# ---------------------------------------------------------------------------
# Real engines: replica-independence + end-to-end failover
# ---------------------------------------------------------------------------

_SERVE_CFG = ServeConfig(queue_depth=64, classes=(("t", 600.0),),
                         default_class="t", linger_s=0.0)


def _tiny_server(seed=2, batch=4):
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="router-t", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    rt = RuntimeConfig(batch_size=batch, max_seq_len=256)
    engine = ScoringEngine(params, cfg, FakeTokenizer(), rt)
    return ScoringServer(engine, "router-t", _SERVE_CFG)


_PAYLOAD_FIELDS = ("model_response", "model_confidence_response",
                   "token_1_prob", "token_2_prob", "log_probabilities",
                   "confidence_value", "weighted_confidence")


def test_router_end_to_end_replica_independent_bitwise():
    """Config-identical replicas produce BITWISE-identical payloads, so
    the router's answer cannot depend on which replica scored a row —
    and a mid-run kill re-admits with zero dropped or double-resolved
    requests."""
    servers = [_tiny_server(seed=2) for _ in range(3)]
    for s in servers:
        s.start()
    router = ReplicaRouter(
        [(f"r{i}", s) for i, s in enumerate(servers)],
        config=RouterConfig(replica_cooldown_s=0.2,
                            cache_entries=0))  # dedup off: every
    # request must genuinely dispatch so placement spreads.
    try:
        futs = [router.submit(_req(i, f"a{i}")) for i in range(8)]
        res = [f.result(60) for f in futs]
        assert all(r.status == STATUS_OK for r in res)
        # Same probe through each replica directly: bitwise equal.
        probe = _req(99, "probe")
        direct = []
        for s in servers:
            r = s.submit(probe).result(60)
            assert r.status == STATUS_OK
            direct.append(tuple(getattr(r, f) for f in _PAYLOAD_FIELDS))
        assert direct[0] == direct[1] == direct[2]
        # Kill one replica with traffic in flight: everything still
        # resolves ok, exactly once.
        futs2 = [router.submit(_req(200 + i, f"b{i}"))
                 for i in range(8)]
        router.kill_replica("r1")
        res2 = [f.result(60) for f in futs2]
        assert all(r.status == STATUS_OK for r in res2)
        assert len({r.request_id for r in res2}) == 8
        assert router.stats.kills == 1
        assert sorted(router.alive_replicas()) == ["r0", "r2"]
    finally:
        router.stop()
        for s in servers:
            s.stop()
