"""Cross-request radix prefix cache over the paged KV allocator.

Pins the PR's load-bearing claims:

- allocator invariants: page refcounts never go negative, the free list
  only holds unreferenced pages, eviction can never free a page an
  in-flight dispatch has pinned;
- radix semantics: page-granular insert/lookup/match, LRU eviction with
  parent cascade, per-bucket namespace isolation (KV is only
  bitwise-reproducible within one bucket shape);
- gather/scatter: a page written from a cache comes back bit-identical
  through the slot gather;
- the headline guarantee: paged decode results — shared, grouped, and
  the serve path — are BITWISE-identical to the contiguous-cache
  (unpaged) path, cold and warm, including cross-length trunk reuse
  (the canonical right-padded slot == position layout is what makes a
  page produced under one row length valid for another).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig, ServeConfig
from lir_tpu.engine import prefix_tree, scheduler as sched
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, paged
from lir_tpu.models.registry import tiny


FUSED_FIELDS = ("generated", "p_yes", "p_no", "top2_ids", "topk_logprobs",
                "topk_ids", "weighted_confidence")


def assert_fused_bitwise(a, b):
    for f in FUSED_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f"fused field {f}")


# ---------------------------------------------------------------------------
# Allocator (models/paged.KVPagePool)
# ---------------------------------------------------------------------------

def test_pool_alloc_refcount_roundtrip():
    pool = paged.KVPagePool(8, page_size=4)
    assert pool.free_pages == 7          # page 0 reserved
    pages = [pool.alloc() for _ in range(7)]
    assert 0 not in pages and pool.alloc() is None
    pool.incref(pages)
    pool.decref(pages[:3])
    assert pool.free_pages == 3 and pool.pages_in_use == 4
    # freed pages are reallocatable; referenced ones are not in the list
    again = [pool.alloc() for _ in range(3)]
    assert sorted(again) == sorted(pages[:3])


def test_pool_decref_below_zero_is_a_crash():
    pool = paged.KVPagePool(4, page_size=4)
    p = pool.alloc()
    pool.incref([p])
    pool.decref([p])
    with pytest.raises(AssertionError):
        pool.decref([p])


def test_window_edges_and_pick():
    assert paged.window_edges(256, 16) == (16, 32, 64, 128)
    assert paged.pick_window(10, 256, 16) == 16
    assert paged.pick_window(100, 256, 16) == 128
    # a needed window >= bucket means nothing useful is cached
    assert paged.pick_window(200, 256, 16) is None
    assert paged.pick_window(1, 16, 16) is None


# ---------------------------------------------------------------------------
# Radix tree (engine/prefix_tree.RadixPrefixCache)
# ---------------------------------------------------------------------------

def _tree(n_pages=16, ps=4):
    return prefix_tree.RadixPrefixCache(paged.KVPagePool(n_pages, ps))


def test_radix_insert_lookup_match_roundtrip():
    t = _tree()
    ids = list(range(11))                 # 2 full pages + a 3-token tail
    start, pages = t.plan_insert(64, ids)
    assert start == 0 and len(pages) == 2
    assert t.match_len(64, ids) == 8      # the tail never caches
    m = t.lookup(64, ids)
    assert m.tokens == 8 and m.pages == tuple(pages)
    t.release(m)
    # extending the sequence caches only the NEW full page
    start2, pages2 = t.plan_insert(64, list(range(14)))
    assert start2 == 8 and len(pages2) == 1
    # an unrelated sequence shares nothing
    assert t.match_len(64, [99, 98, 97, 96]) == 0


def test_radix_partial_match_stops_at_divergence():
    t = _tree()
    a = list(range(12))
    b = list(range(8)) + [77, 78, 79, 80]
    t.plan_insert(64, a)
    assert t.match_len(64, b) == 8        # shares the first two pages
    start, fresh = t.plan_insert(64, b)
    assert start == 8 and len(fresh) == 1


def test_radix_per_bucket_namespaces_are_isolated():
    t = _tree()
    ids = list(range(8))
    t.plan_insert(64, ids)
    assert t.match_len(64, ids) == 8
    assert t.match_len(128, ids) == 0     # other bucket: other namespace
    t.plan_insert(128, ids)
    assert t.pool.pages_in_use == 4       # cached twice, once per bucket


def test_radix_lru_eviction_and_parent_cascade():
    t = _tree(n_pages=16, ps=4)
    old = list(range(8))
    t.plan_insert(64, old)
    new = [50 + i for i in range(8)]
    t.plan_insert(64, new)
    t.lookup(64, new).pages  # touch `new` so `old` is stalest
    freed = t.evict(1)
    assert freed >= 1
    assert t.match_len(64, old) < 8       # oldest leaf went first
    assert t.match_len(64, new) == 8
    # evicting everything evictable cascades leaf -> parent
    t.evict(100)
    assert t.match_len(64, old) == 0


def test_eviction_never_frees_inflight_pinned_pages():
    t = _tree(n_pages=6, ps=4)            # 5 usable pages
    ids = list(range(8))
    t.plan_insert(64, ids)
    m = t.lookup(64, ids)                 # dispatch pin
    assert t.evict(100) == 0              # everything pinned: nothing freed
    assert t.match_len(64, ids) == 8
    # filling the pool forces plan_insert to TRY evicting; pinned pages
    # survive and the insert degrades to a shorter cached prefix
    t.plan_insert(64, [90 + i for i in range(12)])
    assert t.match_len(64, ids) == 8
    t.release(m)
    assert t.evict(100) >= 1              # unpinned now


def test_release_then_evict_returns_page_to_free_list():
    t = _tree(n_pages=4, ps=4)            # 3 usable pages
    ids = list(range(4))
    t.plan_insert(64, ids)
    m = t.lookup(64, ids)
    # while the dispatch pins the page, the node is unevictable BY
    # CONSTRUCTION and the free list can never see the page
    assert t.evict(100) == 0
    assert t.match_len(64, ids) == 4
    free_before = t.pool.free_pages
    t.release(m)                          # drop the dispatch pin
    assert t.pool.free_pages == free_before   # tree still holds its ref
    assert t.evict(100) == 1              # now evictable: page goes free
    assert t.pool.free_pages == free_before + 1
    assert (t.pool.refcount >= 0).all()


# ---------------------------------------------------------------------------
# Gather / scatter
# ---------------------------------------------------------------------------

def test_scatter_then_gather_roundtrip_bitwise():
    cfg = tiny("llama")
    rng = np.random.default_rng(0)
    cache = decoder.init_cache(cfg, batch=2, max_len=32, dtype=jnp.float32)
    cache = jax.tree.map(
        lambda a: jnp.asarray(rng.normal(size=a.shape), a.dtype), cache)
    pool = paged.KVPagePool(8, page_size=8)
    pool.ensure(cache)
    p1, p2 = pool.alloc(), pool.alloc()
    pool.incref([p1, p2])
    # page p1 <- row 0 slots [0, 8); page p2 <- row 1 slots [8, 16)
    pool.scatter(cache, [(p1, 0, 0), (p2, 1, 8)])
    slot_src = np.zeros((1, 16), np.int32)
    slot_src[0, :8] = p1 * 8 + np.arange(8)
    slot_src[0, 8:] = p2 * 8 + np.arange(8)
    out = paged.gather_slots(pool.leaves, jnp.asarray(slot_src))
    for o, c in zip(jax.tree.leaves(out), jax.tree.leaves(cache)):
        np.testing.assert_array_equal(np.asarray(o)[:, :, :8, 0],
                                      np.asarray(c)[:, :, :8, 0])
        np.testing.assert_array_equal(np.asarray(o)[:, :, 8:16, 0],
                                      np.asarray(c)[:, :, 8:16, 1])


# ---------------------------------------------------------------------------
# Price model
# ---------------------------------------------------------------------------

def test_bucket_cost_cached_tokens_discount_and_floor():
    base = sched.bucket_cost(4, 128, 4, 10)
    assert base == 4 * (128 + 10)
    assert sched.bucket_cost(4, 128, 4, 10, cached_tokens=100) == base - 100
    # the decode scan is the floor: cached prefill can never go negative
    assert sched.bucket_cost(4, 128, 4, 10, cached_tokens=10_000) == 4 * 10


# ---------------------------------------------------------------------------
# Engine: paged == unpaged, bitwise
# ---------------------------------------------------------------------------

CFG = tiny("llama")
PARAMS = decoder.init_params(CFG, jax.random.PRNGKey(1))
TOKZ = FakeTokenizer(vocab=CFG.vocab_size)


def _engine(prefix: bool, pages: int = 64, **kw):
    rt = RuntimeConfig(batch_size=4, max_seq_len=128, aot_precompile=False,
                       prefix_cache=prefix, prefix_cache_pages=pages, **kw)
    return ScoringEngine(PARAMS, CFG, TOKZ, rt)


def _legal_prompts(n, trunk_words=70, rng_seed=0):
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle").split()
    rng = np.random.default_rng(rng_seed)
    base = " ".join(rng.choice(words) for _ in range(trunk_words))
    bps = [f"{base} case {i} Answer Yes or No ." for i in range(n)]
    cps = [f"{base} case {i} Give a number 0 to 100 ." for i in range(n)]
    return bps, cps


def _shared(engine, bps, cps, use):
    engine.fresh_handoff()
    yes = np.full((len(bps),), TOKZ.YES, np.int32)
    no = np.full((len(bps),), TOKZ.NO, np.int32)
    return engine.decode_fused_shared(
        bps, cps, yes, no, new_tokens=4, conf_tokens=6, early_stop=False,
        bucket=128, sfx_buckets_ab=(16, 16), reuse_cache=True,
        use_prefix_cache=use, n_real=len(bps))


def test_shared_paged_bitwise_cold_and_warm():
    bps, cps = _legal_prompts(4)
    ref = _engine(False)
    eng = _engine(True)
    r_ref = _shared(ref, bps, cps, False)
    r_cold = _shared(eng, bps, cps, True)     # cold: unpaged + insert
    assert eng.prefix_stats.inserted_pages > 0
    assert eng.prefix_stats.hit_tokens == 0
    r_warm = _shared(eng, bps, cps, True)     # warm: paged resume
    assert eng.prefix_stats.hit_tokens > 0
    for got in (r_cold, r_warm):
        for k in (0, 1):
            assert_fused_bitwise(got[k], r_ref[k])
    assert (eng.prefix_cache.pool.refcount >= 0).all()
    # all dispatch pins released: only the tree's own references remain
    in_use = eng.prefix_cache.pool.pages_in_use
    assert (eng.prefix_cache.pool.refcount[1:].sum() == in_use)


def test_shared_paged_cross_length_trunk_reuse_bitwise():
    """Rows of DIFFERENT prefix lengths sharing one trunk reuse pages
    within a bucket namespace (the canonical slot == position layout's
    raison d'être): warming the 72-token rows caches the trunk, then
    both LONGER rows extending the same trunk and SHORTER rows that are
    a pure truncation of it resume the cached pages, paying prefill
    only for their unshared tails — the remainder window anchors at the
    dispatch's longest real row, so short rows never force a
    bucket-wide recompute."""
    bps, cps = _legal_prompts(4, trunk_words=70)
    tail = ("under the flood exclusion endorsement riders and the "
            "binding arbitration clause")
    long_b = [b.replace(" Answer", f" {tail} Answer") for b in bps]
    long_c = [c.replace(" Give", f" {tail} Give") for c in cps]
    ref = _engine(False)
    eng = _engine(True)
    _shared(eng, bps, cps, True)              # warm the trunk pages
    stats_before = eng.prefix_stats.hit_tokens
    r_ref = _shared(ref, long_b, long_c, False)
    r_warm = _shared(eng, long_b, long_c, True)
    assert eng.prefix_stats.hit_tokens > stats_before
    for k in (0, 1):
        assert_fused_bitwise(r_warm[k], r_ref[k])
    # 40-word rows whose WHOLE prefix is the warm trunk's first half:
    # the max-row-anchored window reaches their tails, so they resume
    # the trunk pages too (with the old bucket-end anchor these could
    # only fall back to the unpaged prefill).
    short_b = [" ".join(bps[0].split()[:40]) + " Answer Yes or No ."]
    short_c = [" ".join(bps[0].split()[:40]) + " Give a number 0 to 100 ."]
    stats_mid = eng.prefix_stats.hit_tokens
    r_ref_s = _shared(ref, short_b * 4, short_c * 4, False)
    r_s = _shared(eng, short_b * 4, short_c * 4, True)
    assert eng.prefix_stats.hit_tokens > stats_mid
    for k in (0, 1):
        assert_fused_bitwise(r_s[k], r_ref_s[k])


def test_shared_paged_bitwise_with_early_stop():
    bps, cps = _legal_prompts(4)
    ref = _engine(False)
    eng = _engine(True)

    def call(engine, use):
        engine.fresh_handoff()
        yes = np.full((4,), TOKZ.YES, np.int32)
        no = np.full((4,), TOKZ.NO, np.int32)
        return engine.decode_fused_shared(
            bps, cps, yes, no, new_tokens=4, conf_tokens=6,
            early_stop=True, bucket=128, sfx_buckets_ab=(16, 16),
            reuse_cache=True, use_prefix_cache=use, n_real=4)

    r_ref = call(ref, False)
    call(eng, True)
    r_warm = call(eng, True)
    for k in (0, 1):
        assert_fused_bitwise(r_warm[k], r_ref[k])


def _groups(n_groups=2, per=2, plen_words=40, seed=5):
    words = ("levee breach flood policy water claim exclusion peril "
             "statute meaning binding interpret").split()
    rng = np.random.default_rng(seed)
    groups = []
    for g in range(n_groups):
        base = [int(TOKZ(w).input_ids[0]) for w in
                rng.choice(words, plen_words)]
        items = []
        for i in range(per):
            sfx = rng.integers(3, CFG.vocab_size, 4).tolist()
            items.append(sched.SweepItem(
                cell=None, bin_ids=tuple(base + sfx + [7]),
                conf_ids=tuple(base + sfx + [9, 11]),
                lcp=plen_words + 4))
        groups.append(sched.PrefixGroup(items=tuple(items),
                                        plen=plen_words))
    return groups


def test_grouped_paged_bitwise_cold_and_warm():
    groups = _groups()
    n = sum(len(g.items) for g in groups)
    yes = np.full((n,), TOKZ.YES, np.int32)
    no = np.full((n,), TOKZ.NO, np.int32)
    ref = _engine(False)
    eng = _engine(True)

    def call(engine, use):
        engine.fresh_handoff()
        out, m = engine.decode_fused_grouped(
            groups, yes, no, new_tokens=4, conf_tokens=6,
            early_stop=False, bucket=64, sfx_bucket=8, reuse_cache=True,
            use_prefix_cache=use)
        return out

    r_ref = call(ref, False)
    r_cold = call(eng, True)
    r_warm = call(eng, True)
    assert eng.prefix_stats.hit_tokens > 0
    assert_fused_bitwise(r_cold, r_ref)
    assert_fused_bitwise(r_warm, r_ref)


def test_aot_paged_executable_matches_lazy_bitwise():
    """The block-table (paged) executables the compile plan precompiles
    bind (pool, slot_src, win_start, ...) in exactly the order the
    runner passes them: a warm dispatch must HIT the registry (no lazy
    fallback) and return results bitwise-identical to the lazy-jit
    paged path."""
    from lir_tpu.engine import compile_plan

    bps, cps = _legal_prompts(4)
    eng_lazy = _engine(True, spec_decode=False)
    _shared(eng_lazy, bps, cps, True)
    r_lazy = _shared(eng_lazy, bps, cps, True)

    # Pin the SEQUENTIAL paged executables specifically — speculative
    # dispatches look up their own spec_k-keyed registry entries
    # (tests/test_spec_decode.py covers those).
    eng = _engine(True, spec_decode=False)
    _shared(eng, bps, cps, True)              # warm the radix cache
    specs = [compile_plan.shared_paged_spec(128, 4, w, 16, 16, 4, 6,
                                            stops_armed=False,
                                            scratch=False)
             for w in paged.window_edges(128, 16)]
    reg = compile_plan.precompile_async(eng, specs, max_workers=2)
    reg.wait()
    eng.exec_registry = reg
    aot_before = eng.compile_stats.aot_hits
    r_aot = _shared(eng, bps, cps, True)
    assert eng.compile_stats.aot_hits == aot_before + 1
    for k in (0, 1):
        assert_fused_bitwise(r_aot[k], r_lazy[k])


def test_tight_pool_evicts_but_never_corrupts():
    """A pool far smaller than the working set churns through eviction;
    results stay bitwise-identical and refcounts sane."""
    ref = _engine(False)
    eng = _engine(True, pages=6)              # 5 usable pages, ~1 row's worth
    for seed in range(3):
        bps, cps = _legal_prompts(4, rng_seed=seed)
        r_ref = _shared(ref, bps, cps, False)
        r_paged = _shared(eng, bps, cps, True)
        for k in (0, 1):
            assert_fused_bitwise(r_paged[k], r_ref[k])
        assert (eng.prefix_cache.pool.refcount >= 0).all()
    assert eng.prefix_stats.evicted_pages > 0 or \
        eng.prefix_stats.inserted_pages <= 5


# ---------------------------------------------------------------------------
# Serve path
# ---------------------------------------------------------------------------

def _serve_once(prefix: bool, reqs):
    from lir_tpu.serve import ScoringServer, ServeRequest

    engine = _engine(prefix)
    cfgs = ServeConfig(queue_depth=64, prefix_cache=prefix,
                       classes=(("bench", 120.0),), default_class="bench")
    payloads = []
    for _ in range(2):                        # pass 2 is the warm pass
        server = ScoringServer(engine, "prefix-test", cfgs).start()
        futs = [server.submit(ServeRequest(
            binary_prompt=b, confidence_prompt=c, klass="bench",
            request_id=str(i))) for i, (b, c) in enumerate(reqs)]
        payloads = [f.result(timeout=120) for f in futs]
        server.stop()
    return engine, payloads


@pytest.mark.slow
def test_serve_prefix_cache_bitwise_and_counts():
    bps, cps = _legal_prompts(6)
    reqs = list(zip(bps, cps))
    eng_off, base = _serve_once(False, reqs)
    eng_on, warm = _serve_once(True, reqs)
    assert eng_on.prefix_stats.hit_tokens > 0
    assert eng_off.prefix_cache is None
    fields = ("status", "token_1_prob", "token_2_prob",
              "log_probabilities", "confidence_value",
              "weighted_confidence", "model_response",
              "model_confidence_response")
    for a, b in zip(base, warm):
        for f in fields:
            assert getattr(a, f, None) == getattr(b, f, None), f


def test_fake_tokenizer_vocab_clamp():
    t = FakeTokenizer(vocab=256)
    ids = t("flood levee coverage exclusion peril deductible").input_ids
    assert max(ids) < 256
    # default keeps the historical 1000-id behavior
    assert FakeTokenizer().VOCAB == 1000
    with pytest.raises(ValueError):
        FakeTokenizer(vocab=2)
