"""bench.py's production-chain sweep path, exercised on CPU.

The TPU headline (bench.py `_production_chain` + `_sweep_path`) scores a
chain-programmed model through the REAL offline-trained BPE tokenizer so
the shipped digit-early-stop default arms (reference workload:
perturb_prompts.py:398-549 parses a standalone integer out of each
confidence response). This guards that configuration end-to-end at toy
size: every swept row must parse confidence == 85 and the tokenizer must
actually provide a stop-class table (the two things the headline number
depends on beyond raw throughput).
"""

import pytest

import bench as bench_mod
from chain7b import (CHAIN_ANSWER_STEP, CHAIN_CONFIDENCE_FORMAT,
                     CHAIN_CONFIDENCE_VALUE, CHAIN_RESPONSE_FORMAT,
                     chain_param_tree, confidence_chain,
                     ship_quantized_chain)
from tiny_checkpoints import build_bpe_tokenizer

from lir_tpu.engine import tokens as tok

pytestmark = pytest.mark.slow  # real-tokenizer sweep: heavy lane


def test_bench_production_chain_sweep_cpu():
    import jax.numpy as jnp

    from lir_tpu.models.registry import ModelConfig

    fast = build_bpe_tokenizer()
    vocab = (len(fast) + 127) // 128 * 128
    cfg = ModelConfig(name="bench-chain-smoke", vocab_size=vocab,
                      hidden_size=64, n_layers=2, n_heads=4,
                      intermediate_size=128, max_seq_len=512,
                      tie_embeddings=False)
    chain, junk_next, junk_second = confidence_chain(
        fast, CHAIN_RESPONSE_FORMAT,
        CHAIN_CONFIDENCE_FORMAT, answer_step=CHAIN_ANSWER_STEP)
    params = chain_param_tree(cfg, chain, junk_next=junk_next,
                              junk_second=junk_second, dtype=jnp.float32)

    # The early stop can only arm if the tokenizer yields surface classes.
    assert tok.digit_stop_classes(fast, cfg.vocab_size) is not None

    # _sweep_path itself asserts confidence_value == 85 on every row when
    # expect_conf is set — a wrong scan position, a truncation-rejected
    # parse, or a stop firing before the integer completes all fail here.
    value, batch, cells = bench_mod._sweep_path(
        params, cfg, on_accel=False, tokenizer=fast, expect_conf=CHAIN_CONFIDENCE_VALUE)
    assert value > 0
    assert cells == bench_mod.SWEEP_CELLS_CPU


def test_binary_branch_eos_stop_preserves_rows():
    """The EOS-only stop on the sweep's binary branch (runner.eos_stop_mask
    -> generate.greedy_decode_fused_shared stop_mask_a) must change
    nothing a consumer reads: position-0 readouts bitwise equal, response
    text equal after the EOS trim every path applies, and the confidence
    branch's parsed integer unchanged."""
    import jax.numpy as jnp
    import numpy as np

    from chain7b import single_token_id
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models.registry import ModelConfig

    fast = build_bpe_tokenizer()
    vocab = (len(fast) + 127) // 128 * 128
    cfg = ModelConfig(name="eos-stop-smoke", vocab_size=vocab,
                      hidden_size=64, n_layers=2, n_heads=4,
                      intermediate_size=128, max_seq_len=512,
                      tie_embeddings=False)
    chain, junk_next, junk_second = confidence_chain(
        fast, CHAIN_RESPONSE_FORMAT, CHAIN_CONFIDENCE_FORMAT, answer_step=CHAIN_ANSWER_STEP)
    # confidence_chain maps EOS -> EOS; remap it to a VISIBLE token so the
    # unstopped decode keeps emitting text after EOS while a working stop
    # forces EOS fill — otherwise both runs are byte-identical and a dead
    # stop_mask_a wiring would pass this test unnoticed.
    eos = fast.eos_token_id
    dot = single_token_id(fast, ".")
    chain[eos] = (dot, eos)
    params = chain_param_tree(cfg, chain, junk_next=junk_next,
                              junk_second=junk_second, dtype=jnp.float32)
    engine = ScoringEngine(params, cfg, fast,
                           RuntimeConfig(batch_size=4, max_seq_len=512))
    assert engine.eos_stop_mask is not None

    mains = ["what is the meaning of flood damage here",
             "does the policy cover the water loss",
             "is the clause binding on the insurer",
             "should the exclusion apply to the claim"]
    bins = [m + " " + CHAIN_RESPONSE_FORMAT for m in mains]
    confs = [m + " " + CHAIN_CONFIDENCE_FORMAT for m in mains]
    yes_ids = np.full((4,), single_token_id(fast, " Yes"), np.int32)
    no_ids = np.full((4,), single_token_id(fast, " No"), np.int32)

    outs = [engine.decode_fused_shared(bins, confs, yes_ids, no_ids,
                                       new_tokens=6, conf_tokens=8,
                                       early_stop=stop)
            for stop in (False, True)]
    (a0, b0), (a1, b1) = outs

    # Engagement probe: every row reaches EOS inside the budget, the
    # unstopped run emits visible text after it (the remapped chain), and
    # the stopped run's post-EOS tail is pure EOS fill. A dead stop_mask_a
    # wiring fails here instead of passing vacuously.
    g0, g1 = np.asarray(a0.generated), np.asarray(a1.generated)
    assert (g0 == eos).any(axis=1).all(), "chain must reach EOS in budget"
    for r0, r1 in zip(g0, g1):
        k = int(np.argmax(r0 == eos))
        assert (r0[k + 1:] != eos).any(), "probe chain must talk past EOS"
        assert (r1[k:] == eos).all(), "stop did not engage (no EOS fill)"

    # Float readouts cross two differently-jitted programs — allclose, not
    # bitwise (tests/test_engine.py parity convention).
    np.testing.assert_allclose(np.asarray(a1.p_yes[:, 0]),
                               np.asarray(a0.p_yes[:, 0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a1.p_no[:, 0]),
                               np.asarray(a0.p_no[:, 0]), rtol=1e-6)
    for r0, r1 in zip(g0, g1):
        assert (engine.decode_completion(r1)
                == engine.decode_completion(r0))
    # Confidence branch: the parsed integer's source tokens are unchanged
    # by the binary branch's stop.
    for r0, r1 in zip(np.asarray(b0.generated), np.asarray(b1.generated)):
        assert (engine.decode_completion(r1)
                == engine.decode_completion(r0))


@pytest.mark.parametrize("family", ["llama", "gpt2ish"])
def test_ship_quantized_chain_matches_host_quantize(family):
    """The on-device chain builder must equal quantize_decoder_params of
    the host-built tree leaf-for-leaf (structure, dtypes, payloads,
    scale floors) — it is what the TPU bench actually ships."""
    import jax
    import numpy as np

    from lir_tpu.models import quant
    from lir_tpu.models.registry import ModelConfig

    fast = build_bpe_tokenizer()
    vocab = (len(fast) + 127) // 128 * 128
    extra = (dict() if family == "llama" else
             dict(norm="layernorm", gated_mlp=False, qkv_bias=True,
                  attn_out_bias=True, mlp_bias=True,
                  pos_embedding="learned", embedding_norm=True))
    cfg = ModelConfig(name=f"chain-eq-{family}", vocab_size=vocab,
                      hidden_size=64, n_layers=2, n_heads=4,
                      intermediate_size=128, max_seq_len=64,
                      tie_embeddings=False, **extra)
    chain, junk_next, junk_second = confidence_chain(
        fast, CHAIN_RESPONSE_FORMAT,
        CHAIN_CONFIDENCE_FORMAT, answer_step=CHAIN_ANSWER_STEP)

    host = quant.quantize_decoder_params(
        chain_param_tree(cfg, chain, junk_next=junk_next,
                         junk_second=junk_second),
        dynamic=True)
    dev = jax.devices("cpu")[0]
    shipped = ship_quantized_chain(jax, dev, cfg, chain,
                                   junk_next=junk_next,
                                   junk_second=junk_second)

    is_q = lambda x: isinstance(x, quant.QuantTensor)  # noqa: E731
    # tree_util spelling: older jax has no jax.tree.leaves_with_path.
    ph, sh = (jax.tree_util.tree_leaves_with_path(t, is_leaf=is_q)
              for t in (host, shipped))
    assert [p for p, _ in ph] == [p for p, _ in sh]
    for (path, a), (_, b) in zip(ph, sh):
        if is_q(a):
            assert is_q(b) and a.dynamic == b.dynamic, path
            np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q),
                                          err_msg=str(path))
            np.testing.assert_allclose(np.asarray(a.scale),
                                       np.asarray(b.scale), rtol=1e-6,
                                       err_msg=str(path))
        else:
            assert a.dtype == b.dtype, path
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(path))
