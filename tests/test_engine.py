"""Engine tests: readout rule, greedy decode, batched scorer, sharded forward.

The readout rule under test is C13 (compare_base_vs_instruct.py:185-305):
scan first 10 generated positions, first top-2 yes/no hit wins, fallback to
position 0. Sharding tests exercise the same Mesh/pjit paths as a v5e-8 via
8 virtual CPU devices (SURVEY.md §4).
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import MeshConfig, RuntimeConfig
from lir_tpu.engine import generate, score, tokens as tok
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder
from lir_tpu.models.loader import config_from_hf, convert_decoder
from lir_tpu.models.registry import tiny
from lir_tpu.parallel import sharding


def _tiny_llama_params(vocab=1000, seed=0):
    import transformers as tf
    torch.manual_seed(seed)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=vocab, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=256, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    return convert_decoder(hf.state_dict(), cfg, fam), cfg, hf


# ---------------------------------------------------------------------------
# Readout rule (pure function, synthetic logits)
# ---------------------------------------------------------------------------

def test_readout_first_top2_match_wins():
    B, T, V = 2, 12, 50
    yes_id, no_id = 7, 9
    logits = np.full((B, T, V), -10.0, np.float32)
    logits[:, :, 3] = 5.0          # dominant distractor everywhere
    logits[:, :, 4] = 4.0          # second-place distractor
    # Row 0: yes enters top-2 at position 3 (beats the 4.0 distractor).
    logits[0, 3, yes_id] = 4.5
    logits[0, 3, no_id] = 1.0
    # Row 1: no match anywhere -> fallback position 0.
    res = score.readout_from_step_logits(
        jnp.asarray(logits), jnp.zeros((B, T), jnp.int32),
        jnp.int32(yes_id), jnp.int32(no_id))
    assert int(res.position_found[0]) == 3 and bool(res.yes_no_found[0])
    assert int(res.position_found[1]) == 0 and not bool(res.yes_no_found[1])
    # Probabilities read at the matched position.
    probs = jax.nn.softmax(jnp.asarray(logits[0, 3]))
    np.testing.assert_allclose(float(res.yes_prob[0]), float(probs[yes_id]),
                               rtol=1e-6)
    # Both readouts present and consistent (SURVEY §1 drift fixed).
    rp = float(res.relative_prob[0])
    orr = float(res.odds_ratio[0])
    assert 0.0 <= rp <= 1.0
    np.testing.assert_allclose(orr / (1 + orr), rp, rtol=1e-4)


def test_weighted_confidence():
    B, V = 1, 40
    ids = jnp.asarray([5, 6], jnp.int32)
    vals = jnp.asarray([0.0, 100.0], jnp.float32)
    logits = np.full((B, 1, V), -10.0, np.float32)
    logits[0, 0, 5] = 2.0   # p(0)
    logits[0, 0, 6] = 2.0   # p(100) equal -> E[v] = 50
    out = score.weighted_confidence(jnp.asarray(logits), ids, vals)
    np.testing.assert_allclose(float(out[0]), 50.0, atol=1e-4)


# ---------------------------------------------------------------------------
# Greedy decode vs repeated full forward
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_greedy_decode_matches_full_forward():
    params, cfg, hf = _tiny_llama_params()
    rng = np.random.default_rng(0)
    S, NEW = 7, 5
    toks = rng.integers(3, 1000, size=(2, S)).astype(np.int32)
    gen, step_logits = generate.greedy_decode(
        params, cfg, jnp.asarray(toks), jnp.ones((2, S), jnp.int32),
        max_new_tokens=NEW)
    gen = np.asarray(gen)

    with torch.no_grad():
        out = hf.generate(torch.tensor(toks.astype(np.int64)),
                          max_new_tokens=NEW, do_sample=False,
                          output_scores=True, return_dict_in_generate=True,
                          pad_token_id=0)
    ref_gen = out.sequences[:, S:].numpy()
    np.testing.assert_array_equal(gen, ref_gen)
    for t in range(NEW):
        np.testing.assert_allclose(np.asarray(step_logits[:, t, :]),
                                   out.scores[t].numpy(), atol=2e-3, rtol=2e-3)


# ---------------------------------------------------------------------------
# End-to-end batched scorer with the fake tokenizer
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_scoring_engine_end_to_end():
    tokenizer = FakeTokenizer()
    params, cfg, _ = _tiny_llama_params(vocab=FakeTokenizer.VOCAB)
    eng = ScoringEngine(params, cfg, tokenizer,
                        RuntimeConfig(batch_size=4, max_new_tokens=12,
                                      max_seq_len=64))
    prompts = [f"Is a tomato number {i} a fruit ? Answer Yes or No" for i in range(6)]
    rows = eng.score_prompts(prompts)
    assert len(rows) == 6
    for r in rows:
        assert 0.0 <= r.yes_prob <= 1.0 and 0.0 <= r.no_prob <= 1.0
        assert np.isnan(r.relative_prob) or 0.0 <= r.relative_prob <= 1.0
        assert 0 <= r.position_found < 10
        assert isinstance(r.completion, str)
    # Deterministic: same prompts -> identical numbers.
    rows2 = eng.score_prompts(prompts)
    np.testing.assert_allclose([r.yes_prob for r in rows],
                               [r.yes_prob for r in rows2], rtol=0, atol=0)


def test_fake_tokenizer_yes_no_ids():
    t = FakeTokenizer()
    # Decoder rule: leading-space variant first; fake tokenizer strips spaces
    # so both resolve to the reserved ids.
    assert tok.yes_no_ids(t) == (FakeTokenizer.YES, FakeTokenizer.NO)


# ---------------------------------------------------------------------------
# Sharded forward on the 8-virtual-device mesh
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sharded_forward_matches_single_device():
    params, cfg, _ = _tiny_llama_params()
    mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    sharded = sharding.shard_params(params, cfg, mesh)

    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(3, 1000, size=(4, 10)).astype(np.int32))
    toks_sharded = jax.device_put(toks, sharding.batch_sharding(mesh))

    ref = decoder.forward(params, cfg, toks)
    out = jax.jit(lambda p, t: decoder.forward(p, cfg, t))(sharded, toks_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_sharded_greedy_decode():
    params, cfg, _ = _tiny_llama_params()
    mesh = sharding.build_mesh(MeshConfig(data=2, model=4))
    sharded = sharding.shard_params(params, cfg, mesh)
    rng = np.random.default_rng(2)
    toks = rng.integers(3, 1000, size=(4, 6)).astype(np.int32)
    mask = np.ones_like(toks)

    ref_gen, ref_logits = generate.greedy_decode(
        params, cfg, jnp.asarray(toks), jnp.asarray(mask), max_new_tokens=4)
    bs = sharding.batch_sharding(mesh)
    gen, logits = generate.greedy_decode(
        sharded, cfg, jax.device_put(jnp.asarray(toks), bs),
        jax.device_put(jnp.asarray(mask), bs), max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(gen), np.asarray(ref_gen))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=1e-3, rtol=1e-3)


@pytest.mark.slow
def test_fused_decode_matches_capture_path():
    """The fused in-scan readout must equal the full-logit-capture path
    bit-for-bit on every field the sweeps consume."""
    from lir_tpu.engine import generate as gen_mod
    from lir_tpu.engine import score as score_mod
    from lir_tpu.engine import tokens as tok_mod

    params, cfg, _ = _tiny_llama_params(vocab=FakeTokenizer.VOCAB)
    tokenizer = FakeTokenizer()
    prompts = ["Is a cat an animal Yes or No",
               "Is a rock an animal Yes or No",
               "some other prompt entirely"]
    toks, mask = tok_mod.left_pad_batch(tokenizer, prompts, 16)
    toks_j, mask_j = jnp.asarray(toks), jnp.asarray(mask)

    B = len(prompts)
    yes_ids = np.full((B,), FakeTokenizer.YES, np.int32)
    no_ids = np.full((B,), FakeTokenizer.NO, np.int32)
    digit_ids, digit_vals = tok_mod.integer_token_table(tokenizer)

    gen, step_logits = gen_mod.greedy_decode(params, cfg, toks_j, mask_j,
                                             max_new_tokens=8)
    ref = score_mod.readout_from_step_logits(
        step_logits, gen, jnp.asarray(yes_ids), jnp.asarray(no_ids),
        scan_positions=8)
    ref_topk_vals, ref_topk_ids = score_mod.topk_logprobs(step_logits, k=10)
    ref_wconf = score_mod.weighted_confidence(
        step_logits, jnp.asarray(digit_ids), jnp.asarray(digit_vals))

    fused = gen_mod.greedy_decode_fused(
        params, cfg, toks_j, mask_j, jnp.asarray(yes_ids),
        jnp.asarray(no_ids), jnp.asarray(digit_ids), jnp.asarray(digit_vals),
        max_new_tokens=8, topk=10)
    out = score_mod.readout_from_fused(
        fused, jnp.asarray(yes_ids), jnp.asarray(no_ids), scan_positions=8)

    np.testing.assert_array_equal(np.asarray(out.generated), np.asarray(ref.generated))
    np.testing.assert_array_equal(np.asarray(out.position_found),
                                  np.asarray(ref.position_found))
    np.testing.assert_array_equal(np.asarray(out.yes_no_found),
                                  np.asarray(ref.yes_no_found))
    np.testing.assert_allclose(np.asarray(out.yes_prob),
                               np.asarray(ref.yes_prob), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.no_prob),
                               np.asarray(ref.no_prob), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(fused.topk_ids),
                                  np.asarray(ref_topk_ids))
    np.testing.assert_allclose(np.asarray(fused.topk_logprobs),
                               np.asarray(ref_topk_vals), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(fused.weighted_confidence),
                               np.asarray(ref_wconf), rtol=1e-5)


# ---------------------------------------------------------------------------
# Shared-prefix fused decode (one prefill serves both sweep formats)
# ---------------------------------------------------------------------------

import dataclasses as _dc

from lir_tpu.models.registry import ModelConfig as _MC


@pytest.mark.parametrize("family,int8kv", [
    ("llama", False),   # rotary + RMSNorm + gated MLP
    ("llama", True),    # + int8 KV cache (extend quantizes suffix k/v)
    ("bloom", False),   # ALiBi + embedding LayerNorm
    ("gpt2", False),    # learned positions + tied embeddings
])
@pytest.mark.slow
def test_shared_prefix_decode_matches_full_prompts(family, int8kv):
    """greedy_decode_fused_shared == two greedy_decode_fused calls on the
    concatenated prompts, for every position-dependent readout. Rows have
    DIFFERENT prefix and suffix lengths, so per-row position bookkeeping
    (left-padded prefix + right-padded suffix) is exercised."""
    from lir_tpu.models.registry import tiny as tiny_cfg

    cfg = tiny_cfg(family)
    if int8kv:
        cfg = _dc.replace(cfg, kv_cache_int8=True)
    params = decoder.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(3)
    V = cfg.vocab_size
    prefix_lens = [10, 17, 5, 23]
    sa_lens = [3, 5, 2, 4]
    sb_lens = [6, 2, 7, 3]
    prefix_ids = [rng.integers(3, V, n).tolist() for n in prefix_lens]
    sa_ids = [rng.integers(3, V, n).tolist() for n in sa_lens]
    sb_ids = [rng.integers(3, V, n).tolist() for n in sb_lens]
    yes_ids = rng.integers(3, V, 4).astype(np.int32)
    no_ids = rng.integers(3, V, 4).astype(np.int32)
    digit_ids = np.asarray([5, 6, 7], np.int32)
    digit_vals = np.asarray([10.0, 50.0, 90.0], np.float32)
    NEW_A, NEW_B = 4, 6

    def ref(full_ids, n_new, d_ids, d_vals):
        toks, mask = tok.left_pad_ids(full_ids, 32, 0)
        return generate.greedy_decode_fused(
            params, cfg, jnp.asarray(toks), jnp.asarray(mask),
            jnp.asarray(yes_ids), jnp.asarray(no_ids),
            jnp.asarray(d_ids), jnp.asarray(d_vals), max_new_tokens=n_new)

    ref_a = ref([p + s for p, s in zip(prefix_ids, sa_ids)], NEW_A,
                np.zeros((0,), np.int32), np.zeros((0,), np.float32))
    ref_b = ref([p + s for p, s in zip(prefix_ids, sb_ids)], NEW_B,
                digit_ids, digit_vals)

    pre, pre_mask = tok.left_pad_ids(prefix_ids, 32, 0)
    sa, sa_mask = tok.right_pad_ids(sa_ids, 8, 0)
    sb, sb_mask = tok.right_pad_ids(sb_ids, 8, 0)
    out_a, out_b = generate.greedy_decode_fused_shared(
        params, cfg, jnp.asarray(pre), jnp.asarray(pre_mask),
        jnp.asarray(sa), jnp.asarray(sa_mask), jnp.asarray(sb),
        jnp.asarray(sb_mask), jnp.asarray(yes_ids), jnp.asarray(no_ids),
        jnp.asarray(digit_ids), jnp.asarray(digit_vals),
        max_new_a=NEW_A, max_new_b=NEW_B)

    # int8 KV: the reference path's FIRST position comes from the dense
    # (unquantized) prefill, while the shared path reads it through the
    # quantized cache — a real ~0.5% numeric difference, same one every
    # decode step already carries. fp32 paths agree to float tolerance.
    tol = dict(rtol=2e-2, atol=2e-2) if int8kv else dict(rtol=1e-4, atol=1e-5)
    for out, refd in ((out_a, ref_a), (out_b, ref_b)):
        if not int8kv:
            np.testing.assert_array_equal(np.asarray(out.generated),
                                          np.asarray(refd.generated))
            np.testing.assert_array_equal(np.asarray(out.top2_ids),
                                          np.asarray(refd.top2_ids))
        np.testing.assert_allclose(np.asarray(out.p_yes),
                                   np.asarray(refd.p_yes), **tol)
        np.testing.assert_allclose(np.asarray(out.p_no),
                                   np.asarray(refd.p_no), **tol)
    if not int8kv:
        np.testing.assert_array_equal(np.asarray(out_a.topk_ids),
                                      np.asarray(ref_a.topk_ids))
        np.testing.assert_allclose(np.asarray(out_a.topk_logprobs),
                                   np.asarray(ref_a.topk_logprobs),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_b.weighted_confidence),
                               np.asarray(ref_b.weighted_confidence), **tol)


@pytest.mark.slow
def test_engine_decode_fused_shared_matches_decode_fused():
    """Runner-level: tokenize/LCP-split/pad host prep reproduces the plain
    decode_fused readouts on real prompt strings (FakeTokenizer)."""
    cfg = _MC(name="shared-smoke", vocab_size=FakeTokenizer.VOCAB,
              hidden_size=64, n_layers=2, n_heads=4, intermediate_size=128,
              max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(2))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=4, max_seq_len=256))
    mains = [f"the quick brown fox {i} jumps over the lazy dog "
             f"word {i * 7} more filler text here" for i in range(4)]
    bins = [m + " Respond with either Yes or No only" for m in mains]
    confs = [m + " Give a confidence number from 0 to 100" for m in mains]
    t1 = np.full((4,), FakeTokenizer.YES, np.int32)
    t2 = np.full((4,), FakeTokenizer.NO, np.int32)

    fused_a = engine.decode_fused(bins, t1, t2, max_new_tokens=4)
    fused_b = engine.decode_fused(confs, t1, t2, with_digits=True,
                                  max_new_tokens=6)
    out_a, out_b = engine.decode_fused_shared(bins, confs, t1, t2,
                                              new_tokens=4, conf_tokens=6)
    np.testing.assert_array_equal(np.asarray(out_a.generated),
                                  np.asarray(fused_a.generated))
    np.testing.assert_allclose(np.asarray(out_a.p_yes),
                               np.asarray(fused_a.p_yes),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out_a.topk_ids),
                                  np.asarray(fused_a.topk_ids))
    np.testing.assert_array_equal(np.asarray(out_b.generated),
                                  np.asarray(fused_b.generated))
    np.testing.assert_allclose(np.asarray(out_b.weighted_confidence),
                               np.asarray(fused_b.weighted_confidence),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_fused_decode_digit_early_stop_mechanics():
    """Early-stopped fused decode vs the plain run: each row's tokens match
    the full decode until its stop point (EOS, or a standalone digit run
    followed by a non-gluing token), then the row emits EOS fill;
    position-0 readouts are bitwise identical. Replayed host-side from the
    full run's tokens with the same class machine."""
    cfg = _MC(name="earlystop-smoke", vocab_size=256, hidden_size=32,
              n_layers=2, n_heads=4, intermediate_size=64, max_seq_len=128)
    params = decoder.init_params(cfg, jax.random.PRNGKey(7))
    rng = np.random.default_rng(11)
    toks = rng.integers(3, 256, size=(4, 8)).astype(np.int32)
    mask = np.ones_like(toks)
    t1 = np.full((4,), 10, np.int32)
    t2 = np.full((4,), 11, np.int32)
    eos = 5
    # Synthetic vocab surface classes: ids 0-2 mod 4 cycle through
    # "▁85"-like (PURE|PREFIX|ENDS_WORD), ","-like (0), "st"-like
    # (STARTS_WORD|ENDS_WORD); eos id is TRANSPARENT.
    cls = np.zeros((256,), np.int32)
    cls[np.arange(256) % 4 == 0] = tok.STOP_PURE | tok.STOP_PREFIX | tok.STOP_ENDS_WORD
    cls[np.arange(256) % 4 == 2] = tok.STOP_STARTS_WORD | tok.STOP_ENDS_WORD
    cls[eos] = tok.STOP_TRANSPARENT
    T = 20
    kw = dict(max_new_tokens=T)
    full = generate.greedy_decode_fused(
        params, cfg, jnp.asarray(toks), jnp.asarray(mask),
        jnp.asarray(t1), jnp.asarray(t2), jnp.zeros((0,), jnp.int32),
        jnp.zeros((0,), jnp.float32), **kw)
    early = generate.greedy_decode_fused(
        params, cfg, jnp.asarray(toks), jnp.asarray(mask),
        jnp.asarray(t1), jnp.asarray(t2), jnp.zeros((0,), jnp.int32),
        jnp.zeros((0,), jnp.float32), stop_mask=jnp.asarray(cls),
        eos_id=jnp.int32(eos), **kw)
    g_full = np.asarray(full.generated)
    g_early = np.asarray(early.generated)
    stopped = 0
    for j in range(4):
        expect, done, run, prev_ew = [], False, False, False
        for t in range(T):
            emit = eos if done else int(g_full[j, t])
            expect.append(emit)
            c = int(cls[emit])
            pure, prefix = bool(c & 1), bool(c & 2)
            glue, ends_w, transp = bool(c & 4), bool(c & 8), bool(c & 16)
            done = done or emit == eos or (run and not glue and not transp)
            if not transp:
                run = (pure and (prefix or not prev_ew)) or (
                    run and pure and not prefix)
                prev_ew = ends_w
        stopped += done
        np.testing.assert_array_equal(g_early[j], expect)
    assert stopped == 4, "seeded run should stop every row inside the budget"
    # Position-0 readouts are computed before any step runs — identical.
    np.testing.assert_array_equal(np.asarray(early.topk_ids),
                                  np.asarray(full.topk_ids))
    np.testing.assert_allclose(np.asarray(early.p_yes[:, 0]),
                               np.asarray(full.p_yes[:, 0]), rtol=1e-6)


def test_digit_stop_classes_surface_semantics():
    """The early-stop class table must read DECODED surfaces, not raw
    strings: byte tokens map to their byte ('<0x0A>' is a newline, '<0x30>'
    is the digit 0), REGISTERED specials are transparent (metadata, not
    surface form: an unregistered <div> that decodes to literal text must
    classify by its surface — ADVICE r4), space-prefixed digits are
    standalone-integer openers, and letter-glued pieces ('st', 'a1b') glue
    — so '1st' never reads as a parseable integer."""
    class Stub:
        all_special_ids = [4, 5]

        def convert_ids_to_tokens(self, ids):
            table = ["▁Yes", "▁85", "<0x0A>", "<0x30>", "</s>",
                     "<|reserved_special_token_0|>", "a1b", "100",
                     "st", ",", "Ġ42", "Ġ", "<div>"]
            return [table[i] for i in ids]

        def __len__(self):
            return 13

    cls = tok.digit_stop_classes(Stub(), 13)
    P, X, W, E, T = (tok.STOP_PURE, tok.STOP_PREFIX, tok.STOP_STARTS_WORD,
                     tok.STOP_ENDS_WORD, tok.STOP_TRANSPARENT)
    assert cls[0] == X | E                 # ▁Yes: fresh word, not digits
    assert cls[1] == P | X | E             # ▁85: standalone integer opener
    assert cls[2] == X                     # newline byte = space prefix only
    assert cls[3] == P | W | E             # '0' byte: digit, glues
    assert cls[4] == T                     # </s>
    assert cls[5] == T                     # reserved special
    assert cls[6] == W | E                 # a1b: glues, not pure
    assert cls[7] == P | W | E             # bare 100: pure but gluing
    assert cls[8] == W | E                 # st: the '1st' glue piece
    assert cls[9] == 0                     # ',' terminator
    assert cls[10] == P | X | E            # Ġ42 (byte-BPE space prefix)
    # 'Ġ' alone is a letter CODEPOINT but decodes to a bare space: prefix
    # only, NOT word-ending ('\n' + '85' must still open a digit run).
    assert cls[11] == X
    # Unregistered <div> is literal text (code-trained vocabs), NOT
    # transparent: both bracket chars are non-word → plain terminator.
    assert cls[12] == 0

    class RawStub:
        """No special-id metadata; transparency must come from the
        decode-to-empty check instead."""

        def convert_ids_to_tokens(self, ids):
            table = ["</s>", "<div>"]
            return [table[i] for i in ids]

        def convert_tokens_to_string(self, toks):
            return "".join("" if t == "</s>" else t for t in toks)

        def __len__(self):
            return 2

    cls2 = tok.digit_stop_classes(RawStub(), 2)
    assert cls2[0] == T
    assert cls2[1] == 0


@pytest.mark.slow
def test_engine_early_stop_disabled_without_token_strings():
    """FakeTokenizer renders ids as '<123>' and exposes no per-token
    strings: the engine must resolve digit_stop_mask to None and score
    identically with early_stop on/off (the bench stays budget-honest)."""
    cfg = _MC(name="nostop-smoke", vocab_size=FakeTokenizer.VOCAB,
              hidden_size=64, n_layers=2, n_heads=4, intermediate_size=128,
              max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(8))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=2, max_seq_len=256))
    assert engine.digit_stop_mask is None
    prompts = ["is a levee failure a flood", "is rust damage covered"]
    t1 = np.full((2,), FakeTokenizer.YES, np.int32)
    t2 = np.full((2,), FakeTokenizer.NO, np.int32)
    on = engine.decode_fused(prompts, t1, t2, with_digits=True,
                             max_new_tokens=6, early_stop=True)
    off = engine.decode_fused(prompts, t1, t2, with_digits=True,
                              max_new_tokens=6, early_stop=False)
    np.testing.assert_array_equal(np.asarray(on.generated),
                                  np.asarray(off.generated))


def test_shared_prefix_len_caps_for_nonempty_suffix():
    a = [1, 2, 3, 4]
    assert tok.shared_prefix_len(a, a) == 3          # strict-prefix guard
    assert tok.shared_prefix_len(a, [1, 2, 9]) == 2
    assert tok.shared_prefix_len([7], [8]) == 0
    assert tok.shared_prefix_len(a, [1, 2, 3, 4, 5]) == 3


@pytest.mark.slow
def test_decode_fused_shared_falls_back_on_long_suffix():
    """Prompt pairs that diverge early (suffix > largest suffix bucket) must
    take the plain two-prefill path, not silently truncate the instruction
    the readout depends on."""
    cfg = _MC(name="fallback-smoke", vocab_size=FakeTokenizer.VOCAB,
              hidden_size=64, n_layers=2, n_heads=4, intermediate_size=128,
              max_seq_len=1024)
    params = decoder.init_params(cfg, jax.random.PRNGKey(4))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=2, max_seq_len=1024))
    # Shared prefix of 2 words; suffixes of ~300 words each (> 256 bucket).
    long_a = "start shared " + " ".join(f"alpha{i}" for i in range(300))
    long_b = "start shared " + " ".join(f"beta{i}" for i in range(300))
    t1 = np.full((2,), FakeTokenizer.YES, np.int32)
    t2 = np.full((2,), FakeTokenizer.NO, np.int32)
    out_a, out_b = engine.decode_fused_shared(
        [long_a] * 2, [long_b] * 2, t1, t2, new_tokens=2, conf_tokens=2)
    ref_a = engine.decode_fused([long_a] * 2, t1, t2, max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(out_a.generated),
                                  np.asarray(ref_a.generated))
    np.testing.assert_allclose(np.asarray(out_a.p_yes),
                               np.asarray(ref_a.p_yes), rtol=1e-6)


@pytest.mark.slow
def test_decode_fused_shared_falls_back_on_overlong_prefix(caplog):
    """When the common token prefix exceeds the largest prefix bucket, the
    shared path must NOT keep more context than the plain path (which
    left-truncates the whole prompt): it falls back to two full prefills so
    over-long semantics stay pinned across paths (ADVICE r3 #2)."""
    cfg = _MC(name="overlong-smoke", vocab_size=FakeTokenizer.VOCAB,
              hidden_size=64, n_layers=2, n_heads=4, intermediate_size=128,
              max_seq_len=1024)
    params = decoder.init_params(cfg, jax.random.PRNGKey(5))
    # rt.max_seq_len=128 -> prefix buckets [64, 128].
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=2, max_seq_len=128))
    shared = " ".join(f"common{i}" for i in range(200))   # lcp >> 128
    bins = [shared + " answer yes or no"] * 2
    confs = [shared + " give a number"] * 2
    t1 = np.full((2,), FakeTokenizer.YES, np.int32)
    t2 = np.full((2,), FakeTokenizer.NO, np.int32)
    with caplog.at_level("INFO", logger="lir_tpu"):
        out_a, out_b = engine.decode_fused_shared(
            bins, confs, t1, t2, new_tokens=2, conf_tokens=2)
    assert any("shared-prefix fallback" in r.message
               and "exceeds the largest bucket" in r.message
               for r in caplog.records)
    ref_a = engine.decode_fused(bins, t1, t2, max_new_tokens=2)
    ref_b = engine.decode_fused(confs, t1, t2, with_digits=True,
                                max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(out_a.generated),
                                  np.asarray(ref_a.generated))
    np.testing.assert_allclose(np.asarray(out_a.p_yes),
                               np.asarray(ref_a.p_yes), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out_b.generated),
                                  np.asarray(ref_b.generated))


@pytest.mark.slow
def test_decode_fused_shared_falls_back_on_learned_pos_overflow(caplog):
    """Learned-position models: prefix bucket + suffix bucket + new tokens
    can overrun the position table even when each bucket individually fits
    (the constructor only trims for the plain path) — the shared path must
    detect this and take the trimmed plain path (ADVICE r3 #1)."""
    cfg = _MC(name="learnedpos-smoke", vocab_size=FakeTokenizer.VOCAB,
              hidden_size=64, n_layers=2, n_heads=4, intermediate_size=128,
              max_seq_len=160, pos_embedding="learned")
    params = decoder.init_params(cfg, jax.random.PRNGKey(6))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=2, max_seq_len=256,
                                         max_new_tokens=4))
    # Constructor trim: buckets <= 160-4 -> [64, 128]. Total prompt ~120
    # tokens fits the 128 bucket (so the over-long-total branch stays
    # quiet), but prefix bucket 128 + suffix bucket 32 + 2 new tokens =
    # 162 > the 160-row position table -> must fall back.
    shared = " ".join(f"body{i}" for i in range(100))
    bins = [shared + " " + " ".join(f"ba{i}" for i in range(18))] * 2
    confs = [shared + " " + " ".join(f"bc{i}" for i in range(18))] * 2
    t1 = np.full((2,), FakeTokenizer.YES, np.int32)
    t2 = np.full((2,), FakeTokenizer.NO, np.int32)
    with caplog.at_level("INFO", logger="lir_tpu"):
        out_a, _ = engine.decode_fused_shared(
            bins, confs, t1, t2, new_tokens=2, conf_tokens=2)
    assert any("shared-prefix fallback" in r.message
               and "learned-position" in r.message for r in caplog.records)
    ref_a = engine.decode_fused(bins, t1, t2, max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(out_a.generated),
                                  np.asarray(ref_a.generated))


@pytest.mark.slow
def test_data_parallel_mesh_8x1_replicated_params():
    """Pure data-parallel serving (mesh 8x1): params replicate, the batch
    shards on `data`, and scores equal the single-device run — the int8-7B
    v5e-8 deployment mode (DEPLOY.md §2; perturb_prompts.py:294-330)."""
    params, cfg, _ = _tiny_llama_params()
    mesh = sharding.build_mesh(MeshConfig(data=8, model=1))
    sharded = sharding.shard_params(params, cfg, mesh)
    rng = np.random.default_rng(3)
    toks = rng.integers(3, 1000, size=(8, 6)).astype(np.int32)
    mask = np.ones_like(toks)

    ref_gen, ref_logits = generate.greedy_decode(
        params, cfg, jnp.asarray(toks), jnp.asarray(mask), max_new_tokens=4)
    bs = sharding.batch_sharding(mesh)
    gen, logits = generate.greedy_decode(
        sharded, cfg, jax.device_put(jnp.asarray(toks), bs),
        jax.device_put(jnp.asarray(mask), bs), max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(gen), np.asarray(ref_gen))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=1e-4, rtol=1e-4)
    # Params really are replicated: with model=1 every device holds the
    # FULL weight (the named model axis has size 1 -> no actual split).
    wq = sharded["layers"]["wq"]
    assert wq.sharding.shard_shape(wq.shape) == wq.shape


@pytest.mark.slow
def test_sample_decode_typed_prng_key_batch():
    """Per-row PRNG streams must work with BOTH key flavors: legacy
    uint32 (B, 2) arrays and modern typed keys (shape (B,)). The typed
    batch previously misrouted into the single-key path and crashed."""
    cfg = _MC(name="key-smoke", vocab_size=64, hidden_size=32, n_layers=2,
              n_heads=4, intermediate_size=64, max_seq_len=64)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(3, 64, (3, 5)), jnp.int32)
    mask = jnp.ones_like(toks)

    legacy = jnp.stack([jax.random.PRNGKey(i) for i in range(3)])
    assert generate.is_per_row_keys(legacy)
    g1 = generate.sample_decode(params, cfg, toks, mask, legacy,
                                max_new_tokens=4)
    typed = jax.vmap(jax.random.key)(jnp.arange(3, dtype=jnp.uint32))
    assert generate.is_per_row_keys(typed)
    g2 = generate.sample_decode(params, cfg, toks, mask, typed,
                                max_new_tokens=4)
    assert g1.shape == g2.shape == (3, 4)
    # Scalar keys of both flavors route to the single-stream path.
    assert not generate.is_per_row_keys(jax.random.PRNGKey(0))
    assert not generate.is_per_row_keys(jax.random.key(0))
    g3 = generate.sample_decode(params, cfg, toks, mask, jax.random.key(7),
                                max_new_tokens=4)
    assert g3.shape == (3, 4)


@pytest.mark.slow
def test_shared_prefix_scorer_on_dp_mesh():
    """The sweep's shared-prefix scorer on a pure data-parallel (8x1)
    engine — the recommended int8-7B serving mode — equals the
    single-device run."""
    params, cfg, _ = _tiny_llama_params()
    mesh = sharding.build_mesh(MeshConfig(data=8, model=1))
    sharded = sharding.shard_params(params, cfg, mesh)
    tok_f = FakeTokenizer()
    rt = RuntimeConfig(batch_size=8, max_seq_len=64)
    plain = ScoringEngine(params, cfg, tok_f, rt)
    dp = ScoringEngine(sharded, cfg, tok_f, rt)
    mains = [f"levee failure case number {i} in the policy ?"
             for i in range(8)]
    bins = [m + " Answer Yes or No ." for m in mains]
    confs = [m + " Give a number 0 to 100 ." for m in mains]
    t1 = np.full((8,), FakeTokenizer.YES, np.int32)
    t2 = np.full((8,), FakeTokenizer.NO, np.int32)
    pa, pb = plain.decode_fused_shared(bins, confs, t1, t2,
                                       new_tokens=3, conf_tokens=4)
    da, db = dp.decode_fused_shared(bins, confs, t1, t2,
                                    new_tokens=3, conf_tokens=4)
    np.testing.assert_array_equal(np.asarray(da.generated),
                                  np.asarray(pa.generated))
    np.testing.assert_allclose(np.asarray(da.p_yes), np.asarray(pa.p_yes),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(db.weighted_confidence),
                               np.asarray(pb.weighted_confidence), atol=1e-3)
