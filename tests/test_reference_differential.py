"""Differential parity against the EXECUTED reference (VERDICT r1 #2, r3 #1).

tools/reference_differential.py ran ALL 11 actually-runnable reference
analysis/survey scripts (the full list is its SCRIPTS dict; of the 15
scripts total, perturb_prompts.py and both compare_* scripts need API
keys / GPU weights and are covered instead by the staged-oracle
differentials in test_reference_scorer_oracle.py and
test_reference_perturb_oracle.py, and
analyze_llm_agreement_bootstrap.py (C40) is dead code — see PARITY.md)
on the committed data CSVs + the pinned synthetic D6 + our regenerated D7,
capturing every numeric artifact into tests/golden/reference_executed.json.
These tests recompute the same quantities with lir_tpu's pipelines from the
IDENTICAL inputs and diff them under the BASELINE ≤1% gate (deterministic
point estimates) or a CI-width tolerance (bootstrap quantities — the two
sides use different RNGs by design; SURVEY.md §7 hard part 6).
"""

import json
from pathlib import Path

import jax
import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

GOLDEN_PATH = Path(__file__).parent / "golden" / "reference_executed.json"
KEY = jax.random.PRNGKey(7)

REL = 0.01          # the ≤1% gate for deterministic point estimates
BOOT_ABS = 0.03     # |Δ| tolerance for independently-resampled bootstrap means
CI_ABS = 0.06       # |Δ| tolerance for CI endpoints
REPLAY_ABS = 1e-3   # |Δ| gate for bootstrap quantities under INDEX REPLAY:
# wherever the reference seeds np.random (model_comparison_graph.py:258,
# calculate_cohens_kappa.py:185 — BASELINE.md RNG row), its exact resample
# index arrays are regenerated with RandomState(42) and fed into the
# vmapped kernels (VERDICT r4 #6), leaving only f32-vs-f64 kernel noise.
# The unseeded scripts (survey_analysis_consolidated.py,
# analyze_llm_agreement_simple_bootstrap.py draw from unseeded global
# state) stay at the distributional BOOT_ABS/CI_ABS tolerances.


def _choice_rows(rs, n_rows: int, n: int):
    """Replay n_rows of the reference's ``np.random.choice(n, size=n,
    replace=True)`` draws from an already-positioned RandomState."""
    return np.stack([rs.choice(n, size=n, replace=True)
                     for _ in range(n_rows)])


@pytest.fixture(scope="module")
def golden():
    if not GOLDEN_PATH.exists():
        pytest.skip("run tools/reference_differential.py first")
    return json.loads(GOLDEN_PATH.read_text())


@pytest.fixture(scope="module")
def instruct_df(reference_data_dir):
    df = pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")
    df = df[~df["model"].str.contains("opt-iml-1.3b")]
    return df[~df["model"].str.contains("mistral", case=False)]


def _close(a, b, rel=REL, abs_tol=0.0):
    a, b = float(a), float(b)
    if np.isnan(a) and np.isnan(b):
        return True
    return abs(a - b) <= max(abs_tol, rel * abs(b))


# ---------------------------------------------------------------------------
# model_comparison_graph.py — correlation suite + aggregate kappa
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("method", ["pearson", "spearman"])
def test_correlation_suite_vs_executed_reference(golden, instruct_df, method):
    from lir_tpu.stats import bootstrap_correlation_matrix

    ref = golden["model_comparison_graph"][method]
    pivot = instruct_df.pivot_table(
        index="prompt", columns="model", values="relative_prob")
    pivot = pivot[ref["models"]]            # reference column order
    # INDEX REPLAY: the reference seeds 42 at the top of each
    # calculate_model_correlations call and draws 1000 choice(n_prompts)
    # rows (:258-263). Its draws index into unique_prompts (APPEARANCE
    # order, :221) and gather by label — map them onto the sorted
    # pivot_table row order our kernel sees.
    unique_prompts = instruct_df["prompt"].unique()
    pos = {p: i for i, p in enumerate(pivot.index)}
    u2pos = np.array([pos[p] for p in unique_prompts])
    rs = np.random.RandomState(42)
    idx = u2pos[_choice_rows(rs, 1000, pivot.shape[0])]
    res = bootstrap_correlation_matrix(
        pivot.values, KEY, n_bootstrap=1000, method=method, indices=idx)

    # Deterministic point estimates: the ≤1% gate.
    assert _close(res["mean_correlation"], ref["mean_correlation"], abs_tol=1e-4)
    assert _close(res["median_correlation"], ref["median_correlation"], abs_tol=1e-4)
    assert _close(res["std_correlation"], ref["std_correlation"], abs_tol=1e-4)
    assert _close(res["min_correlation"], ref["min_correlation"], abs_tol=1e-4)
    assert _close(res["max_correlation"], ref["max_correlation"], abs_tol=1e-4)
    np.testing.assert_allclose(
        np.asarray(res["correlation_matrix"]),
        np.asarray(ref["correlation_matrix"]), rtol=REL, atol=1e-6)
    # Bootstrap CIs under index replay: identical resamples, so only
    # kernel-level (f32 masked-corr vs pandas f64) noise remains.
    for lo_hi, ours in (("mean_ci", res["mean_ci"]),
                        ("median_ci", res["median_ci"])):
        assert _close(ours[0], ref[lo_hi][0], abs_tol=REPLAY_ABS)
        assert _close(ours[1], ref[lo_hi][1], abs_tol=REPLAY_ABS)


def test_aggregate_kappa_vs_executed_reference(golden, instruct_df):
    from lir_tpu.stats import aggregate_kappa

    ref = golden["model_comparison_graph"]["aggregate_kappa"]
    pivot = instruct_df.pivot_table(
        index="prompt", columns="model", values="relative_prob")
    binary = (pivot.dropna() > 0.5).astype(int).values
    # INDEX REPLAY: in the executed script the kappa bootstrap CONTINUES
    # the np.random stream of the last (spearman) correlation call —
    # seed(42) then 1000 choice(n_prompts) burn-in (:732-766) — then per
    # iteration draws rate indices and flat-value indices (:627-632).
    rs = np.random.RandomState(42)
    _choice_rows(rs, 1000, pivot.shape[0])          # spearman burn-in
    rate_rows, flat_rows = [], []
    for _ in range(1000):
        rate_rows.append(rs.choice(binary.shape[0], size=binary.shape[0],
                                   replace=True))
        flat_rows.append(rs.choice(binary.size, size=binary.size,
                                   replace=True))
    res = aggregate_kappa(binary, KEY, n_boot=1000,
                          indices=(np.stack(rate_rows),
                                   np.stack(flat_rows)))

    assert res["n_models"] == int(ref["n_models"])
    assert _close(res["aggregate_kappa"], ref["aggregate_kappa"], abs_tol=1e-6)
    assert _close(res["observed_agreement"], ref["observed_agreement"], abs_tol=1e-6)
    assert _close(res["chance_agreement"], ref["chance_agreement"], abs_tol=1e-6)
    assert _close(res["kappa_ci_lower"], ref["kappa_ci_lower"],
                  abs_tol=REPLAY_ABS)
    assert _close(res["kappa_ci_upper"], ref["kappa_ci_upper"],
                  abs_tol=REPLAY_ABS)


# ---------------------------------------------------------------------------
# calculate_cohens_kappa.py — two-source kappa combiner on identical inputs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kappa_run(reference_data_dir, tmp_path_factory):
    from lir_tpu.analysis.kappa_combined import run_kappa_analysis
    from lir_tpu.data import synthetic

    out = tmp_path_factory.mktemp("kappa")
    d6 = synthetic.write_synthetic_d6(out / "combined_results.csv")
    return run_kappa_analysis(
        Path(reference_data_dir) / "instruct_model_comparison_results.csv",
        d6, out, n_bootstrap=1000, make_figures=False)


def test_perturbation_self_kappa_vs_executed_reference(golden, kappa_run):
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["perturbation_kappa_metrics"])
    ours = kappa_run["perturbation_kappa"].set_index("prompt")
    ref = ref.set_index("prompt")
    assert set(ours.index) == set(ref.index)
    for prompt in ref.index:
        r, o = ref.loc[prompt], ours.loc[prompt]
        assert int(o["n_variations"]) == int(r["n_variations"])
        # agree_percent is deterministic on identical inputs: exact-ish.
        assert _close(o["agree_percent"], r["agree_percent"], abs_tol=1e-9)
        # self-kappa: 1000 independent bootstrap pairs on each side. The
        # statistic's expectation is ~0 by construction (unpaired samples);
        # both sides must land in the same tight band. On near-constant
        # decisions sklearn's cohen_kappa_score is 0/0 -> the executed
        # reference records NaN (its degenerate-input behavior); ours
        # defines those resamples as 0 — accept a finite near-zero value.
        if np.isnan(r["self_kappa"]):
            assert abs(float(o["self_kappa"])) < 0.05
        else:
            assert _close(o["self_kappa"], r["self_kappa"], abs_tol=0.02)


def test_self_kappa_index_replay_vs_executed_reference(golden, tmp_path):
    """INDEX REPLAY for the per-prompt self-kappa (VERDICT r4 #6): the
    reference seeds 42 per prompt and interleaves idx1/idx2 draws
    (calculate_cohens_kappa.py:185-192). Feeding that exact stream into
    the vmapped kernel leaves only f32 kernel noise — the ≤REPLAY_ABS
    gate. A finite golden mean implies the reference hit zero NaN draws
    on that prompt, so the dropped-draw asymmetry cannot bite."""
    from lir_tpu.data import synthetic
    from lir_tpu.stats.kappa import self_kappa_bootstrap

    ref = pd.DataFrame(
        golden["calculate_cohens_kappa"]["perturbation_kappa_metrics"]
    ).set_index("prompt")
    d6_path = synthetic.write_synthetic_d6(tmp_path / "combined_results.csv")
    df = pd.read_csv(d6_path)
    # The reference's own preparation rule (:158-166).
    rel = df["Token_1_Prob"] / (df["Token_1_Prob"] + df["Token_2_Prob"])
    df["binary_decision"] = (rel > 0.5).astype(int)
    checked = 0
    for prompt, group in df.groupby("Original Main Part"):
        if prompt not in ref.index or np.isnan(ref.loc[prompt, "self_kappa"]):
            continue
        decisions = group["binary_decision"].values
        rs = np.random.RandomState(42)          # re-seeded per prompt (:185)
        idx1, idx2 = [], []
        for _ in range(1000):
            idx1.append(rs.choice(len(decisions), size=len(decisions),
                                  replace=True))
            idx2.append(rs.choice(len(decisions), size=len(decisions),
                                  replace=True))
        res = self_kappa_bootstrap(
            decisions, KEY, n_boot=1000,
            indices=(np.stack(idx1), np.stack(idx2)))
        assert _close(res["self_kappa"], ref.loc[prompt, "self_kappa"],
                      abs_tol=REPLAY_ABS)
        checked += 1
    assert checked >= 3, "too few finite self-kappa prompts replayed"


def test_model_agree_percent_vs_executed_reference(golden, kappa_run):
    """agree_percent/n_models per word-meaning prompt match the executed
    reference. Its avg_pairwise_kappa is NaN for every prompt (the
    single-observation cohen_kappa_score defect, calculate_cohens_kappa.py:
    124-127, executed and confirmed) — a documented defect-to-fix, so our
    real-valued kappa column is intentionally NOT diffed against it."""
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["model_kappa_metrics"])
    assert ref["avg_pairwise_kappa"].isna().all()  # the defect, as executed
    ours = kappa_run["model_kappa"].set_index("prompt")
    ref = ref.set_index("prompt")
    shared = set(ours.index) & set(ref.index)
    assert len(shared) == len(ref)
    for prompt in shared:
        assert int(ours.loc[prompt, "n_models"]) == int(ref.loc[prompt, "n_models"])
        assert _close(ours.loc[prompt, "agree_percent"],
                      ref.loc[prompt, "agree_percent"], abs_tol=1e-9)


def test_combined_kappa_prompt_matching_vs_executed_reference(golden, kappa_run):
    """The keyword matcher must select the same legal-prompt titles from the
    same two datasets as the executed reference."""
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["combined_kappa_results"])
    ours = kappa_run["combined_frame"]
    assert set(ours["Prompt"]) == set(ref["Prompt"])
    ref = ref.set_index("Prompt")
    ours = ours.set_index("Prompt")
    for title in ref.index:
        # Perturbation-side kappa feeding the combination: same tight band
        # (NaN in the executed reference = its degenerate constant-decision
        # behavior; ours is defined as ~0 there).
        r = float(ref.loc[title, "Perturbation Kappa"])
        o = float(ours.loc[title, "Perturbation Kappa"])
        if np.isnan(r):
            assert abs(o) < 0.05
        else:
            assert _close(o, r, abs_tol=0.02)


# ---------------------------------------------------------------------------
# survey_analysis_consolidated.py — full survey pipeline on identical inputs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def survey_run(reference_data_dir, tmp_path_factory):
    from lir_tpu.survey.run import run_survey_pipeline

    out = tmp_path_factory.mktemp("survey")
    run_survey_pipeline(
        Path(reference_data_dir) / "word_meaning_survey_results.csv",
        Path(reference_data_dir) / "instruct_model_comparison_results.csv",
        Path(reference_data_dir) / "model_comparison_results.csv",
        out, n_bootstrap_standard=300, n_bootstrap_small=100,
        n_bootstrap_large=1000, run_simulated_individuals=False)
    return {
        "consolidated": json.loads(
            (out / "consolidated_analysis_results.json").read_text()),
        "bootstrap": json.loads(
            (out / "llm_human_agreement_bootstrap.json").read_text()),
    }


def test_exclusion_stats_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["exclusion_stats"]
    ours = survey_run["consolidated"]["exclusion_stats"]
    for k in ("attention_failed", "duration_excluded", "identical_excluded",
              "final_count", "total_excluded"):
        assert int(ours[k]) == int(ref[k]), k
    assert _close(ours["median_duration"], ref["median_duration"], abs_tol=1e-9)


def test_question_matching_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["matching_stats"]
    ours = survey_run["consolidated"]["matching_stats"]
    assert ours["n_matched"] == ref["n_matched"] == 50
    assert ours["matches"] == ref["matches"]


def test_human_llm_correlation_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["human_llm_correlation"]
    ours = survey_run["consolidated"]["human_llm_correlation"]
    assert ours["n_questions"] == ref["n_questions"]
    assert _close(ours["correlation"], ref["correlation"])
    assert _close(ours["p_value"], ref["p_value"], rel=0.05)
    assert _close(ours["ci_lower"], ref["ci_lower"], abs_tol=CI_ABS)
    assert _close(ours["ci_upper"], ref["ci_upper"], abs_tol=CI_ABS)


def test_per_item_agreement_vs_executed_reference(golden, survey_run):
    for side in ("human", "llm"):
        ref = golden["survey_consolidated"]["per_item_agreement"][side]
        ours = survey_run["consolidated"]["per_item_agreement"][side]
        assert ours["n_items"] == ref["n_items"]
        assert _close(ours["overall_mean"], ref["overall_mean"])
        assert _close(ours["overall_std"], ref["overall_std"], rel=0.05)


def test_meta_correlation_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["meta_correlation"]
    ours = survey_run["consolidated"]["meta_correlation"]
    assert ours["n_matched_items"] == ref["n_matched_items"]
    assert _close(ours["correlation"], ref["correlation"], abs_tol=1e-4)
    assert _close(ours["human_mean_agreement"], ref["human_mean_agreement"])
    assert _close(ours["llm_mean_agreement"], ref["llm_mean_agreement"])


def test_cross_prompt_correlations_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["cross_prompt_correlations"]
    ours = survey_run["consolidated"]["cross_prompt_correlations"]
    for side in ("human", "llm"):
        assert ours[side]["n_pairs"] == ref[side]["n_pairs"]
        assert _close(ours[side]["mean_correlation"],
                      ref[side]["mean_correlation"], abs_tol=1e-6)
    assert _close(ours["difference"]["mean_difference"],
                  ref["difference"]["mean_difference"], abs_tol=BOOT_ABS)


# ---------------------------------------------------------------------------
# analyze_llm_agreement_simple_bootstrap.py — D9 on identical inputs
# ---------------------------------------------------------------------------

def test_bootstrap_agreement_vs_executed_reference(golden, survey_run):
    ref_models = {r["model"]: r for r in
                  golden["llm_human_agreement_bootstrap"]["model_results"]}
    our_models = {r["model"]: r for r in
                  survey_run["bootstrap"]["model_results"]}
    assert set(our_models) == set(ref_models)
    for name, ref in ref_models.items():
        ours = our_models[name]
        assert ours["model_type"] == ref["model_type"]
        # Bootstrap means concentrate around the deterministic full-sample
        # metric; both sides must agree to BOOT_ABS despite different RNGs.
        assert _close(ours["mae_mean"], ref["mae_mean"], abs_tol=BOOT_ABS)
        assert _close(ours["pearson_r_mean"], ref["pearson_r_mean"],
                      abs_tol=2 * BOOT_ABS)


def test_overall_comparison_vs_executed_reference(golden, survey_run):
    ref = golden["llm_human_agreement_bootstrap"]["overall_comparison"]
    ours = survey_run["bootstrap"]["overall_comparison"]
    assert ours["base_models_count"] == ref["base_models_count"]
    assert ours["instruct_models_count"] == ref["instruct_models_count"]
    for metric in ("mae",):
        r, o = ref["metrics"][metric], ours["metrics"][metric]
        assert _close(o["base_mean"], r["base_mean"], abs_tol=BOOT_ABS)
        assert _close(o["instruct_mean"], r["instruct_mean"], abs_tol=BOOT_ABS)
        assert _close(o["difference"], r["difference"], abs_tol=2 * BOOT_ABS)


# ---------------------------------------------------------------------------
# analyze_perturbation_results.py — the 2,025-line per-model analyzer
# (VERDICT r3 #1 lead item). Identical input: the synthetic D6 (edge model
# included) after the same CSV round trip the sandbox staged.
# ---------------------------------------------------------------------------

PERT_MODELS = ["synthetic-scorer-v1", "synthetic-edge-v1"]


@pytest.fixture(scope="module")
def pert_analyzer_run(tmp_path_factory):
    from lir_tpu.analysis.perturbation import analyze_model
    from lir_tpu.data import synthetic

    out = tmp_path_factory.mktemp("pert")
    csv = out / "combined_results.csv"
    synthetic.synthetic_perturbation_frame().to_csv(csv, index=False)
    df = pd.read_csv(csv)
    return {
        model: analyze_model(
            df[df["Model"] == model].copy(), model,
            out / model.replace("-", "_"), make_figures=False)
        for model in PERT_MODELS
    }


def _golden_pert(golden, model, stem):
    if "analyze_perturbation_results" not in golden:
        pytest.skip("golden predates the perturbation-analyzer capture")
    return pd.DataFrame(golden["analyze_perturbation_results"][model][stem])


def _diff_frames(ours: pd.DataFrame, ref: pd.DataFrame, *, tight=(),
                 loose=(), loose_abs=0.0, exact=(), skip=()):
    """Column-wise diff of two artifact frames with per-column tolerance."""
    assert len(ours) == len(ref), (len(ours), len(ref))
    for col in ref.columns:
        if col in skip:
            continue
        r = ref[col].to_numpy()
        assert col in ours.columns, f"missing column {col!r}"
        o = ours[col].to_numpy()
        if col in exact:
            assert list(o) == list(r), col
        elif col in loose:
            np.testing.assert_allclose(
                o.astype(float), r.astype(float), atol=loose_abs,
                rtol=0.05, equal_nan=True, err_msg=col)
        elif col in tight or np.issubdtype(r.dtype, np.number):
            np.testing.assert_allclose(
                o.astype(float), r.astype(float), rtol=1e-6, atol=1e-9,
                equal_nan=True, err_msg=col)
        else:
            assert list(o) == list(r), col


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_summary_stats_vs_executed_reference(
        golden, pert_analyzer_run, model):
    ref = _golden_pert(golden, model, "summary_statistics")
    _diff_frames(pert_analyzer_run[model]["summary"], ref)


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_normality_vs_executed_reference(
        golden, pert_analyzer_run, model):
    ref = _golden_pert(golden, model, "normality_test_results")
    _diff_frames(pert_analyzer_run[model]["normality"], ref,
                 exact=("Column", "KS Normal (p>0.05)",
                        "AD Normal (stat<crit)"))


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_truncated_fit_vs_executed_reference(
        golden, pert_analyzer_run, model):
    """The zero/one-inflated truncated-normal MC fit. Deterministic columns
    (observed moments, inflation proportions) hold the 1% gate; the fitted/
    simulated moments carry two independent 100k-sample MC runs -> abs
    tolerance scaled by the column's units (confidence rows are 0-100)."""
    ref = _golden_pert(golden, model, "truncated_normal_test_results")
    ours = pert_analyzer_run[model]["truncated"]
    assert len(ours) == len(ref)
    key = ["Prompt", "Column"]
    ref = ref.sort_values(key).reset_index(drop=True)
    ours = ours.sort_values(key).reset_index(drop=True)
    assert list(ours["Prompt"]) == list(ref["Prompt"])
    assert list(ours["Column"]) == list(ref["Column"])
    for i in range(len(ref)):
        scale = 100.0 if float(ref.loc[i, "Observed Mean"]) > 1.5 else 1.0
        for col in ("Observed Mean", "Observed Std Dev", "Interior Mean",
                    "Interior Std Dev"):
            assert _close(ours.loc[i, col], ref.loc[i, col],
                          rel=1e-6, abs_tol=1e-9 * scale), (i, col)
        for col in ("Zero Proportion", "One Proportion"):
            assert _close(ours.loc[i, col], ref.loc[i, col],
                          rel=0, abs_tol=1e-12), (i, col)
        for col in ("Underlying Normal Mean", "Underlying Normal Std Dev",
                    "Simulated Mean", "Simulated Std Dev"):
            assert _close(ours.loc[i, col], ref.loc[i, col],
                          rel=0.05, abs_tol=0.05 * scale), (i, col)
        assert _close(ours.loc[i, "KS Statistic"], ref.loc[i, "KS Statistic"],
                      rel=0, abs_tol=0.08), i


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_kappa_vs_executed_reference(
        golden, pert_analyzer_run, model):
    ref = _golden_pert(golden, model, "cohens_kappa_results")
    ours = pert_analyzer_run[model]["kappa"]
    for theirs, mine in (("Cohen's Kappa", "Cohen's Kappa"),
                         ("Observed Agreement", "Observed Agreement"),
                         ("Expected Agreement", "Expected Agreement")):
        assert _close(ours[mine].iloc[0], ref[theirs].iloc[0],
                      rel=1e-9, abs_tol=1e-9), theirs


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_output_compliance_vs_executed_reference(
        golden, pert_analyzer_run, model):
    """Integer counts per compliance category must match EXACTLY — the edge
    model plants non-compliant first tokens, non-compliant full responses,
    unparseable payloads, and ast-literal payloads in known proportions."""
    ref = _golden_pert(golden, model, "output_compliance_results")
    _diff_frames(pert_analyzer_run[model]["compliance"], ref,
                 exact=("Prompt", "Expected_First_Tokens", "Total_Samples",
                        "First_Token_Compliant", "First_Token_Non_Compliant",
                        "Conditional_Subsequent_Compliant",
                        "Conditional_Subsequent_Non_Compliant"))


@pytest.mark.parametrize("model", PERT_MODELS)
def test_perturbation_confidence_compliance_vs_executed_reference(
        golden, pert_analyzer_run, model):
    """Every confidence error category (float / text / out-of-range /
    other) counted exactly as the executed reference counts them."""
    ref = _golden_pert(golden, model, "confidence_compliance_results")
    _diff_frames(pert_analyzer_run[model]["confidence_compliance"], ref,
                 exact=("Prompt", "Total_Confidence_Samples",
                        "Confidence_Compliant", "Confidence_Non_Compliant",
                        "Float_Errors", "Text_Errors", "Out_Of_Range_Errors",
                        "Other_Errors"))


# ---------------------------------------------------------------------------
# analyze_results_base_versus_instruct.py — C28 on the committed D2
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bvi_run(reference_data_dir):
    from lir_tpu.analysis.base_vs_instruct import family_differences

    df = pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
    return family_differences(df)


def test_base_versus_instruct_stats_vs_executed_reference(golden, bvi_run):
    if "base_versus_instruct" not in golden:
        pytest.skip("golden predates the base-versus-instruct capture")
    ref = pd.DataFrame(
        golden["base_versus_instruct"]["model_rel_prob_statistics"])
    ours = bvi_run["statistics"]
    assert set(ours["Model_Family"]) == set(ref["Model_Family"])
    ref = ref.set_index("Model_Family")
    ours = ours.set_index("Model_Family")
    for fam in ref.index:
        for col in ("Mean", "Std_Dev", "Lower_CI_95", "Upper_CI_95",
                    "CI_Width"):
            assert _close(ours.loc[fam, col], ref.loc[fam, col],
                          rel=1e-6, abs_tol=1e-9), (fam, col)
        assert int(ours.loc[fam, "Num_Samples"]) == int(
            ref.loc[fam, "Num_Samples"])


def test_base_versus_instruct_heatmap_vs_executed_reference(golden, bvi_run):
    if "base_versus_instruct" not in golden:
        pytest.skip("golden predates the base-versus-instruct capture")
    ref = pd.DataFrame(
        golden["base_versus_instruct"]["prompt_rel_prob_heatmap_data"]
    ).set_index("Prompt")
    pivot = bvi_run["prompt_differences"].pivot_table(
        index="Prompt", columns="Model Family", values="Difference",
        aggfunc="mean")
    assert set(pivot.columns) == set(ref.columns)
    assert set(pivot.index) == set(ref.index)
    for fam in ref.columns:
        np.testing.assert_allclose(
            pivot.loc[ref.index, fam].to_numpy(dtype=float),
            ref[fam].to_numpy(dtype=float), rtol=1e-6, atol=1e-9,
            equal_nan=True, err_msg=fam)


# ---------------------------------------------------------------------------
# analyze_llm_human_agreement.py / analyze_base_vs_instruct_vs_human.py /
# analyze_model_family_differences.py / calculate_correlation_pvalues.py
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def detailed_and_mapping(golden, reference_data_dir, tmp_path_factory):
    """The exact D7 + question mapping the sandbox staged: detailed survey
    stats from OUR loader, mapping from the executed consolidated run."""
    from lir_tpu.survey import loader

    out = tmp_path_factory.mktemp("detailed")
    sdf, qcols = loader.load_survey(
        Path(reference_data_dir) / "word_meaning_survey_results.csv")
    clean, _ = loader.apply_exclusions(sdf, qcols)
    path = out / "survey_analysis_detailed.json"
    loader.write_survey_detailed(clean, qcols, path)
    detailed = json.loads(path.read_text())
    mapping = golden["survey_consolidated"]["matching_stats"]["matches"]
    return detailed, mapping


def test_llm_human_agreement_vs_executed_reference(
        golden, reference_data_dir, detailed_and_mapping):
    """C39 point metrics per model: deterministic on identical inputs."""
    if "llm_human_agreement" not in golden:
        pytest.skip("golden predates the llm-human-agreement capture")
    from lir_tpu.survey.human_llm import (analyze_all_models,
                                          human_averages_from_detailed)

    detailed, mapping = detailed_and_mapping
    ha = human_averages_from_detailed(detailed, mapping)
    instruct = pd.read_csv(
        f"{reference_data_dir}/instruct_model_comparison_results.csv")
    base = pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
    ours = {r["model"]: r for r in analyze_all_models(ha, instruct, base)}
    ref = {r["model"]: r for r in golden["llm_human_agreement"]["model_results"]}
    assert set(ours) == set(ref)
    for name, r in ref.items():
        o = ours[name]
        assert o["n_questions"] == r["n_questions"], name
        for metric in ("mae", "rmse", "mape", "pearson_r"):
            assert _close(o[metric], r[metric], rel=1e-6, abs_tol=1e-9), (
                name, metric)


def test_base_vs_instruct_vs_human_vs_executed_reference(
        golden, reference_data_dir, detailed_and_mapping):
    """The proportion-based correlation table (model_human_correlations.csv)."""
    if "base_vs_instruct_vs_human" not in golden:
        pytest.skip("golden predates this capture")
    from lir_tpu.survey.proportions import (
        human_proportions_from_detailed, model_vs_proportion_correlations)

    detailed, mapping = detailed_and_mapping
    props = human_proportions_from_detailed(detailed, mapping)
    instruct = pd.read_csv(
        f"{reference_data_dir}/instruct_model_comparison_results.csv")
    ours = {r["model"]: r
            for r in model_vs_proportion_correlations(instruct, props)}
    ref = pd.DataFrame(golden["base_vs_instruct_vs_human"])
    assert set(ours) == set(ref["model"])
    for _, r in ref.iterrows():
        o = ours[r["model"]]
        if np.isnan(r["pearson_r"]):
            # The executed reference keeps NaN-probability rows (Qwen: 20)
            # and constant inputs, so pearsonr returns NaN for 3 models.
            # Ours drops NaN rows first (documented fix): Qwen gets a
            # defined r on its 30 valid questions; the two constant-input
            # models stay NaN on both sides.
            assert (np.isnan(o["pearson_r"])
                    or o["n_questions"] < int(r["n_questions"]))
            continue
        assert o["n_questions"] == int(r["n_questions"])
        for col in ("pearson_r", "pearson_p", "spearman_r", "mae"):
            assert _close(o[col], r[col], rel=1e-6, abs_tol=1e-9), (
                r["model"], col)


def test_family_differences_vs_executed_reference(golden):
    """C42 on the SAME bootstrap payload the reference script consumed. The
    summary table (CI-combination arithmetic) is deterministic up to the
    report's printed rounding; the seed-42 MC section uses independent RNGs
    on each side -> moment-level tolerances."""
    if "family_differences" not in golden:
        pytest.skip("golden predates the family-differences capture")
    from lir_tpu.survey.family_differences import analyze_family_differences

    res = analyze_family_differences(
        golden["llm_human_agreement_bootstrap"], KEY)
    by_upper = {fam.upper(): v for fam, v in res.items()
                if not isinstance(v, dict) or not v.get("missing")}

    table = golden["family_differences"]["summary_table"]
    assert table, "summary table parsed empty"
    for fam, metrics in table.items():
        ours_fam = by_upper[fam.upper()]
        for metric, r in metrics.items():
            o = ours_fam[metric.lower()]
            # printed at 4dp (1dp for MAPE): tolerance = print rounding.
            tol = 0.06 if metric == "MAPE" else 6e-4
            assert _close(o["difference"], r["diff"], rel=0, abs_tol=tol)
            assert _close(o["ci_combined_range"][0], r["ci"][0], rel=0,
                          abs_tol=tol)
            assert _close(o["ci_combined_range"][1], r["ci"][1], rel=0,
                          abs_tol=tol)
            assert o["significant_combined_range"] == r["significant"], (
                fam, metric)

    mc = golden["family_differences"]["mc_differences"]
    assert mc, "MC section parsed empty"
    for fam, metrics in mc.items():
        ours_fam = by_upper[fam.upper()]
        for metric, r in metrics.items():
            o = ours_fam[metric.lower()]["mc_difference"]
            tol = 1.0 if metric == "MAPE" else 0.01
            assert _close(o["difference_mean"], r["diff"], rel=0, abs_tol=tol)
            assert _close(o["ci_lower"], r["ci"][0], rel=0, abs_tol=2 * tol)
            assert _close(o["ci_upper"], r["ci"][1], rel=0, abs_tol=2 * tol)
            assert _close(o["p_value"], r["p"], rel=0, abs_tol=0.03)


def test_correlation_pvalues_vs_executed_reference(golden, reference_data_dir):
    """C43: pairwise r/p for every LLM pair plus the distribution-level
    comparison, deterministic on identical inputs."""
    if "correlation_pvalues" not in golden:
        pytest.skip("golden predates the correlation-pvalues capture")
    from lir_tpu.survey.pvalues import run_pvalue_analysis

    instruct = pd.read_csv(
        f"{reference_data_dir}/instruct_model_comparison_results.csv")
    base = pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
    from lir_tpu.survey.loader import load_survey

    survey_df, _ = load_survey(
        Path(reference_data_dir) / "word_meaning_survey_results.csv")
    res = run_pvalue_analysis(instruct, base, survey_df)

    ref_pairs = {frozenset((c["model1"], c["model2"])): c
                 for c in golden["correlation_pvalues"]["llm_correlations"]}
    our_pairs = {frozenset((c["model1"], c["model2"])): c
                 for c in res["llm_correlations"]}
    # The executed reference silently DROPS every base model: its concat
    # materializes a relative_prob column that is NaN for all D1 rows, and
    # the row reader prefers it (:42,57-58) — only the 45 instruct pairs
    # survive. Ours fixes that defect (pvalues.py docstring), so our pair
    # set is a strict superset; every surviving reference pair must match
    # exactly, and every extra pair must involve a base-CSV model.
    assert set(ref_pairs) <= set(our_pairs)
    base_models = set(
        pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
        ["model"].unique())
    for k in set(our_pairs) - set(ref_pairs):
        assert k & base_models, k
    for k, r in ref_pairs.items():
        o = our_pairs[k]
        assert o["n_questions"] == r["n_questions"], k
        if r["correlation"] is None:
            assert not np.isfinite(o["correlation"])
            continue
        # Our masked-Pearson kernel runs in float32 (jax default): agree to
        # ~1e-5 absolute — three orders below the 1% BASELINE gate.
        assert _close(o["correlation"], r["correlation"], rel=1e-5,
                      abs_tol=1e-5), k
        assert _close(o["p_value"], r["p_value"], rel=1e-3,
                      abs_tol=1e-6), k

    assert len(res["human_correlations"]) == (
        golden["correlation_pvalues"]["n_human_correlations"])
    cmp_ref = golden["correlation_pvalues"]["comparison"]
    cmp_ours = res["comparison"]
    # Human stats: identical inputs on both sides -> the tight gate.
    for k in ("mean", "std", "median"):
        assert _close(cmp_ours["human_stats"][k], cmp_ref["human_stats"][k],
                      rel=1e-5, abs_tol=1e-9), k
    assert (cmp_ours["human_stats"]["n_pairs"]
            == cmp_ref["human_stats"]["n_pairs"])
    assert (cmp_ours["human_stats"]["significant_pairs"]
            == cmp_ref["human_stats"]["significant_pairs"])
    # LLM-side stats + distribution tests: the reference's are computed on
    # its defect-truncated 45-pair list. Recompute the same statistics over
    # exactly those pairs using OUR correlation values — deterministic, so
    # the tight gate applies.
    import scipy.stats as sps

    llm_vals = [our_pairs[k]["correlation"] for k in ref_pairs
                if np.isfinite(our_pairs[k]["correlation"])]
    human_vals = [c["correlation"] for c in res["human_correlations"]
                  if np.isfinite(c["correlation"])]
    assert len(llm_vals) == cmp_ref["llm_stats"]["n_pairs"]
    assert _close(np.mean(llm_vals), cmp_ref["llm_stats"]["mean"],
                  rel=1e-6, abs_tol=1e-9)
    assert _close(np.std(llm_vals), cmp_ref["llm_stats"]["std"],
                  rel=1e-6, abs_tol=1e-9)
    assert _close(np.median(llm_vals), cmp_ref["llm_stats"]["median"],
                  rel=1e-6, abs_tol=1e-9)
    mw = sps.mannwhitneyu(llm_vals, human_vals, alternative="two-sided")
    ks = sps.ks_2samp(llm_vals, human_vals)
    tt = sps.ttest_ind(llm_vals, human_vals)
    for name, stat in (("mann_whitney", mw.statistic),
                       ("kolmogorov_smirnov", ks.statistic),
                       ("t_test", tt.statistic)):
        assert _close(stat, cmp_ref["comparison_tests"][name]["statistic"],
                      rel=1e-5, abs_tol=1e-9), name
    pooled = np.sqrt((np.std(llm_vals) ** 2 + np.std(human_vals) ** 2) / 2)
    d = (np.mean(llm_vals) - np.mean(human_vals)) / pooled
    assert _close(d, cmp_ref["comparison_tests"]["effect_size"]["cohens_d"],
                  rel=1e-5, abs_tol=1e-9)


# ---------------------------------------------------------------------------
# bootstrap_confidence_intervals.py — C38 (captured only by the full, slow
# run of tools/reference_differential.py; skipped against older goldens)
# ---------------------------------------------------------------------------

def test_simulated_bootstrap_vs_executed_reference(
        golden, reference_data_dir, detailed_and_mapping):
    if "bootstrap_confidence_intervals" not in golden:
        pytest.skip("golden captured with LIR_SKIP_SLOW_BOOTSTRAP=1")
    from lir_tpu.survey.simulated import run_simulated_bootstrap

    detailed, mapping = detailed_and_mapping
    base = pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
    res = run_simulated_bootstrap(
        base, mapping, detailed, KEY, n_bootstrap=2000)
    ref = golden["bootstrap_confidence_intervals"]

    for side in ("base", "instruct"):
        r, o = ref["overall_results"][side], res["overall_results"][side]
        assert _close(o["mean"], r["mean"], rel=0, abs_tol=BOOT_ABS), side
        assert _close(o["ci_lower"], r["ci_lower"], rel=0,
                      abs_tol=CI_ABS), side
        assert _close(o["ci_upper"], r["ci_upper"], rel=0,
                      abs_tol=CI_ABS), side
    r, o = ref["overall_results"]["difference"], res["overall_results"]["difference"]
    assert _close(o["mean"], r["mean"], rel=0, abs_tol=BOOT_ABS)

    ref_models = ref["per_model_results"]
    our_models = res["per_model_results"]
    assert set(our_models) == set(ref_models)
    for name, r in ref_models.items():
        o = our_models[name]
        assert o["type"] == r["type"], name
        assert _close(o["mean"], r["mean"], rel=0, abs_tol=0.05), name
