"""Differential parity against the EXECUTED reference (VERDICT r1 #2).

tools/reference_differential.py ran the reference's own analysis scripts
(model_comparison_graph.py, calculate_cohens_kappa.py,
survey_analysis_consolidated.py, analyze_llm_agreement_simple_bootstrap.py)
on the committed data CSVs + the pinned synthetic D6 + our regenerated D7,
capturing every numeric artifact into tests/golden/reference_executed.json.
These tests recompute the same quantities with lir_tpu's pipelines from the
IDENTICAL inputs and diff them under the BASELINE ≤1% gate (deterministic
point estimates) or a CI-width tolerance (bootstrap quantities — the two
sides use different RNGs by design; SURVEY.md §7 hard part 6).
"""

import json
from pathlib import Path

import jax
import numpy as np
import pandas as pd
import pytest

GOLDEN_PATH = Path(__file__).parent / "golden" / "reference_executed.json"
KEY = jax.random.PRNGKey(7)

REL = 0.01          # the ≤1% gate for deterministic point estimates
BOOT_ABS = 0.03     # |Δ| tolerance for independently-resampled bootstrap means
CI_ABS = 0.06       # |Δ| tolerance for CI endpoints


@pytest.fixture(scope="module")
def golden():
    if not GOLDEN_PATH.exists():
        pytest.skip("run tools/reference_differential.py first")
    return json.loads(GOLDEN_PATH.read_text())


@pytest.fixture(scope="module")
def instruct_df(reference_data_dir):
    df = pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")
    df = df[~df["model"].str.contains("opt-iml-1.3b")]
    return df[~df["model"].str.contains("mistral", case=False)]


def _close(a, b, rel=REL, abs_tol=0.0):
    a, b = float(a), float(b)
    if np.isnan(a) and np.isnan(b):
        return True
    return abs(a - b) <= max(abs_tol, rel * abs(b))


# ---------------------------------------------------------------------------
# model_comparison_graph.py — correlation suite + aggregate kappa
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("method", ["pearson", "spearman"])
def test_correlation_suite_vs_executed_reference(golden, instruct_df, method):
    from lir_tpu.stats import bootstrap_correlation_matrix

    ref = golden["model_comparison_graph"][method]
    pivot = instruct_df.pivot_table(
        index="prompt", columns="model", values="relative_prob")
    pivot = pivot[ref["models"]]            # reference column order
    res = bootstrap_correlation_matrix(
        pivot.values, KEY, n_bootstrap=500, method=method)

    # Deterministic point estimates: the ≤1% gate.
    assert _close(res["mean_correlation"], ref["mean_correlation"], abs_tol=1e-4)
    assert _close(res["median_correlation"], ref["median_correlation"], abs_tol=1e-4)
    assert _close(res["std_correlation"], ref["std_correlation"], abs_tol=1e-4)
    assert _close(res["min_correlation"], ref["min_correlation"], abs_tol=1e-4)
    assert _close(res["max_correlation"], ref["max_correlation"], abs_tol=1e-4)
    np.testing.assert_allclose(
        np.asarray(res["correlation_matrix"]),
        np.asarray(ref["correlation_matrix"]), rtol=REL, atol=1e-6)
    # Bootstrap CIs: different resampling RNGs -> width-level tolerance.
    for lo_hi, ours in (("mean_ci", res["mean_ci"]),
                        ("median_ci", res["median_ci"])):
        assert _close(ours[0], ref[lo_hi][0], abs_tol=CI_ABS)
        assert _close(ours[1], ref[lo_hi][1], abs_tol=CI_ABS)


def test_aggregate_kappa_vs_executed_reference(golden, instruct_df):
    from lir_tpu.stats import aggregate_kappa

    ref = golden["model_comparison_graph"]["aggregate_kappa"]
    pivot = instruct_df.pivot_table(
        index="prompt", columns="model", values="relative_prob")
    binary = (pivot.dropna() > 0.5).astype(int).values
    res = aggregate_kappa(binary, KEY, n_boot=1000)

    assert res["n_models"] == int(ref["n_models"])
    assert _close(res["aggregate_kappa"], ref["aggregate_kappa"], abs_tol=1e-6)
    assert _close(res["observed_agreement"], ref["observed_agreement"], abs_tol=1e-6)
    assert _close(res["chance_agreement"], ref["chance_agreement"], abs_tol=1e-6)
    assert _close(res["kappa_ci_lower"], ref["kappa_ci_lower"], abs_tol=CI_ABS)
    assert _close(res["kappa_ci_upper"], ref["kappa_ci_upper"], abs_tol=CI_ABS)


# ---------------------------------------------------------------------------
# calculate_cohens_kappa.py — two-source kappa combiner on identical inputs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kappa_run(reference_data_dir, tmp_path_factory):
    from lir_tpu.analysis.kappa_combined import run_kappa_analysis
    from lir_tpu.data import synthetic

    out = tmp_path_factory.mktemp("kappa")
    d6 = synthetic.write_synthetic_d6(out / "combined_results.csv")
    return run_kappa_analysis(
        Path(reference_data_dir) / "instruct_model_comparison_results.csv",
        d6, out, n_bootstrap=1000, make_figures=False)


def test_perturbation_self_kappa_vs_executed_reference(golden, kappa_run):
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["perturbation_kappa_metrics"])
    ours = kappa_run["perturbation_kappa"].set_index("prompt")
    ref = ref.set_index("prompt")
    assert set(ours.index) == set(ref.index)
    for prompt in ref.index:
        r, o = ref.loc[prompt], ours.loc[prompt]
        assert int(o["n_variations"]) == int(r["n_variations"])
        # agree_percent is deterministic on identical inputs: exact-ish.
        assert _close(o["agree_percent"], r["agree_percent"], abs_tol=1e-9)
        # self-kappa: 1000 independent bootstrap pairs on each side. The
        # statistic's expectation is ~0 by construction (unpaired samples);
        # both sides must land in the same tight band. On near-constant
        # decisions sklearn's cohen_kappa_score is 0/0 -> the executed
        # reference records NaN (its degenerate-input behavior); ours
        # defines those resamples as 0 — accept a finite near-zero value.
        if np.isnan(r["self_kappa"]):
            assert abs(float(o["self_kappa"])) < 0.05
        else:
            assert _close(o["self_kappa"], r["self_kappa"], abs_tol=0.02)


def test_model_agree_percent_vs_executed_reference(golden, kappa_run):
    """agree_percent/n_models per word-meaning prompt match the executed
    reference. Its avg_pairwise_kappa is NaN for every prompt (the
    single-observation cohen_kappa_score defect, calculate_cohens_kappa.py:
    124-127, executed and confirmed) — a documented defect-to-fix, so our
    real-valued kappa column is intentionally NOT diffed against it."""
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["model_kappa_metrics"])
    assert ref["avg_pairwise_kappa"].isna().all()  # the defect, as executed
    ours = kappa_run["model_kappa"].set_index("prompt")
    ref = ref.set_index("prompt")
    shared = set(ours.index) & set(ref.index)
    assert len(shared) == len(ref)
    for prompt in shared:
        assert int(ours.loc[prompt, "n_models"]) == int(ref.loc[prompt, "n_models"])
        assert _close(ours.loc[prompt, "agree_percent"],
                      ref.loc[prompt, "agree_percent"], abs_tol=1e-9)


def test_combined_kappa_prompt_matching_vs_executed_reference(golden, kappa_run):
    """The keyword matcher must select the same legal-prompt titles from the
    same two datasets as the executed reference."""
    ref = pd.DataFrame(golden["calculate_cohens_kappa"]["combined_kappa_results"])
    ours = kappa_run["combined_frame"]
    assert set(ours["Prompt"]) == set(ref["Prompt"])
    ref = ref.set_index("Prompt")
    ours = ours.set_index("Prompt")
    for title in ref.index:
        # Perturbation-side kappa feeding the combination: same tight band
        # (NaN in the executed reference = its degenerate constant-decision
        # behavior; ours is defined as ~0 there).
        r = float(ref.loc[title, "Perturbation Kappa"])
        o = float(ours.loc[title, "Perturbation Kappa"])
        if np.isnan(r):
            assert abs(o) < 0.05
        else:
            assert _close(o, r, abs_tol=0.02)


# ---------------------------------------------------------------------------
# survey_analysis_consolidated.py — full survey pipeline on identical inputs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def survey_run(reference_data_dir, tmp_path_factory):
    from lir_tpu.survey.run import run_survey_pipeline

    out = tmp_path_factory.mktemp("survey")
    run_survey_pipeline(
        Path(reference_data_dir) / "word_meaning_survey_results.csv",
        Path(reference_data_dir) / "instruct_model_comparison_results.csv",
        Path(reference_data_dir) / "model_comparison_results.csv",
        out, n_bootstrap_standard=300, n_bootstrap_small=100,
        n_bootstrap_large=1000, run_simulated_individuals=False)
    return {
        "consolidated": json.loads(
            (out / "consolidated_analysis_results.json").read_text()),
        "bootstrap": json.loads(
            (out / "llm_human_agreement_bootstrap.json").read_text()),
    }


def test_exclusion_stats_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["exclusion_stats"]
    ours = survey_run["consolidated"]["exclusion_stats"]
    for k in ("attention_failed", "duration_excluded", "identical_excluded",
              "final_count", "total_excluded"):
        assert int(ours[k]) == int(ref[k]), k
    assert _close(ours["median_duration"], ref["median_duration"], abs_tol=1e-9)


def test_question_matching_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["matching_stats"]
    ours = survey_run["consolidated"]["matching_stats"]
    assert ours["n_matched"] == ref["n_matched"] == 50
    assert ours["matches"] == ref["matches"]


def test_human_llm_correlation_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["human_llm_correlation"]
    ours = survey_run["consolidated"]["human_llm_correlation"]
    assert ours["n_questions"] == ref["n_questions"]
    assert _close(ours["correlation"], ref["correlation"])
    assert _close(ours["p_value"], ref["p_value"], rel=0.05)
    assert _close(ours["ci_lower"], ref["ci_lower"], abs_tol=CI_ABS)
    assert _close(ours["ci_upper"], ref["ci_upper"], abs_tol=CI_ABS)


def test_per_item_agreement_vs_executed_reference(golden, survey_run):
    for side in ("human", "llm"):
        ref = golden["survey_consolidated"]["per_item_agreement"][side]
        ours = survey_run["consolidated"]["per_item_agreement"][side]
        assert ours["n_items"] == ref["n_items"]
        assert _close(ours["overall_mean"], ref["overall_mean"])
        assert _close(ours["overall_std"], ref["overall_std"], rel=0.05)


def test_meta_correlation_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["meta_correlation"]
    ours = survey_run["consolidated"]["meta_correlation"]
    assert ours["n_matched_items"] == ref["n_matched_items"]
    assert _close(ours["correlation"], ref["correlation"], abs_tol=1e-4)
    assert _close(ours["human_mean_agreement"], ref["human_mean_agreement"])
    assert _close(ours["llm_mean_agreement"], ref["llm_mean_agreement"])


def test_cross_prompt_correlations_vs_executed_reference(golden, survey_run):
    ref = golden["survey_consolidated"]["cross_prompt_correlations"]
    ours = survey_run["consolidated"]["cross_prompt_correlations"]
    for side in ("human", "llm"):
        assert ours[side]["n_pairs"] == ref[side]["n_pairs"]
        assert _close(ours[side]["mean_correlation"],
                      ref[side]["mean_correlation"], abs_tol=1e-6)
    assert _close(ours["difference"]["mean_difference"],
                  ref["difference"]["mean_difference"], abs_tol=BOOT_ABS)


# ---------------------------------------------------------------------------
# analyze_llm_agreement_simple_bootstrap.py — D9 on identical inputs
# ---------------------------------------------------------------------------

def test_bootstrap_agreement_vs_executed_reference(golden, survey_run):
    ref_models = {r["model"]: r for r in
                  golden["llm_human_agreement_bootstrap"]["model_results"]}
    our_models = {r["model"]: r for r in
                  survey_run["bootstrap"]["model_results"]}
    assert set(our_models) == set(ref_models)
    for name, ref in ref_models.items():
        ours = our_models[name]
        assert ours["model_type"] == ref["model_type"]
        # Bootstrap means concentrate around the deterministic full-sample
        # metric; both sides must agree to BOOT_ABS despite different RNGs.
        assert _close(ours["mae_mean"], ref["mae_mean"], abs_tol=BOOT_ABS)
        assert _close(ours["pearson_r_mean"], ref["pearson_r_mean"],
                      abs_tol=2 * BOOT_ABS)


def test_overall_comparison_vs_executed_reference(golden, survey_run):
    ref = golden["llm_human_agreement_bootstrap"]["overall_comparison"]
    ours = survey_run["bootstrap"]["overall_comparison"]
    assert ours["base_models_count"] == ref["base_models_count"]
    assert ours["instruct_models_count"] == ref["instruct_models_count"]
    for metric in ("mae",):
        r, o = ref["metrics"][metric], ours["metrics"][metric]
        assert _close(o["base_mean"], r["base_mean"], abs_tol=BOOT_ABS)
        assert _close(o["instruct_mean"], r["instruct_mean"], abs_tol=BOOT_ABS)
        assert _close(o["difference"], r["difference"], abs_tol=2 * BOOT_ABS)
