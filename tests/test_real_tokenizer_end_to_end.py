"""End-to-end with a REAL tokenizer and a real safetensors checkpoint
(VERDICT r1 missing #1 / #9).

The zero-egress image ships no pretrained checkpoints, so this builds a
GENUINE HF checkpoint locally: a byte-level BPE tokenizer trained in-process
with the `tokenizers` library (real merges, real leading-space " Yes"
semantics, saved as tokenizer.json) plus a random-weight GPT-2 model saved
with save_pretrained. `factory.load_engine` then runs UNMOCKED —
AutoConfig/AutoTokenizer/safetensors from disk — and the scored
relative_prob is compared against a torch implementation of the reference's
measurement rule (compare_base_vs_instruct.py:185-305) on the same
checkpoint.

A second, skip-gated test runs the same comparison against a REAL
pretrained checkpoint when one is provided via LIR_TPU_CHECKPOINT_DIR
(see README "Real-checkpoint smoke test" for the fetch-once recipe).
"""

import os
from pathlib import Path

import numpy as np
import pytest
import torch

from lir_tpu.config import RuntimeConfig
from lir_tpu.data.prompts import format_instruct_prompt
from lir_tpu.models.factory import load_engine

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py


@pytest.fixture(scope="module")
def bpe_checkpoint(tmp_path_factory):
    """Train a real byte-level BPE tokenizer + save a GPT-2 checkpoint
    (shared builder: tools/tiny_checkpoints.py, also used by the staged
    reference-scorer oracle so both sides score identical weights)."""
    from tiny_checkpoints import build_bpe_gpt2

    path = tmp_path_factory.mktemp("real_ckpt") / "bpe-gpt2"
    return build_bpe_gpt2(path)


def _reference_yes_no(model, tokenizer, prompt: str, yes_id: int, no_id: int,
                      max_look_ahead: int = 10):
    """The reference's measurement rule in torch
    (compare_base_vs_instruct.py:185-305): greedy generate with scores, scan
    the first 10 generated positions, read P(yes)/P(no) at the first
    position whose top-2 contains either target id; fallback position 0."""
    ids = torch.tensor([tokenizer(prompt).input_ids])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=max_look_ahead + 2, do_sample=False,
            output_scores=True, return_dict_in_generate=True,
            pad_token_id=tokenizer.eos_token_id)
    position = 0
    for p in range(min(max_look_ahead, len(out.scores))):
        probs = torch.softmax(out.scores[p][0], dim=-1)
        top2 = torch.topk(probs, k=2).indices.tolist()
        if yes_id in top2 or no_id in top2:
            position = p
            break
    probs = torch.softmax(out.scores[position][0], dim=-1)
    yes_p, no_p = float(probs[yes_id]), float(probs[no_id])
    return yes_p, no_p, yes_p / (yes_p + no_p)


def test_unmocked_load_and_score_matches_torch(bpe_checkpoint):
    path, torch_model, fast = bpe_checkpoint

    # max_seq_len 256: the formatted few-shot prompt is ~134 BPE tokens and
    # buckets are powers of two — 128 would silently left-truncate while the
    # torch reference scores the full prompt.
    engine = load_engine(path, RuntimeConfig(batch_size=4, max_new_tokens=12,
                                             max_seq_len=256))
    # The real tokenizer resolved the LEADING-SPACE ids (hard part #1).
    assert engine.yes_id == fast(" Yes", add_special_tokens=False).input_ids[0]
    assert engine.no_id == fast(" No", add_special_tokens=False).input_ids[0]
    assert engine.yes_id != engine.no_id

    prompt = format_instruct_prompt('Is a "screenshot" a "photograph"?')
    row = engine.score_prompts([prompt])[0]
    ref_yes, ref_no, ref_rel = _reference_yes_no(
        torch_model, fast, prompt, engine.yes_id, engine.no_id)

    assert abs(row.yes_prob - ref_yes) < 2e-3
    assert abs(row.no_prob - ref_no) < 2e-3
    # The BASELINE gate: relative_prob within 1%.
    assert abs(row.relative_prob - ref_rel) <= 0.01 * max(ref_rel, 1e-9)


def test_d2_schema_row_from_real_checkpoint(bpe_checkpoint, tmp_path):
    """Full stage-3 slice (SURVEY.md §7): load -> score -> D2-schema CSV."""
    import pandas as pd
    from lir_tpu.data import schemas
    from lir_tpu.engine.sweep import run_word_meaning_sweep

    path, _, _ = bpe_checkpoint
    engine = load_engine(path, RuntimeConfig(batch_size=4, max_new_tokens=12,
                                             max_seq_len=128))
    rows = run_word_meaning_sweep(
        engine, "bpe-gpt2", "instruct",
        ['Is a "screenshot" a "photograph"?', 'Is a "drone" an "aircraft"?'],
        format_instruct_prompt)
    out = tmp_path / "instruct_model_comparison_results.csv"
    schemas.write_instruct_comparison_csv(rows, out)
    df = pd.read_csv(out)
    assert list(df.columns) == list(schemas.INSTRUCT_COMPARISON_COLUMNS)
    assert len(df) == 2
    assert df["relative_prob"].between(0, 1).all()


@pytest.mark.skipif(
    not os.environ.get("LIR_TPU_CHECKPOINT_DIR"),
    reason="set LIR_TPU_CHECKPOINT_DIR to a local HF checkpoint "
           "(README: real-checkpoint smoke test)")
def test_real_pretrained_checkpoint_smoke():
    """BASELINE config 3 with actual pretrained weights, when available:
    load the checkpoint, score one word-meaning prompt, compare
    relative_prob against the reference rule run in torch."""
    import transformers as tf

    ckpt = Path(os.environ["LIR_TPU_CHECKPOINT_DIR"])
    engine = load_engine(ckpt, RuntimeConfig(batch_size=4, max_new_tokens=12))
    tokenizer = tf.AutoTokenizer.from_pretrained(ckpt, local_files_only=True)
    torch_model = tf.AutoModelForCausalLM.from_pretrained(
        ckpt, local_files_only=True, torch_dtype=torch.float32).eval()

    prompt = format_instruct_prompt('Is a "screenshot" a "photograph"?')
    row = engine.score_prompts([prompt])[0]
    _, _, ref_rel = _reference_yes_no(
        torch_model, tokenizer, prompt, engine.yes_id, engine.no_id)
    assert abs(row.relative_prob - ref_rel) <= 0.01 * max(abs(ref_rel), 1e-9)


# ---------------------------------------------------------------------------
# Sentencepiece-style (Metaspace/Unigram) family — llama/mistral/t5/baichuan
# resolve "▁Yes", not " Yes"-as-bytelevel (VERDICT r2 missing #1;
# compare_base_vs_instruct.py:244-247 vs :208-209)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sp_checkpoint(tmp_path_factory):
    """Build a GENUINE sentencepiece-style tokenizer (Unigram model +
    Metaspace pre-tokenizer, the llama/t5 scheme) + a random-weight Llama
    checkpoint saved with save_pretrained (shared builder:
    tools/tiny_checkpoints.py; the Unigram vocab is constructed explicitly
    so the metaspace resolution under test is deterministic)."""
    from tiny_checkpoints import build_sp_llama

    path = tmp_path_factory.mktemp("real_ckpt_sp") / "sp-llama"
    return build_sp_llama(path)


def test_sentencepiece_metaspace_yes_no_resolution(sp_checkpoint):
    """tokens.yes_no_ids must land on the METASPACE pieces ("▁Yes"/"▁No")
    for a sentencepiece-family tokenizer — the exact mis-resolution SURVEY
    §7 hard part 1 warns silently corrupts every downstream number."""
    path, _, fast = sp_checkpoint
    engine = load_engine(path, RuntimeConfig(batch_size=4, max_new_tokens=12,
                                             max_seq_len=128))
    assert fast.convert_ids_to_tokens(engine.yes_id) == "▁Yes"
    assert fast.convert_ids_to_tokens(engine.no_id) == "▁No"
    assert engine.yes_id != engine.no_id
    # The leading-space and bare forms both resolve to the metaspace piece
    # (real llama behavior: sentencepiece prepends ▁ at word starts).
    assert engine.yes_id == fast(" Yes", add_special_tokens=False).input_ids[0]
    assert engine.yes_id == fast("Yes", add_special_tokens=False).input_ids[0]
    # Integer-token table picked up the metaspace digit pieces (confidence
    # readout path).
    ids, vals = engine.digit_table
    sp85 = fast(" 85", add_special_tokens=False).input_ids
    assert len(sp85) == 1 and sp85[0] in set(int(i) for i in ids)
    assert vals[list(ids).index(sp85[0])] == 85.0


def test_sentencepiece_unmocked_score_matches_torch(sp_checkpoint):
    """Same differential as the byte-BPE test, through the metaspace ids:
    UNMOCKED factory.load_engine vs the reference rule run in torch on the
    identical checkpoint."""
    path, torch_model, fast = sp_checkpoint
    engine = load_engine(path, RuntimeConfig(batch_size=4, max_new_tokens=12,
                                             max_seq_len=128))
    prompt = format_instruct_prompt('Is a "tomato" a "vegetable"?')
    row = engine.score_prompts([prompt])[0]
    ref_yes, ref_no, ref_rel = _reference_yes_no(
        torch_model, fast, prompt, engine.yes_id, engine.no_id)
    assert abs(row.yes_prob - ref_yes) < 2e-3
    assert abs(row.no_prob - ref_no) < 2e-3
    assert abs(row.relative_prob - ref_rel) <= 0.01 * max(ref_rel, 1e-9)


def test_digit_stop_mask_and_early_stop_sweep_equivalence(sp_checkpoint,
                                                          tmp_path):
    """The confidence early stop on a REAL metaspace tokenizer: the digit
    mask marks exactly the digit-bearing pieces, and a sweep with the early
    stop records the SAME Confidence Value / Weighted Confidence / binary
    probs as one without it — only decode steps are saved, never answers."""
    import dataclasses

    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.sweep import run_perturbation_sweep

    path, _, fast = sp_checkpoint
    rt = RuntimeConfig(batch_size=2, max_new_tokens=8, max_seq_len=128)
    engine = load_engine(path, rt)
    from lir_tpu.engine import tokens as tok

    assert engine.digit_stop_mask is not None
    mask = np.asarray(engine.digit_stop_mask)
    sp85 = fast(" 85", add_special_tokens=False).input_ids[0]
    assert mask[sp85] & tok.STOP_PURE and mask[sp85] & tok.STOP_PREFIX
    assert mask[fast("100", add_special_tokens=False).input_ids[0]] & tok.STOP_PURE
    assert not (mask[engine.yes_id] & tok.STOP_PURE)
    assert not (mask[engine.no_id] & tok.STOP_PURE)
    assert mask[fast.eos_token_id] & tok.STOP_TRANSPARENT

    lp = (LegalPrompt(
        main="Is a tomato a vegetable?",
        response_format="Answer either 'Yes' or 'No'.",
        target_tokens=("Yes", "No"),
        confidence_format="Give a confidence number from 0 to 100"),)
    perts = (["Is a tomato really a vegetable?",
              "Would a tomato count as a vegetable?",
              "Is a tomato considered a vegetable?"],)

    def sweep(early, sub):
        eng = load_engine(path, dataclasses.replace(rt, sweep_early_stop=early))
        d = tmp_path / sub
        d.mkdir()
        return run_perturbation_sweep(eng, "sp-llama", lp, perts,
                                      d / "d6.xlsx")

    rows_es, rows_no = sweep(True, "es"), sweep(False, "no")
    assert len(rows_es) == len(rows_no) == 4
    for a, b in zip(rows_es, rows_no):
        assert a.confidence_value == b.confidence_value
        np.testing.assert_allclose(a.weighted_confidence,
                                   b.weighted_confidence, rtol=1e-5)
        np.testing.assert_allclose(a.token_1_prob, b.token_1_prob, rtol=1e-5)
        # The early-stopped text is the full text truncated at the row's
        # stop point (EOS fill decodes away) — never different content.
        assert b.model_confidence_response.startswith(
            a.model_confidence_response)


def test_sentencepiece_perturbation_sweep_shared_prefix(sp_checkpoint,
                                                       tmp_path):
    """The shared-prefix sweep path (LCP token split + suffix extension)
    with a REAL metaspace tokenizer: D6 rows come out finite and the
    binary probs equal the plain (non-shared) fused scoring path."""
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.sweep import run_perturbation_sweep

    path, _, _ = sp_checkpoint
    engine = load_engine(path, RuntimeConfig(batch_size=2, max_new_tokens=8,
                                             max_seq_len=128))
    lp = (LegalPrompt(
        main="Is a tomato a vegetable?",
        response_format="Answer either 'Yes' or 'No'.",
        target_tokens=("Yes", "No"),
        confidence_format="Give a confidence number from 0 to 100"),)
    perts = (["Is a tomato really a vegetable?",
              "Would a tomato count as a vegetable?",
              "Is a tomato considered a vegetable?"],)
    rows = run_perturbation_sweep(engine, "sp-llama", lp, perts,
                                  tmp_path / "d6.xlsx")
    assert len(rows) == 4
    assert all(np.isfinite(r.token_1_prob) for r in rows)
    assert all(np.isfinite(r.weighted_confidence) for r in rows)
    # Cross-check one cell against the non-shared scoring path.
    import jax.numpy as jnp
    from lir_tpu.engine import score as score_mod
    t1 = np.full((2,), engine.yes_id, np.int32)
    t2 = np.full((2,), engine.no_id, np.int32)
    fused = engine.decode_fused([rows[0].full_rephrased_prompt] * 2, t1, t2,
                                max_new_tokens=4)
    ref = score_mod.readout_from_fused(fused, jnp.asarray(t1),
                                       jnp.asarray(t2), scan_positions=1)
    np.testing.assert_allclose(rows[0].token_1_prob, float(ref.yes_prob[0]),
                               rtol=1e-4, atol=1e-6)
