"""Speculative scoring decode (engine/spec.py +
generate.greedy_decode_fused_shared_spec): acceptance edge cases pinned
against the sequential path.

The parity contract under test: every CONSUMED result — the emitted
token streams, position-0 probabilities, top-2 stream, top-20 logprob
map, weighted confidence, and hence every sweep row and serve payload —
is bitwise-identical to the sequential scan's, for ANY draft quality
(zero-accept, full-accept, ragged per-row accepts, stop conditions
inside the draft window, corrupted drafts). Interior per-step float
rows match within float tolerance (the verify window's longer cache
extent regroups reduction lanes — the same bar PR-7's fused-vs-dense
kernels cleared).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.engine import generate, scheduler as sched, spec as spec_mod
from lir_tpu.engine import tokens as tok
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, paged
from lir_tpu.models.registry import ModelConfig

VOCAB = 256
CFG = ModelConfig(name="spec-tiny", vocab_size=VOCAB, hidden_size=32,
                  n_layers=1, n_heads=2, n_kv_heads=2,
                  intermediate_size=64, max_seq_len=512)
PARAMS = decoder.init_params(CFG, jax.random.PRNGKey(3))
TOKZ = FakeTokenizer(vocab=VOCAB)

CONSUMED_FIELDS = ("generated", "top2_ids", "topk_logprobs", "topk_ids",
                   "weighted_confidence")


def _assert_consumed_bitwise(spec_out, seq_out):
    """Every consumed readout bitwise; per-step floats to tolerance."""
    for f in CONSUMED_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(spec_out, f)),
            np.asarray(getattr(seq_out, f)), err_msg=f)
    for f in ("p_yes", "p_no"):
        a = np.asarray(getattr(spec_out, f))
        b = np.asarray(getattr(seq_out, f))
        np.testing.assert_array_equal(a[:, 0], b[:, 0],
                                      err_msg=f"{f}[pos0]")
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-7,
                                   err_msg=f)


# ---------------------------------------------------------------------------
# generate-level: controlled drafts straight into the spec executable
# ---------------------------------------------------------------------------

def _rows(seed=0, B=3, plen=24, sfx=4):
    rng = np.random.default_rng(seed)
    # Reserved low ids (pad etc.) excluded; distinct tokens so the
    # n-gram drafter has no accidental matches unless a test wants them.
    ids = rng.choice(np.arange(8, VOCAB), size=(B, plen + 2 * sfx),
                     replace=False if B * (plen + 2 * sfx) < VOCAB - 8
                     else True)
    prefixes = [list(map(int, ids[r, :plen])) for r in range(B)]
    sfx_a = [list(map(int, ids[r, plen:plen + sfx])) for r in range(B)]
    sfx_b = [list(map(int, ids[r, plen + sfx:])) for r in range(B)]
    return prefixes, sfx_a, sfx_b


def _shared_args(prefixes, sfx_a_ids, sfx_b_ids, bucket=32, sb=8):
    pad = 0
    prefix, prefix_mask = tok.right_pad_ids(prefixes, bucket, pad)
    sfx_a, sfx_a_mask = tok.right_pad_ids(sfx_a_ids, sb, pad)
    sfx_b, sfx_b_mask = tok.right_pad_ids(sfx_b_ids, sb, pad)
    B = len(prefixes)
    yes = np.full((B,), 7, np.int32)
    no = np.full((B,), 9, np.int32)
    digit_ids = np.arange(10, 16, dtype=np.int32)
    digit_vals = np.arange(6, dtype=np.float32) * 10.0
    return (jnp.asarray(prefix), jnp.asarray(prefix_mask),
            jnp.asarray(sfx_a), jnp.asarray(sfx_a_mask),
            jnp.asarray(sfx_b), jnp.asarray(sfx_b_mask),
            jnp.asarray(yes), jnp.asarray(no), jnp.asarray(digit_ids),
            jnp.asarray(digit_vals))


def _seq(args, Ta=4, Tb=8, **kw):
    return jax.device_get(generate.greedy_decode_fused_shared(
        PARAMS, CFG, *args, max_new_a=Ta, max_new_b=Tb, **kw))


def _spec_inputs(prefixes, sfx_a_ids, sfx_b_ids, Ta, Tb, bucket=32, sb=8,
                 draft_a=None, draft_b=None):
    B = len(prefixes)

    def ctx_of(sfx_ids, budget):
        rows = [p + s for p, s in zip(prefixes, sfx_ids)]
        width = bucket + sb + budget
        ctx = np.zeros((B, width), np.int32)
        lens = np.zeros((B,), np.int32)
        for r, row in enumerate(rows):
            ctx[r, :len(row)] = row
            lens[r] = len(row)
        return jnp.asarray(ctx), jnp.asarray(lens)

    def drafts(d, budget):
        toks = np.zeros((B, budget), np.int32)
        lens = np.zeros((B,), np.int32)
        if d is not None:
            for r, row in enumerate(d):
                n = min(len(row), budget)
                toks[r, :n] = row[:n]
                lens[r] = n
        return jnp.asarray(toks), jnp.asarray(lens)

    ca, cal = ctx_of(sfx_a_ids, Ta)
    cb, cbl = ctx_of(sfx_b_ids, Tb)
    da, dal = drafts(draft_a, Ta)
    db, dbl = drafts(draft_b, Tb)
    return (ca, cal, da, dal, cb, cbl, db, dbl)


def _spec(args, spec_inputs, Ta=4, Tb=8, k=4, **kw):
    out = generate.greedy_decode_fused_shared_spec(
        PARAMS, CFG, *args, *spec_inputs, max_new_a=Ta, max_new_b=Tb,
        spec_k=k, **kw)
    return jax.device_get(out)


def test_zero_accept_bitwise_and_forward_parity():
    """Deterministically-wrong tree drafts (sequential stream + 1): the
    verifier rejects everything, results stay bitwise, and the window
    scan runs exactly as many forwards as the sequential scan."""
    prefixes, sa, sb = _rows(seed=1)
    args = _shared_args(prefixes, sa, sb)
    seq_a, seq_b = _seq(args)
    wrong_a = (np.asarray(seq_a.generated) + 1) % VOCAB
    wrong_b = (np.asarray(seq_b.generated) + 1) % VOCAB
    si = _spec_inputs(prefixes, sa, sb, 4, 8, draft_a=wrong_a,
                      draft_b=wrong_b)
    out_a, out_b, sp_a, sp_b = _spec(args, si)
    _assert_consumed_bitwise(out_a, seq_a)
    _assert_consumed_bitwise(out_b, seq_b)
    for sp, T in ((sp_a, 4), (sp_b, 8)):
        assert int(np.sum(sp.accepted)) == 0
        assert int(sp.chunks) == int(sp.seq_steps) == T


def test_full_accept_bitwise_and_2x_fewer_forwards():
    """Perfect tree drafts (the sequential stream itself): every window
    accepts whole, the confidence scan retires in ceil(T/k) forwards —
    >= 2x fewer than sequential — and results stay bitwise."""
    prefixes, sa, sb = _rows(seed=2)
    args = _shared_args(prefixes, sa, sb)
    seq_a, seq_b = _seq(args)
    si = _spec_inputs(prefixes, sa, sb, 4, 8,
                      draft_a=np.asarray(seq_a.generated),
                      draft_b=np.asarray(seq_b.generated))
    out_a, out_b, sp_a, sp_b = _spec(args, si)
    _assert_consumed_bitwise(out_a, seq_a)
    _assert_consumed_bitwise(out_b, seq_b)
    assert int(np.sum(sp_b.accepted)) == int(np.sum(sp_b.drafted))
    assert int(sp_b.seq_steps) == 8
    assert int(sp_b.chunks) * 2 <= int(sp_b.seq_steps)
    assert int(sp_b.chunks) == 2           # ceil(8 / 4)
    # All accepted drafts came from the tree lane.
    assert int(sp_b.accepted[0]) == int(np.sum(sp_b.accepted))


def test_ragged_per_row_accept_lengths_in_one_batch():
    """Row 1 drafts garbage while rows 0/2 draft perfectly: per-row
    accept lengths diverge inside one window scan and every row's
    results still match the sequential batch bitwise."""
    prefixes, sa, sb = _rows(seed=3)
    args = _shared_args(prefixes, sa, sb)
    seq_a, seq_b = _seq(args)
    da = np.asarray(seq_a.generated).copy()
    db = np.asarray(seq_b.generated).copy()
    da[1] = (da[1] + 3) % VOCAB
    db[1] = (db[1] + 3) % VOCAB
    out_a, out_b, sp_a, sp_b = _spec(
        args, _spec_inputs(prefixes, sa, sb, 4, 8, draft_a=da, draft_b=db))
    _assert_consumed_bitwise(out_a, seq_a)
    _assert_consumed_bitwise(out_b, seq_b)
    # Mixed accepts: more than zero, fewer than everything.
    acc = int(np.sum(sp_b.accepted))
    assert 0 < acc < int(np.sum(sp_b.drafted))
    # The slow row gates the window scan: forwards land between the
    # full-accept floor and the sequential count.
    assert 2 <= int(sp_b.chunks) <= 8


def _eos_stop_case(digit_stop: bool):
    """Arm a stop rule chosen so it triggers INSIDE a draft window: run
    the unstopped sequential scan, pick the confidence branch's step-1
    emission of row 0 as eos/digit-terminator, then compare stopped
    sequential vs stopped speculative (perfect drafts) bitwise."""
    prefixes, sa, sb = _rows(seed=4)
    args = _shared_args(prefixes, sa, sb)
    free_a, free_b = _seq(args)
    eos_id = int(np.asarray(free_b.generated)[0, 1])
    cls = np.zeros((VOCAB,), np.int32)
    if digit_stop:
        # Step-0 emissions open a standalone digit run; anything
        # non-pure terminates it -> rows stop after their "integer".
        for t in np.asarray(free_b.generated)[:, 0]:
            cls[int(t)] = tok.STOP_PURE | tok.STOP_PREFIX | tok.STOP_ENDS_WORD
    stop = jnp.asarray(cls)
    kw = dict(stop_mask_a=stop, stop_mask_b=stop,
              eos_id=jnp.int32(eos_id))
    seq_a, seq_b = _seq(args, **kw)
    # Draft the STOPPED stream (what a warm tree would have recorded).
    out_a, out_b, sp_a, sp_b = _spec(
        args, _spec_inputs(prefixes, sa, sb, 4, 8,
                           draft_a=np.asarray(seq_a.generated),
                           draft_b=np.asarray(seq_b.generated)),
        **kw)
    _assert_consumed_bitwise(out_a, seq_a)
    _assert_consumed_bitwise(out_b, seq_b)
    # The stop actually engaged: EOS fill appears in the stream.
    gen = np.asarray(seq_b.generated)
    assert (gen[0] == eos_id).any()
    return sp_b


def test_eos_inside_draft_window_bitwise():
    sp = _eos_stop_case(digit_stop=False)
    # Early stop saves sequential forwards too; speculation must not
    # run more than the sequential scan.
    assert int(sp.chunks) <= int(sp.seq_steps) + 1


def test_digit_stop_inside_draft_window_bitwise():
    _eos_stop_case(digit_stop=True)


def test_spec_out_accounting_identity():
    prefixes, sa, sb = _rows(seed=5)
    args = _shared_args(prefixes, sa, sb)
    seq_a, seq_b = _seq(args)
    out = _spec(args, _spec_inputs(prefixes, sa, sb, 4, 8,
                                   draft_a=np.asarray(seq_a.generated),
                                   draft_b=np.asarray(seq_b.generated)))
    _, _, sp_a, sp_b = out
    from lir_tpu.utils.profiling import SpecStats

    st = SpecStats()
    for sp in (sp_a, sp_b):
        st.add_branch(sp.drafted, sp.accepted, int(sp.chunks),
                      int(sp.seq_steps))
    assert st.drafted_tokens == st.accepted_tokens + st.rejected_tokens
    assert st.dispatches_saved == st.seq_forwards - st.decode_forwards
    assert 0.0 < st.accept_rate <= 1.0


# ---------------------------------------------------------------------------
# engine-level: drafting sources, warm repeats, fleet, faults
# ---------------------------------------------------------------------------

def _engine(spec_on=True, prefix=False, k=4, **kw):
    rt = RuntimeConfig(batch_size=4, max_seq_len=256, spec_decode=spec_on,
                       spec_k=k, piggyback_prefill=False,
                       prefix_cache=prefix, prefix_cache_pages=64, **kw)
    return ScoringEngine(PARAMS, CFG, TOKZ, rt)


def _prompts(n=4, seed=11):
    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()
    base = " ".join(rng.choice(words) for _ in range(30))
    bps = [f"{base} case {i} Answer Yes or No ." for i in range(n)]
    cps = [f"{base} case {i} Give a number 0 to 100 ." for i in range(n)]
    return bps, cps


def _dispatch(eng, bps, cps):
    B = len(bps)
    yes = np.full((B,), eng.yes_id, np.int32)
    no = np.full((B,), eng.no_id, np.int32)
    return jax.device_get(eng.decode_fused_shared(
        bps, cps, yes, no, new_tokens=4, conf_tokens=8, reuse_cache=True))


def test_radix_miss_ngram_fallback_bitwise():
    """No prefix cache -> no tree: drafts come from the n-gram lane
    only, and engine-level consumed results stay bitwise vs OFF."""
    bps, cps = _prompts(seed=13)
    on = _engine(True, prefix=False)
    off = _engine(False, prefix=False)
    r_on = _dispatch(on, bps, cps)
    r_off = _dispatch(off, bps, cps)
    for k in (0, 1):
        _assert_consumed_bitwise(r_on[k], r_off[k])
    on.spec_flush()
    s = on.spec_stats
    assert s.spec_dispatches == 1
    assert s.draft_tree == 0
    assert s.draft_ngram > 0


def test_warm_repeat_tree_drafts_2x_fewer_dispatches():
    """The headline: an identical repeat dispatch on a warm tree drafts
    every row's whole reply and verifies it in >= 2x fewer forwards,
    results bitwise vs the sequential engine warm AND cold."""
    bps, cps = _prompts(seed=17)
    on = _engine(True, prefix=True)
    off = _engine(False, prefix=True)
    with on._tok_lock:
        bin_ids = [TOKZ(p).input_ids for p in bps]
        conf_ids = [TOKZ(p).input_ids for p in cps]
    lcp = [tok.shared_prefix_len(a, b) for a, b in zip(bin_ids, conf_ids)]
    bucket = tok.pick_bucket([max(n, 1) for n in lcp], on.buckets)

    r1 = _dispatch(on, bps, cps)
    on.spec_record(bucket, bin_ids, np.asarray(r1[0].generated), len(bps))
    on.spec_record(bucket, conf_ids, np.asarray(r1[1].generated), len(bps))
    on.spec_flush()
    fwd1 = on.spec_stats.decode_forwards
    r2 = _dispatch(on, bps, cps)
    on.spec_flush()
    s = on.spec_stats
    warm_fwd = s.decode_forwards - fwd1
    warm_seq = s.seq_forwards - fwd1
    assert s.accepted_tree > 0
    assert warm_seq >= 2 * warm_fwd, (warm_seq, warm_fwd)

    o1 = _dispatch(off, bps, cps)
    o2 = _dispatch(off, bps, cps)
    for k in (0, 1):
        _assert_consumed_bitwise(r1[k], o1[k])
        _assert_consumed_bitwise(r2[k], o2[k])


def test_fleet_draft_parity_with_self_draft_and_sequential():
    """A fleet draft model (any weights) only changes SPEED: results are
    bitwise the sequential path's and the self-draft path's, and the
    draft tokens count into the fleet lane. A perfect drafter (the
    verifier itself) accepts everything."""
    dcfg = dataclasses.replace(CFG, name="spec-draft", n_layers=1)
    dparams = decoder.init_params(dcfg, jax.random.PRNGKey(23))
    bps, cps = _prompts(seed=19)

    off = _engine(False)
    self_draft = _engine(True)
    fleet = _engine(True, spec_draft_model="drafty")
    fleet.set_spec_draft(dparams, dcfg, "drafty")
    r_off = _dispatch(off, bps, cps)
    r_self = _dispatch(self_draft, bps, cps)
    r_fleet = _dispatch(fleet, bps, cps)
    for k in (0, 1):
        _assert_consumed_bitwise(r_fleet[k], r_off[k])
        _assert_consumed_bitwise(r_self[k], r_off[k])
    fleet.spec_flush()
    assert fleet.spec_stats.draft_fleet > 0
    assert fleet.spec_stats.draft_ngram == 0

    perfect = _engine(True, spec_draft_model="self")
    perfect.set_spec_draft(PARAMS, CFG, "self")
    r_p = _dispatch(perfect, bps, cps)
    for k in (0, 1):
        _assert_consumed_bitwise(r_p[k], r_off[k])
    perfect.spec_flush()
    s = perfect.spec_stats
    assert s.accepted_fleet == s.draft_fleet > 0
    assert s.seq_forwards >= 2 * s.decode_forwards


def test_draft_model_vocab_mismatch_refused():
    bad = dataclasses.replace(CFG, vocab_size=VOCAB // 2)
    eng = _engine(True)
    with pytest.raises(ValueError, match="vocab"):
        eng.set_spec_draft(PARAMS, bad, "bad")


def test_draft_corrupt_fault_costs_only_reverification():
    """Seeded draft_corrupt: corrupted tree drafts are rejected by the
    verifier — results bitwise vs the uncorrupted warm dispatch, and
    the rejection counter records the injection."""
    from lir_tpu import faults

    bps, cps = _prompts(seed=29)

    def warm_engine():
        eng = _engine(True, prefix=True)
        with eng._tok_lock:
            bin_ids = [TOKZ(p).input_ids for p in bps]
            conf_ids = [TOKZ(p).input_ids for p in cps]
        lcp = [tok.shared_prefix_len(a, b)
               for a, b in zip(bin_ids, conf_ids)]
        bucket = tok.pick_bucket([max(n, 1) for n in lcp], eng.buckets)
        r1 = _dispatch(eng, bps, cps)
        eng.spec_record(bucket, bin_ids, np.asarray(r1[0].generated),
                        len(bps))
        eng.spec_record(bucket, conf_ids, np.asarray(r1[1].generated),
                        len(bps))
        return eng

    clean = warm_engine()
    r_clean = _dispatch(clean, bps, cps)
    clean.spec_flush()
    assert clean.spec_stats.accepted_tree > 0  # warm drafts DID land

    eng = warm_engine()
    plan = faults.FaultPlan(seed=5, schedules={
        "draft": faults.SiteSchedule.draft_corrupt_at(0, rows=(0, 1))})
    faults.wrap_engine(eng, plan)
    r_bad = _dispatch(eng, bps, cps)
    eng.spec_flush()
    assert plan.injected("draft") == 1
    assert eng.spec_stats.rejected_tokens > 0
    for k in (0, 1):
        _assert_consumed_bitwise(r_bad[k], r_clean[k])


def test_fused_interpret_mode_parity():
    """The Pallas multi-query verify kernel (flash_decode_mq) under the
    interpreter: consumed results match the sequential fused path — the
    CPU proof of the route that runs compiled on the chip."""
    fcfg = dataclasses.replace(CFG, fused_decode=True)
    prev = decoder.FUSED_DECODE_INTERPRET_ON_CPU
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True
    try:
        bps, cps = _prompts(n=3, seed=31)
        yes = np.full((3,), 7, np.int32)
        no = np.full((3,), 9, np.int32)

        def run(spec_on):
            rt = RuntimeConfig(batch_size=4, max_seq_len=256,
                               spec_decode=spec_on, spec_k=3,
                               piggyback_prefill=False, fused_decode=True)
            eng = ScoringEngine(PARAMS, fcfg, TOKZ, rt)
            return jax.device_get(eng.decode_fused_shared(
                bps, cps, yes, no, new_tokens=3, conf_tokens=4,
                reuse_cache=True))

        r_on = run(True)
        r_off = run(False)
        for k in (0, 1):
            _assert_consumed_bitwise(r_on[k], r_off[k])
    finally:
        decoder.FUSED_DECODE_INTERPRET_ON_CPU = prev


# ---------------------------------------------------------------------------
# the radix tree's token history (continuation / record_tail)
# ---------------------------------------------------------------------------

def _tree(pages=32, ps=4):
    pool = paged.KVPagePool(pages, ps)
    from lir_tpu.engine.prefix_tree import RadixPrefixCache

    return RadixPrefixCache(pool)


def test_continuation_replays_recorded_tail():
    tree = _tree()
    ids = list(range(20, 30))                       # 10 tokens, ps=4
    tree.record_tail(0, ids, [51, 52, 53])
    assert tree.continuation(0, ids, 8) == (51, 52, 53)
    assert tree.continuation(0, ids, 2) == (51, 52)
    # Different remainder -> no match; different bucket -> namespace miss.
    assert tree.continuation(0, ids[:-1], 8) == ()
    assert tree.continuation(1, ids, 8) == ()
    # Most-recent record wins for the same remainder.
    tree.record_tail(0, ids, [60, 61])
    assert tree.continuation(0, ids, 8) == (60, 61)


def test_continuation_descends_cached_page_keys():
    """A longer sequence cached as pages makes the tree itself predict
    the shorter prompt's continuation — no tail record needed."""
    tree = _tree()
    long_ids = list(range(40, 56))                  # 4 full pages
    start, pages = tree.plan_insert(0, long_ids)
    assert start == 0 and len(pages) == 4
    probe = long_ids[:6]                            # 1 page + 2 remainder
    cont = tree.continuation(0, probe, 6)
    assert cont == tuple(long_ids[6:12])
    # Page descent composes with a recorded tail at the deep node.
    tree.record_tail(0, long_ids, [91, 92])
    assert tree.continuation(0, long_ids, 4) == (91, 92)


def test_record_tail_caps_and_refusals():
    tree = _tree()
    ids = list(range(8))
    assert not tree.record_tail(0, ids, [])         # nothing to record
    assert not tree.record_tail(0, ids, [1] * 600)  # overlong refusal
    root_ids = list(range(8, 12))
    for i in range(40):                             # LRU cap per node
        tree.record_tail(0, root_ids + [100 + i], [i], max_tails=8)
    node = tree._root(0)
    assert len(node.tails) <= 8


def test_continuation_probe_takes_no_references():
    tree = _tree()
    ids = list(range(70, 82))
    tree.plan_insert(0, ids)
    before = list(tree.pool.refcount)
    tree.record_tail(0, ids, [5, 6])
    tree.continuation(0, ids, 4)
    assert list(tree.pool.refcount) == before


# ---------------------------------------------------------------------------
# pricing + planning satellites
# ---------------------------------------------------------------------------

def test_scheduler_spec_pricing_and_headroom():
    # Default (non-spec) pricing is byte-identical to the pre-spec model.
    assert sched.decode_token_cost(True) == sched.DECODE_TOKEN_COST_FUSED
    assert sched.decode_token_cost(False) == sched.DECODE_TOKEN_COST_UNFUSED
    assert sched.decode_token_cost(True, True) == sched.DECODE_TOKEN_COST_SPEC
    base = sched.bucket_cost(4, 128, 8, 12)
    assert base == sched.bucket_cost(4, 128, 8, 12, spec_decode=False)
    spec_cost = sched.bucket_cost(4, 128, 8, 12, spec_decode=True)
    assert spec_cost < base
    assert (base - spec_cost) == 4 * 12 * (
        sched.DECODE_TOKEN_COST_FUSED - sched.DECODE_TOKEN_COST_SPEC)
    # Widened watchdog seed for SPECULATING engines: a zero-accept
    # dispatch that degenerates to the UNFUSED sequential cost stays
    # inside a spec-calibrated seed; non-spec engines keep the original
    # fused/unfused spread (their scenarios' deadlines are unchanged).
    assert (sched.watchdog_seed_headroom(spec_decode=True)
            == sched.DECODE_TOKEN_COST_UNFUSED / sched.DECODE_TOKEN_COST_SPEC)
    assert (sched.watchdog_seed_headroom()
            == sched.DECODE_TOKEN_COST_UNFUSED
            / sched.DECODE_TOKEN_COST_FUSED)
    assert (sched.watchdog_seed_headroom(True) * sched.DECODE_TOKEN_COST_SPEC
            >= sched.DECODE_TOKEN_COST_UNFUSED)
    # The engine's own watchdog picks the spec-aware seed.
    assert (_engine(True).watchdog.seed_headroom
            == sched.watchdog_seed_headroom(True))
    assert (_engine(False).watchdog.seed_headroom
            == sched.watchdog_seed_headroom(False))


def test_plan_specs_covers_spec_variants_per_bucket_batch_k():
    from lir_tpu.engine import compile_plan
    from lir_tpu.utils.profiling import OccupancyStats

    planner = sched.RaggedScheduler(tok.bucket_ladder(256), 4,
                                    group_cells=False,
                                    stats=OccupancyStats())
    items = []
    rng = np.random.default_rng(0)
    for n in (30, 30, 30, 30, 60, 60, 60, 60):
        ids = [int(x) for x in rng.integers(8, VOCAB, size=n)]
        items.append(sched.SweepItem(cell=None, bin_ids=tuple(ids + [1]),
                                     conf_ids=tuple(ids + [2]),
                                     lcp=n))
    dispatches = planner.schedule(items)
    specs = compile_plan.plan_specs(dispatches, 4, 4, 8, False, spec_k=4)
    spec_specs = [s for s in specs if s.spec_k]
    assert spec_specs, "no speculative executables planned"
    assert all(s.spec_k == 4 and not s.spec_draft for s in spec_specs)
    # One spec variant per planned sequential shared shape.
    seq_shared = [s for s in specs if s.kind == "shared" and not s.spec_k]
    assert len(spec_specs) == len(seq_shared)


def test_spec_stats_in_metrics_registry():
    from lir_tpu.observe.registry import STATS_SCHEMA, engine_registry
    from lir_tpu.utils.profiling import SpecStats

    eng = _engine(True)
    snap = engine_registry(eng).snapshot()
    assert "spec" in snap["sources"]
    assert snap["sources"]["spec"]["type"] == "SpecStats"
    schema = set(STATS_SCHEMA["SpecStats"])
    public = {f.name for f in dataclasses.fields(SpecStats)
              if not f.name.startswith("_")}
    assert schema == public


# ---------------------------------------------------------------------------
# sweep-level: kill/resume with speculation ON folds bitwise (PR-9)
# ---------------------------------------------------------------------------

def test_kill_resume_with_spec_on_accum_bitwise(tmp_path):
    """A mid-sweep kill with speculation ON: the resumed run's streaming
    accumulator is bitwise an uninterrupted spec-ON run's — and that
    one is bitwise a spec-OFF run's (speculation is invisible to the
    PR-9 lattice)."""
    from pathlib import Path

    from lir_tpu import faults
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep

    rng = np.random.default_rng(43)
    words = ("coverage policy flood water damage claim insurer "
             "premium exclusion peril").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(8), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([text(8) for _ in range(7)],)

    def engine(spec_on):
        return _engine(spec_on)

    def accum(path):
        return stream_mod.load_accum(
            Path(path).with_suffix(stream_mod.ACCUM_SUFFIX))

    run_perturbation_sweep(engine(True), "spec", lp, perts,
                           tmp_path / "on.csv", checkpoint_every=4)
    run_perturbation_sweep(engine(False), "spec", lp, perts,
                           tmp_path / "off.csv", checkpoint_every=4)
    acc_on, acc_off = accum(tmp_path / "on.csv"), accum(tmp_path / "off.csv")
    for f in ("filled", "rel", "conf", "dec"):
        np.testing.assert_array_equal(getattr(acc_on, f),
                                      getattr(acc_off, f), err_msg=f)

    eng = engine(True)
    plan = faults.FaultPlan(seed=13, schedules={
        "dispatch": faults.SiteSchedule.kill_at(1)},
        stats=eng.fault_stats)
    faults.wrap_engine(eng, plan)
    out = tmp_path / "killed.csv"
    with pytest.raises(faults.InjectedPreemption):
        run_perturbation_sweep(eng, "spec", lp, perts, out,
                               checkpoint_every=4)
    run_perturbation_sweep(engine(True), "spec", lp, perts, out,
                           checkpoint_every=4)
    acc = accum(out)
    for f in ("filled", "rel", "conf", "dec"):
        np.testing.assert_array_equal(getattr(acc, f),
                                      getattr(acc_on, f), err_msg=f)
