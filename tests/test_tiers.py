"""Tiered KV + weight store (serve/tiers.py): demote to host DRAM and
disk instead of dying, restart-warm serving.

Pins the PR's load-bearing claims:

- demote -> promote round-trips are BITWISE for bf16 and int8
  (payload+scale) KV pages: a demotion is an export kept on the
  ladder, a promotion is the ordinary checksummed paged-warm import,
  so promoted pages decode exactly like never-demoted ones;
- the three-tier residency ladder: host-budget overflow spills LRU
  entries to the disk tier; listener events announce every movement
  (the router's cluster-index tier dimension rides them);
- pinned pages REFUSE demotion (in-flight dispatch references win;
  TierStats.pin_refusals) and refcounts stay sane;
- the governor's evict_pages rung becomes a reversible demotion with a
  tier store attached — a rung walk down and back up moves pages off
  HBM and a later promote restores them bitwise;
- restart-warm: a fresh process reseeds its radix tree and its fleet
  weight staging from the disk tier, and re-serves bitwise;
- kill-mid-spill: a torn tail on the disk index JSONL is truncated at
  load (the manifest discipline), never a crash or a corrupt entry;
- the seeded chaos kinds: ``tier_corrupt`` is refused by the promote
  checksums (poisoned entry dropped, local re-prefill bitwise),
  ``disk_stall`` abandons the promote past ``disk_timeout_s`` and
  KEEPS the entry (a stall is not corruption) — zero wrong answers.
"""

import dataclasses

import jax
import numpy as np
import pytest

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import (GovernorConfig, RuntimeConfig, ServeConfig,
                            TierConfig)
from lir_tpu.engine import hbm
from lir_tpu.engine import tokens as tok
from lir_tpu.engine.fleet import ModelFleet
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, weights
from lir_tpu.models.quant import QuantTensor
from lir_tpu.models.registry import ModelConfig, tiny
from lir_tpu.serve import ScoringServer, ServeRequest
from lir_tpu.serve import migrate as mig
from lir_tpu.serve import tiers as tiers_mod

CFG = tiny("llama")
PARAMS = decoder.init_params(CFG, jax.random.PRNGKey(1))
TOKZ = FakeTokenizer(vocab=CFG.vocab_size)

FUSED_FIELDS = ("generated", "p_yes", "p_no", "top2_ids", "topk_logprobs",
                "topk_ids", "weighted_confidence")


def _engine(pages: int = 64, params=PARAMS, cfg=CFG, **kw):
    rt = RuntimeConfig(batch_size=4, max_seq_len=128,
                       aot_precompile=False, prefix_cache=True,
                       prefix_cache_pages=pages, **kw)
    return ScoringEngine(params, cfg, TOKZ, rt)


def _prompts(n, trunk_words=60, seed=0):
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster").split()
    rng = np.random.default_rng(seed)
    base = " ".join(rng.choice(words) for _ in range(trunk_words))
    bps = [f"{base} case {i} Answer Yes or No ." for i in range(n)]
    cps = [f"{base} case {i} Give a number 0 to 100 ." for i in range(n)]
    return bps, cps


def _prefixes(bps, cps):
    bin_ids = [TOKZ(p).input_ids for p in bps]
    conf_ids = [TOKZ(p).input_ids for p in cps]
    lcps = [tok.shared_prefix_len(a, b)
            for a, b in zip(bin_ids, conf_ids)]
    return [list(a[:n]) for a, n in zip(bin_ids, lcps)]


def _shared(engine, bps, cps, early_stop=False):
    engine.fresh_handoff()
    yes = np.full((len(bps),), TOKZ.YES, np.int32)
    no = np.full((len(bps),), TOKZ.NO, np.int32)
    return engine.decode_fused_shared(
        bps, cps, yes, no, new_tokens=4, conf_tokens=6,
        early_stop=early_stop, bucket=128, sfx_buckets_ab=(16, 16),
        reuse_cache=True, use_prefix_cache=True, n_real=len(bps))


def assert_fused_bitwise(a, b):
    for f in FUSED_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f"fused field {f}")


def _assert_pins_released(engine):
    pool = engine.prefix_cache.pool
    assert (pool.refcount >= 0).all()
    assert pool.refcount[1:].sum() == pool.pages_in_use


def _export_snapshot(engine, bucket, ids):
    """Canonical host bytes of a cached prefix (the page-level bitwise
    probe: chunked owned host copies + per-chunk CRCs)."""
    e = mig.export_prefix(engine, bucket, ids)
    assert e is not None
    return e


def _assert_exports_bitwise(a, b):
    """Real pages only: chunk padding gathers the pool's trash page 0,
    whose dead bytes legitimately differ across engines (blocks are
    (L, K, N, ps[, hd]) — pages on axis 2)."""
    assert a.n_pages == b.n_pages and a.start_tokens == b.start_tokens
    for (ha, ra), (hb, rb) in zip(a.chunks, b.chunks):
        assert ra == rb
        for la, lb in zip(jax.tree.leaves(ha), jax.tree.leaves(hb)):
            np.testing.assert_array_equal(np.asarray(la)[:, :, :ra],
                                          np.asarray(lb)[:, :, :rb])


def _store(tmp_path, **kw):
    cfg = TierConfig(enabled=True, disk_dir=str(tmp_path / "tier"), **kw)
    return tiers_mod.TieredPageStore(cfg)


# ---------------------------------------------------------------------------
# Demote -> promote round trips
# ---------------------------------------------------------------------------

def test_demote_promote_roundtrip_bitwise(tmp_path):
    """The headline: pages demoted through host AND disk come back
    through the paged-warm import and the next decode is bitwise the
    pre-demotion warm decode."""
    eng = _engine()
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    _shared(eng, bps, cps)                       # cold fill
    warm = _shared(eng, bps, cps)                # warm reference
    # Tiny host budget: demotion spills through the full ladder.
    store = _store(tmp_path, host_budget_mb=0.0001)
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    assert eng.prefix_cache.match_len(128, prefixes[0]) == 0
    s = store.stats.summary()
    assert s["pages_demoted"] > 0 and s["bytes_spilled"] > 0
    assert store.match_len(128, prefixes[0]) > 0
    assert store.promote(eng, 128, prefixes[0]) > 0
    got = _shared(eng, bps, cps)
    for k in (0, 1):
        assert_fused_bitwise(got[k], warm[k])
    _assert_pins_released(eng)
    s = store.stats.summary()
    assert s["pages_promoted"] > 0 and s["bytes_promoted"] > 0


def test_demote_promote_roundtrip_bitwise_int8_kv(tmp_path):
    """int8-KV flavor at the PAGE level: quantized payload+scale pages
    that crossed host+disk re-export bitwise-identical bytes."""
    cfg_q = dataclasses.replace(CFG, kv_cache_int8=True)
    params_q = decoder.init_params(cfg_q, jax.random.PRNGKey(7))
    bps, cps = _prompts(3, seed=3)
    prefixes = _prefixes(bps, cps)
    eng = _engine(params=params_q, cfg=cfg_q)
    eng.prefill_insert(128, prefixes)
    before = _export_snapshot(eng, 128, prefixes[0])
    store = _store(tmp_path, host_budget_mb=0.0001)
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    assert store.promote(eng, 128, prefixes[0]) > 0
    after = _export_snapshot(eng, 128, prefixes[0])
    _assert_exports_bitwise(before, after)
    _assert_pins_released(eng)


def test_three_tier_residency_spill_and_events(tmp_path):
    """Host budget overflow spills LRU entries down to disk; every
    movement fires a TierListener event (the cluster index's feed)."""
    eng = _engine()
    # Three DISTINCT trunks -> three disjoint radix paths -> three tier
    # entries (a shared trunk would collapse to one).
    for seed in (0, 1, 2):
        bps, cps = _prompts(1, seed=seed)
        eng.prefill_insert(128, _prefixes(bps, cps))
    # Budget sized for roughly one export: later demotions spill the
    # LRU entries to disk.
    store = _store(tmp_path, host_budget_mb=0.07)
    eng.attach_tiers(store)
    events = []
    store.add_listener(lambda ev, tier, b, ids: events.append((ev, tier)))
    assert store.demote(eng, n_pages=999)
    s = store.summary()
    assert s["disk_entries"] > 0            # something spilled
    assert s["demotions"].get("host", 0) > 0
    assert ("insert", "host") in events
    assert ("evict", "host") in events      # the spill's host departure
    assert ("insert", "disk") in events
    assert s["disk_bytes"] > 0
    # emit_residency replays the current residency for a rejoin.
    events.clear()
    store.emit_residency()
    assert events and all(ev == "insert" for ev, _ in events)


def test_pinned_pages_refuse_demotion(tmp_path):
    """In-flight dispatch pins win: a pinned path demotes nothing
    (pin_refusals counts), the whole-tree walk finds no evictable
    leaf, and refcounts stay sane throughout."""
    eng = _engine()
    bps, cps = _prompts(2)
    prefixes = _prefixes(bps, cps)
    eng.prefill_insert(128, prefixes)
    store = _store(tmp_path)
    eng.attach_tiers(store)
    tree = eng.prefix_cache
    pin = tree.lookup(128, prefixes[0], record=False)
    assert pin.pages
    before = tree.match_len(128, prefixes[0])
    assert store.demote_prefix(eng, 128, tuple(prefixes[0])) == 0
    assert store.stats.summary()["pin_refusals"] == 1
    assert tree.match_len(128, prefixes[0]) == before   # path intact
    tree.release(pin)
    _assert_pins_released(eng)
    # Unpinned, the same path demotes.
    assert store.demote_prefix(eng, 128, tuple(prefixes[0])) > 0


# ---------------------------------------------------------------------------
# Governor integration: reclaim rungs as reversible demotions
# ---------------------------------------------------------------------------

def test_governor_rung_walk_demotes_then_promotes_back(tmp_path):
    """Sustained pressure walks the ladder onto evict_pages, which now
    DEMOTES (tier counters move, HBM pages free); pressure release
    re-arms the rung; a promote restores the pages bitwise."""
    eng = _engine()
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    eng.prefill_insert(128, prefixes)
    before = _export_snapshot(eng, 128, prefixes[0])
    store = _store(tmp_path)
    eng.attach_tiers(store)
    MB = 1 << 20
    gov = hbm.HbmGovernor(
        GovernorConfig(enabled=True, engage_pressure=0.9,
                       hysteresis=0.15, sustain_ticks=1),
        budget_bytes=100 * MB)
    eng.governor = gov
    gov.set_action("evict_pages", engage=eng._evict_cold_pages)
    gov.update("pressure_src", 99 * MB)
    for _ in range(len(hbm.RUNGS) + 1):
        gov.tick()
    assert "evict_pages" in gov.engaged_rungs()
    s = store.stats.summary()
    assert s["pages_demoted"] > 0           # the rung demoted, not deleted
    assert eng.prefix_cache.match_len(128, prefixes[0]) == 0
    gov.update("pressure_src", 1 * MB)      # pressure clears
    for _ in range(len(hbm.RUNGS) + 1):
        gov.tick()
    assert gov.engaged_rungs() == []        # walked back up
    assert store.promote(eng, 128, prefixes[0]) > 0
    after = _export_snapshot(eng, 128, prefixes[0])
    _assert_exports_bitwise(before, after)
    _assert_pins_released(eng)


# ---------------------------------------------------------------------------
# Restart-warm
# ---------------------------------------------------------------------------

def test_restart_warm_reseed_bitwise(tmp_path):
    """Process death with a disk tier: a FRESH engine + store over the
    same directory reseed the radix tree and re-serve bitwise what the
    first incarnation served."""
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    eng = _engine()
    _shared(eng, bps, cps)
    warm = _shared(eng, bps, cps)
    store = _store(tmp_path, host_budget_mb=0.0001)   # everything to disk
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    del eng, store                          # "kill" the process

    eng2 = _engine()
    store2 = _store(tmp_path)
    eng2.attach_tiers(store2)
    n = store2.reseed(eng2)
    assert n > 0
    assert store2.stats.summary()["restart_pages_reseeded"] == n
    assert eng2.prefix_cache.match_len(128, prefixes[0]) > 0
    got = _shared(eng2, bps, cps)
    for k in (0, 1):
        assert_fused_bitwise(got[k], warm[k])
    _assert_pins_released(eng2)


def test_server_constructor_wires_tiers(tmp_path):
    """ScoringServer(tiers=...) builds the store, attaches it to the
    engine, registers TierStats in the metrics registry, and reseeds
    at construction (before the supervisor thread exists)."""
    eng = _engine()
    cfg = TierConfig(enabled=True, disk_dir=str(tmp_path / "t"))
    srv = ScoringServer(eng, "m", ServeConfig(
        classes=(("t", 600.0),), default_class="t", cache_entries=0),
        tiers=cfg)
    assert srv.tiers is not None
    assert getattr(eng, "_tier_store", None) is srv.tiers
    assert "tiers" in srv.metrics.snapshot()["sources"]


def test_torn_disk_index_tolerated_kill_mid_spill(tmp_path):
    """A spill killed mid-append leaves a torn JSONL tail on the disk
    index; the next load truncates it (manifest discipline), keeps
    every complete record, and the surviving entries promote bitwise."""
    eng = _engine()
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    eng.prefill_insert(128, prefixes)
    before = _export_snapshot(eng, 128, prefixes[0])
    store = _store(tmp_path, host_budget_mb=0.0001)
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    n_entries = store.summary()["disk_entries"]
    assert n_entries > 0
    index_path = store.disk.index_path
    faults.tear_jsonl_tail(index_path)

    store2 = _store(tmp_path)               # reload over the torn index
    assert store2.summary()["disk_entries"] == n_entries
    eng2 = _engine()
    assert store2.reseed(eng2) > 0
    after = _export_snapshot(eng2, 128, prefixes[0])
    _assert_exports_bitwise(before, after)
    # The truncated index accepts new appends (the spill that died
    # mid-write simply re-runs).
    eng3 = _engine()
    bps3, cps3 = _prompts(2, seed=9)
    eng3.prefill_insert(128, _prefixes(bps3, cps3))
    eng3.attach_tiers(store2)
    assert store2.demote(eng3, n_pages=999)


# ---------------------------------------------------------------------------
# Weight tier
# ---------------------------------------------------------------------------

def _tiny_cfg(name):
    return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)


def _tiny_engine(name, seed):
    cfg = _tiny_cfg(name)
    return ScoringEngine(
        decoder.init_params(cfg, jax.random.PRNGKey(seed)), cfg,
        FakeTokenizer(), RuntimeConfig(batch_size=4, max_seq_len=256))


def _assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_weight_store_roundtrip_bitwise(tmp_path):
    """A staged host tree recorded to disk comes back leaf-for-leaf
    bitwise (CRC-verified), nested structure intact."""
    staged = weights.host_stage(
        decoder.init_params(_tiny_cfg("w"), jax.random.PRNGKey(3)))
    ws = tiers_mod.TieredWeightStore(tmp_path / "w")
    assert ws.put("m0", staged) > 0
    assert ws.has("m0")
    assert ws.put("m0", staged) == 0        # immutable: record once
    got = ws.get("m0")
    _assert_trees_bitwise(staged, got)
    assert ws.stats.summary()["demotions"].get("weights", 0) == 1


def test_weight_store_quant_tensor_roundtrip(tmp_path):
    """int8 weights: QuantTensor leaves (payload + scale + dynamic
    flag) survive the disk tier bitwise and come back AS QuantTensor."""
    rng = np.random.default_rng(5)
    staged = {
        "dense": {"w": rng.standard_normal((8, 8)).astype(np.float32)},
        "q": QuantTensor(
            q=rng.integers(-127, 127, (8, 8), dtype=np.int8),
            scale=rng.standard_normal((8, 1)).astype(np.float32),
            dynamic=False),
    }
    ws = tiers_mod.TieredWeightStore(tmp_path / "w")
    assert ws.put("mq", staged) > 0
    got = ws.get("mq")
    assert isinstance(got["q"], QuantTensor)
    assert got["q"].dynamic is False
    np.testing.assert_array_equal(np.asarray(got["q"].q),
                                  np.asarray(staged["q"].q))
    np.testing.assert_array_equal(np.asarray(got["q"].scale),
                                  np.asarray(staged["q"].scale))
    _assert_trees_bitwise(staged["dense"], got["dense"])


def test_weight_store_corrupt_record_refused(tmp_path):
    """A rotted on-disk leaf fails its CRC: get() refuses (None), the
    record drops, checksum_refusals counts — the model cold-loads
    instead of serving corrupt weights."""
    staged = weights.host_stage(
        decoder.init_params(_tiny_cfg("w"), jax.random.PRNGKey(3)))
    ws = tiers_mod.TieredWeightStore(tmp_path / "w")
    assert ws.put("m0", staged) > 0
    npz = next(p for p in (tmp_path / "w").iterdir()
               if p.suffix == ".npz")
    raw = bytearray(npz.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    npz.write_bytes(bytes(raw))
    assert ws.get("m0") is None
    assert ws.stats.summary()["checksum_refusals"] >= 1
    assert not ws.has("m0")


def test_fleet_attach_mirrors_and_restart_warm_reseeds(tmp_path):
    """attach_tiers mirrors every staged tree (covering the cache's
    own insert-time LRU evictions, not just the evict_idle rung), and
    a fresh fleet restart-warm re-stages them bitwise."""
    e0, e1 = _tiny_engine("m0", 0), _tiny_engine("m1", 1)
    orig0 = weights.host_stage(e0.params)
    nb = weights.tree_bytes(e0.params)
    fleet = ModelFleet.from_engines([("m0", e0), ("m1", e1)],
                                    cache_budget_bytes=nb + nb // 2,
                                    prefetch=False)
    ws = tiers_mod.TieredWeightStore(tmp_path / "w")
    fleet.attach_tiers(ws)
    try:
        assert sorted(ws.models()) == ["m0", "m1"]
        _assert_trees_bitwise(orig0, ws.get("m0"))
    finally:
        fleet.shutdown()

    e0b = _tiny_engine("m0", 0)
    fleet2 = ModelFleet.from_engines([("m0", e0b)], prefetch=False)
    try:
        for slot in fleet2._slots.values():
            slot.staged = None              # cold restart: staging lost
        assert fleet2.reseed_weights(ws) == 1
        assert ws.stats.summary()["restart_weights_reseeded"] == 1
        _assert_trees_bitwise(orig0, fleet2._slots["m0"].staged)
    finally:
        fleet2.shutdown()


def test_fleet_evict_idle_records_via_governor_rung(tmp_path):
    """The evict_weights rung demotes: evict_idle still frees the HBM
    copy (engage contract True) and the victim's staged tree is on
    disk afterwards."""
    e0, e1 = _tiny_engine("m0", 0), _tiny_engine("m1", 1)
    fleet = ModelFleet.from_engines([("m0", e0), ("m1", e1)],
                                    prefetch=False)
    ws = tiers_mod.TieredWeightStore(tmp_path / "w")
    try:
        fleet._tier_store = ws              # skip attach-time mirror
        assert fleet.evict_idle() is True
        assert len(ws.models()) == 1        # exactly the victim
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# Chaos kinds
# ---------------------------------------------------------------------------

def test_tier_corrupt_refused_and_reprefill_bitwise(tmp_path):
    """tier_corrupt flips promoted bytes under the checksums: the
    import refuses, the poisoned entry drops everywhere, and the local
    re-prefill is bitwise — never a wrong answer."""
    eng = _engine()
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    eng.prefill_insert(128, prefixes)
    before = _export_snapshot(eng, 128, prefixes[0])
    store = _store(tmp_path)
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    plan = faults.FaultPlan(seed=7, schedules={
        "tiers": faults.SiteSchedule.tier_corrupt_at(0)})
    faults.wrap_tiers(store, plan)
    assert store.promote(eng, 128, prefixes[0]) == 0
    assert store.stats.summary()["checksum_refusals"] == 1
    assert plan.stats.summary()["injected"].get("tiers") == 1
    assert store.match_len(128, prefixes[0]) == 0    # entry dropped
    eng.prefill_insert(128, prefixes)                # local re-prefill
    after = _export_snapshot(eng, 128, prefixes[0])
    _assert_exports_bitwise(before, after)
    _assert_pins_released(eng)


def test_disk_stall_abandons_then_retry_succeeds(tmp_path):
    """disk_stall sleeps past disk_timeout_s then proceeds (a wedged
    read, not a death): the store abandons the promote, KEEPS the
    entry, and an unstalled retry promotes it bitwise."""
    eng = _engine()
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    eng.prefill_insert(128, prefixes)
    before = _export_snapshot(eng, 128, prefixes[0])
    store = _store(tmp_path, host_budget_mb=0.0001,
                   disk_timeout_s=0.05)
    eng.attach_tiers(store)
    assert store.demote(eng, n_pages=999)
    plan = faults.FaultPlan(seed=7, schedules={
        "tiers": faults.SiteSchedule.disk_stall_at(0, seconds=0.2)})
    faults.wrap_tiers(store, plan)
    assert store.promote(eng, 128, prefixes[0]) == 0
    assert store.stats.summary()["disk_stalls"] == 1
    assert store.match_len(128, prefixes[0]) > 0     # entry KEPT
    assert store.promote(eng, 128, prefixes[0]) > 0  # retry clean
    after = _export_snapshot(eng, 128, prefixes[0])
    _assert_exports_bitwise(before, after)
    _assert_pins_released(eng)
