"""Sequence-parallelism parity: ring attention and Ulysses all-to-all must
match single-device softmax attention exactly, on a virtual 8-device mesh
(the same Mesh/shard_map code paths as a real slice — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.config import MeshConfig
from lir_tpu.parallel import (
    reference_attention,
    ring_attention,
    seq_sharded,
    ulysses_attention,
)
from lir_tpu.parallel.sharding import build_mesh

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshConfig(data=1, model=1, seq=8))


def _qkv(B=2, S=64, H=8, hd=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (B, S, H, hd)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _qkv()
        expected = reference_attention(q, k, v, causal=causal)
        qs = jax.device_put(q, seq_sharded(seq_mesh))
        ks = jax.device_put(k, seq_sharded(seq_mesh))
        vs = jax.device_put(v, seq_sharded(seq_mesh))
        out = ring_attention(qs, ks, vs, seq_mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=2e-5
        )

    def test_output_stays_seq_sharded(self, seq_mesh):
        q, k, v = _qkv()
        qs = jax.device_put(q, seq_sharded(seq_mesh))
        out = ring_attention(qs, qs, qs, seq_mesh)

        # jax versions differ on whether trailing None axes are kept in a
        # result spec; compare specs normalized to the same rank.
        def _norm(spec):
            axes = list(spec)
            while axes and axes[-1] is None:
                axes.pop()
            return tuple(axes)

        assert _norm(out.sharding.spec) == _norm(seq_sharded(seq_mesh).spec)

    def test_jit_compatible(self, seq_mesh):
        q, k, v = _qkv(S=32)
        fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, seq_mesh))
        out = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(reference_attention(q, k, v)),
            atol=2e-5,
        )

    def test_single_block_fully_masked_rows(self, seq_mesh):
        # Causal masking with S == shards: first device's rows attend only
        # to themselves; no NaNs from the -inf accumulator path.
        q, k, v = _qkv(S=8)
        out = ring_attention(
            jax.device_put(q, seq_sharded(seq_mesh)),
            jax.device_put(k, seq_sharded(seq_mesh)),
            jax.device_put(v, seq_sharded(seq_mesh)),
            seq_mesh,
        )
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(reference_attention(q, k, v)),
            atol=2e-5,
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _qkv()
        expected = reference_attention(q, k, v, causal=causal)
        out = ulysses_attention(
            jax.device_put(q, seq_sharded(seq_mesh)),
            jax.device_put(k, seq_sharded(seq_mesh)),
            jax.device_put(v, seq_sharded(seq_mesh)),
            seq_mesh, causal=causal,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=2e-5
        )

    def test_head_divisibility_enforced(self, seq_mesh):
        q, k, v = _qkv(H=6)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, seq_mesh)


def test_ring_matches_ulysses(seq_mesh):
    q, k, v = _qkv(seed=3)
    a = ring_attention(q, k, v, seq_mesh)
    b = ulysses_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


class TestMultihost:
    """Single-process degradations of the multi-host helpers (a real
    multi-process run needs multiple hosts; the sharding math is
    process-count-parameterized so it is testable here)."""

    def test_gather_identity_single_process(self):
        from lir_tpu.parallel import gather_rows

        rows = np.arange(12, dtype=np.float32).reshape(4, 3)
        np.testing.assert_array_equal(gather_rows(rows), rows)

    def test_host_shard_partition(self):
        from lir_tpu.parallel import host_shard

        items = list(range(10))
        shards = [host_shard(items, i, 3) for i in range(3)]
        assert shards[0] == [0, 3, 6, 9]
        assert shards[1] == [1, 4, 7]
        assert shards[2] == [2, 5, 8]
        # Partition: disjoint and complete.
        merged = sorted(x for s in shards for x in s)
        assert merged == items

    def test_barrier_noop_single_process(self):
        from lir_tpu.parallel import barrier

        barrier("test-point")  # must not raise


def test_ring_attention_gqa_repeat(seq_mesh):
    """K/V with fewer heads than q are repeated internally (GQA)."""
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    k_full = jnp.repeat(k, 4, axis=2)
    v_full = jnp.repeat(v, 4, axis=2)
    expected = reference_attention(q, k_full, v_full, causal=True)
    out = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


# ---------------------------------------------------------------------------
# Sequence-parallel MODEL forward (parallel/seq_forward): the full decoder
# with attention routed through the ring / Ulysses kernels must match the
# dense single-mesh forward exactly — including left-pad masks and ALiBi.
# ---------------------------------------------------------------------------

from lir_tpu.models import decoder
from lir_tpu.models.registry import ModelConfig
from lir_tpu.parallel import (
    forward_seq_parallel,
    prefill_seq_parallel,
    seq_batch_sharding,
)


def _llama_tiny(**kw):
    base = dict(name="seqfwd-llama", vocab_size=128, hidden_size=32,
                n_layers=2, n_heads=8, intermediate_size=64, max_seq_len=128)
    base.update(kw)
    return ModelConfig(**base)


def _tokens(cfg, B=2, S=32, seed=7, left_pad=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(3, cfg.vocab_size, (B, S))
    mask = np.ones((B, S), np.int32)
    if left_pad:
        for b in range(B):
            n = (b * left_pad) % S
            toks[b, :n] = 0
            mask[b, :n] = 0
    return jnp.asarray(toks, jnp.int32), jnp.asarray(mask)


class TestSeqParallelForward:
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_matches_dense_forward(self, seq_mesh, impl):
        cfg = _llama_tiny()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        toks, mask = _tokens(cfg)
        expected = decoder.forward(params, cfg, toks, mask)
        sb = seq_batch_sharding(seq_mesh)
        out = forward_seq_parallel(
            params, cfg, jax.device_put(toks, sb), jax.device_put(mask, sb),
            mesh=seq_mesh, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=3e-4, rtol=1e-4)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_left_padded_parity(self, seq_mesh, impl):
        """Ragged left-padded batches: mask-aware positions must propagate
        into the sharded kernels exactly like _causal_bias."""
        cfg = _llama_tiny()
        params = decoder.init_params(cfg, jax.random.PRNGKey(1))
        toks, mask = _tokens(cfg, B=4, left_pad=5)
        expected = decoder.forward(params, cfg, toks, mask)
        out = forward_seq_parallel(params, cfg, toks, mask,
                                   mesh=seq_mesh, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=3e-4, rtol=1e-4)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_alibi_family(self, seq_mesh, impl):
        """bloom's ALiBi bias is applied inside the seq-parallel kernels."""
        cfg = _llama_tiny(name="seqfwd-bloom", pos_embedding="alibi",
                          norm="layernorm", embedding_norm=True,
                          gated_mlp=False, activation="gelu",
                          qkv_bias=True, attn_out_bias=True, mlp_bias=True)
        params = decoder.init_params(cfg, jax.random.PRNGKey(2))
        toks, mask = _tokens(cfg, left_pad=3)
        expected = decoder.forward(params, cfg, toks, mask)
        out = forward_seq_parallel(params, cfg, toks, mask,
                                   mesh=seq_mesh, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=3e-4, rtol=1e-4)

    def test_gqa_family(self, seq_mesh):
        cfg = _llama_tiny(name="seqfwd-gqa", n_kv_heads=2)
        params = decoder.init_params(cfg, jax.random.PRNGKey(3))
        toks, mask = _tokens(cfg)
        expected = decoder.forward(params, cfg, toks, mask)
        out = forward_seq_parallel(params, cfg, toks, mask, mesh=seq_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=3e-4, rtol=1e-4)

    def test_needs_mesh(self):
        cfg = _llama_tiny()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        toks, mask = _tokens(cfg)
        with pytest.raises(ValueError, match="mesh"):
            forward_seq_parallel(params, cfg, toks, mask)


class TestSeqParallelPrefill:
    def test_matches_dense_prefill_and_decodes(self, seq_mesh):
        """Seq-sharded prefill fills the SAME cache as dense prefill, and an
        ordinary dense decode step continues from it identically — the
        long-prompt recipe (shard the O(S^2) phase, decode cheap)."""
        cfg = _llama_tiny()
        params = decoder.init_params(cfg, jax.random.PRNGKey(4))
        toks, mask = _tokens(cfg, B=2, S=32, left_pad=4)
        max_len = 40

        el, (eck, ecv), epos = decoder.prefill(params, cfg, toks, mask, max_len)
        ol, (ock, ocv), opos = prefill_seq_parallel(
            params, cfg, toks, mask, max_len, mesh=seq_mesh)

        np.testing.assert_allclose(np.asarray(ol), np.asarray(el),
                                   atol=3e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(ock), np.asarray(eck),
                                   atol=3e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(ocv), np.asarray(ecv),
                                   atol=3e-4, rtol=1e-4)
        np.testing.assert_array_equal(np.asarray(opos), np.asarray(epos))

        # One dense decode step from each cache must agree.
        B, S = toks.shape
        tok_next = jnp.argmax(el, axis=-1).astype(jnp.int32)
        full_mask = jnp.concatenate(
            [mask, jnp.zeros((B, max_len - S), mask.dtype)], axis=1)
        full_mask = full_mask.at[:, S].set(1)
        args = (tok_next, epos, jnp.int32(S), full_mask)
        dl, _ = decoder.decode_step(params, cfg, (eck, ecv), *args)
        sl, _ = decoder.decode_step(params, cfg, (ock, ocv), *args)
        np.testing.assert_allclose(np.asarray(sl), np.asarray(dl),
                                   atol=3e-4, rtol=1e-4)


def test_multihost_initialize_single_process_degrade():
    """multihost.initialize(): no cluster -> False, never raises (pod
    bring-up is opt-in; single-host jobs proceed unchanged); required=True
    escalates the same condition to a hard error (the CLI's --multihost).

    Runs in a subprocess with cluster env vars scrubbed: jax's cluster
    auto-detection must see a clean environment (the axon plugin exports
    TPU_WORKER_HOSTNAMES in-process), and a successful bring-up would
    leave a distributed service running for the rest of the session."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent("""
        import os
        for v in ('SLURM_JOB_ID', 'OMPI_COMM_WORLD_SIZE',
                  'COORDINATOR_ADDRESS', 'TPU_WORKER_HOSTNAMES',
                  'CLOUD_TPU_TASK_ID', 'TPU_SKIP_MDS_QUERY'):
            os.environ.pop(v, None)
        os.environ['JAX_PLATFORMS'] = 'cpu'
        import jax
        jax.config.update('jax_platforms', 'cpu')
        from lir_tpu.parallel import multihost
        assert multihost.initialize() is False
        assert not multihost.is_multiprocess()
        try:
            multihost.initialize(required=True)
        except RuntimeError as e:
            assert 'multihost' in str(e)
        else:
            raise AssertionError('required=True did not escalate')
        print('DEGRADE-OK')
    """)
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=300,
                          cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DEGRADE-OK" in proc.stdout


def test_engine_seq_parallel_prefill_matches_plain(seq_mesh):
    """ScoringEngine(seq_mesh=...): the engine's production scoring path
    (fused decode) prefills seq-sharded and must score identically to the
    plain engine — the long-context path wired end to end (CLI --mesh
    1x1x8 -> factory -> engine -> generate -> decoder prefill)."""
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="eng-sp", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=8,
                      intermediate_size=64, max_seq_len=128)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    rt = RuntimeConfig(batch_size=4, max_new_tokens=5, max_seq_len=128)
    prompts = ["Is a tomato a vegetable ?",
               "Is a whale considered a fish in law ?"]

    plain = ScoringEngine(params, cfg, FakeTokenizer(), rt)
    sp = ScoringEngine(params, cfg, FakeTokenizer(), rt, seq_mesh=seq_mesh)
    assert sp._prefill_fn is not None

    r_plain = plain.score_prompts(prompts)
    r_sp = sp.score_prompts(prompts)
    for a, b in zip(r_plain, r_sp):
        np.testing.assert_allclose(b.relative_prob, a.relative_prob,
                                   atol=1e-4)
        assert b.completion == a.completion


def test_multihost_initialize_already_up_is_success(monkeypatch):
    """A launcher that already brought jax.distributed up must not turn
    --multihost into a hard error: initialize(required=True) probes
    process_count() and returns True (ADVICE r2 #2)."""
    import jax

    from lir_tpu.parallel import multihost

    def _raise(*a, **k):
        raise RuntimeError("jax.distributed.initialize was already called")

    monkeypatch.setattr(jax.distributed, "initialize", _raise)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert multihost.initialize(required=True) is True
    assert multihost.initialize() is True


def test_engine_shared_prefix_on_seq_mesh(seq_mesh):
    """The SWEEP's shared-prefix scorer composes with the seq-parallel
    prefill: the shared prefix prefills seq-sharded (ring attention), the
    suffix extensions and fused scans run dense, and the readouts equal
    the plain engine's."""
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="eng-sp-shared", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=8,
                      intermediate_size=64, max_seq_len=128)
    params = decoder.init_params(cfg, jax.random.PRNGKey(1))
    rt = RuntimeConfig(batch_size=2, max_new_tokens=5, max_seq_len=128)
    mains = ["Is a levee failure considered a flood event under the policy ?",
             "Would a burst dam count as a flood for coverage purposes ?"]
    bins = [m + " Answer Yes or No ." for m in mains]
    confs = [m + " Give a number 0 to 100 ." for m in mains]
    t1 = np.full((2,), FakeTokenizer.YES, np.int32)
    t2 = np.full((2,), FakeTokenizer.NO, np.int32)

    plain = ScoringEngine(params, cfg, FakeTokenizer(), rt)
    sp = ScoringEngine(params, cfg, FakeTokenizer(), rt, seq_mesh=seq_mesh)
    pa, pb = plain.decode_fused_shared(bins, confs, t1, t2,
                                       new_tokens=3, conf_tokens=4)
    sa, sb = sp.decode_fused_shared(bins, confs, t1, t2,
                                    new_tokens=3, conf_tokens=4)
    np.testing.assert_array_equal(np.asarray(sa.generated),
                                  np.asarray(pa.generated))
    np.testing.assert_allclose(np.asarray(sa.p_yes), np.asarray(pa.p_yes),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(sb.weighted_confidence),
                               np.asarray(pb.weighted_confidence), atol=1e-3)
