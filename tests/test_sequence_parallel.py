"""Sequence-parallelism parity: ring attention and Ulysses all-to-all must
match single-device softmax attention exactly, on a virtual 8-device mesh
(the same Mesh/shard_map code paths as a real slice — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.config import MeshConfig
from lir_tpu.parallel import (
    reference_attention,
    ring_attention,
    seq_sharded,
    ulysses_attention,
)
from lir_tpu.parallel.sharding import build_mesh

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshConfig(data=1, model=1, seq=8))


def _qkv(B=2, S=64, H=8, hd=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (B, S, H, hd)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _qkv()
        expected = reference_attention(q, k, v, causal=causal)
        qs = jax.device_put(q, seq_sharded(seq_mesh))
        ks = jax.device_put(k, seq_sharded(seq_mesh))
        vs = jax.device_put(v, seq_sharded(seq_mesh))
        out = ring_attention(qs, ks, vs, seq_mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=2e-5
        )

    def test_output_stays_seq_sharded(self, seq_mesh):
        q, k, v = _qkv()
        qs = jax.device_put(q, seq_sharded(seq_mesh))
        out = ring_attention(qs, qs, qs, seq_mesh)
        assert out.sharding.spec == seq_sharded(seq_mesh).spec

    def test_jit_compatible(self, seq_mesh):
        q, k, v = _qkv(S=32)
        fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, seq_mesh))
        out = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(reference_attention(q, k, v)),
            atol=2e-5,
        )

    def test_single_block_fully_masked_rows(self, seq_mesh):
        # Causal masking with S == shards: first device's rows attend only
        # to themselves; no NaNs from the -inf accumulator path.
        q, k, v = _qkv(S=8)
        out = ring_attention(
            jax.device_put(q, seq_sharded(seq_mesh)),
            jax.device_put(k, seq_sharded(seq_mesh)),
            jax.device_put(v, seq_sharded(seq_mesh)),
            seq_mesh,
        )
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(reference_attention(q, k, v)),
            atol=2e-5,
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _qkv()
        expected = reference_attention(q, k, v, causal=causal)
        out = ulysses_attention(
            jax.device_put(q, seq_sharded(seq_mesh)),
            jax.device_put(k, seq_sharded(seq_mesh)),
            jax.device_put(v, seq_sharded(seq_mesh)),
            seq_mesh, causal=causal,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=2e-5
        )

    def test_head_divisibility_enforced(self, seq_mesh):
        q, k, v = _qkv(H=6)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, seq_mesh)


def test_ring_matches_ulysses(seq_mesh):
    q, k, v = _qkv(seed=3)
    a = ring_attention(q, k, v, seq_mesh)
    b = ulysses_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


class TestMultihost:
    """Single-process degradations of the multi-host helpers (a real
    multi-process run needs multiple hosts; the sharding math is
    process-count-parameterized so it is testable here)."""

    def test_gather_identity_single_process(self):
        from lir_tpu.parallel import gather_rows

        rows = np.arange(12, dtype=np.float32).reshape(4, 3)
        np.testing.assert_array_equal(gather_rows(rows), rows)

    def test_host_shard_partition(self):
        from lir_tpu.parallel import host_shard

        items = list(range(10))
        shards = [host_shard(items, i, 3) for i in range(3)]
        assert shards[0] == [0, 3, 6, 9]
        assert shards[1] == [1, 4, 7]
        assert shards[2] == [2, 5, 8]
        # Partition: disjoint and complete.
        merged = sorted(x for s in shards for x in s)
        assert merged == items

    def test_barrier_noop_single_process(self):
        from lir_tpu.parallel import barrier

        barrier("test-point")  # must not raise


def test_ring_attention_gqa_repeat(seq_mesh):
    """K/V with fewer heads than q are repeated internally (GQA)."""
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    k_full = jnp.repeat(k, 4, axis=2)
    v_full = jnp.repeat(v, 4, axis=2)
    expected = reference_attention(q, k_full, v_full, causal=True)
    out = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)
