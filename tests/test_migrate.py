"""Disaggregated prefill/decode serving: KV-page migration + the
cluster-wide prefix index (serve/migrate.py, engine/prefix_tree.
ClusterPrefixIndex, serve/router.py roles).

Pins the PR's load-bearing claims:

- device legs: pages extracted from one pool re-inserted into ANOTHER
  pool (different size — the different-mesh stand-in the CPU suite can
  exercise) come back bitwise through the slot gather;
- the prefill-only dispatch (engine.prefill_insert) produces page
  VALUES bitwise-identical to the pages a full scoring dispatch of the
  same bucket inserts — the property that makes remote prefill
  transparent;
- export/import round-trip: chunked, double-buffered, checksummed;
  a corrupted chunk is refused with the destination tree/refcounts
  rolled back untouched; a cancelled transfer leaves refcounts sane;
- cluster index: insert/evict listener events maintain the router-side
  match, eviction prunes it;
- the headline: migrated-page decode == colocated local-prefill decode
  BITWISE — cold, warm, early-stop, and int8-KV flavors;
- router integration: page residency wins placement, the disagg chain
  serves end-to-end with scoring only on decode replicas, and the
  migration_stall / migration_corrupt chaos kinds fall back to local
  re-prefill with payloads still bitwise.
"""

import dataclasses

import jax
import numpy as np
import pytest

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import (MigrationConfig, RouterConfig, RuntimeConfig,
                            ServeConfig)
from lir_tpu.engine import prefix_tree
from lir_tpu.engine import tokens as tok
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, paged
from lir_tpu.models.registry import ModelConfig, tiny
from lir_tpu.serve import migrate as mig
from lir_tpu.serve import (ReplicaRouter, ScoringServer, ServeRequest)

CFG = tiny("llama")
PARAMS = decoder.init_params(CFG, jax.random.PRNGKey(1))
TOKZ = FakeTokenizer(vocab=CFG.vocab_size)

FUSED_FIELDS = ("generated", "p_yes", "p_no", "top2_ids", "topk_logprobs",
                "topk_ids", "weighted_confidence")

PAYLOAD_FIELDS = ("model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")


def _engine(prefix: bool, pages: int = 64, params=PARAMS, cfg=CFG,
            **kw):
    rt = RuntimeConfig(batch_size=4, max_seq_len=128,
                       aot_precompile=False, prefix_cache=prefix,
                       prefix_cache_pages=pages, **kw)
    return ScoringEngine(params, cfg, TOKZ, rt)


def _prompts(n, trunk_words=70, seed=0):
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster").split()
    rng = np.random.default_rng(seed)
    base = " ".join(rng.choice(words) for _ in range(trunk_words))
    bps = [f"{base} case {i} Answer Yes or No ." for i in range(n)]
    cps = [f"{base} case {i} Give a number 0 to 100 ." for i in range(n)]
    return bps, cps


def _prefixes(bps, cps):
    bin_ids = [TOKZ(p).input_ids for p in bps]
    conf_ids = [TOKZ(p).input_ids for p in cps]
    lcps = [tok.shared_prefix_len(a, b)
            for a, b in zip(bin_ids, conf_ids)]
    return [list(a[:n]) for a, n in zip(bin_ids, lcps)]


def _shared(engine, bps, cps, use, early_stop=False):
    engine.fresh_handoff()
    yes = np.full((len(bps),), TOKZ.YES, np.int32)
    no = np.full((len(bps),), TOKZ.NO, np.int32)
    return engine.decode_fused_shared(
        bps, cps, yes, no, new_tokens=4, conf_tokens=6,
        early_stop=early_stop, bucket=128, sfx_buckets_ab=(16, 16),
        reuse_cache=True, use_prefix_cache=use, n_real=len(bps))


def assert_fused_bitwise(a, b):
    for f in FUSED_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f"fused field {f}")


def _assert_pins_released(engine):
    pool = engine.prefix_cache.pool
    assert (pool.refcount >= 0).all()
    assert pool.refcount[1:].sum() == pool.pages_in_use


def _migrate_all(src, dst, bucket, prefixes, config=None):
    cfg = config or MigrationConfig(chunk_pages=2)
    moved = 0
    for ids in prefixes:
        e = mig.export_prefix(src, bucket, ids, config=cfg)
        if e is not None:
            moved += mig.import_prefix(dst, e, config=cfg).pages
    return moved


# ---------------------------------------------------------------------------
# Device legs (models/paged.extract_pages / insert_pages)
# ---------------------------------------------------------------------------

def test_extract_insert_roundtrip_between_pools_bitwise():
    """Pages written into one pool come back bitwise after an
    extract -> insert hop into a DIFFERENT-sized pool (the
    different-mesh pool stand-in CPU can exercise: leaf shapes differ
    in n_pages, sharding is re-derived at device_put)."""
    aval = jax.eval_shape(
        lambda k: jax.random.normal(k, (2, 2, 32, 4, 8)),
        jax.random.PRNGKey(0))
    cache = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 32, 4, 8))
    src = paged.KVPagePool(16, page_size=4)
    src.ensure(aval)
    src.scatter(cache, [(1, 0, 0), (2, 0, 4), (3, 1, 8)])
    blocks = src.extract([1, 2, 3])
    dst = paged.KVPagePool(8, page_size=4)
    dst.ensure(aval)
    dst.insert(blocks, [5, 6, 7])
    got = dst.extract([5, 6, 7])
    for a, b in zip(jax.tree.leaves(blocks), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the block contents really are the cache slices
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(got)[0])[:, :, 0],
        np.asarray(cache)[:, :, 0:4, 0])


# ---------------------------------------------------------------------------
# Prefill-only dispatch parity (the disaggregation keystone)
# ---------------------------------------------------------------------------

def test_prefill_insert_pages_bitwise_vs_dispatch_pages():
    """engine.prefill_insert's pages are BITWISE the pages a full
    scoring dispatch of the same bucket inserts — remote prefill is
    transparent by construction."""
    bps, cps = _prompts(4)
    prefixes = _prefixes(bps, cps)
    eng_a = _engine(True)
    _shared(eng_a, bps, cps, True)        # dispatch-produced pages
    eng_b = _engine(True)
    covered = eng_b.prefill_insert(128, prefixes)
    ps = eng_b.prefix_cache.page_size
    assert covered == (len(prefixes[0]) // ps) * ps
    for ids in prefixes:
        ma = eng_a.prefix_cache.lookup(128, ids, record=False)
        mb = eng_b.prefix_cache.lookup(128, ids, record=False)
        assert ma.tokens == mb.tokens > 0
        ba = eng_a.prefix_cache.pool.extract(ma.pages)
        bb = eng_b.prefix_cache.pool.extract(mb.pages)
        for x, y in zip(jax.tree.leaves(ba), jax.tree.leaves(bb)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        eng_a.prefix_cache.release(ma)
        eng_b.prefix_cache.release(mb)
    _assert_pins_released(eng_b)


def test_prefill_insert_skips_already_cached_rows():
    bps, cps = _prompts(2)
    prefixes = _prefixes(bps, cps)
    eng = _engine(True)
    eng.prefill_insert(128, prefixes)
    inserted = eng.prefix_stats.inserted_pages
    covered = eng.prefill_insert(128, prefixes)   # repeat: no new pages
    assert eng.prefix_stats.inserted_pages == inserted
    assert covered > 0


# ---------------------------------------------------------------------------
# Export / import round-trip
# ---------------------------------------------------------------------------

def test_export_import_roundtrip_bitwise_different_pool():
    """Exported pages re-imported on a different-sized pool are
    bitwise, chunked at a stable width with per-chunk checksums."""
    bps, cps = _prompts(3)
    prefixes = _prefixes(bps, cps)
    src = _engine(True, pages=64)
    src.prefill_insert(128, prefixes)
    dst = _engine(True, pages=24)
    cfg = MigrationConfig(chunk_pages=2)
    e = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    assert e is not None and e.n_pages > 0
    assert len(e.checksums) == len(e.chunks) >= 2
    assert e.nbytes == src.prefix_cache.pool.page_nbytes() * e.n_pages
    r = mig.import_prefix(dst, e, config=cfg)
    assert r.pages == e.n_pages
    ms = src.prefix_cache.lookup(128, prefixes[0], record=False)
    md = dst.prefix_cache.lookup(128, prefixes[0], record=False)
    assert ms.tokens == md.tokens
    bs = src.prefix_cache.pool.extract(ms.pages)
    bd = dst.prefix_cache.pool.extract(md.pages)
    for x, y in zip(jax.tree.leaves(bs), jax.tree.leaves(bd)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    src.prefix_cache.release(ms)
    dst.prefix_cache.release(md)
    _assert_pins_released(src)
    _assert_pins_released(dst)


def test_import_is_idempotent_and_partial_pulls_align():
    """Re-importing an already-held prefix lands zero pages; an export
    taken from a partial offset fills exactly the destination's gap."""
    bps, cps = _prompts(1, trunk_words=80)
    prefixes = _prefixes(bps, cps)
    src = _engine(True)
    src.prefill_insert(128, prefixes)
    dst = _engine(True)
    cfg = MigrationConfig(chunk_pages=2)
    ps = src.prefix_cache.page_size
    # destination already holds the first 2 pages (local prefill of a
    # shorter prefix sharing the trunk)
    dst.prefill_insert(128, [prefixes[0][:2 * ps]])
    have = dst.prefix_cache.match_len(128, prefixes[0])
    assert have == 2 * ps
    e = mig.export_prefix(src, 128, prefixes[0], from_token=have,
                          config=cfg)
    assert e.start_tokens == have
    r = mig.import_prefix(dst, e, config=cfg)
    want = (len(prefixes[0]) // ps) * ps
    assert dst.prefix_cache.match_len(128, prefixes[0]) == want
    assert r.pages == (want - have) // ps
    # idempotent: nothing more to land
    e2 = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    assert mig.import_prefix(dst, e2, config=cfg).pages == 0
    _assert_pins_released(dst)


def test_corrupt_chunk_refused_and_rolled_back():
    """A chunk corrupted in flight fails the checksum verify: NO page
    lands, the destination tree gains no nodes, refcounts and the free
    list are exactly as before — then a clean retry succeeds."""
    bps, cps = _prompts(2)
    prefixes = _prefixes(bps, cps)
    src = _engine(True)
    src.prefill_insert(128, prefixes)
    dst = _engine(True, pages=24)
    cfg = MigrationConfig(chunk_pages=2)
    e = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    faults.corrupt_export_chunks(e, seed="t")
    free_before = dst.prefix_cache.pool.free_pages
    nodes_before = len(dst.prefix_cache)
    with pytest.raises(mig.MigrationError, match="checksum"):
        mig.import_prefix(dst, e, config=cfg)
    assert dst.prefix_cache.pool.free_pages == free_before
    assert len(dst.prefix_cache) == nodes_before
    assert (dst.prefix_cache.pool.refcount >= 0).all()
    # a clean export still lands afterwards
    e2 = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    assert mig.import_prefix(dst, e2, config=cfg).pages == e2.n_pages


def test_cancelled_transfer_keeps_refcounts_sane():
    """A transfer that dies mid-import (device-put failure stand-in)
    rolls back: fresh nodes removed, their pages freed, no leaked
    pins."""
    bps, cps = _prompts(1)
    prefixes = _prefixes(bps, cps)
    src = _engine(True)
    src.prefill_insert(128, prefixes)
    dst = _engine(True)
    cfg = MigrationConfig(chunk_pages=1, verify=False)
    e = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    # poison the second chunk's host tree so the import's device_put
    # raises after the first chunk already queued
    e.chunks[1] = (None, e.chunks[1][1])
    free_before = dst.prefix_cache.pool.free_pages
    with pytest.raises(Exception):
        mig.import_prefix(dst, e, config=cfg)
    assert dst.prefix_cache.pool.free_pages == free_before
    assert len(dst.prefix_cache) == 0
    assert (dst.prefix_cache.pool.refcount >= 0).all()


# ---------------------------------------------------------------------------
# Cluster prefix index
# ---------------------------------------------------------------------------

def test_cluster_index_follows_insert_and_evict_events():
    """Tree listener events maintain the router-side index; evicting
    pages on the replica PRUNES the cluster match."""
    bps, cps = _prompts(2)
    prefixes = _prefixes(bps, cps)
    eng = _engine(True)
    idx = prefix_tree.ClusterPrefixIndex(eng.prefix_cache.page_size)
    import functools
    eng.prefix_cache.add_listener(
        functools.partial(idx.on_event, "r0"))
    eng.prefill_insert(128, prefixes)
    ps = eng.prefix_cache.page_size
    want = len(prefixes[0]) // ps
    assert idx.match_pages(128, prefixes[0]) == {"r0": want}
    assert idx.best_holder(128, prefixes[0]) == ("r0", want)
    assert idx.best_holder(128, prefixes[0],
                           exclude=("r0",)) == (None, 0)
    # evict everything: the index must end empty
    eng.prefix_cache.evict(eng.prefix_cache.pool.n_pages)
    assert idx.match_pages(128, prefixes[0]) == {}


def test_cluster_index_bucket_namespaces_and_partial_match():
    idx = prefix_tree.ClusterPrefixIndex(4)
    idx.on_event("a", "insert", 64, tuple(range(8)))
    idx.on_event("b", "insert", 64, tuple(range(4)))
    idx.on_event("b", "insert", 32, tuple(range(8)))
    probe = tuple(range(8))
    assert idx.match_pages(64, probe) == {"a": 2, "b": 1}
    assert idx.best_holder(64, probe) == ("a", 2)
    assert idx.match_pages(32, probe) == {"b": 2}
    # divergent tail matches only the shared leading pages
    assert idx.match_pages(64, (0, 1, 2, 3, 9, 9, 9, 9)) \
        == {"a": 1, "b": 1}
    idx.drop_replica("a")
    assert idx.match_pages(64, probe) == {"b": 1}


def test_forget_tail_rolls_back_and_notifies():
    eng = _engine(True)
    bps, cps = _prompts(1)
    prefixes = _prefixes(bps, cps)
    events = []
    eng.prefix_cache.add_listener(
        lambda ev, b, ids: events.append((ev, b, len(ids))))
    eng.prefill_insert(128, prefixes)
    n = len(eng.prefix_cache)
    assert events and events[0][0] == "insert"
    removed = eng.prefix_cache.forget_tail(128, prefixes[0], 2)
    assert removed == 2
    assert len(eng.prefix_cache) == n - 2
    assert [e for e in events if e[0] == "evict"]
    assert (eng.prefix_cache.pool.refcount >= 0).all()


# ---------------------------------------------------------------------------
# Migrated decode == colocated decode (bitwise)
# ---------------------------------------------------------------------------

def _migrated_vs_colocated(early_stop=False, params=PARAMS, cfg=CFG):
    bps, cps = _prompts(4, seed=3)
    prefixes = _prefixes(bps, cps)
    src = _engine(True, params=params, cfg=cfg)
    src.prefill_insert(128, prefixes)
    dst = _engine(True, pages=32, params=params, cfg=cfg)
    moved = _migrate_all(src, dst, 128, prefixes)
    assert moved > 0
    got = _shared(dst, bps, cps, True, early_stop=early_stop)
    assert dst.prefix_stats.hit_tokens > 0, "decode did not resume warm"
    ref = _engine(False, params=params, cfg=cfg)
    want = _shared(ref, bps, cps, False, early_stop=early_stop)
    for k in (0, 1):
        assert_fused_bitwise(got[k], want[k])
    _assert_pins_released(dst)


def test_migrated_decode_bitwise_cold():
    """Decode resuming from migrated pages == the colocated unpaged
    run, bitwise (the destination never prefilled this prefix)."""
    _migrated_vs_colocated()


def test_migrated_decode_bitwise_warm_repeat():
    """Second dispatch on the destination (fully warm, migrated pages
    now mixed with locally-inserted ones) stays bitwise."""
    bps, cps = _prompts(4, seed=5)
    prefixes = _prefixes(bps, cps)
    src = _engine(True)
    src.prefill_insert(128, prefixes)
    dst = _engine(True)
    _migrate_all(src, dst, 128, prefixes)
    first = _shared(dst, bps, cps, True)
    second = _shared(dst, bps, cps, True)
    ref = _engine(False)
    want = _shared(ref, bps, cps, False)
    for got in (first, second):
        for k in (0, 1):
            assert_fused_bitwise(got[k], want[k])


def test_migrated_decode_bitwise_early_stop():
    _migrated_vs_colocated(early_stop=True)


def test_migrated_decode_bitwise_int8_kv():
    """int8-KV flavor: migrated-page decode == LOCAL-prefill paged
    decode, bitwise. The reference is the colocated PAGED engine (its
    own prefill_insert warmed it): int8 pages are payload+scale pairs
    and the warm window-recompute attends over their dequantized
    values, so paged-warm was never bitwise against the UNPAGED
    prefill (which attends over unquantized in-flight k/v) — that
    pre-existing quantization property is orthogonal to migration,
    whose contract is that migrated pages behave exactly like locally
    produced ones."""
    cfg_q = dataclasses.replace(CFG, kv_cache_int8=True)
    params_q = decoder.init_params(cfg_q, jax.random.PRNGKey(7))
    bps, cps = _prompts(4, seed=3)
    prefixes = _prefixes(bps, cps)
    src = _engine(True, params=params_q, cfg=cfg_q)
    src.prefill_insert(128, prefixes)
    dst = _engine(True, pages=32, params=params_q, cfg=cfg_q)
    assert _migrate_all(src, dst, 128, prefixes) > 0
    got = _shared(dst, bps, cps, True)
    assert dst.prefix_stats.hit_tokens > 0
    ref = _engine(True, params=params_q, cfg=cfg_q)
    ref.prefill_insert(128, prefixes)         # local prefill, same pages
    want = _shared(ref, bps, cps, True)
    assert ref.prefix_stats.hit_tokens > 0
    for k in (0, 1):
        assert_fused_bitwise(got[k], want[k])
    _assert_pins_released(dst)


# ---------------------------------------------------------------------------
# Router integration
# ---------------------------------------------------------------------------

_SERVE_CFG = ServeConfig(classes=(("t", 600.0),), default_class="t",
                         linger_s=0.002, cache_entries=0)


def _tiny_server(seed=2, batch=4):
    mcfg = ModelConfig(name="migrate-t", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(seed))
    rt = RuntimeConfig(batch_size=batch, max_seq_len=256)
    engine = ScoringEngine(params, mcfg, FakeTokenizer(), rt)
    return ScoringServer(engine, "migrate-t", _SERVE_CFG)


def _req(body, rid):
    return ServeRequest(
        binary_prompt=f"{body} Answer Yes or No .",
        confidence_prompt=f"{body} Give a number from 0 to 100 .",
        klass="t", request_id=rid)


def _trunk(seed, words=55):
    rng = np.random.default_rng(seed)
    vocab = ("coverage policy flood water damage claim insurer "
             "premium").split()
    return " ".join(rng.choice(vocab) for _ in range(words))


def test_page_op_queue_runs_on_supervisor_and_propagates_errors():
    server = _tiny_server().start()
    try:
        fut = server.submit_page_op(lambda eng: eng.prefix_cache.page_size)
        assert fut.result(30) == server.engine.prefix_cache.page_size

        def boom(eng):
            raise ValueError("page op boom")

        fut2 = server.submit_page_op(boom)
        with pytest.raises(ValueError, match="page op boom"):
            fut2.result(30)
    finally:
        server.stop()


def test_router_disagg_end_to_end_bitwise_and_decode_only():
    """1 prefill + 2 decode replicas: every request ok, scoring lands
    ONLY on decode replicas, pages migrate, payloads bitwise a
    colocated single server's."""
    reqs = [_req(f"{_trunk(9)} case {i}", str(i)) for i in range(5)]
    colo = _tiny_server().start()
    base = [colo.submit(r).result(120) for r in reqs]
    colo.stop()
    servers = [_tiny_server().start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        migrate=MigrationConfig(min_prefix_tokens=16,
                                chunk_pages=2)).start()
    try:
        res = [router.submit(r).result(120) for r in reqs]
        assert all(r.status == "ok" for r in res)
        for got, want in zip(res, base):
            for f in PAYLOAD_FIELDS:
                assert getattr(got, f) == getattr(want, f), f
        assert router.migrate_stats.pages_migrated > 0
        assert router.migrate_stats.prefill_ops > 0
        assert router.stats.per_replica.get("pre", 0) == 0
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_migration_stall_falls_back_to_local_reprefill():
    """migration_stall past the chain deadline: the request resolves ok
    and bitwise via LOCAL re-prefill; stalls/fallbacks counted."""
    req = _req(f"{_trunk(13)} case 0", "s0")
    colo = _tiny_server().start()
    want = colo.submit(req).result(120)
    colo.stop()
    servers = [_tiny_server().start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        migrate=MigrationConfig(min_prefix_tokens=16, chunk_pages=2,
                                timeout_s=0.3)).start()
    plan = faults.FaultPlan(seed=5, schedules={
        "migrate": faults.SiteSchedule.migration_stall_at(
            0, seconds=0.8)})
    faults.wrap_migrator(router.migrator, plan)
    try:
        got = router.submit(req).result(120)
        assert got.status == "ok"
        for f in PAYLOAD_FIELDS:
            assert getattr(got, f) == getattr(want, f), f
        assert plan.injected("migrate") == 1
        assert router.migrate_stats.refetch_fallbacks == 1
        assert router.migrate_stats.stalls >= 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_migration_corrupt_falls_back_to_local_reprefill():
    """migration_corrupt: checksum verify refuses the pages, the
    destination rolls back untouched, the request resolves ok and
    bitwise via local re-prefill."""
    req = _req(f"{_trunk(17)} case 0", "c0")
    colo = _tiny_server().start()
    want = colo.submit(req).result(120)
    colo.stop()
    servers = [_tiny_server().start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        migrate=MigrationConfig(min_prefix_tokens=16, chunk_pages=2,
                                timeout_s=5.0)).start()
    plan = faults.FaultPlan(seed=6, schedules={
        "migrate": faults.SiteSchedule.migration_corrupt_at(0)})
    faults.wrap_migrator(router.migrator, plan)
    try:
        got = router.submit(req).result(120)
        assert got.status == "ok"
        for f in PAYLOAD_FIELDS:
            assert getattr(got, f) == getattr(want, f), f
        assert router.migrate_stats.corrupt_chunks == 1
        assert router.migrate_stats.refetch_fallbacks == 1
        for s in servers:
            assert (s.engine.prefix_cache.pool.refcount >= 0).all()
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_kill_mid_migration_recovers_on_survivor():
    """The SOURCE replica dying mid-chain fails the migration over:
    the request re-prefills locally on a survivor, resolves ok and
    bitwise, nothing dropped."""
    req = _req(f"{_trunk(21)} case 0", "k0")
    colo = _tiny_server().start()
    want = colo.submit(req).result(120)
    colo.stop()
    servers = [_tiny_server().start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        migrate=MigrationConfig(min_prefix_tokens=16, chunk_pages=2,
                                timeout_s=5.0)).start()
    plan = faults.FaultPlan(seed=7, schedules={
        "migrate": faults.SiteSchedule.migration_stall_at(
            0, seconds=0.6)})
    faults.wrap_migrator(router.migrator, plan)
    try:
        fut = router.submit(req)
        router.kill_replica("pre")
        got = fut.result(120)
        assert got.status == "ok"
        for f in PAYLOAD_FIELDS:
            assert getattr(got, f) == getattr(want, f), f
        assert router.migrate_stats.refetch_fallbacks >= 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_transfer_buffers_ride_the_hbm_ledger():
    """Export/import staging registers `migrate_buf:<model>` bytes in
    the PR-14 HBM governor's ledger for the transfer's duration and
    unregisters after — a squeeze accounts for in-flight migrations
    next to the pool reservation."""
    from lir_tpu.config import GovernorConfig

    bps, cps = _prompts(1)
    prefixes = _prefixes(bps, cps)
    rt = RuntimeConfig(batch_size=4, max_seq_len=128,
                       aot_precompile=False, prefix_cache=True,
                       prefix_cache_pages=64)
    src = ScoringEngine(PARAMS, CFG, TOKZ, rt,
                        governor_config=GovernorConfig())
    src.prefill_insert(128, prefixes)
    seen = []
    real_register = src.governor.register

    def spy(name, nbytes):
        seen.append((name, nbytes))
        real_register(name, nbytes)

    src.governor.register = spy
    cfg = MigrationConfig(chunk_pages=2)
    e = mig.export_prefix(src, 128, prefixes[0], config=cfg)
    key = f"migrate_buf:{CFG.name}"
    assert any(n == key and b > 0 for n, b in seen)
    assert key not in src.governor.ledger()       # unregistered after
    dst = ScoringEngine(PARAMS, CFG, TOKZ, rt,
                        governor_config=GovernorConfig())
    seen_d = []
    real_d = dst.governor.register
    dst.governor.register = lambda n, b: (seen_d.append((n, b)),
                                          real_d(n, b))
    mig.import_prefix(dst, e, config=cfg)
    assert any(n == key and b > 0 for n, b in seen_d)
    assert key not in dst.governor.ledger()


def test_migration_stats_schema_mirror():
    """Every MigrationStats public field rides STATS_SCHEMA (and hence
    the metrics endpoint) — the metrics-drift contract, mirrored here
    so a drift fails next to the feature too."""
    import dataclasses as dc

    from lir_tpu.observe.registry import STATS_SCHEMA
    from lir_tpu.utils.profiling import MigrationStats

    fields = {f.name for f in dc.fields(MigrationStats)
              if not f.name.startswith("_")}
    assert fields == set(STATS_SCHEMA["MigrationStats"])
    s = MigrationStats()
    s.add_transfer(pages=3, nbytes=100, chunks=2, exposed_s=0.5,
                   hidden_s=0.2)
    s.count("refetch_fallbacks")
    summ = s.summary()
    assert summ["pages_migrated"] == 3 and summ["migrations"] == 1
    assert summ["refetch_fallbacks"] == 1
