"""Factory round-trip: HF checkpoint directory -> ScoringEngine, logits
matching the torch reference model."""

import numpy as np
import pytest
import torch

from lir_tpu.config import RuntimeConfig
from lir_tpu.models.factory import engine_factory, is_encoder_decoder, load_engine


@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    import transformers as tf

    torch.manual_seed(0)
    model = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=256, tie_word_embeddings=False)).eval()
    path = tmp_path_factory.mktemp("ckpt") / "org__tiny-llama"
    path.mkdir()
    model.save_pretrained(path, safe_serialization=True)
    # No tokenizer files on purpose (zero-egress env): tokenizer-dependent
    # tests monkeypatch AutoTokenizer with the fake backend tokenizer.
    return path, model


def test_encdec_routing_rule():
    assert is_encoder_decoder("google/flan-t5-base")
    assert is_encoder_decoder("bigscience/T0_3B")
    assert is_encoder_decoder("allenai/tk-instruct-3b-def")
    assert not is_encoder_decoder("meta-llama/Llama-2-7b-hf")
    assert not is_encoder_decoder("tiiuae/falcon-7b")


@pytest.mark.slow
def test_state_dict_lazy_loading(tiny_checkpoint):
    from lir_tpu.models.factory import load_state_dict

    path, model = tiny_checkpoint
    state = load_state_dict(path)
    ref = model.state_dict()
    assert set(state.keys()) == set(ref.keys())
    key = "model.embed_tokens.weight"
    np.testing.assert_allclose(
        np.asarray(state[key]), ref[key].numpy(), atol=0
    )


@pytest.mark.slow
def test_load_engine_forward_parity(tiny_checkpoint, monkeypatch):
    """Engine built from the on-disk checkpoint produces the same logits as
    the torch model (the stage-3 validation gate, SURVEY.md §7 build order)."""
    import jax.numpy as jnp
    import transformers as tf

    path, torch_model = tiny_checkpoint

    # Bypass AutoTokenizer (no tokenizer files in the synthetic checkpoint).
    from lir_tpu.backends.fake import FakeTokenizer

    monkeypatch.setattr(
        tf.AutoTokenizer, "from_pretrained",
        classmethod(lambda cls, *a, **k: FakeTokenizer()),
    )
    engine = load_engine(path, RuntimeConfig(batch_size=4, max_new_tokens=4))
    assert not engine.encoder_decoder

    ids = np.array([[5, 9, 12, 40, 7]], dtype=np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.from_numpy(ids)).logits.numpy()
    from lir_tpu.models import decoder

    ours = np.asarray(
        decoder.forward(engine.params, engine.cfg, jnp.asarray(ids, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref_logits, atol=2e-3)


@pytest.mark.slow
def test_engine_factory_resolution(tiny_checkpoint, monkeypatch):
    import transformers as tf

    from lir_tpu.backends.fake import FakeTokenizer

    monkeypatch.setattr(
        tf.AutoTokenizer, "from_pretrained",
        classmethod(lambda cls, *a, **k: FakeTokenizer()),
    )
    path, _ = tiny_checkpoint
    factory = engine_factory(path.parent)
    engine = factory("org/tiny-llama")  # resolves org__tiny-llama
    assert engine.cfg.n_layers == 2
    with pytest.raises(FileNotFoundError, match="no local checkpoint"):
        factory("org/absent-model")


@pytest.mark.slow
def test_params_cache_roundtrip(tiny_checkpoint, tmp_path, monkeypatch):
    """Convert-once semantics: second load restores from the orbax cache
    without touching the safetensors state dict."""
    import transformers as tf

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.models import cache as cache_mod
    from lir_tpu.models import factory as factory_mod

    monkeypatch.setattr(
        tf.AutoTokenizer, "from_pretrained",
        classmethod(lambda cls, *a, **k: FakeTokenizer()),
    )
    path, _ = tiny_checkpoint
    cache_root = tmp_path / "param_cache"

    e1 = load_engine(path, cache_root=cache_root)
    assert cache_mod.has_cached(cache_root, path.name)

    # Break the state-dict path: a cache hit must never call it.
    monkeypatch.setattr(
        factory_mod, "load_state_dict",
        lambda _p: (_ for _ in ()).throw(AssertionError("cache missed")),
    )
    e2 = load_engine(path, cache_root=cache_root)
    assert e2.cfg == e1.cfg
    np.testing.assert_allclose(
        np.asarray(e2.params["tok_embed"]), np.asarray(e1.params["tok_embed"])
    )


@pytest.fixture(scope="module")
def tiny_t5_checkpoint(tmp_path_factory):
    import transformers as tf

    torch.manual_seed(2)
    # vocab >= FakeTokenizer.VOCAB: the fake tokenizer hashes words into
    # ids up to 999; a smaller embedding would clamp them to garbage rows
    # and score NaN.
    model = tf.T5ForConditionalGeneration(tf.T5Config(
        vocab_size=1024, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_heads=4, feed_forward_proj="gated-gelu",
        tie_word_embeddings=False, decoder_start_token_id=0)).eval()
    path = tmp_path_factory.mktemp("ckpt_t5") / "org__tiny-t5"
    path.mkdir()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


@pytest.mark.slow
def test_load_engine_t5_mesh_shards_params(tiny_t5_checkpoint, monkeypatch):
    """--mesh is honored for encoder-decoder checkpoints: params shard with
    the enc-dec specs instead of being silently ignored (VERDICT r2 missing
    #4); --kv-cache-int8 warns that it has no effect on the seq2seq path
    (ADVICE r2 #4); a seq>1 mesh raises."""
    import logging

    import transformers as tf

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import MeshConfig

    path, _ = tiny_t5_checkpoint
    monkeypatch.setattr(
        tf.AutoTokenizer, "from_pretrained",
        classmethod(lambda cls, *a, **k: FakeTokenizer()),
    )
    with pytest.raises(ValueError, match="seq=2 > 1 is not supported"):
        load_engine(path, RuntimeConfig(batch_size=2),
                    mesh_cfg=MeshConfig(data=2, model=2, seq=2))

    import lir_tpu.models.factory as factory_mod
    with pytest.MonkeyPatch.context() as mp:
        records = []
        mp.setattr(factory_mod.log, "warning",
                   lambda msg, *a: records.append(msg % a if a else msg))
        engine = load_engine(path, RuntimeConfig(batch_size=2),
                             mesh_cfg=MeshConfig(data=2, model=4),
                             kv_cache_int8=True)
        assert any("kv-cache-int8" in r and "no effect" in r for r in records)
    assert engine.encoder_decoder
    wq = engine.params["encoder"]["wq"]
    assert wq.sharding.shard_shape(wq.shape)[-1] == wq.shape[-1] // 4
    # Sharded engine still scores (full seq2seq decode on the mesh).
    rows = engine.score_prompts(["Is a tomato a vegetable ?"] * 2)
    assert all(np.isfinite(r.yes_prob) for r in rows)
