"""perturb_prompts.py EXECUTED as the C3/C4/C6/C8 oracle (VERDICT r4 #2).

tools/reference_perturb_oracle.py staged the reference's perturb_prompts.py
with mechanical patches and ran it END TO END against stub openai/anthropic
clients replaying the deterministic payloads in tools/perturb_oracle_data.py
— twice: scenario A (Step-1 rephrasing generation through the reference's
numbered-list parser, seed-42 random subset of 20, reasoning model in its
default confidence-only SKIP mode) and scenario B (canned perturbations
loaded through the reference's own verification path, full grid, 10-run
reasoning averaging). The capture (tests/golden/reference_perturb_oracle.json)
holds every uploaded batch request and the final 15-column workbook.

These tests rebuild the same grids with lir_tpu (engine/grid +
backends/api), replay the IDENTICAL payloads through decode_batch_results,
and diff: request bodies positionally (grid cardinality + custom_id
mapping), every workbook measurement column at exact/≤1%, the rephrasing
parser byte-for-byte, and the seed-42 subset selection.
"""

import hashlib
import json
import math
from pathlib import Path

import pytest

from lir_tpu.backends import api as api_mod
from lir_tpu.data import schemas
from lir_tpu.data.prompts import LEGAL_PROMPTS
from lir_tpu.engine import grid as grid_mod
from lir_tpu.engine.rephrase import parse_numbered_rephrasings

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

GOLDEN_PATH = Path(__file__).parent / "golden" / "reference_perturb_oracle.json"

REGULAR = "gpt-4.1-2025-04-14"
REASONING = "o3-2025-04-16"
REL = 0.01
N_SESSIONS = 100                      # perturb_prompts.py:791


@pytest.fixture(scope="module")
def golden():
    if not GOLDEN_PATH.exists():
        pytest.skip("run tools/reference_perturb_oracle.py first")
    return json.loads(GOLDEN_PATH.read_text())


def _scenario_a_rephrasings():
    """Regenerate what the executed reference parsed in Step 1: sessions
    run sequentially, 100 per prompt, prompts in order — the stub Claude's
    call counter is therefore prompt_idx * 100 + session."""
    from perturb_oracle_data import parsed_rephrasings

    out = []
    for p_idx, prompt in enumerate(LEGAL_PROMPTS):
        rephrasings = []
        for s in range(N_SESSIONS):
            rephrasings.extend(
                parsed_rephrasings(p_idx * N_SESSIONS + s, prompt.main))
        out.append(rephrasings)
    return out


def _scenario_b_rephrasings():
    from reference_perturb_oracle import _canned_perturbations

    return [item["rephrasings"] for item in _canned_perturbations()]


def _cells_for(scenario: str, model: str):
    # include_original=False: the executed reference's grid is the
    # rephrasings alone (the original-prompt cell is a lir_tpu extension).
    if scenario == "scenario_a":
        cells = grid_mod.build_grid(model, LEGAL_PROMPTS,
                                    _scenario_a_rephrasings(),
                                    include_original=False)
        return grid_mod.random_subset(cells, 20, seed=42)
    return grid_mod.build_grid(model, LEGAL_PROMPTS,
                               _scenario_b_rephrasings(),
                               include_original=False)


def _requests_for(scenario: str, model: str):
    cells = _cells_for(scenario, model)
    if model == REASONING:
        return api_mod.build_batch_requests(
            cells, model, reasoning_model=True,
            skip_reasoning_logprobs=(scenario == "scenario_a"))
    return api_mod.build_batch_requests(cells, model)


def test_step1_parser_matches_executed_reference(golden):
    """The reference's Step-1 parser ran against 500 canned Claude
    sessions; its saved perturbations.json is hash-pinned. Our parser
    produces the identical rephrasings from the identical texts, so the
    two parsers agree byte-for-byte on preambles, 'N.'/'N ' forms, and
    continuation lines."""
    from perturb_oracle_data import claude_rephrasings, parsed_rephrasings

    expected = []
    for p_idx, prompt in enumerate(LEGAL_PROMPTS):
        item = {
            "original_main": prompt.main,
            "response_format": prompt.response_format,
            "target_tokens": list(prompt.target_tokens),
            "confidence_format": prompt.confidence_format,
            "rephrasings": _scenario_a_rephrasings()[p_idx],
        }
        expected.append(item)
    digest = hashlib.sha256(
        json.dumps(expected, sort_keys=True, ensure_ascii=False)
        .encode()).hexdigest()
    pg = golden["scenario_a"]["perturbations"]
    assert digest == pg["sha256"], "executed parser output drifted"
    assert pg["counts"] == [len(i["rephrasings"]) for i in expected]
    assert pg["samples"] == [i["rephrasings"][:3] for i in expected]

    # OUR parser on the same canned session texts == the regenerated
    # (hash-verified) reference output.
    for k in (0, 137, 499):
        main = LEGAL_PROMPTS[k // N_SESSIONS].main
        assert parse_numbered_rephrasings(
            claude_rephrasings(k, main)) == parsed_rephrasings(k, main)


@pytest.mark.parametrize("scenario", ["scenario_a", "scenario_b"])
@pytest.mark.parametrize("model", [REGULAR, REASONING])
def test_grid_matches_executed_reference(golden, scenario, model):
    """Positional body-for-body equality with the captured uploads: same
    cardinality, same (prompt, rephrase, format, run) order, identical
    request bodies (model, messages, response_format, sampling/logprob
    params). custom_id naming differs by design (ours is structured,
    the reference counts req-N) — positional equality carries the
    mapping."""
    ref_requests = golden[scenario]["uploads"][model]
    ours, _ = _requests_for(scenario, model)
    assert len(ours) == len(ref_requests)
    for our_req, ref_req in zip(ours, ref_requests):
        assert our_req["body"] == ref_req["body"]
        assert our_req["method"] == ref_req["method"]
        assert our_req["url"] == ref_req["url"]


def _row_key(row):
    return (row["Model"], row["Original Main Part"],
            row["Rephrased Main Part"])


@pytest.mark.parametrize("scenario", ["scenario_a", "scenario_b"])
def test_decoder_matches_executed_workbook(golden, scenario):
    """Replay the identical batch payloads through decode_batch_results
    and diff every D6 measurement column against the workbook the
    executed reference wrote."""
    from perturb_oracle_data import openai_batch_result_line

    rows_by_key = {}
    for model in (REGULAR, REASONING):
        ref_requests = golden[scenario]["uploads"][model]
        ours, id_map = _requests_for(scenario, model)
        # The payload the reference decoded, re-keyed onto our custom ids
        # (positional identity established by the grid test).
        results = []
        for our_req, ref_req in zip(ours, ref_requests):
            line = json.loads(openai_batch_result_line(ref_req))
            line["custom_id"] = our_req["custom_id"]
            results.append(line)
        skip = model == REASONING and scenario == "scenario_a"
        scores = api_mod.decode_batch_results(results, id_map,
                                              reasoning_skip=skip)
        for base_id, score in scores.items():
            cell = id_map.get(
                f"{base_id}_confidence") or id_map.get(f"{base_id}_binary")
            rows_by_key[(model, cell.original_main,
                         cell.rephrased_main)] = (score, cell)

    workbook = golden[scenario]["workbook"]
    assert (golden[scenario]["workbook_columns"]
            == list(schemas.PERTURBATION_COLUMNS))
    assert len(workbook) == len(rows_by_key)
    for row in workbook:
        score, cell = rows_by_key[_row_key(row)]
        assert score.response_text == row["Model Response"]
        assert score.confidence_text == row["Model Confidence Response"]
        assert score.log_probabilities == row["Log Probabilities"]
        assert score.token_1_prob == pytest.approx(
            row["Token_1_Prob"], rel=REL, abs=1e-12)
        assert score.token_2_prob == pytest.approx(
            row["Token_2_Prob"], rel=REL, abs=1e-12)
        ref_odds = row["Odds_Ratio"]
        if ref_odds is None:          # pandas serializes inf as null
            assert math.isinf(score.odds_ratio)
        else:
            assert score.odds_ratio == pytest.approx(ref_odds, rel=REL)
        if row["Confidence Value"] is None:
            assert score.confidence_value is None
        else:
            assert score.confidence_value == int(row["Confidence Value"])
        if row["Weighted Confidence"] is None:
            assert score.weighted_confidence is None
        else:
            assert score.weighted_confidence == pytest.approx(
                row["Weighted Confidence"], rel=REL)
        assert (f"{cell.rephrased_main} {cell.response_format}"
                == row["Full Rephrased Prompt"])
        assert (f"{cell.rephrased_main} {cell.confidence_format}"
                == row["Full Confidence Prompt"])


def test_error_line_semantics_match_reference():
    """Errored batch lines follow the reference's asymmetric handling
    (perturb_prompts.py:370-410,448-466): a cell whose single binary
    result errored is DROPPED (warning), while a skip-mode cell whose
    confidence errored is still emitted with None values and the literal
    placeholders."""
    cells = grid_mod.build_grid(REGULAR, LEGAL_PROMPTS[:1], [["v1"]],
                                include_original=False)
    _, id_map = api_mod.build_batch_requests(cells, REGULAR)
    err = {"custom_id": "p0_r0_binary", "response": None,
           "error": {"message": "rate limited"}}
    good_conf = {"custom_id": "p0_r0_confidence", "response": {"body": {
        "choices": [{"message": {"content": "88"}, "logprobs": None}]}}}
    scores = api_mod.decode_batch_results([err, good_conf], id_map)
    assert scores == {}

    cells = grid_mod.build_grid(REASONING, LEGAL_PROMPTS[:1], [["v1"]],
                                include_original=False)
    _, id_map = api_mod.build_batch_requests(cells, REASONING,
                                             reasoning_model=True)
    err_conf = {"custom_id": "p0_r0_confidence", "response": None,
                "error": {"message": "expired"}}
    scores = api_mod.decode_batch_results([err_conf], id_map,
                                          reasoning_skip=True)
    s = scores["p0_r0"]
    assert s.response_text == "N/A (skipped for reasoning model)"
    assert s.log_probabilities == "N/A for reasoning models"
    assert s.confidence_value is None
    assert s.weighted_confidence is None
    assert s.odds_ratio == 0.0


def test_random_subset_matches_executed_selection(golden):
    """grid.random_subset with seed 42 picks the SAME 20 perturbations
    the executed reference's create_random_subset chose (both sample an
    identically ordered population through seeded Mersenne Twister)."""
    cells = _cells_for("scenario_a", REGULAR)
    ours = {(c.original_main, c.rephrased_main) for c in cells}
    assert len(cells) == 20
    ref = {(r["Original Main Part"], r["Rephrased Main Part"])
           for r in golden["scenario_a"]["workbook"]}
    assert ours == ref
