"""Tests for the sweep extras: on-pod rephraser (C3), multi-model sweep
driver (C10/C15/C16), the preserved API backend (C7-C9), sampling decode,
and the throughput meter."""

import json

import jax
import numpy as np
import pandas as pd
import pytest
import torch

from lir_tpu.backends import api as api_mod
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.data.prompts import LEGAL_PROMPTS
from lir_tpu.engine import generate as gen_mod
from lir_tpu.engine import grid as grid_mod
from lir_tpu.engine.multi import (
    ModelSpec,
    base_instruct_pairs,
    format_for,
    run_model_comparison_sweep,
)
from lir_tpu.engine.rephrase import (
    load_or_generate_perturbations,
    parse_numbered_rephrasings,
)
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models.loader import config_from_hf, convert_decoder
from lir_tpu.utils.profiling import ThroughputMeter

KEY = jax.random.PRNGKey(0)


def _tiny_llama_params(vocab=1000, seed=0):
    import transformers as tf
    torch.manual_seed(seed)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=vocab, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=256, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    return convert_decoder(hf.state_dict(), cfg, fam), cfg, hf


class TestRephraseParser:
    def test_numbered_list(self):
        text = (
            "Here are 3 rephrasings:\n"
            "1. First question?\n"
            "2. Second question\n"
            "   with a continuation line\n"
            "3 Third without dot\n"
        )
        out = parse_numbered_rephrasings(text)
        assert out == [
            "First question?",
            "Second question with a continuation line",
            "Third without dot",
        ]

    def test_unnumbered_first_line(self):
        assert parse_numbered_rephrasings("just one line") == ["just one line"]

    def test_blank_and_preamble_skipped(self):
        out = parse_numbered_rephrasings("\nHere are the items\n1. A?\n\n2. B?")
        assert out == ["A?", "B?"]


class TestRephraseCache:
    def test_generate_and_cache_roundtrip(self, tmp_path):
        calls = []

        def fake_generate(texts, key):
            calls.append(len(texts))
            return [
                "1. Variant one?\n2. Variant two?" for _ in texts
            ]

        prompts = LEGAL_PROMPTS[:2]
        cache = tmp_path / "perturbations.json"
        res = load_or_generate_perturbations(
            cache, prompts, fake_generate, KEY,
            sessions_per_prompt=4, rephrasings_per_session=2,
        )
        assert cache.exists()
        assert len(res) == 2
        # 4 sessions x 2 parsed rephrasings each.
        assert len(res[0][1]) == 8

        # Reload hits the cache: generator must NOT be called again.
        n_calls = len(calls)
        res2 = load_or_generate_perturbations(cache, prompts, fake_generate, KEY)
        assert len(calls) == n_calls
        assert res2 == res

    def test_cache_invalidated_on_prompt_change(self, tmp_path):
        def fake_generate(texts, key):
            return ["1. X?" for _ in texts]

        cache = tmp_path / "perturbations.json"
        load_or_generate_perturbations(
            cache, LEGAL_PROMPTS[:1], fake_generate, KEY,
            sessions_per_prompt=1,
        )
        # Different prompt list -> cache invalid -> regenerated (2 entries).
        res = load_or_generate_perturbations(
            cache, LEGAL_PROMPTS[:2], fake_generate, KEY,
            sessions_per_prompt=1,
        )
        assert len(res) == 2

    def test_missing_cache_without_generator_raises(self, tmp_path):
        with pytest.raises(RuntimeError, match="rephraser"):
            load_or_generate_perturbations(
                tmp_path / "missing.json", LEGAL_PROMPTS[:1], None
            )


class TestRephrasePipelining:
    """generate_rephrasings overlaps host decode with device sampling:
    with a two-phase (dispatch/fetch) closure, batch N+1 is dispatched
    BEFORE batch N's ids are fetched, and results match the sync path."""

    @staticmethod
    def _two_phase(events):
        def dispatch(texts, key):
            i = len([e for e in events if e[0] == "dispatch"])
            events.append(("dispatch", i))
            return (i, len(texts))

        def fetch(handle):
            i, n = handle
            events.append(("fetch", i))
            return [f"1. Variant {i} a?\n2. Variant {i} b?"] * n

        def generate_text(texts, key):
            return fetch(dispatch(texts, key))

        generate_text.dispatch = dispatch
        generate_text.fetch = fetch
        return generate_text

    def test_dispatch_runs_ahead_of_fetch(self):
        from lir_tpu.engine.rephrase import generate_rephrasings

        events = []
        res = generate_rephrasings(
            self._two_phase(events), LEGAL_PROMPTS[:1], KEY,
            sessions_per_prompt=6, rephrasings_per_session=2,
            sessions_per_batch=2)
        # 3 batches x 2 sessions x 2 rephrasings, none dropped.
        assert len(res[0][1]) == 12
        order = [e for e in events if e[0] in ("dispatch", "fetch")]
        # Pipelined: dispatch(k+1) precedes fetch(k) for every interior k.
        assert order == [("dispatch", 0), ("dispatch", 1), ("fetch", 0),
                         ("dispatch", 2), ("fetch", 1), ("fetch", 2)]

    def test_pipelined_matches_sync_results(self):
        from lir_tpu.engine.rephrase import generate_rephrasings

        two_phase = self._two_phase([])
        res_pipe = generate_rephrasings(
            two_phase, LEGAL_PROMPTS[:2], KEY,
            sessions_per_prompt=5, rephrasings_per_session=2,
            sessions_per_batch=2)

        sync_events = []
        sync = self._two_phase(sync_events)
        plain = lambda texts, key: sync(texts, key)  # noqa: E731 — no attrs
        res_sync = generate_rephrasings(
            plain, LEGAL_PROMPTS[:2], KEY,
            sessions_per_prompt=5, rephrasings_per_session=2,
            sessions_per_batch=2)
        assert res_pipe == res_sync

    def test_failed_dispatch_skips_batch_only(self):
        from lir_tpu.engine.rephrase import generate_rephrasings

        events = []
        gen = self._two_phase(events)
        real_dispatch = gen.dispatch

        def flaky_dispatch(texts, key):
            h = real_dispatch(texts, key)
            if h[0] == 1:
                raise RuntimeError("device hiccup")
            return h

        gen.dispatch = flaky_dispatch
        res = generate_rephrasings(
            gen, LEGAL_PROMPTS[:1], KEY,
            sessions_per_prompt=6, rephrasings_per_session=2,
            sessions_per_batch=2)
        # Batch 1 skipped (session-skip parity); batches 0 and 2 land.
        assert len(res[0][1]) == 8


@pytest.mark.slow
class TestSampleDecode:
    def test_shapes_and_determinism(self):
        params, cfg, _ = _tiny_llama_params()
        toks = np.full((2, 8), 5, dtype=np.int32)
        mask = np.ones_like(toks)
        import jax.numpy as jnp

        g1 = gen_mod.sample_decode(
            params, cfg, jnp.asarray(toks), jnp.asarray(mask), KEY,
            temperature=0.9, max_new_tokens=6,
        )
        g2 = gen_mod.sample_decode(
            params, cfg, jnp.asarray(toks), jnp.asarray(mask), KEY,
            temperature=0.9, max_new_tokens=6,
        )
        assert g1.shape == (2, 6)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))

    def test_eos_stop_matches_unstopped_up_to_eos(self):
        """sample_decode(eos_id=...) must equal the unstopped sampler on
        every row UP TO its first EOS, then emit EOS fill (HF-generate
        parity). Probe engagement with a near-greedy chain that keeps
        emitting a visible token after EOS: a dead eos_id wiring would
        reproduce the unstopped tail and fail the fill assertion."""
        import jax.numpy as jnp

        from lir_tpu.models import decoder
        from lir_tpu.models.registry import ModelConfig

        # Deterministic chain at temperature ~0: 5 -> 6 -> EOS(3) -> 7 ...
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        from chain7b import chain_param_tree

        eos = 3
        cfg = ModelConfig(name="sample-eos-smoke", vocab_size=64,
                          hidden_size=32, n_layers=2, n_heads=4,
                          intermediate_size=64, max_seq_len=64,
                          tie_embeddings=False)
        chain = {5: (6, 7), 6: (eos, 7), eos: (7, 8), 7: (7, 8)}
        params = chain_param_tree(cfg, chain, junk_next=7, junk_second=8,
                                  dtype=jnp.float32)
        toks = jnp.asarray(np.full((2, 4), 5, dtype=np.int32))
        mask = jnp.ones_like(toks)
        kw = dict(temperature=1e-4, max_new_tokens=6)
        free = gen_mod.sample_decode(params, cfg, toks, mask, KEY, **kw)
        stop = gen_mod.sample_decode(params, cfg, toks, mask, KEY,
                                     eos_id=jnp.int32(eos), **kw)
        free, stop = np.asarray(free), np.asarray(stop)
        for r0, r1 in zip(free, stop):
            k = int(np.argmax(r0 == eos))
            assert (r0 == eos).any() and (r0[k + 1:] != eos).any(), \
                "probe chain must emit EOS then keep talking"
            np.testing.assert_array_equal(r1[:k + 1], r0[:k + 1])
            assert (r1[k:] == eos).all(), "stop did not engage"

    def test_low_temperature_approaches_greedy(self):
        params, cfg, _ = _tiny_llama_params()
        import jax.numpy as jnp

        toks = jnp.asarray(np.full((1, 8), 5, dtype=np.int32))
        mask = jnp.ones_like(toks)
        sampled = gen_mod.sample_decode(
            params, cfg, toks, mask, KEY, temperature=1e-4, max_new_tokens=5
        )
        greedy, _ = gen_mod.greedy_decode(
            params, cfg, toks, mask, max_new_tokens=5
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


@pytest.mark.slow
class TestMultiModelSweep:
    def _engine_factory(self):
        params, cfg, _ = _tiny_llama_params(vocab=FakeTokenizer.VOCAB)

        def factory(name):
            if "broken" in name:
                raise RuntimeError("load failure")
            return ScoringEngine(
                params, cfg, FakeTokenizer(),
                RuntimeConfig(batch_size=8, max_new_tokens=4, max_seq_len=128),
            )

        return factory

    def test_sweep_writes_csvs_and_handles_failure(self, tmp_path):
        specs = [
            ModelSpec("org/tiny-base", "base"),
            ModelSpec("org/tiny-instruct", "instruct"),
            ModelSpec("org/broken-model", "instruct"),
        ]
        questions = ["Is a cat an animal", "Is a rock an animal"]
        res = run_model_comparison_sweep(
            specs, self._engine_factory(), tmp_path, questions=questions,
        )
        d1 = pd.read_csv(tmp_path / "model_comparison_results.csv")
        assert len(d1) == 6  # 3 models x 2 questions, incl. NaN rows
        broken = d1[d1["model"] == "org/broken-model"]
        assert broken["yes_prob"].isna().all()
        assert (broken["model_output"] == "ERROR").all()

        d2 = pd.read_csv(tmp_path / "instruct_model_comparison_results.csv")
        assert set(d2["model"]) == {"org/tiny-instruct", "org/broken-model"}
        assert "relative_prob" in d2.columns

        assert (tmp_path / "sweep_session_log.txt").exists()
        assert res["throughput"]["prompts"] == 4  # 2 ok models x 2 questions
        assert res["per_model"]["org/broken-model"]["status"].startswith("error")

    def test_formatter_routing(self):
        assert "Question:" in format_for(ModelSpec("x/base-model", "base"))("Q?")
        # D1 semantics: instruct models still get the few-shot prefix.
        d1_instruct = format_for(ModelSpec("x/chat", "instruct"))("Q?")
        assert d1_instruct.startswith("Question:")
        assert d1_instruct.rstrip().endswith("without any other text.")
        # bloom-7b1 gets the base scaffold (reference special case).
        assert "Answer:" in format_for(
            ModelSpec("bigscience/bloom-7b1", "base")
        )("Q?")
        # D2 semantics: bare question, Baichuan chat template.
        d2 = format_for(ModelSpec("x/chat", "instruct"), "instruct_only")("Q?")
        assert d2.startswith("Q?")
        bc = format_for(
            ModelSpec("baichuan-inc/Baichuan2-7B-Chat", "instruct"),
            "instruct_only",
        )("Q?")
        assert bc.startswith("<human>:") and bc.endswith("<bot>:")

    def test_pair_expansion(self):
        specs = base_instruct_pairs([("a/base", "a/chat"), ("b/base", "b/chat")])
        assert [s.name for s in specs] == ["a/base", "a/chat", "b/base", "b/chat"]
        assert [s.base_or_instruct for s in specs] == [
            "base", "instruct", "base", "instruct",
        ]


class FakeTransport:
    """In-memory BatchTransport: echoes deterministic completions."""

    def __init__(self):
        self.files = {}
        self.batches = {}
        self.poll_count = 0

    def upload_jsonl(self, lines):
        fid = f"file-{len(self.files)}"
        self.files[fid] = list(lines)
        return fid

    def create_batch(self, file_id):
        bid = f"batch-{len(self.batches)}"
        self.batches[bid] = file_id
        return bid

    def batch_status(self, batch_id):
        self.poll_count += 1
        return "completed" if self.poll_count > 1 else "in_progress"

    def batch_output_file(self, batch_id):
        fid = self.batches[batch_id]
        out = []
        for line in self.files[fid]:
            req = json.loads(line)
            is_binary = req["custom_id"].endswith("_binary")
            if is_binary:
                content = "Covered"
                logprobs = {
                    "content": [
                        {
                            "token": "Covered",
                            "logprob": -0.2,
                            "top_logprobs": [
                                {"token": "Covered", "logprob": -0.2},
                                {"token": "Not", "logprob": -1.8},
                            ],
                        }
                    ]
                }
            else:
                content = "85"
                logprobs = {
                    "content": [
                        {
                            "token": "85",
                            "logprob": -0.1,
                            "top_logprobs": [
                                {"token": "85", "logprob": -0.1},
                                {"token": "90", "logprob": -2.0},
                                {"token": "high", "logprob": -3.0},
                            ],
                        }
                    ]
                }
            out.append(
                json.dumps(
                    {
                        "custom_id": req["custom_id"],
                        "response": {
                            "body": {
                                "choices": [
                                    {
                                        "message": {"content": content},
                                        "logprobs": logprobs,
                                    }
                                ]
                            }
                        },
                    }
                )
            )
        ofid = f"out-{batch_id}"
        self.files[ofid] = out
        return ofid

    def download_jsonl(self, file_id):
        return self.files[file_id]


class TestApiBackend:
    def test_request_building_and_chunking(self):
        cells = grid_mod.build_grid(
            "gpt-x", LEGAL_PROMPTS[:2], [["v1", "v2"], ["v1"]]
        )
        requests, id_map = api_mod.build_batch_requests(cells, "gpt-x")
        # 2 formats per cell; 3+2 cells.
        assert len(requests) == 10
        assert len(id_map) == 10
        binary = [r for r in requests if r["custom_id"].endswith("_binary")]
        assert all(r["body"]["top_logprobs"] == 20 for r in binary)
        assert all(r["body"]["temperature"] == 0 for r in requests)

        chunks = api_mod.chunk_requests(requests, max_batch_size=4)
        assert [len(c) for c in chunks] == [4, 4, 2]

    def test_reasoning_model_requests(self):
        cells = grid_mod.build_grid("o3", LEGAL_PROMPTS[:1], [["v1"]])
        # Default = the reference's SKIP_REASONING_MODEL_LOGPROBS=True
        # mode: confidence-only grid (perturb_prompts.py:211).
        requests, _ = api_mod.build_batch_requests(
            cells, "o3", reasoning_model=True
        )
        assert [r["custom_id"] for r in requests] == [
            "p0_r0_confidence", "p0_r1_confidence"]
        assert all(r["body"]["max_completion_tokens"] == 2000 for r in requests)
        assert all("temperature" not in r["body"] for r in requests)
        # Non-skip mode: 10 binary runs + confidence per cell.
        requests, _ = api_mod.build_batch_requests(
            cells, "o3", reasoning_model=True, skip_reasoning_logprobs=False
        )
        assert len(requests) == 22

    def test_end_to_end_decode(self):
        cells = grid_mod.build_grid("gpt-x", LEGAL_PROMPTS[:1], [["v1"]])
        requests, id_map = api_mod.build_batch_requests(cells, "gpt-x")
        transport = FakeTransport()
        results = api_mod.run_batch(
            transport, requests, poll_interval=0, sleep=lambda s: None
        )
        assert results is not None
        scores = api_mod.decode_batch_results(results, id_map)
        assert len(scores) == 2  # original + 1 rephrasing
        s = next(iter(scores.values()))
        assert s.token_1_prob == pytest.approx(np.exp(-0.2))
        assert s.token_2_prob == pytest.approx(np.exp(-1.8))
        assert s.confidence_value == 85
        # E[v] over the two integer tokens only.
        p85, p90 = np.exp(-0.1), np.exp(-2.0)
        assert s.weighted_confidence == pytest.approx(
            (85 * p85 + 90 * p90) / (p85 + p90)
        )

    def test_terminal_failure_returns_none(self):
        class FailingTransport(FakeTransport):
            def batch_status(self, batch_id):
                return "failed"

        cells = grid_mod.build_grid("gpt-x", LEGAL_PROMPTS[:1], [[]])
        requests, _ = api_mod.build_batch_requests(cells, "gpt-x")
        assert api_mod.run_batch(
            FailingTransport(), requests, poll_interval=0, sleep=lambda s: None
        ) is None


class TestThroughputMeter:
    def test_prompts_per_chip(self):
        meter = ThroughputMeter(n_devices=8)
        with meter.measure():
            pass
        meter.elapsed = 2.0
        meter.add(prompts=160)
        assert meter.prompts_per_sec == pytest.approx(80.0)
        assert meter.prompts_per_sec_per_chip == pytest.approx(10.0)
        summary = meter.summary()
        assert summary["n_devices"] == 8
        assert summary["prompts_per_sec_per_chip"] == pytest.approx(10.0)


@pytest.mark.slow
class TestReasoningRuns:
    def test_run_requests_and_averaging(self):
        cells = grid_mod.build_grid("o3", LEGAL_PROMPTS[:1], [[]])
        requests, id_map = api_mod.build_batch_requests(
            cells, "o3", reasoning_model=True, reasoning_runs=4,
            skip_reasoning_logprobs=False
        )
        # 1 cell -> 4 binary runs + 1 confidence.
        assert len(requests) == 5
        run_ids = [r["custom_id"] for r in requests if "_run" in r["custom_id"]]
        assert len(run_ids) == 4

        # Synthesize results: 3 runs answer "Covered", 1 answers
        # "Not Covered" (which contains both targets -> counts as token 1
        # under the reference's if/elif order).
        results = []
        answers = ["Covered", "Covered", "Covered", "Not Covered"]
        for cid, ans in zip(run_ids, answers):
            results.append({
                "custom_id": cid,
                "response": {"body": {"choices": [
                    {"message": {"content": ans}, "logprobs": None}
                ]}},
            })
        results.append({
            "custom_id": "p0_r0_confidence",
            "response": {"body": {"choices": [
                {"message": {"content": "The answer is 73"}, "logprobs": None}
            ]}},
        })
        scores = api_mod.decode_batch_results(results, id_map)
        s = scores["p0_r0"]
        assert s.token_1_prob == pytest.approx(1.0)  # all 4 contain "Covered"
        assert s.token_2_prob == pytest.approx(0.0)
        assert s.response_text == "Covered"
        assert s.confidence_value == 73
        assert s.weighted_confidence == 73


@pytest.mark.slow
class TestEncDecEngine:
    """End-to-end ScoringEngine on the T5 branch (the reference's Seq2Seq
    routing, compare_base_vs_instruct.py:203-241): greedy decode + C13
    readout + generation parity vs HF generate."""

    @pytest.fixture(scope="class")
    def t5_engine(self):
        import transformers as tf
        from lir_tpu.models.loader import convert_t5, t5_config_from_hf

        torch.manual_seed(0)
        hf_cfg = tf.T5Config(
            vocab_size=FakeTokenizer.VOCAB, d_model=64, d_kv=16, d_ff=128,
            num_layers=2, num_heads=4, feed_forward_proj="gated-gelu",
            tie_word_embeddings=False, decoder_start_token_id=0,
            eos_token_id=0, pad_token_id=0,
        )
        hf = tf.T5ForConditionalGeneration(hf_cfg).eval()
        cfg = t5_config_from_hf(hf.config)
        params = convert_t5(hf.state_dict(), cfg)
        engine = ScoringEngine(
            params, cfg, FakeTokenizer(),
            RuntimeConfig(batch_size=4, max_new_tokens=5, max_seq_len=64),
            encoder_decoder=True,
        )
        return engine, hf

    def test_score_prompts_shapes(self, t5_engine):
        engine, _ = t5_engine
        rows = engine.score_prompts(["Is a cat an animal", "Is a rock alive"])
        assert len(rows) == 2
        for r in rows:
            assert 0.0 <= r.yes_prob <= 1.0
            assert 0.0 <= r.no_prob <= 1.0
            assert np.isfinite(r.relative_prob) or (r.yes_prob + r.no_prob) == 0

    def test_greedy_generation_matches_hf(self, t5_engine):
        import jax.numpy as jnp
        from lir_tpu.engine import generate as gen_mod

        engine, hf = t5_engine
        enc = np.asarray([[5, 9, 12, 40, 7, 3]], dtype=np.int32)
        gen, _ = gen_mod.t5_greedy_decode(
            engine.params, engine.cfg, jnp.asarray(enc),
            jnp.ones_like(jnp.asarray(enc)), max_new_tokens=5)
        with torch.no_grad():
            ref = hf.generate(
                torch.tensor(enc.astype(np.int64)), max_new_tokens=5,
                do_sample=False, min_new_tokens=5,
            ).numpy()
        # HF prepends decoder_start (0); compare the 5 generated tokens.
        np.testing.assert_array_equal(np.asarray(gen)[0], ref[0, 1:6])


def test_throughput_meter_mfu_fields():
    """flops_per_prompt turns the sweep summary into an MFU sanity check
    (VERDICT r1 weak #2: no implied-TFLOPS figure existed anywhere)."""
    from lir_tpu.utils.profiling import ThroughputMeter, scoring_step_flops
    from lir_tpu.models.registry import llama2_7b

    m = ThroughputMeter(n_devices=1)
    per_prompt = scoring_step_flops(llama2_7b(), 1, 256, 10)
    m.elapsed = 2.0
    m.add(100, flops=100 * per_prompt)
    s = m.summary()
    assert s["implied_tflops_per_chip"] > 0
    expected = per_prompt * 100 / 2.0 / 1e12
    assert abs(s["implied_tflops_per_chip"] - round(expected, 2)) < 1e-9
    # CPU backend: unknown chip -> no mfu key rather than a bogus number.
    assert "mfu" not in s


# ---------------------------------------------------------------------------
# MFU gate: chip kind table + armed-on-unknown behavior (VERDICT r2 weak #6)
# ---------------------------------------------------------------------------

class _FakeDev:
    def __init__(self, kind):
        self.device_kind = kind


def test_chip_peak_table_covers_tpu_generations():
    from lir_tpu.utils import profiling as prof
    # bf16 peaks
    assert prof.chip_peak_flops(_FakeDev("TPU v4")) == 275e12
    assert prof.chip_peak_flops(_FakeDev("TPU v5p")) == 459e12
    assert prof.chip_peak_flops(_FakeDev("TPU v5 lite")) == 197e12
    assert prof.chip_peak_flops(_FakeDev("TPU v6 lite")) == 918e12
    # int8: 2x everywhere EXCEPT v4 (no accelerated s8 path)
    assert prof.chip_peak_flops(_FakeDev("TPU v4"), int8=True) == 275e12
    assert prof.chip_peak_flops(_FakeDev("TPU v5p"), int8=True) == 2 * 459e12
    assert prof.chip_peak_flops(_FakeDev("TPU v6 lite"), int8=True) == 2 * 918e12
    # unknown kind -> None (bench.py then ABORTS unless --allow-ungated)
    assert prof.chip_peak_flops(_FakeDev("TPU v9 hyper")) is None
    assert prof.chip_peak_flops(_FakeDev("")) is None


@pytest.mark.slow
def test_bench_aborts_on_unknown_chip(monkeypatch, tmp_path):
    """bench.py must exit non-zero when the chip kind has no peak entry and
    --allow-ungated was not passed (the gate can't arm -> refuse to report).
    Run in-process with a faked accelerator device list."""
    import subprocess
    import sys as _sys
    code = r"""
import sys, types
import jax
class _D:
    platform = "tpu"
    device_kind = "TPU v99 imaginary"
jax.devices = lambda *a, **k: [_D()]
sys.argv = ["bench.py"]
import bench
try:
    bench.main()
except SystemExit as e:
    sys.exit(e.code)
print("REACHED-REPORT")
sys.exit(0)
"""
    r = subprocess.run([_sys.executable, "-c", code], capture_output=True,
                       text=True, cwd="/root/repo",
                       env={"PATH": "/usr/bin:/bin",
                            "JAX_PLATFORMS": "cpu",
                            "HOME": "/root"})
    assert r.returncode == 1, (r.returncode, r.stdout, r.stderr)
    assert "MFU sanity gate" in r.stderr
    assert "REACHED-REPORT" not in r.stdout


def test_cli_perturb_budget_and_stop_flags(monkeypatch, tmp_path):
    """The perturb subcommand must thread the decode-budget/stop flags
    into RuntimeConfig (DEPLOY.md §1 tells operators to size
    --sweep-confidence-tokens — the flag has to exist and land)."""
    import lir_tpu.cli as cli

    captured = {}

    class _Stop(Exception):
        pass

    def fake_factory(root, rt, *a, **kw):
        captured["rt"] = rt
        raise _Stop

    monkeypatch.setattr("lir_tpu.models.factory.engine_factory",
                        fake_factory)
    base = ["perturb", "--checkpoints", str(tmp_path), "--model", "m"]
    with pytest.raises(_Stop):
        cli.main(base + ["--sweep-confidence-tokens", "16",
                         "--sweep-decode-tokens", "2", "--no-early-stop"])
    rt = captured["rt"]
    assert rt.sweep_confidence_tokens == 16
    assert rt.sweep_decode_tokens == 2
    assert rt.sweep_early_stop is False

    with pytest.raises(_Stop):
        cli.main(base)
    rt = captured["rt"]                 # defaults untouched
    assert rt.sweep_confidence_tokens == 8
    assert rt.sweep_decode_tokens == 4
    assert rt.sweep_early_stop is True


def test_cli_bench_passes_clean_argv(monkeypatch):
    """`lir_tpu bench` must not leak the CLI's own argv into bench.py's
    argparse (bench.py now parses --allow-ungated itself)."""
    import sys

    import lir_tpu.cli as cli

    seen = {}

    def fake_run_path(path, run_name):
        seen["argv"] = list(sys.argv)
        seen["run_name"] = run_name

    monkeypatch.setattr("runpy.run_path", fake_run_path)
    before = list(sys.argv)
    cli.main(["bench", "--allow-ungated"])
    assert seen["run_name"] == "__main__"
    assert seen["argv"][0].endswith("bench.py")
    assert seen["argv"][1:] == ["--allow-ungated"]
    assert sys.argv == before          # restored

    cli.main(["bench"])
    assert seen["argv"][1:] == []

    cli.main(["bench", "--model", "mistral_7b", "--sweep-batches", "48,40"])
    assert seen["argv"][1:] == ["--model", "mistral_7b",
                                "--sweep-batches", "48,40"]


def test_cli_bench_rejects_unknowns_before_subcommand(monkeypatch):
    """Only tokens AFTER the `bench` subcommand forward to bench.py; a
    typo of the CLI's own flags (which argparse sees before the
    subcommand) fails with the CLI's usage error, not bench.py's
    (ADVICE r5, cli.py:470)."""
    import lir_tpu.cli as cli

    called = []
    monkeypatch.setattr("runpy.run_path",
                        lambda path, run_name: called.append(path))
    for argv in (["--typo", "bench"],
                 ["--allow-ungatd", "bench", "--model", "x"]):
        with pytest.raises(SystemExit) as exc:
            cli.main(argv)
        assert exc.value.code == 2      # argparse usage error
    assert called == []                  # bench.py never ran
    cli.main(["bench", "--no-varlen"])   # post-subcommand still forwards
    assert called
