"""Seeded donation-safety violations (tests/test_lint.py). Never
imported — parsed by the lint pass only."""

import functools

import jax


@functools.partial(jax.jit, donate_argnames=("scratch",))
def consume(x, scratch):
    del scratch  # donated: memory reuse only
    return x + 1


def chain_bad(x, scratch):
    out = consume(x, scratch)
    return out + scratch.sum()       # VIOLATION: read after donation


def chain_bad_kw(x, scratch):
    out = consume(x, scratch=scratch)
    return out, scratch              # VIOLATION: read after kw donation
