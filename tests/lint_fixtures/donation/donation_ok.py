"""Clean twins for donation-safety: the sanctioned idioms the pass must
NOT flag."""

import functools

import jax


@functools.partial(jax.jit, donate_argnames=("carry",))
def step(carry):
    return carry * 2


def chain_ok(carry):
    carry = step(carry)          # rebind from the result: the chain idiom
    return carry + 1


def branch_ok(x, scratch, flag):
    if flag:
        out = step(scratch)      # donation in this arm only
    else:
        out = x + scratch.sum()  # sibling arm: never reached after it
    return out


def identity_ok(scratch):
    out = step(scratch)
    used = scratch is not None   # identity test touches the ref, not
    return out, used             # the dead buffer


def splat_ok(x, kwargs):
    out = step(x, **kwargs)      # **splat is not a donated slot
    return out, kwargs
