"""Same syntactic pattern as hot_bad, but OUTSIDE the hot-path scope
(stats code syncs freely) — the pass must not flag it."""

import jax.numpy as jnp
import numpy as np


def cold_readout(values):
    dev = jnp.asarray(values) * 2
    return np.asarray(dev)
