"""Seeded host-sync violations in a hot-path module
(tests/test_lint.py)."""

import jax
import jax.numpy as jnp
import numpy as np


def bad_asarray(tokens):
    fused = jnp.dot(tokens, tokens)
    return np.asarray(fused)          # VIOLATION: implicit transfer


def bad_float(tokens):
    total = jnp.sum(tokens)
    return float(total)               # VIOLATION: scalar coercion sync


def bad_truthiness(tokens):
    mask = jnp.any(tokens)
    if mask:                          # VIOLATION: truthiness blocks
        return 1
    return 0


def bad_iteration(tokens):
    rows = jnp.abs(tokens)
    out = []
    for r in rows:                    # VIOLATION: per-element sync
        out.append(r)
    return out


def _decode_row(row):
    return row.tolist()               # VIOLATION: reached with device arg


def bad_cross_function(tokens):
    dev = jnp.exp(tokens)
    return _decode_row(dev)
