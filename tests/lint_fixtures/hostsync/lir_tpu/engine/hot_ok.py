"""Clean twins for host-sync: sanctioned readout patterns."""

import jax
import jax.numpy as jnp
import numpy as np

from lir_tpu.utils.annotations import host_readout


def ok_device_get(tokens):
    fused = jnp.dot(tokens, tokens)
    host = jax.device_get(fused)      # explicit boundary
    return float(host[0])


@host_readout
def ok_declared_boundary(tokens):
    total = jnp.sum(tokens)
    return float(total)               # allowed: declared readout


def ok_allow_comment(tokens):
    total = jnp.sum(tokens)
    return float(total)  # lint: allow(host-sync)


def ok_shape_metadata(tokens):
    total = jnp.sum(tokens)
    n = int(total.shape[0]) if total.ndim else 0   # static metadata
    return n


def ok_host_data(lengths):
    arr = np.asarray(lengths, np.int32)            # host list in
    return arr.tolist()
