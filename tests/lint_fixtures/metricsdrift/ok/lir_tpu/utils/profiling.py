"""Clean twin: every public field declared, no stale entries."""

import dataclasses


@dataclasses.dataclass
class FooStats:
    hits: int = 0
    misses: int = 0
    _private: int = 0


@dataclasses.dataclass
class BarStats:
    count: int = 0
