STATS_SCHEMA = {
    "FooStats": ("hits", "misses"),
    "BarStats": ("count",),
}
