"""Seeded metrics-drift violations: a field missing from the schema, a
class missing entirely, and a stale schema entry (see registry.py)."""

import dataclasses


@dataclasses.dataclass
class FooStats:
    hits: int = 0
    misses: int = 0          # missing from STATS_SCHEMA["FooStats"]
    _private: int = 0        # underscore: owes nothing to the endpoint


@dataclasses.dataclass
class OrphanStats:           # no STATS_SCHEMA entry at all
    count: int = 0
