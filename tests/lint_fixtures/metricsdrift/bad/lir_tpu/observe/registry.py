STATS_SCHEMA = {
    "FooStats": ("hits", "evictions"),   # "evictions" is stale
}
