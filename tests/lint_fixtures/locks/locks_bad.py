"""Seeded lock-discipline violations (tests/test_lint.py)."""

import threading
from collections import deque


class BadServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = deque()          # guarded-by: _lock
        self._state = "closed"     # guarded-by: _lock

    def submit(self, item):
        self._q.append(item)       # VIOLATION: mutator outside the lock

    def trip(self):
        self._state = "open"       # VIOLATION: assignment outside lock

    def ok_read(self):
        return len(self._q)        # reads are not enforced


class TypoServer:
    def __init__(self):
        self._x = 0                # guarded-by: _missing_lock
        # VIOLATION: annotation names a lock the class never creates
