"""Clean twins for lock-discipline: every annotated mutation holds its
lock (or runs in a held-by-caller method)."""

import threading
from collections import deque


class GoodServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._q = deque()          # guarded-by: _lock | _cond
        self._state = "closed"     # guarded-by: _lock

    def submit(self, item):
        with self._cond:           # the Condition wraps the same lock
            self._q.append(item)

    def drain(self):
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out

    def trip(self):
        with self._lock:
            self._transition("open")

    def _transition(self, to):  # guarded-by: _lock
        self._state = to

    def depth(self):
        return len(self._q)        # read: not enforced
