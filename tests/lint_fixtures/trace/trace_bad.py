"""Seeded trace-hazard violations (tests/test_lint.py)."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("n",))
def bad_branch(x, n):
    if x > 0:                # VIOLATION: python branch on traced value
        return x * n
    return -x


@jax.jit
def bad_coerce(x):
    return float(x)          # VIOLATION: scalar coercion under trace


@jax.jit
def bad_item(x):
    y = jnp.sum(x)
    return y.item()          # VIOLATION: .item() under trace


@jax.jit
def bad_set(x):
    leaves = {}
    for name in {"alpha", "beta"}:   # VIOLATION: unordered set feeds
        leaves[name] = x * 2         # pytree construction
    return leaves


def helper(y):
    return int(y)            # VIOLATION: reached with traced arg


@jax.jit
def bad_propagated(x):
    return helper(x * 2)
