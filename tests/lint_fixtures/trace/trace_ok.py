"""Clean twins for trace-hazard: static branches the pass must NOT
flag."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("flag",))
def ok_static_branch(x, flag):
    if flag:                     # static argument: resolved at trace time
        return x
    return -x


@jax.jit
def ok_shape_branch(x):
    if x.shape[0] > 1:           # shape metadata is static under trace
        return jnp.sum(x)
    return x


@jax.jit
def ok_identity(x, y=None):
    if y is None:                # identity test: no concretization
        return x
    return x + y


@jax.jit
def ok_lax_cond(x):
    return lax.cond(jnp.sum(x) > 0, lambda v: v, lambda v: -v, x)


@jax.jit
def ok_dict_iteration(x):
    out = {}
    for k, v in {"a": x, "b": x * 2}.items():   # dicts are ordered
        out[k] = v + 1
    return out


def host_probe(key):
    return key.ndim == 2         # metadata probe: returns a static bool


@jax.jit
def ok_metadata_call(x, key):
    per_row = host_probe(key)
    if per_row:                  # static bool from a metadata probe
        return x * 2
    return x
