"""Mini config with a drifted knob: ``fancy_knob`` has no CLI flag, no
DEPLOY.md mention, and is missing from the hand-built manifest-key
projection in engine/runner.py."""

import dataclasses


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    batch_size: int = 32
    fancy_knob: int = 7
    log_level: str = "info"    # host-only


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    queue_depth: int = 256
