"""Mini runner whose manifest key degraded into a hand-picked
projection — the drift the pass exists to catch."""


def cache_manifest_key(self):
    from ..utils import compile_cache

    return compile_cache.manifest_key(
        self.cfg, {"batch_size": self.rt.batch_size}, buckets=[64])
