"""Mini CLI knowing only batch_size / queue_depth / log_level."""

FLAGS = ["--batch-size", "--queue-depth", "--log-level"]
