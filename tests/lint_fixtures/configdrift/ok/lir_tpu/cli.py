"""Mini CLI covering every knob."""

FLAGS = ["--batch-size", "--fancy-knob", "--queue-depth", "--log-level"]
