"""Clean twin: the whole RuntimeConfig feeds the manifest key, so every
field participates by construction."""


def cache_manifest_key(self):
    from ..utils import compile_cache

    return compile_cache.manifest_key(self.cfg, self.rt, buckets=[64])
