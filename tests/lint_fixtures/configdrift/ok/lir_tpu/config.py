"""Clean twin: every knob flagged, documented, and key-covered."""

import dataclasses


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    batch_size: int = 32
    fancy_knob: int = 7
    log_level: str = "info"    # host-only


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    queue_depth: int = 256
