"""Compile-plan tests (engine/compile_plan.py + utils/compile_cache.py).

Pins the cache-keying contract the cold-start tentpole relies on:
- the manifest key separates every input that changes an executable
  (model config, quant mode, mesh, bucket ladder, runtime budgets) — no
  stale-executable reuse is possible across configurations;
- plan_specs mirrors the runner's padding and cache-handoff variant
  selection exactly, so every planned executable is the one dispatched;
- same-shape dispatches reuse ONE registry executable (and the donated
  variant is a distinct one);
- precompiled-vs-lazy sweep results are bitwise identical;
- the persistent disk cache round-trips a recompile after
  jax.clear_caches() into a cache hit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.engine import compile_plan, scheduler as sched_mod
from lir_tpu.engine import tokens as tok
from lir_tpu.utils import compile_cache
from lir_tpu.utils.profiling import CompileStats, OccupancyStats


# ---------------------------------------------------------------------------
# Manifest key: every configuration input separates the key space
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Cfg:
    name: str = "m"
    hidden_size: int = 64
    n_layers: int = 2
    vocab_size: int = 1000


def test_manifest_key_deterministic_and_sensitive():
    cfg, rt = _Cfg(), RuntimeConfig()
    base = dict(buckets=(64, 128, 256), quant="fp",
                mesh={"devices": 8, "platform": "cpu"})
    key = compile_cache.manifest_key(cfg, rt, **base)
    # Deterministic: same inputs, same key (stable across processes too —
    # sha256 over canonical JSON, no id()/hash() randomness).
    assert key == compile_cache.manifest_key(cfg, rt, **base)
    assert len(key) == 16

    # Each input that changes compiled programs changes the key.
    variants = [
        compile_cache.manifest_key(
            dataclasses.replace(cfg, hidden_size=128), rt, **base),
        compile_cache.manifest_key(
            cfg, dataclasses.replace(rt, sweep_decode_tokens=6), **base),
        compile_cache.manifest_key(
            cfg, rt, **{**base, "quant": "int8-dyn"}),
        compile_cache.manifest_key(
            cfg, rt, **{**base, "mesh": {"devices": 1, "platform": "cpu"}}),
        compile_cache.manifest_key(
            cfg, rt, **{**base, "buckets": (64, 96, 128, 256)}),
    ]
    assert len({key, *variants}) == 1 + len(variants)


def test_quant_mode_fingerprint():
    from lir_tpu.models.quant import QuantTensor

    fp = {"w": jnp.zeros((4, 4), jnp.float32)}
    q8 = {"w": QuantTensor(q=jnp.zeros((4, 4), jnp.int8),
                           scale=jnp.ones((4,), jnp.float32))}
    q8d = {"w": QuantTensor(q=jnp.zeros((4, 4), jnp.int8),
                            scale=jnp.ones((4,), jnp.float32),
                            dynamic=True)}
    modes = {compile_cache.quant_mode(p) for p in (fp, q8, q8d)}
    assert len(modes) == 3  # fp32 / int8 / int8-dyn all distinct


# ---------------------------------------------------------------------------
# plan_specs mirrors the runner: padding + handoff variants
# ---------------------------------------------------------------------------

def _items(lengths, fmt_len=6):
    items = []
    for i, n in enumerate(lengths):
        base = [100 + i] * n
        items.append(sched_mod.SweepItem(
            cell=("cell", i), bin_ids=tuple(base + [7] * fmt_len),
            conf_ids=tuple(base + [9] * fmt_len), lcp=n))
    return items


def test_plan_specs_variants_and_order():
    # 12 same-bucket cells at batch 4 -> 3 shared dispatches of one
    # shape: spec 1 scratchless (first of the handoff chain), spec 2 the
    # donated variant serving dispatches 2 AND 3 — exactly two
    # executables, in first-use order.
    buckets = tok.bucket_ladder(256)
    planner = sched_mod.RaggedScheduler(buckets, 4, group_cells=False,
                                        stats=OccupancyStats())
    dispatches = planner.schedule(_items([30] * 12))
    assert len(dispatches) == 3
    specs = compile_plan.plan_specs(dispatches, 4, new_tokens=4,
                                    conf_tokens=8, stops_armed=False)
    assert len(specs) == 2
    assert [s.scratch for s in specs] == [False, True]
    assert all(s.kind == "shared" and s.batch == 4 for s in specs)
    assert specs[0] == dataclasses.replace(specs[1], scratch=False)

    # The padded tail dispatch (13th cell -> power-of-two pad) is its own
    # shape; stops_armed flips every spec (different traced pytree).
    d13 = planner.schedule(_items([30] * 13))
    specs13 = compile_plan.plan_specs(d13, 4, 4, 8, stops_armed=False)
    assert {s.batch for s in specs13} == {4, 1}
    armed = compile_plan.plan_specs(d13, 4, 4, 8, stops_armed=True)
    assert set(armed).isdisjoint(specs13)


def test_plan_specs_padded_rows_match_runner_tail():
    from lir_tpu.engine.runner import _tail_batch

    planner = sched_mod.RaggedScheduler(tok.bucket_ladder(256), 8,
                                        group_cells=False,
                                        stats=OccupancyStats())
    for n in (1, 3, 5, 8, 11):
        dispatches = planner.schedule(_items([40] * n))
        for d in dispatches:
            rows = d.padded_rows(8)
            expect = (8 if len(d.items) == 8
                      else _tail_batch(len(d.items), 8))
            assert rows == (expect, expect)


def test_sweep_specs_for_ladder_covers_every_edge():
    engine = _tiny_engine(RuntimeConfig(batch_size=4, max_seq_len=256))
    specs = compile_plan.sweep_specs_for_ladder(engine, sfx_buckets=(8, 16))
    # Every (edge, sfx, handoff) combination plans BOTH the sequential
    # executable and its speculative sibling (spec_k-keyed).
    seq = [s for s in specs if not s.spec_k]
    spec = [s for s in specs if s.spec_k]
    assert len(seq) == len(engine.buckets) * 2 * 2
    assert len(spec) == len(seq)
    assert all(s.spec_k == engine.rt.spec_k for s in spec)
    assert {s.bucket for s in specs} == set(engine.buckets)
    assert all(s.batch == 4 and s.kind == "shared" for s in specs)
    # FakeTokenizer exposes no per-token strings -> stops can't arm.
    assert not any(s.stops_armed for s in specs)


# ---------------------------------------------------------------------------
# Engine-level: registry reuse + bitwise parity with the lazy path
# ---------------------------------------------------------------------------

def _tiny_engine(rt, seed=2):
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="cp-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    return ScoringEngine(params, cfg, FakeTokenizer(), rt)


def _grid(n_cells, words_each=12, seed=5):
    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer "
             "premium exclusion endorsement").split()

    def text():
        return " ".join(rng.choice(words) for _ in range(words_each)) + " ?"

    lp = (LegalPrompt(main=text(), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    return lp, ([text() for _ in range(n_cells - 1)],)


def test_same_shape_dispatches_reuse_one_executable(tmp_path):
    """12 equal-length cells at batch 4 = 3 dispatches of one shape: with
    piggybacking OFF the registry compiles exactly three executables
    (fresh + donated handoff variants, plus the streaming-stats fold for
    the one fold width) and serves every dispatch AND every fold — zero
    lazy misses."""
    from lir_tpu.engine.sweep import run_perturbation_sweep

    compile_plan.exec_cache_clear()  # order-independence: force compiles
    engine = _tiny_engine(RuntimeConfig(batch_size=4, max_seq_len=256,
                                        piggyback_prefill=False))
    lp, perts = _grid(12)
    rows = run_perturbation_sweep(engine, "cp", lp, perts,
                                  tmp_path / "r.xlsx",
                                  checkpoint_every=100)
    assert len(rows) == 12
    reg = engine.exec_registry
    # fresh + donated handoff variants of the sequential AND speculative
    # shared executables, plus the streaming-stats fold.
    assert reg is not None and len(reg) == 5
    assert {s.kind for s in reg._futures} == {"shared", "stream_fold"}
    # 3 dispatch hits + 3 accumulator-fold hits.
    assert engine.compile_stats.aot_hits == 6
    assert engine.compile_stats.lazy_misses == 0
    assert len(engine.compile_stats.shapes) == 5
    assert all(t > 0 for t in engine.compile_stats.shapes.values())
    # Registry is namespaced by the engine's manifest key.
    assert reg.manifest_key == engine.cache_manifest_key


def test_piggyback_chain_runs_precompiled(tmp_path):
    """With piggybacking ON (the default), the same 3-dispatch plan chains
    through the piggyback executables: the plan additionally covers the
    opener/step/drain stages, every chain call is served by the registry,
    and nothing falls back to lazy jit."""
    from lir_tpu.engine.sweep import run_perturbation_sweep

    compile_plan.exec_cache_clear()
    engine = _tiny_engine(RuntimeConfig(batch_size=4, max_seq_len=256))
    lp, perts = _grid(12)
    rows = run_perturbation_sweep(engine, "cp-piggy", lp, perts,
                                  tmp_path / "r.xlsx",
                                  checkpoint_every=100)
    assert len(rows) == 12
    reg = engine.exec_registry
    # 2 plain + 2 speculative (fresh + donated each, kept for the
    # unchained/recovery fallback) + the piggyback chain's 3 stages +
    # the streaming-stats fold width.
    assert reg is not None and len(reg) == 8
    kinds = {s.kind for s in reg._futures}
    assert {"piggy_prefill", "piggy_step", "piggy_drain",
            "stream_fold"} <= kinds
    # opener + 2 steps + drain + 3 accumulator folds, all registry-served.
    assert engine.compile_stats.aot_hits == 7
    assert engine.compile_stats.lazy_misses == 0
    assert engine.kernel_stats.counters.get("piggybacked_steps") == 2


def test_engines_with_different_configs_get_different_manifest_keys():
    e1 = _tiny_engine(RuntimeConfig(batch_size=4, max_seq_len=256))
    e2 = _tiny_engine(RuntimeConfig(batch_size=8, max_seq_len=256))
    e3 = _tiny_engine(RuntimeConfig(batch_size=4, max_seq_len=512))
    keys = {e.cache_manifest_key for e in (e1, e2, e3)}
    assert len(keys) == 3  # batch and ladder both separate the key space


@pytest.mark.slow
def test_precompiled_matches_lazy_bitwise(tmp_path):
    """AOT-precompiled and lazily-jitted sweeps hash to the same HLO, so
    their rows must agree BITWISE (with the persistent cache enabled the
    lazy path literally deserializes the executable the AOT path wrote)."""
    from lir_tpu.engine.sweep import run_perturbation_sweep

    compile_cache.enable_persistent_cache(tmp_path / "xla")
    try:
        lp, perts = _grid(13, seed=9)

        def run(aot, sub):
            rt = RuntimeConfig(batch_size=4, max_seq_len=256,
                               aot_precompile=aot)
            engine = _tiny_engine(rt)
            return run_perturbation_sweep(
                engine, "cp-bitwise", lp, perts,
                tmp_path / sub / "r.xlsx", checkpoint_every=100), engine

        rows_a, eng_a = run(True, "aot")
        jax.clear_caches()
        rows_l, _ = run(False, "lazy")
        assert eng_a.compile_stats.aot_hits > 0

        key = lambda r: (r.original_main, r.rephrased_main)  # noqa: E731
        by_key = {key(r): r for r in rows_l}
        assert set(map(key, rows_a)) == set(by_key)
        for r in rows_a:
            l = by_key[key(r)]
            assert r.token_1_prob == l.token_1_prob
            assert r.token_2_prob == l.token_2_prob
            assert r.weighted_confidence == l.weighted_confidence
            assert r.model_response == l.model_response
            assert r.model_confidence_response == l.model_confidence_response
            assert r.log_probabilities == l.log_probabilities
    finally:
        compile_cache.disable_persistent_cache()


# ---------------------------------------------------------------------------
# Persistent disk cache round-trip + observability counters
# ---------------------------------------------------------------------------

def test_persistent_cache_roundtrip_and_counters(tmp_path):
    cache_dir = compile_cache.enable_persistent_cache(tmp_path / "xla")
    try:
        assert cache_dir == tmp_path / "xla"

        @jax.jit
        def f(x):
            return jnp.tanh(x @ x.T).sum()

        x = jnp.ones((64, 64))
        before = compile_cache.persistent_cache_counters()
        float(f(x))
        mid = compile_cache.persistent_cache_counters()
        assert mid["requests"] > before["requests"]
        assert any(cache_dir.iterdir())  # executable serialized to disk

        # A "restarted worker": in-memory executables dropped, disk warm.
        jax.clear_caches()
        float(f(x))
        after = compile_cache.persistent_cache_counters()
        assert after["hits"] > mid["hits"]

        # CompileStats scopes the process-global counters to a window.
        stats = CompileStats()
        stats.snapshot_persistent()
        jax.clear_caches()
        float(f(x))
        stats.finish_persistent()
        assert stats.persistent_hits >= 1
        summ = stats.summary()
        assert summ["persistent_cache_hits"] >= 1
        assert summ["persistent_cache_misses"] >= 0
    finally:
        compile_cache.disable_persistent_cache()


def test_manifest_written_next_to_cache(tmp_path):
    compile_cache.enable_persistent_cache(tmp_path / "xla")
    try:
        path = compile_cache.write_manifest(
            "abc123", {"model": _Cfg(), "buckets": (64, 128)})
        assert path is not None and path.exists()
        import json

        payload = json.loads(path.read_text())
        assert payload["key"] == "abc123"
        assert payload["buckets"] == [64, 128]
        # Idempotent: second write returns the same file.
        assert compile_cache.write_manifest("abc123", {}) == path
    finally:
        compile_cache.disable_persistent_cache()
    # No cache enabled -> no-op, not an error.
    assert compile_cache.write_manifest("zzz", {}) is None
