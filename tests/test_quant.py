"""Weight-only int8 quantization: numerics, memory, and end-to-end engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, quant
from lir_tpu.models.loader import config_from_hf, convert_decoder

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py


@pytest.fixture(scope="module")
def tiny_model():
    import transformers as tf

    torch.manual_seed(0)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=FakeTokenizer.VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=256, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    return convert_decoder(hf.state_dict(), cfg, fam), cfg


class TestQuantTensor:
    def test_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
        qt = quant.quantize(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (128,)
        err = np.abs(np.asarray(qt.dequant()) - np.asarray(w))
        # Symmetric int8: error bounded by scale/2 per column.
        bound = np.asarray(qt.scale) / 2 + 1e-7
        assert (err <= bound[None, :]).all()

    def test_matmul_matches_dequant(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(3, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
        qt = quant.quantize(w)
        np.testing.assert_allclose(
            np.asarray(quant.matmul(x, qt)),
            np.asarray(x @ qt.dequant()),
            rtol=1e-5, atol=1e-5,
        )

    def test_stacked_layer_shapes(self):
        w = jnp.asarray(np.random.default_rng(2).normal(size=(4, 16, 32)),
                        jnp.float32)
        qt = quant.quantize(w)
        assert qt.q.shape == (4, 16, 32)
        assert qt.scale.shape == (4, 32)


class TestQuantizedDecoder:
    def test_memory_halves_and_readout_close(self, tiny_model):
        params, cfg = tiny_model
        qparams = quant.quantize_decoder_params(params)
        # Big matrices dominate: quantized tree well under 60% of dense.
        assert quant.param_bytes(qparams) < 0.6 * quant.param_bytes(params)

        toks = jnp.asarray(
            np.random.default_rng(0).integers(3, 256, (2, 12)), jnp.int32)
        dense_logits = decoder.forward(params, cfg, toks)
        q_logits = decoder.forward(qparams, cfg, toks)
        p_dense = jax.nn.softmax(dense_logits[:, -1], axis=-1)
        p_quant = jax.nn.softmax(q_logits[:, -1], axis=-1)
        # Weight-only int8: readout probabilities track the dense model.
        assert float(jnp.abs(p_dense - p_quant).max()) < 0.05
        # Top-1 token agrees.
        np.testing.assert_array_equal(
            np.asarray(jnp.argmax(p_dense, -1)),
            np.asarray(jnp.argmax(p_quant, -1)),
        )

    def test_scoring_engine_runs_quantized(self, tiny_model):
        params, cfg = tiny_model
        qparams = quant.quantize_decoder_params(params)
        engine = ScoringEngine(
            qparams, cfg, FakeTokenizer(),
            RuntimeConfig(batch_size=4, max_new_tokens=4, max_seq_len=64),
        )
        rows = engine.score_prompts(["Is a cat an animal", "some prompt"])
        assert len(rows) == 2
        assert all(np.isfinite(r.yes_prob) for r in rows)


def test_factory_int8_mesh_composes(tmp_path):
    """int8 + multi-device mesh is a supported combination now (VERDICT r1
    #6; the reference composed 8-bit with multi-device placement,
    compare_base_vs_instruct.py:424-435). The factory no longer rejects it —
    with no checkpoint on disk only FileNotFoundError remains."""
    from lir_tpu.config import MeshConfig
    from lir_tpu.models.factory import load_engine

    with pytest.raises(OSError):  # AutoConfig: no checkpoint at the path
        load_engine(tmp_path / "nonexistent",
                    mesh_cfg=MeshConfig(data=1, model=8),
                    quantize_int8=True)


class TestDynamicActivationInt8:
    """Dynamic mode (--int8-dynamic): per-token activation quantization +
    s8 x s8 dots — the TPU-native LLM.int8() vector-wise analogue of the
    reference's 8-bit mode (compare_base_vs_instruct.py:431-435), measured
    1.2-1.5x faster than bf16-dequant matmuls on v5e (bench.py)."""

    def test_matmul_close_to_weight_only(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(5, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
        qt = quant.quantize(w)
        import dataclasses
        dyn = dataclasses.replace(qt, dynamic=True)
        a = np.asarray(quant.matmul(x, qt))
        b = np.asarray(quant.matmul(x, dyn))
        # Activation quantization adds ~1/127 relative noise per element.
        np.testing.assert_allclose(b, a, atol=3e-2 * np.abs(a).max())

    def test_static_field_is_jit_stable(self):
        """dynamic is pytree METADATA: one QuantTensor leaf count, and jit
        retraces (not crashes) when the flag flips."""
        qt = quant.quantize(jnp.ones((8, 4), jnp.float32))
        assert len(jax.tree_util.tree_leaves(qt)) == 2
        import dataclasses
        dyn = dataclasses.replace(qt, dynamic=True)
        f = jax.jit(lambda x, w: quant.matmul(x, w))
        x = jnp.ones((2, 8), jnp.float32)
        assert np.isfinite(np.asarray(f(x, qt))).all()
        assert np.isfinite(np.asarray(f(x, dyn))).all()

    def test_decoder_readout_close_to_weight_only(self, tiny_model):
        params, cfg = tiny_model
        q_static = quant.quantize_decoder_params(params)
        q_dyn = quant.quantize_decoder_params(params, dynamic=True)
        # lm_head must STAY weight-only: its fp32 activations feed the C13
        # readout directly.
        assert not q_dyn["lm_head"].dynamic
        assert q_dyn["layers"]["wq"].dynamic
        toks = jnp.asarray(
            np.random.default_rng(4).integers(3, cfg.vocab_size, (2, 12)),
            jnp.int32)
        ls = decoder.forward(q_static, cfg, toks)
        ld = decoder.forward(q_dyn, cfg, toks)
        ps = np.asarray(jax.nn.softmax(ls[:, -1], axis=-1))
        pd = np.asarray(jax.nn.softmax(ld[:, -1], axis=-1))
        assert np.isfinite(pd).all()
        # Readout-level agreement: softmax probabilities stay close.
        np.testing.assert_allclose(pd, ps, atol=5e-2)

    def test_sharding_preserves_dynamic_flag(self):
        from lir_tpu.config import MeshConfig
        from lir_tpu.models.registry import ModelConfig
        from lir_tpu.parallel import sharding

        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        cfg = ModelConfig(name="dyn-shard", vocab_size=64, hidden_size=32,
                          n_layers=2, n_heads=8, intermediate_size=64,
                          max_seq_len=64)
        params = quant.random_quantized_params(
            cfg, jax.random.PRNGKey(0), dtype=jnp.float32, dynamic=True)
        mesh = sharding.build_mesh(MeshConfig(data=1, model=8, seq=1))
        sharded = sharding.shard_params(params, cfg, mesh)
        assert sharded["layers"]["wq"].dynamic
        assert not sharded["lm_head"].dynamic


class TestInt8KVCache:
    """cfg.kv_cache_int8: int8 cache payload + per-vector scales. Halves
    cache HBM — the single-chip long-context limiter (a 7B at seq 1024
    OOMed with the bf16 cache, fits with int8; SCALE.md) — and runs decode
    attention as s8 x s8 dots."""

    def _setup(self):
        import dataclasses
        from lir_tpu.models.registry import ModelConfig

        cfg = ModelConfig(name="kvq", vocab_size=128, hidden_size=32,
                          n_layers=2, n_heads=4, intermediate_size=64,
                          max_seq_len=128)
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, dataclasses.replace(cfg, kv_cache_int8=True), params

    def test_cache_structure_and_memory(self):
        cfg, cfg_q, _ = self._setup()
        ck, cv = decoder.init_cache(cfg_q, batch=3, max_len=16)
        (q8, s32) = ck
        assert q8.dtype == jnp.int8 and s32.dtype == jnp.float32
        assert q8.shape == (2, 4, 16, 3, 8)
        assert s32.shape == (2, 4, 16, 3)

    def test_greedy_decode_matches_bf16_cache(self):
        from lir_tpu.engine import generate, score

        cfg, cfg_q, params = self._setup()
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(3, 128, (3, 16)), jnp.int32)
        mask = jnp.ones((3, 16), jnp.int32).at[1, :4].set(0)
        gen_a, sl_a = generate.greedy_decode(params, cfg, toks, mask,
                                             max_new_tokens=6)
        gen_b, sl_b = generate.greedy_decode(params, cfg_q, toks, mask,
                                             max_new_tokens=6)
        # Greedy argmaxes survive the quantization noise on this scale...
        np.testing.assert_array_equal(np.asarray(gen_a), np.asarray(gen_b))
        # ...and per-step softmax probabilities stay close (cache noise is
        # ~0.4% per element, two layers deep).
        pa = np.asarray(jax.nn.softmax(jnp.asarray(sl_a), axis=-1))
        pb = np.asarray(jax.nn.softmax(jnp.asarray(sl_b), axis=-1))
        np.testing.assert_allclose(pb, pa, atol=5e-3)

    def test_fused_scorer_with_int8_cache(self):
        from lir_tpu.engine import generate, score

        cfg, cfg_q, params = self._setup()
        rng = np.random.default_rng(1)
        B = 3
        toks = jnp.asarray(rng.integers(3, 128, (B, 12)), jnp.int32)
        mask = jnp.ones((B, 12), jnp.int32)
        yes = jnp.full((B,), 1, jnp.int32)
        no = jnp.full((B,), 2, jnp.int32)
        digits = jnp.arange(10, 110, dtype=jnp.int32)
        vals = jnp.arange(0, 100, dtype=jnp.float32)
        fa = generate.greedy_decode_fused(params, cfg, toks, mask, yes, no,
                                          digits, vals, max_new_tokens=5)
        fb = generate.greedy_decode_fused(params, cfg_q, toks, mask, yes, no,
                                          digits, vals, max_new_tokens=5)
        ra = score.readout_from_fused(fa, yes, no)
        rb = score.readout_from_fused(fb, yes, no)
        np.testing.assert_allclose(np.asarray(rb.yes_prob),
                                   np.asarray(ra.yes_prob), atol=5e-3)

    def test_gqa_int8_cache(self):
        """MQA/GQA head repeat on the head-major cache axis."""
        import dataclasses
        from lir_tpu.engine import generate
        from lir_tpu.models.registry import ModelConfig

        cfg = ModelConfig(name="kvq-gqa", vocab_size=128, hidden_size=32,
                          n_layers=2, n_heads=4, n_kv_heads=1,
                          intermediate_size=64, max_seq_len=128)
        params = decoder.init_params(cfg, jax.random.PRNGKey(2))
        cfg_q = dataclasses.replace(cfg, kv_cache_int8=True)
        rng = np.random.default_rng(3)
        toks = jnp.asarray(rng.integers(3, 128, (2, 10)), jnp.int32)
        mask = jnp.ones((2, 10), jnp.int32)
        ga, sa = generate.greedy_decode(params, cfg, toks, mask, max_new_tokens=4)
        gb, sb = generate.greedy_decode(params, cfg_q, toks, mask, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))


class TestEncDecInt8:
    """T5-family int8 (quantize_encdec_params): the reference loads its
    t5/T0/tk-instruct models through the SAME 8-bit config as the decoders
    (compare_base_vs_instruct.py:431-435, routing :444-455)."""

    @pytest.fixture(scope="class")
    def t5(self):
        import transformers as tf
        from lir_tpu.models.loader import convert_t5, t5_config_from_hf

        torch.manual_seed(1)
        hf_cfg = tf.T5Config(
            vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2,
            num_heads=4, feed_forward_proj="gated-gelu",
            tie_word_embeddings=False, decoder_start_token_id=0)
        hf = tf.T5ForConditionalGeneration(hf_cfg).eval()
        cfg = t5_config_from_hf(hf.config)
        return convert_t5(hf.state_dict(), cfg), cfg

    @pytest.mark.parametrize("dynamic", [False, True])
    def test_forward_close_to_dense(self, t5, dynamic):
        from lir_tpu.models import encdec

        params, cfg = t5
        qp = quant.quantize_encdec_params(params, dynamic=dynamic)
        assert qp["encoder"]["wq"].q.dtype == jnp.int8
        assert qp["decoder"]["co"].dynamic == dynamic
        assert not qp["lm_head"].dynamic  # logit head stays weight-only

        rng = np.random.default_rng(5)
        enc = jnp.asarray(rng.integers(0, 256, (2, 10)), jnp.int32)
        dec = jnp.asarray([[0, 5, 9], [0, 7, 3]], jnp.int32)
        mask = jnp.ones((2, 10), jnp.int32)
        dense = encdec.forward(params, cfg, enc, mask, dec)
        q = encdec.forward(qp, cfg, enc, mask, dec)
        pd = np.asarray(jax.nn.softmax(dense, axis=-1))
        pq = np.asarray(jax.nn.softmax(q, axis=-1))
        # Random-init T5 logits are sharp (untrained torch init), so int8
        # noise lands on near-argmax classes; 8e-2 bounds the dynamic mode
        # on this synthetic worst case (weight-only measures ~2e-2).
        np.testing.assert_allclose(pq, pd, atol=8e-2)
        # The scored quantity is the two-token relative prob — pin it tight.
        rel_d = pd[..., 5] / (pd[..., 5] + pd[..., 9] + 1e-12)
        rel_q = pq[..., 5] / (pq[..., 5] + pq[..., 9] + 1e-12)
        np.testing.assert_allclose(rel_q, rel_d, atol=5e-2)

    def test_memory_halves(self, t5):
        params, _ = t5
        before = quant.param_bytes(params)
        after = quant.param_bytes(quant.quantize_encdec_params(params))
        assert after < 0.55 * before  # fp32 matrices -> int8 (+small scales)
