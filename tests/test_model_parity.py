"""Logit-parity tests: our JAX transformers vs transformers (torch CPU).

SURVEY.md §7 stage 3 gate: "Validate logits vs transformers CPU to ~1e-3".
Each family gets a tiny random HF model built locally from a config (no
network), its state_dict converted by models/loader.py, and full-sequence
logits compared. This pins the fused-QKV de-interleaving, rotary conventions,
ALiBi slopes, parallel-block wiring, and norm/activation choices per family
(reference architectures exercised at compare_base_vs_instruct.py:136-180).
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from lir_tpu.models import decoder, encdec, loader
from lir_tpu.models.loader import config_from_hf, convert_decoder, convert_t5, t5_config_from_hf

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

torch.manual_seed(0)

TINY = dict(vocab=256, hidden=64, layers=2, heads=4)


def _hf_tiny(family):
    import transformers as tf
    v, d, l, h = TINY["vocab"], TINY["hidden"], TINY["layers"], TINY["heads"]
    if family == "gpt2":
        cfg = tf.GPT2Config(vocab_size=v, n_embd=d, n_layer=l, n_head=h,
                            n_positions=128)
        return tf.GPT2LMHeadModel(cfg)
    if family == "gpt_neox":
        cfg = tf.GPTNeoXConfig(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                               num_attention_heads=h, intermediate_size=4 * d,
                               rotary_pct=0.25, use_parallel_residual=True,
                               max_position_embeddings=128)
        return tf.GPTNeoXForCausalLM(cfg)
    if family == "llama":
        cfg = tf.LlamaConfig(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                             num_attention_heads=h, num_key_value_heads=h,
                             intermediate_size=2 * d, max_position_embeddings=128,
                             tie_word_embeddings=False)
        return tf.LlamaForCausalLM(cfg)
    if family == "mistral":
        cfg = tf.MistralConfig(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                               num_attention_heads=h, num_key_value_heads=2,
                               intermediate_size=2 * d, max_position_embeddings=128,
                               sliding_window=None, tie_word_embeddings=False)
        return tf.MistralForCausalLM(cfg)
    if family == "qwen2":
        cfg = tf.Qwen2Config(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                             num_attention_heads=h, num_key_value_heads=h,
                             intermediate_size=2 * d, max_position_embeddings=128,
                             attention_bias=True, tie_word_embeddings=False)
        return tf.Qwen2ForCausalLM(cfg)
    if family == "falcon":
        cfg = tf.FalconConfig(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                              num_attention_heads=h, multi_query=True,
                              new_decoder_arch=False, parallel_attn=True,
                              bias=False, alibi=False)
        return tf.FalconForCausalLM(cfg)
    if family == "bloom":
        cfg = tf.BloomConfig(vocab_size=v, hidden_size=d, n_layer=l, n_head=h)
        return tf.BloomForCausalLM(cfg)
    if family == "opt":
        cfg = tf.OPTConfig(vocab_size=v, hidden_size=d, num_hidden_layers=l,
                           num_attention_heads=h, ffn_dim=4 * d,
                           word_embed_proj_dim=d, max_position_embeddings=128,
                           do_layer_norm_before=True)
        return tf.OPTForCausalLM(cfg)
    raise KeyError(family)


FAMILIES = ["gpt2", "gpt_neox", "llama", "mistral", "qwen2", "falcon", "bloom", "opt"]


@pytest.mark.parametrize("family", FAMILIES)
def test_decoder_logit_parity(family):
    hf = _hf_tiny(family).eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam, dtype=jnp.float32)

    rng = np.random.default_rng(7)
    tokens = rng.integers(0, TINY["vocab"], size=(2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    ours = np.asarray(decoder.forward(params, cfg, jnp.asarray(tokens)))

    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


def test_left_padding_invariance():
    """Left-padded rows must produce the same end-of-prompt logits as unpadded
    (the engine batches ragged prompts this way; reference runs them one by
    one, compare_base_vs_instruct.py:243)."""
    hf = _hf_tiny("llama").eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)

    rng = np.random.default_rng(3)
    toks = rng.integers(0, TINY["vocab"], size=(1, 9)).astype(np.int32)
    full = decoder.forward(params, cfg, jnp.asarray(toks))

    pad = 5
    padded = np.concatenate([np.zeros((1, pad), np.int32), toks], axis=1)
    mask = np.concatenate([np.zeros((1, pad), np.int32),
                           np.ones((1, 9), np.int32)], axis=1)
    out = decoder.forward(params, cfg, jnp.asarray(padded), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out[0, -1]), np.asarray(full[0, -1]),
                               atol=1e-4, rtol=1e-4)


def test_prefill_matches_forward():
    hf = _hf_tiny("gpt_neox").eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)

    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(0, TINY["vocab"], size=(2, 8)).astype(np.int32))
    mask = jnp.ones_like(toks)
    full = decoder.forward(params, cfg, toks)
    last, cache, next_pos = decoder.prefill(params, cfg, toks, mask, max_len=16)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               atol=1e-4, rtol=1e-4)
    # Cache layout is (L, K, T, B, hd) — head-major/batch-minor so the
    # decode while-loop aliases it instead of copying (decoder.init_cache).
    assert cache[0].shape == (cfg.n_layers, cfg.n_kv_heads, 16, 2, cfg.head_dim)
    assert np.all(np.asarray(next_pos) == 8)


def test_decode_step_matches_forward():
    """prefill + decode_step over 3 greedy tokens == full forward re-run."""
    hf = _hf_tiny("llama").eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)

    rng = np.random.default_rng(5)
    S, T = 6, 12
    toks = jnp.asarray(rng.integers(0, TINY["vocab"], size=(1, S)).astype(np.int32))
    mask = jnp.ones_like(toks)

    logits, cache, pos = decoder.prefill(params, cfg, toks, mask, max_len=T)
    seq = list(np.asarray(toks)[0])
    cache_mask = np.zeros((1, T), np.int32)
    cache_mask[0, :S] = 1
    for t in range(3):
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seq.append(int(nxt[0]))
        cache_mask[0, S + t] = 1
        logits, cache = decoder.decode_step(
            params, cfg, cache, nxt, pos + t, jnp.int32(S + t),
            jnp.asarray(cache_mask))
        ref = decoder.forward(params, cfg, jnp.asarray([seq], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(ref[0, -1]),
                                   atol=1e-3, rtol=1e-3)


def test_t5_logit_parity():
    import transformers as tf
    hf_cfg = tf.T5Config(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                         num_layers=2, num_heads=4, feed_forward_proj="gated-gelu",
                         tie_word_embeddings=False, decoder_start_token_id=0)
    hf = tf.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = t5_config_from_hf(hf.config)
    params = convert_t5(hf.state_dict(), cfg)

    rng = np.random.default_rng(13)
    enc = rng.integers(0, 256, size=(2, 10)).astype(np.int32)
    dec = rng.integers(0, 256, size=(2, 4)).astype(np.int32)
    dec[:, 0] = 0
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(enc.astype(np.int64)),
                 decoder_input_ids=torch.tensor(dec.astype(np.int64))).logits.numpy()
    ours = np.asarray(encdec.forward(params, cfg, jnp.asarray(enc),
                                     jnp.ones_like(jnp.asarray(enc)),
                                     jnp.asarray(dec)))
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


class TestQwenV1NativeNames:
    """Qwen-v1 (model_type "qwen") native tensor names (VERDICT r1 missing
    #6). No transformers class exists offline (trust_remote_code family), so
    the mapping is pinned two ways: (a) a native-name state dict and its
    llama-format conversion must produce IDENTICAL pytrees (the llama path
    is torch-parity-tested above); (b) the HF-config adapter halves
    intermediate_size per the public modeling_qwen.py ff_dim rule."""

    def _native_sd(self, rng, D=32, F=48, L=2, V=64):
        sd = {"transformer.wte.weight": rng.normal(size=(V, D)),
              "transformer.ln_f.weight": rng.normal(size=(D,)),
              "lm_head.weight": rng.normal(size=(V, D))}
        for i in range(L):
            p = f"transformer.h.{i}."
            sd[p + "ln_1.weight"] = rng.normal(size=(D,))
            sd[p + "attn.c_attn.weight"] = rng.normal(size=(3 * D, D))
            sd[p + "attn.c_attn.bias"] = rng.normal(size=(3 * D,))
            sd[p + "attn.c_proj.weight"] = rng.normal(size=(D, D))
            sd[p + "ln_2.weight"] = rng.normal(size=(D,))
            sd[p + "mlp.w1.weight"] = rng.normal(size=(F, D))   # up
            sd[p + "mlp.w2.weight"] = rng.normal(size=(F, D))   # gate (silu)
            sd[p + "mlp.c_proj.weight"] = rng.normal(size=(D, F))
        return sd

    def _llama_equiv(self, sd, L=2):
        """The llama-format rename of the same weights (what conversion
        scripts emit: c_attn split to q/k/v, w2 -> gate_proj, w1 -> up)."""
        out = {"model.embed_tokens.weight": sd["transformer.wte.weight"],
               "model.norm.weight": sd["transformer.ln_f.weight"],
               "lm_head.weight": sd["lm_head.weight"]}
        D = sd["transformer.h.0.ln_1.weight"].shape[0]
        for i in range(L):
            p, q = f"transformer.h.{i}.", f"model.layers.{i}."
            ca, cb = sd[p + "attn.c_attn.weight"], sd[p + "attn.c_attn.bias"]
            out[q + "input_layernorm.weight"] = sd[p + "ln_1.weight"]
            out[q + "self_attn.q_proj.weight"] = ca[:D]
            out[q + "self_attn.k_proj.weight"] = ca[D:2 * D]
            out[q + "self_attn.v_proj.weight"] = ca[2 * D:]
            out[q + "self_attn.q_proj.bias"] = cb[:D]
            out[q + "self_attn.k_proj.bias"] = cb[D:2 * D]
            out[q + "self_attn.v_proj.bias"] = cb[2 * D:]
            out[q + "self_attn.o_proj.weight"] = sd[p + "attn.c_proj.weight"]
            out[q + "post_attention_layernorm.weight"] = sd[p + "ln_2.weight"]
            out[q + "mlp.gate_proj.weight"] = sd[p + "mlp.w2.weight"]
            out[q + "mlp.up_proj.weight"] = sd[p + "mlp.w1.weight"]
            out[q + "mlp.down_proj.weight"] = sd[p + "mlp.c_proj.weight"]
        return out

    def test_native_matches_llama_format(self):
        from lir_tpu.models.registry import ModelConfig
        import jax

        rng = np.random.default_rng(11)
        cfg = ModelConfig(name="qwen-tiny", vocab_size=64, hidden_size=32,
                          n_layers=2, n_heads=4, intermediate_size=48,
                          max_seq_len=64, qkv_bias=True, norm_eps=1e-6)
        native_sd = self._native_sd(rng)
        p_native = convert_decoder(native_sd, cfg, "qwen")
        p_llama = convert_decoder(self._llama_equiv(native_sd), cfg, "qwen")

        flat_n = jax.tree_util.tree_leaves_with_path(p_native)
        flat_l = dict(jax.tree_util.tree_leaves_with_path(p_llama))
        assert len(flat_n) == len(flat_l)
        for path, leaf in flat_n:
            np.testing.assert_array_equal(
                np.asarray(leaf), np.asarray(flat_l[path]), err_msg=str(path))

        toks = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        np.testing.assert_allclose(
            np.asarray(decoder.forward(p_native, cfg, toks)),
            np.asarray(decoder.forward(p_llama, cfg, toks)), atol=0)

    def test_config_adapter(self):
        from types import SimpleNamespace

        hf = SimpleNamespace(
            model_type="qwen", vocab_size=151936, hidden_size=4096,
            num_hidden_layers=32, num_attention_heads=32, seq_length=2048,
            intermediate_size=22016, layer_norm_epsilon=1e-6,
            rotary_emb_base=10000.0, no_bias=True, name_or_path="qwen-7b")
        cfg, fam = config_from_hf(hf)
        assert fam == "qwen"
        assert cfg.intermediate_size == 11008   # ff_dim = 22016 // 2
        assert cfg.qkv_bias and cfg.norm == "rmsnorm"
        assert cfg.norm_eps == 1e-6
        assert cfg.max_seq_len == 2048
