"""Multi-model fleet engine tests (engine/fleet.py + models/weights.py
+ the serve fleet layer).

Pins the contracts the fleet tentpole rides on:

- weight-cache refcount invariants: never negative, pinned/in-flight
  models unevictable, eviction is LRU, evict-then-reload is bitwise;
- the prefetch pipeline: a fleet sweep loads model i+1 in the
  background while model i scores (prefetch_hits, swap_s_hidden > 0)
  and per-model rows are BITWISE what standalone engines produce;
- multi.py failure routing: a model that cannot load emits NaN rows
  classified error:model; rows with corrupt readouts quarantine as
  error:numerics with the guard counters moving — never written as
  plausible numbers;
- the per-model partition-rule registry (parallel/sharding.py):
  regex-over-path rules resolve per model and win over the structural
  defaults, for both monolithic shard_params and the chunked streamer;
- fleet serving: a fleet_score fan-out answers per-model P(yes)/P(no)
  plus pairwise kappa/disagreement, with kappa EXACTLY
  stats/streaming.kappa_from_counts (== the analysis layer's
  within_group_kappa) on the same decisions, and per-model results
  bitwise-identical to a single-model ScoringServer.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig, ServeConfig
from lir_tpu.engine.fleet import ModelFleet
from lir_tpu.engine.multi import (MODEL_ERROR, ModelSpec,
                                  run_model_comparison_sweep)
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_word_meaning_sweep
from lir_tpu.models import decoder, weights
from lir_tpu.models.registry import ModelConfig
from lir_tpu.serve import (FleetScoringServer, ScoringServer, ServeRequest,
                           aggregate_fleet, fleet_decision)
from lir_tpu.utils.profiling import FleetStats


def _tiny_cfg(name):
    return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)


def _tiny_params(seed):
    return decoder.init_params(_tiny_cfg("x"), jax.random.PRNGKey(seed))


def _tiny_engine(name, seed, batch_size=4):
    return ScoringEngine(
        decoder.init_params(_tiny_cfg(name), jax.random.PRNGKey(seed)),
        _tiny_cfg(name), FakeTokenizer(),
        RuntimeConfig(batch_size=batch_size, max_seq_len=256))


QUESTIONS = ["Is a cat an animal", "Is a rock an animal",
             "Is rain considered weather"]


# ---------------------------------------------------------------------------
# WeightCache invariants
# ---------------------------------------------------------------------------


class TestWeightCache:
    def test_refcount_never_negative(self):
        wc = weights.WeightCache()
        p = _tiny_params(0)
        wc.insert("a", p)
        wc.acquire("a")
        wc.release("a")
        with pytest.raises(AssertionError, match="negative"):
            wc.release("a")

    def test_in_flight_model_is_unevictable(self):
        p = _tiny_params(0)
        nb = weights.tree_bytes(p)
        wc = weights.WeightCache(budget_bytes=nb + nb // 2)
        wc.insert("a", p)
        wc.acquire("a")          # in-flight dispatch holds a
        with pytest.raises(weights.WeightCacheOOM):
            wc.insert("b", _tiny_params(1))
        wc.release("a")          # dispatch done -> a becomes evictable
        wc.insert("b", _tiny_params(1))
        assert "a" not in wc and "b" in wc

    def test_pinned_model_is_unevictable(self):
        p = _tiny_params(0)
        nb = weights.tree_bytes(p)
        wc = weights.WeightCache(budget_bytes=nb + nb // 2)
        wc.insert("a", p)
        wc.pin("a")
        with pytest.raises(weights.WeightCacheOOM):
            wc.insert("b", _tiny_params(1))
        wc.unpin("a")
        wc.insert("b", _tiny_params(1))
        assert "a" not in wc and "b" in wc

    def test_eviction_is_lru(self):
        stats = FleetStats()
        p = _tiny_params(0)
        nb = weights.tree_bytes(p)
        wc = weights.WeightCache(budget_bytes=2 * nb + nb // 2,
                                 stats=stats)
        wc.insert("a", _tiny_params(0), nb)
        wc.insert("b", _tiny_params(1), nb)
        wc.acquire("a")          # a is MRU now
        wc.release("a")
        wc.insert("c", _tiny_params(2), nb)   # evicts b, the LRU
        assert wc.resident_models == ["a", "c"]
        assert stats.evictions == 1 and stats.resident_models == 2

    def test_drop_refuses_in_flight(self):
        wc = weights.WeightCache()
        wc.insert("a", _tiny_params(0))
        wc.acquire("a")
        with pytest.raises(weights.WeightCacheOOM):
            wc.drop("a")
        wc.release("a")
        wc.drop("a")
        assert "a" not in wc

    def test_evict_then_reload_is_bitwise(self):
        """The acceptance pin: weights that were evicted and re-streamed
        from host staging are bit-for-bit the originals."""
        e0, e1 = _tiny_engine("m0", 0), _tiny_engine("m1", 1)
        original = jax.tree.map(lambda x: np.asarray(x).copy(), e0.params)
        nb = weights.tree_bytes(e0.params)
        fleet = ModelFleet.from_engines([("m0", e0), ("m1", e1)],
                                        cache_budget_bytes=nb + nb // 2,
                                        prefetch=False)
        try:
            # Boot under a one-model budget already evicted m0 for m1.
            assert not fleet.resident("m0") and fleet.resident("m1")
            assert e0.params is None        # HBM reference dropped
            eng = fleet.acquire("m0")       # re-stream, evicting m1
            got = jax.tree.map(np.asarray, eng.params)
            for a, b in zip(jax.tree.leaves(original),
                            jax.tree.leaves(got)):
                np.testing.assert_array_equal(
                    a.view(np.uint8), b.view(np.uint8))
            assert fleet.stats.evictions == 2
            assert fleet.stats.loads == 1
            fleet.release("m0")
        finally:
            fleet.shutdown()


# ---------------------------------------------------------------------------
# Partition-rule registry
# ---------------------------------------------------------------------------


class TestPartitionRuleRegistry:
    def test_match_partition_rules_paths_and_scalars(self):
        from jax.sharding import PartitionSpec as P

        from lir_tpu.parallel import sharding

        params = {"layers": {"wq": np.zeros((2, 8, 8)),
                             "ln1": {"scale": np.zeros((2, 8))}},
                  "scalar": np.zeros(())}
        rules = [("layers/wq", P(None, None, "model")), (".*", P())]
        tree = sharding.match_partition_rules(rules, params)
        assert tree["layers"]["wq"] == P(None, None, "model")
        assert tree["layers"]["ln1"]["scale"] == P()
        assert tree["scalar"] == P()   # scalars replicate before rules

    def test_unmatched_param_is_loud(self):
        from jax.sharding import PartitionSpec as P

        from lir_tpu.parallel import sharding

        with pytest.raises(ValueError, match="partition rule not found"):
            sharding.match_partition_rules(
                [("nope", P())], {"w": np.zeros((4, 4))})

    def test_registry_overrides_defaults_for_matching_model(self):
        from jax.sharding import PartitionSpec as P

        from lir_tpu.config import MeshConfig
        from lir_tpu.parallel import sharding

        cfg = _tiny_cfg("special/fleet-model")
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        mesh = sharding.build_mesh(MeshConfig(data=1, model=2))
        default = sharding.spec_tree_for(cfg, mesh, params)
        rules = [("layers/(wq|wk|wv|wo|w_up|w_down)", P()), (".*", P())]
        sharding.register_partition_rules("special/", lambda c, m: rules)
        try:
            tree = sharding.spec_tree_for(cfg, mesh, params)
            assert tree["layers"]["wq"] == P()
            assert default["layers"]["wq"] != P()
            # A NON-matching model keeps the structural defaults.
            other = sharding.spec_tree_for(_tiny_cfg("plain"), mesh,
                                           params)
            assert other["layers"]["wq"] == default["layers"]["wq"]
        finally:
            sharding.unregister_partition_rules("special/")

    def test_streamed_placement_honors_registry(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from lir_tpu.config import MeshConfig
        from lir_tpu.parallel import sharding

        cfg = _tiny_cfg("ruled/streamed")
        params = decoder.init_params(cfg, jax.random.PRNGKey(3))
        mesh = sharding.build_mesh(MeshConfig(data=1, model=2))
        rules = [("w_up", P(None, None, "model")), (".*", P())]
        sharding.register_partition_rules("ruled/", lambda c, m: rules)
        try:
            streamed = weights.stream_params(
                weights.host_stage(params), cfg=cfg, mesh=mesh,
                chunk_bytes=512)
            assert (streamed["layers"]["w_up"].sharding
                    == NamedSharding(mesh, P(None, None, "model")))
            assert (streamed["layers"]["wq"].sharding
                    == NamedSharding(mesh, P()))
            for a, b in zip(jax.tree.leaves(params),
                            jax.tree.leaves(streamed)):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
        finally:
            sharding.unregister_partition_rules("ruled/")


# ---------------------------------------------------------------------------
# Fleet sweep: prefetch pipeline + bitwise parity + failure routing
# ---------------------------------------------------------------------------


def _factory(seeds):
    def factory(name):
        if "broken" in name:
            raise RuntimeError("checkpoint load failure")
        return _tiny_engine(name, seeds[name])
    return factory


class TestFleetSweep:
    SEEDS = {"org/m0": 0, "org/m1": 1, "org/m2": 2}

    def test_fleet_rows_bitwise_vs_standalone_engines(self, tmp_path):
        specs = [ModelSpec(n, "instruct") for n in self.SEEDS]
        res = run_model_comparison_sweep(
            specs, _factory(self.SEEDS), tmp_path, questions=QUESTIONS)
        # The in-memory frame (csv text rounds floats; bitwise means
        # comparing the actual float64 values the sweep produced).
        df = res["model_comparison_csv"]
        for name, seed in self.SEEDS.items():
            # Score through the same formatter the driver used, on a
            # STANDALONE engine (no fleet, no streaming).
            from lir_tpu.engine.multi import format_for
            ref = run_word_meaning_sweep(
                _tiny_engine(name, seed), name, "instruct", QUESTIONS,
                format_for(ModelSpec(name, "instruct")))
            got = df[df["model"] == name]
            assert list(got["prompt"]) == [r.prompt for r in ref]
            # Bitwise: the fleet moved the weights, never transformed
            # them, so every probability matches exactly.
            assert list(got["yes_prob"]) == [r.yes_prob for r in ref]
            assert list(got["no_prob"]) == [r.no_prob for r in ref]
        assert all(v["status"] == "ok" for v in res["per_model"].values())

    def test_prefetch_pipeline_counters(self, tmp_path):
        specs = [ModelSpec(n, "instruct") for n in self.SEEDS]
        res = run_model_comparison_sweep(
            specs, _factory(self.SEEDS), tmp_path, questions=QUESTIONS)
        fleet = res["fleet"]
        # First model loads inline (nothing to hide behind); every
        # later one rides the background streamer.
        assert fleet["loads"] == 3
        assert fleet["prefetch_misses"] == 1
        assert fleet["prefetch_hits"] == 2
        assert fleet["swap_s_hidden"] > 0.0
        assert fleet["resident_models"] == 3   # unbounded budget: co-resident

    def test_no_prefetch_is_fully_exposed(self, tmp_path):
        specs = [ModelSpec(n, "instruct") for n in self.SEEDS]
        res = run_model_comparison_sweep(
            specs, _factory(self.SEEDS), tmp_path, questions=QUESTIONS,
            weight_prefetch=False)
        fleet = res["fleet"]
        assert fleet["prefetch_hits"] == 0
        assert fleet["swap_s_hidden"] == 0.0
        assert fleet["swap_s_exposed"] > 0.0

    def test_model_failure_is_classified_and_counted(self, tmp_path):
        specs = [ModelSpec("org/m0", "instruct"),
                 ModelSpec("org/broken", "instruct")]
        res = run_model_comparison_sweep(
            specs, _factory(dict(self.SEEDS, **{"org/broken": 9})),
            tmp_path, questions=QUESTIONS)
        status = res["per_model"]["org/broken"]["status"]
        assert status.startswith(MODEL_ERROR)
        assert res["guard"]["quarantine_reasons"][MODEL_ERROR] == 1
        df = __import__("pandas").read_csv(
            tmp_path / "model_comparison_results.csv")
        broken = df[df["model"] == "org/broken"]
        assert len(broken) == len(QUESTIONS)
        assert broken["yes_prob"].isna().all()

    def test_numerics_quarantine_on_corrupt_readouts(self, tmp_path):
        """A model whose readouts are NaN (SDC / corrupt weights) must
        quarantine as error:numerics — cell identity kept, measurement
        fields nulled, counters moving — not write plausible garbage."""
        def factory(name):
            eng = _tiny_engine(name, 0)
            if name == "org/corrupt":
                eng.params = dict(
                    eng.params,
                    tok_embed=jnp.full_like(eng.params["tok_embed"],
                                            jnp.nan))
            return eng

        specs = [ModelSpec("org/ok", "instruct"),
                 ModelSpec("org/corrupt", "instruct")]
        res = run_model_comparison_sweep(
            specs, factory, tmp_path, questions=QUESTIONS)
        assert res["per_model"]["org/ok"]["status"] == "ok"
        corrupt = res["per_model"]["org/corrupt"]
        assert corrupt["status"].startswith("error:numerics")
        assert corrupt["rows_quarantined"] == len(QUESTIONS)
        assert res["guard"]["quarantined"]["multi"] == len(QUESTIONS)
        df = __import__("pandas").read_csv(
            tmp_path / "model_comparison_results.csv")
        bad = df[df["model"] == "org/corrupt"]
        assert bad["yes_prob"].isna().all()
        assert (bad["model_output"] == "ERROR").all()

    def test_fleet_sweep_under_tight_budget_still_bitwise(self, tmp_path):
        """One-model budget: every switch evicts + reloads, results
        unchanged (the evict-then-reload bitwise contract end to end)."""
        nb = weights.tree_bytes(_tiny_params(0))
        specs = [ModelSpec(n, "instruct") for n in self.SEEDS]
        res = run_model_comparison_sweep(
            specs, _factory(self.SEEDS), tmp_path, questions=QUESTIONS,
            weight_cache_bytes=nb + nb // 2)
        assert all(v["status"] == "ok" for v in res["per_model"].values())
        assert res["fleet"]["evictions"] >= 2
        assert res["fleet"]["resident_models"] == 1
        df = res["model_comparison_csv"]
        from lir_tpu.engine.multi import format_for
        for name, seed in self.SEEDS.items():
            ref = run_word_meaning_sweep(
                _tiny_engine(name, seed), name, "instruct", QUESTIONS,
                format_for(ModelSpec(name, "instruct")))
            got = df[df["model"] == name]
            assert list(got["yes_prob"]) == [r.yes_prob for r in ref]


# ---------------------------------------------------------------------------
# Fleet serving: fleet_score fan-out + kappa + bitwise parity
# ---------------------------------------------------------------------------


_SERVE_CFG = ServeConfig(queue_depth=64, classes=(("t", 600.0),),
                         default_class="t", linger_s=0.01)


def _request(rid="q0"):
    body = "the policy covers flood damage under the endorsement"
    return ServeRequest(
        binary_prompt=f"{body} Answer Yes or No .",
        confidence_prompt=f"{body} Give a number from 0 to 100 .",
        klass="t", request_id=rid)


class TestFleetServe:
    def _fleet(self, budget=None):
        engines = [(f"m{i}", _tiny_engine(f"m{i}", i)) for i in range(3)]
        return ModelFleet.from_engines(engines,
                                       cache_budget_bytes=budget)

    def test_fleet_score_answers_probs_and_kappa(self):
        fleet = self._fleet()
        server = FleetScoringServer(fleet, _SERVE_CFG,
                                    fleet_deadline_s=600.0).start()
        try:
            res = server.submit_fleet(_request()).result(timeout=300)
        finally:
            server.stop()
            fleet.shutdown()
        assert res["status"] == "ok"
        assert res["n_models"] == 3 and res["n_valid"] == 3
        for m in res["per_model"].values():
            assert m["status"] == "ok"
            assert 0.0 <= m["token_1_prob"] <= 1.0
            assert m["decision"] in (0, 1)
        # kappa EXACTLY the streaming contingency path == the analysis
        # layer's within_group_kappa on the same decisions.
        from lir_tpu.stats import streaming
        from lir_tpu.stats.kappa import within_group_kappa

        decs = [m["decision"] for m in res["per_model"].values()]
        n_g, s_g = streaming.group_counts(
            np.zeros(len(decs), np.int64), np.asarray(decs, np.int64))
        ref = streaming.kappa_from_counts(n_g, s_g)
        ref2 = within_group_kappa(np.asarray(decs, int),
                                  np.zeros(len(decs), int))
        for k in ("kappa", "observed_agreement", "expected_agreement"):
            assert res["kappa"][k] == float(ref[k]) == float(ref2[k])
        assert res["disagreement"] == 1.0 - res["kappa"]["observed_agreement"]
        assert fleet.stats.fleet_requests == 1
        assert fleet.stats.fleet_rows == 3

    def test_fleet_per_model_results_bitwise_vs_single_server(self):
        fleet = self._fleet()
        server = FleetScoringServer(fleet, _SERVE_CFG,
                                    fleet_deadline_s=600.0).start()
        try:
            res = server.submit_fleet(_request()).result(timeout=300)
        finally:
            server.stop()
            fleet.shutdown()
        for i in range(3):
            single = ScoringServer(_tiny_engine(f"m{i}", i), f"m{i}",
                                   _SERVE_CFG).start()
            try:
                ref = single.submit(_request("ref")).result(timeout=300)
            finally:
                single.stop()
            got = res["per_model"][f"m{i}"]
            assert got["token_1_prob"] == ref.token_1_prob
            assert got["token_2_prob"] == ref.token_2_prob
            assert got["weighted_confidence"] == ref.weighted_confidence

    def test_single_model_routing(self):
        fleet = self._fleet()
        server = FleetScoringServer(fleet, _SERVE_CFG,
                                    fleet_deadline_s=600.0).start()
        try:
            r = server.submit(_request("solo"), "m1").result(timeout=300)
        finally:
            server.stop()
            fleet.shutdown()
        assert r.status == "ok"
        assert r.request_id == "solo"

    def test_fleet_serve_under_eviction_pressure(self):
        """A one-model weight budget forces swap-per-dispatch; every
        sub-request still resolves ok and the counters show the churn."""
        nb = weights.tree_bytes(_tiny_params(0))
        fleet = self._fleet(budget=nb + nb // 2)
        server = FleetScoringServer(fleet, _SERVE_CFG,
                                    fleet_deadline_s=600.0).start()
        try:
            res = server.submit_fleet(_request()).result(timeout=300)
        finally:
            server.stop()
            fleet.shutdown()
        assert res["status"] == "ok" and res["n_valid"] == 3
        assert fleet.stats.evictions >= 2
        assert fleet.stats.loads >= 2

    def test_fleet_decision_matches_streaming_rule(self):
        assert fleet_decision(0.6, 0.2) == 1
        assert fleet_decision(0.2, 0.6) == 0
        assert fleet_decision(None, 0.5) is None
        assert fleet_decision(0.0, 0.0) is None
        assert fleet_decision(float("nan"), 0.5) is None

    def test_aggregate_partial_and_error_statuses(self):
        from lir_tpu.serve import ServeResult

        ok = ServeResult(request_id="a#m0", status="ok",
                         token_1_prob=0.7, token_2_prob=0.1)
        bad = ServeResult(request_id="a#m1", status="error", note="boom")
        agg = aggregate_fleet("a", {"m0": ok, "m1": bad}, 0.1)
        assert agg["status"] == "partial"
        assert agg["n_valid"] == 1
        assert agg["per_model"]["m1"]["decision"] is None
        assert np.isnan(agg["disagreement"])   # < 2 valid decisions
        agg2 = aggregate_fleet("a", {"m1": bad}, 0.1)
        assert agg2["status"] == "error"
