"""Ragged sweep scheduler tests (engine/scheduler.py).

Pins the three properties the scheduler's callers rely on:
- planning is deterministic and TOTAL (every grid cell lands in exactly
  one dispatch, identical inputs plan identical schedules),
- slot refill / bucket-ladder dispatch composition changes ONLY the
  batching — per-cell sweep results are identical to the legacy
  todo-order path on the fake backend,
- the cross-cell prefix-group decode reproduces decode_fused_shared on
  its pairwise special case (one cell per group, [bin, conf] members).
"""

import json

import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.engine import scheduler as sched_mod
from lir_tpu.engine import tokens as tok
from lir_tpu.utils.profiling import OccupancyStats


# ---------------------------------------------------------------------------
# Bucket ladder (tokens.bucket_ladder / assign_bucket) — pure host-side
# ---------------------------------------------------------------------------

def test_bucket_ladder_shape_and_alignment():
    edges = tok.bucket_ladder(1024)
    assert edges == tuple(sorted(set(edges)))          # strictly increasing
    assert edges[-1] == 1024                           # covers the ceiling
    for e in edges:
        # flash-eligibility: lane-friendly under one block, whole blocks
        # above it (tokens.FLASH_BLOCK) — a misaligned edge silently
        # drops every dispatch in its bucket to dense attention.
        assert e % (16 if e <= tok.FLASH_BLOCK else tok.FLASH_BLOCK) == 0
    # ~sqrt(2) spacing keeps worst-case padding bounded: no step doubles.
    for a, b in zip(edges, edges[1:]):
        assert b <= 2 * a
    # Tiny ceilings degenerate to a single bucket.
    assert tok.bucket_ladder(48) == (48,)


def test_assign_bucket_total_and_deterministic():
    edges = tok.bucket_ladder(512)
    for n in range(1, 600):
        b = tok.assign_bucket(n, edges)
        assert b in edges
        if n <= max(edges):
            # smallest covering edge
            assert b >= n and all(e < n for e in edges if e < b)
        else:
            # over-long: largest bucket (left-truncation semantics)
            assert b == max(edges)
        assert tok.assign_bucket(n, edges) == b


# ---------------------------------------------------------------------------
# Planning: totality, determinism, slot refill accounting
# ---------------------------------------------------------------------------

def _items(lengths, fmt_len=6):
    """SweepItems with distinct token contents: per-cell prompts share
    their first `n` tokens between formats (lcp == n)."""
    items = []
    for i, n in enumerate(lengths):
        base = [100 + i] * n
        items.append(sched_mod.SweepItem(
            cell=("cell", i), bin_ids=tuple(base + [7] * fmt_len),
            conf_ids=tuple(base + [9] * fmt_len), lcp=n))
    return items


def test_schedule_is_total_and_deterministic():
    rng = np.random.default_rng(0)
    lengths = rng.integers(4, 500, 57).tolist()
    buckets = tok.bucket_ladder(512)

    def plan():
        planner = sched_mod.RaggedScheduler(buckets, 8, stats=OccupancyStats())
        return planner.schedule(_items(lengths))

    dispatches = plan()
    seen = [it.cell for d in dispatches for it in d.items]
    assert sorted(seen) == sorted(("cell", i) for i in range(len(lengths)))
    assert len(seen) == len(set(seen))  # exactly once
    for d in dispatches:
        assert d.kind in ("shared", "grouped")
        assert d.bucket in buckets
        # every member's planned prefix fits its dispatch bucket
        for it in d.items:
            assert min(it.prefix_len, max(buckets)) <= d.bucket

    again = plan()
    assert [(d.kind, d.bucket, d.cells) for d in dispatches] == \
           [(d.kind, d.bucket, d.cells) for d in again]


def test_slot_refill_promotes_ragged_tail_once():
    # 9 short cells at batch 4: two full dispatches + a 1-cell tail. The
    # cost model promotes the tail into the 96 bucket (1 * 96 < 1-slot
    # padded dispatch at 64? no — vs _tail_batch(1,4)=1 slot * 64) only
    # when cheaper, so just pin totality + the refilled counter's books.
    lengths = [30] * 9 + [90] * 4
    stats = OccupancyStats()
    planner = sched_mod.RaggedScheduler(
        tok.bucket_ladder(256), 4, group_cells=False, stats=stats)
    dispatches = planner.schedule(_items(lengths))
    assert sum(len(d.items) for d in dispatches) == len(lengths)
    assert sum(b.cells for b in stats.buckets.values()) == len(lengths)
    assert sum(b.refilled for b in stats.buckets.values()) == \
           sum(d.refilled for d in dispatches)
    assert 0.0 < stats.occupancy_pct <= 100.0
    assert 0.0 <= stats.padding_waste_pct < 100.0


def test_prefix_groups_form_only_on_long_shared_prefixes():
    # 4 cells sharing 24 leading tokens (>= min_group_prefix, >= half of
    # each prefill) group; 4 cells with disjoint prompts never do.
    shared = [50 + i for i in range(24)]
    items = []
    for i in range(4):
        ids = shared + [200 + i] * (4 + i)
        items.append(sched_mod.SweepItem(
            cell=("g", i), bin_ids=tuple(ids + [7] * 5),
            conf_ids=tuple(ids + [9] * 5), lcp=len(ids)))
    solo = _items([40, 45, 50, 55])
    planner = sched_mod.RaggedScheduler(
        tok.bucket_ladder(256), 8, stats=OccupancyStats())
    dispatches = planner.schedule(items + solo)
    grouped = [d for d in dispatches if d.kind == "grouped"]
    assert len(grouped) == 1
    assert sorted(it.cell for it in grouped[0].items) == \
           sorted(("g", i) for i in range(4))
    assert grouped[0].groups[0].plen >= 24
    # the disjoint cells all ride shared dispatches
    rest = [it.cell for d in dispatches if d.kind == "shared"
            for it in d.items]
    assert sorted(rest) == sorted(("cell", i) for i in range(4))


# ---------------------------------------------------------------------------
# Engine-level parity on the fake backend
# ---------------------------------------------------------------------------

def _tiny_engine(rt, seed=2):
    import jax

    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="sched-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=64, n_layers=2, n_heads=4,
                      intermediate_size=128, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    return ScoringEngine(params, cfg, FakeTokenizer(), rt), params, cfg


def _varlen_grid(rng):
    """2 prompts x variable-length rephrasings spanning several buckets;
    prompt 0's rephrasings share their first 20 words so the ragged run
    also exercises the cross-cell prefix-group path."""
    from lir_tpu.data.prompts import LegalPrompt

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    shared_head = " ".join(rng.choice(words) for _ in range(20))
    prompts = (
        LegalPrompt(main=shared_head + " " + text(8),
                    response_format="Answer Yes or No .",
                    target_tokens=("Yes", "No"),
                    confidence_format="Give a number from 0 to 100 ."),
        LegalPrompt(main=text(30),
                    response_format="Answer Yes or No .",
                    target_tokens=("Yes", "No"),
                    confidence_format="Give a number from 0 to 100 ."),
    )
    perturbations = (
        # same 20-word head, short tails -> a 4+ cell prefix group
        [shared_head + " " + text(4 + i) for i in range(4)],
        # disjoint, strongly varied lengths -> bucket ladder + refill
        [text(n) for n in (5, 90, 140, 12, 70, 25, 110)],
    )
    return prompts, perturbations


@pytest.mark.slow
def test_ragged_sweep_matches_legacy_per_cell(tmp_path):
    """The tentpole's safety property: bucket ladder + slot refill +
    prefix grouping change dispatch COMPOSITION only — every cell's D6
    readout equals the legacy todo-order path's: token/text readouts bit
    for bit, float readouts to shape-fusion tolerance (a cell padded to
    a different bucket length fuses slightly differently; the last ulp
    of a logprob can move)."""
    from lir_tpu.engine.sweep import run_perturbation_sweep

    rng = np.random.default_rng(11)
    prompts, perturbations = _varlen_grid(rng)

    def run(ragged, sub):
        rt = RuntimeConfig(batch_size=4, max_seq_len=256,
                           ragged_scheduler=ragged)
        engine, _, _ = _tiny_engine(rt)
        rows = run_perturbation_sweep(
            engine, "sched-tiny", prompts, perturbations,
            tmp_path / sub / "results.xlsx", checkpoint_every=100)
        return rows, engine

    rows_r, eng_r = run(True, "ragged")
    rows_l, _ = run(False, "legacy")
    assert len(rows_r) == len(rows_l) == 13

    def key(r):
        return (r.original_main, r.rephrased_main)

    by_key = {key(r): r for r in rows_l}
    assert set(map(key, rows_r)) == set(by_key)
    for r in rows_r:
        l = by_key[key(r)]
        assert r.model_response == l.model_response
        assert r.model_confidence_response == l.model_confidence_response
        assert r.confidence_value == l.confidence_value
        np.testing.assert_allclose(r.token_1_prob, l.token_1_prob,
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(r.token_2_prob, l.token_2_prob,
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(r.weighted_confidence,
                                   l.weighted_confidence,
                                   rtol=1e-5, atol=1e-7)
        lp_r, lp_l = (json.loads(r.log_probabilities),
                      json.loads(l.log_probabilities))
        assert list(lp_r) == list(lp_l)  # same top-20 ids, same order
        np.testing.assert_allclose(list(lp_r.values()),
                                   list(lp_l.values()), atol=2e-6)

    # The ragged run actually scheduled (counters populated and sane).
    stats = eng_r.occupancy
    assert stats is not None
    assert sum(b.cells for b in stats.buckets.values()) == 13
    assert 0.0 < stats.occupancy_pct <= 100.0
    assert 0.0 <= stats.padding_waste_pct < 100.0
    assert stats.grouped_cells >= 4  # the shared-head rephrasings grouped


@pytest.mark.slow
def test_grouped_decode_matches_shared_pairwise():
    """decode_fused_grouped on one-cell groups ([bin, conf] members,
    group_idx = [0,0,1,1,...]) == decode_fused_shared on the same
    prompts — the pairwise special case the grouped path generalizes."""
    engine, _, _ = _tiny_engine(
        RuntimeConfig(batch_size=4, max_seq_len=256))
    mains = [f"the quick brown fox {i} jumps over the lazy dog "
             f"word {i * 7} extra filler text here" for i in range(4)]
    bins = [m + " Respond with either Yes or No only" for m in mains]
    confs = [m + " Give a confidence number from 0 to 100" for m in mains]
    t1 = np.full((4,), FakeTokenizer.YES, np.int32)
    t2 = np.full((4,), FakeTokenizer.NO, np.int32)
    NEW = 4

    ftok = engine.tokenizer
    bin_ids = [ftok(p).input_ids for p in bins]
    conf_ids = [ftok(p).input_ids for p in confs]
    items = sched_mod.build_items(bin_ids, conf_ids, list(range(4)))
    groups = [sched_mod.PrefixGroup(items=(it,), plen=it.lcp)
              for it in items]
    bucket = tok.pick_bucket([it.prefix_len for it in items],
                             engine.buckets)
    sfx = tok.pick_bucket(
        [max(len(it.bin_ids), len(it.conf_ids)) - it.lcp for it in items],
        sched_mod.SUFFIX_BUCKETS)

    out, m = engine.decode_fused_grouped(
        groups, t1, t2, NEW, NEW, early_stop=False,
        bucket=bucket, sfx_bucket=sfx)
    assert m == 8
    ref_a, ref_b = engine.decode_fused_shared(
        bins, confs, t1, t2, new_tokens=NEW, conf_tokens=NEW,
        early_stop=False)

    for start, ref in ((0, ref_a), (1, ref_b)):
        rows = slice(start, m, 2)
        np.testing.assert_array_equal(np.asarray(out.generated[rows]),
                                      np.asarray(ref.generated))
        np.testing.assert_allclose(np.asarray(out.p_yes[rows]),
                                   np.asarray(ref.p_yes),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out.p_no[rows]),
                                   np.asarray(ref.p_no),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(out.topk_ids[rows]),
                                      np.asarray(ref.topk_ids))
        np.testing.assert_allclose(np.asarray(out.topk_logprobs[rows]),
                                   np.asarray(ref.topk_logprobs),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out.weighted_confidence[1:m:2]),
        np.asarray(ref_b.weighted_confidence), rtol=1e-5, atol=1e-6)
