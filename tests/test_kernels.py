"""PR-7 fused kernel layer: Pallas flash-decode (interpret mode on CPU —
the same kernel runs compiled on the chip), int8 matmul fusion, and
chunked prefill/decode piggybacking.

Three parity contracts pinned here:
- flash_decode == the dense decode-attention path: exact argmax through
  the greedy loop, logits within float tolerance, for masked/padded rows,
  GQA, ALiBi, and every bucket-ladder cache extent;
- quant.matmul's fused s8 x s8 dot == the dequantized reference for both
  static and dynamic QuantTensors, and quant.shared_quant is bit-identical
  to per-matrix activation quantization;
- a piggybacked dispatch chain == the sequential dispatches per row
  (int readouts exact, float readouts to tolerance), including through
  the sweep's chain orchestration on the fake backend.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.engine import generate
from lir_tpu.models import decoder, quant
from lir_tpu.models.registry import ModelConfig
from lir_tpu.ops import flash_decode, pick_split


def _tiny_cfg(**kw) -> ModelConfig:
    base = dict(name="kernels-tiny", vocab_size=128, hidden_size=32,
                n_layers=2, n_heads=4, n_kv_heads=2, intermediate_size=64,
                max_seq_len=512)
    base.update(kw)
    return ModelConfig(**base)


def _dense_decode_reference(q, k, v, q_pos, mask, key_pos, slopes=None):
    """The decode path's dense attention (decoder._attention_cached +
    _causal_bias semantics), spelled out independently."""
    B, H, hd = q.shape
    K = k.shape[0]
    G = H // K
    qg = q.reshape(B, 1, K, G, hd)
    scores = jnp.einsum("bskgd,ktbd->bkgst", qg, k).astype(jnp.float32)
    T = k.shape[1]
    scores = scores.reshape(B, H, 1, T) / math.sqrt(hd)
    allowed = (key_pos[:, None, :] <= q_pos[:, None, None]) & (mask[:, None, :] > 0)
    bias = jnp.where(allowed, 0.0, jnp.float32(-1e9))[:, None, :, :]
    if slopes is not None:
        bias = bias + (slopes[None, :, None, None]
                       * key_pos.astype(jnp.float32)[:, None, None, :])
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(q.dtype)
    pg = probs.reshape(B, K, G, 1, T)
    out = jnp.einsum("bkgst,ktbd->bskgd", pg, v)
    return out.reshape(B, H, hd)


class TestFlashDecodeKernel:
    def _case(self, T, seed=0, B=3, H=4, K=2, hd=16):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(K, T, B, hd)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(K, T, B, hd)), jnp.float32)
        mask = np.zeros((B, T), np.int32)
        mask[0, : max(T // 4, 1)] = 1        # short row
        mask[1, T // 8: T - T // 8] = 1      # interior hole pattern
        mask[2, :] = 1                       # full row
        key_pos = np.maximum(np.cumsum(mask, -1) - 1, 0)
        q_pos = np.asarray([mask[r].sum() - 1 for r in range(B)], np.int32)
        return (q, k, v, jnp.asarray(q_pos), jnp.asarray(mask),
                jnp.asarray(key_pos))

    @pytest.mark.parametrize("T", [8, 76, 128, 152, 280])
    def test_matches_dense_per_bucket_extent(self, T):
        """Every cache extent the bucket ladder plans (bucket + suffix +
        decode budget — including the non-power-of-two ones) lowers with
        an exact split and matches the dense path."""
        q, k, v, q_pos, mask, key_pos = self._case(T)
        exp = _dense_decode_reference(q, k, v, q_pos, mask, key_pos)
        got = flash_decode(q, k, v, q_pos, mask, key_pos, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_pick_split_is_exact_division(self):
        for T in (8, 76, 108, 128, 152, 280, 1024):
            s = pick_split(T)
            assert T % s == 0 and 1 <= s <= min(T, 128)
        assert pick_split(128) == 128
        assert pick_split(280) == 56        # largest 8-aligned divisor
        assert pick_split(76) == 76         # no 8-aligned divisor: 1 split

    def test_masked_rows_and_causality(self):
        """A key slot is visible iff masked valid AND its position <= the
        query's — tightening q_pos must change the output."""
        q, k, v, q_pos, mask, key_pos = self._case(128, seed=3)
        full = flash_decode(q, k, v, q_pos, mask, key_pos, interpret=True)
        clipped = flash_decode(q, k, v, q_pos - 5, mask, key_pos,
                               interpret=True)
        exp = _dense_decode_reference(q, k, v, q_pos - 5, mask, key_pos)
        np.testing.assert_allclose(np.asarray(clipped), np.asarray(exp),
                                   atol=2e-5)
        assert float(jnp.abs(full - clipped).max()) > 1e-4

    def test_alibi_slopes(self):
        q, k, v, q_pos, mask, key_pos = self._case(64, seed=4, H=4, K=4)
        slopes = jnp.asarray(decoder.alibi_slopes(4))
        exp = _dense_decode_reference(q, k, v, q_pos, mask, key_pos,
                                      slopes=slopes)
        got = flash_decode(q, k, v, q_pos, mask, key_pos,
                           alibi_slopes=slopes, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_mqa_grouping(self):
        q, k, v, q_pos, mask, key_pos = self._case(64, seed=5, H=4, K=1)
        exp = _dense_decode_reference(q, k, v, q_pos, mask, key_pos)
        got = flash_decode(q, k, v, q_pos, mask, key_pos, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)


@pytest.fixture()
def fused_decode_interpret():
    """Arm the tier-1 interpret hook; jit caches key on cfg, so tests
    rename their cfg per mode instead of clearing global caches."""
    old = decoder.FUSED_DECODE_INTERPRET_ON_CPU
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True
    yield
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = old


class TestFusedDecodeRouting:
    def test_greedy_decode_argmax_identical(self, fused_decode_interpret):
        """The full greedy loop through decode_step: fused flash-decode
        argmax-identical to the dense path, logits to tolerance."""
        cfg = _tiny_cfg()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(3, 128, (3, 12)), jnp.int32)
        mask = np.ones((3, 12), np.int32)
        mask[0, :5] = 0                      # left-padded row
        mask = jnp.asarray(mask)
        dense_cfg = dataclasses.replace(cfg, fused_decode=False)
        gen_d, lg_d = generate.greedy_decode(params, dense_cfg, toks, mask,
                                             max_new_tokens=6)
        gen_f, lg_f = generate.greedy_decode(params, cfg, toks, mask,
                                             max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(gen_d), np.asarray(gen_f))
        np.testing.assert_allclose(np.asarray(lg_d), np.asarray(lg_f),
                                   atol=2e-5)

    def test_alibi_model_argmax_identical(self, fused_decode_interpret):
        cfg = _tiny_cfg(name="kernels-alibi", pos_embedding="alibi",
                        norm="layernorm", gated_mlp=False, n_kv_heads=4)
        params = decoder.init_params(cfg, jax.random.PRNGKey(1),
                                     dtype=jnp.float32)
        rng = np.random.default_rng(1)
        toks = jnp.asarray(rng.integers(3, 128, (2, 10)), jnp.int32)
        mask = jnp.ones((2, 10), jnp.int32)
        dense_cfg = dataclasses.replace(cfg, fused_decode=False)
        gen_d, _ = generate.greedy_decode(params, dense_cfg, toks, mask,
                                          max_new_tokens=5)
        gen_f, _ = generate.greedy_decode(params, cfg, toks, mask,
                                          max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(gen_d), np.asarray(gen_f))

    def test_no_fused_decode_flag_restores_dense(self):
        """RuntimeConfig.fused_decode=False reaches the model config (the
        --no-fused-decode path) and the dense route stays dense on CPU
        without the hook."""
        from lir_tpu.backends.fake import FakeTokenizer
        from lir_tpu.config import RuntimeConfig
        from lir_tpu.engine.runner import ScoringEngine

        cfg = _tiny_cfg(vocab_size=FakeTokenizer.VOCAB)
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        eng = ScoringEngine(params, cfg, FakeTokenizer(),
                            RuntimeConfig(batch_size=2, fused_decode=False))
        assert eng.cfg.fused_decode is False
        eng2 = ScoringEngine(params, cfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=2))
        assert eng2.cfg.fused_decode is True
        # CPU without the interpret hook: routing stays dense either way.
        assert not decoder._fused_decode_ok(
            eng2.cfg, 1, (jnp.zeros((1,)), None, None))


class TestInt8MatmulFusion:
    def test_static_fused_matches_dequant_reference(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(5, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 48)), jnp.float32)
        qt = quant.quantize(w)
        np.testing.assert_allclose(
            np.asarray(quant.matmul(x, qt)), np.asarray(x @ qt.dequant()),
            rtol=1e-5, atol=1e-5)

    def test_dynamic_fused_matches_dequant_reference(self):
        """The s8 x s8 -> s32 dot with output-side scales equals the
        matmul of BOTH dequantized operands (integer accumulation is
        exact; only the scale multiplies round)."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(5, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 48)), jnp.float32)
        qt = dataclasses.replace(quant.quantize(w), dynamic=True)
        xq, xs = quant.dynamic_quant(x)
        ref = ((np.asarray(xq, np.float32) * np.asarray(xs)[:, None])
               @ np.asarray(qt.dequant()))
        np.testing.assert_allclose(np.asarray(quant.matmul(x, qt)), ref,
                                   rtol=1e-5, atol=1e-5)

    def test_shared_quant_bitwise_equals_per_matrix(self):
        """One shared activation quantization (the wq/wk/wv and
        w_up/w_gate call sites) is BIT-identical to quantizing per
        matrix — same amax/127 rule on the same tensor."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(3, 7, 32)), jnp.float32)
        w1 = dataclasses.replace(
            quant.quantize(jnp.asarray(rng.normal(size=(32, 16)),
                                       jnp.float32)), dynamic=True)
        w2 = dataclasses.replace(
            quant.quantize(jnp.asarray(rng.normal(size=(32, 24)),
                                       jnp.float32)), dynamic=True)
        xq = quant.shared_quant(x, w1, w2)
        assert isinstance(xq, quant.QuantActivation)
        np.testing.assert_array_equal(np.asarray(quant.matmul(xq, w1)),
                                      np.asarray(quant.matmul(x, w1)))
        np.testing.assert_array_equal(np.asarray(quant.matmul(xq, w2)),
                                      np.asarray(quant.matmul(x, w2)))

    def test_shared_quant_passthrough_for_static_or_dense(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(3, 32)), jnp.float32)
        w_static = quant.quantize(jnp.asarray(rng.normal(size=(32, 16)),
                                              jnp.float32))
        w_dyn = dataclasses.replace(w_static, dynamic=True)
        assert quant.shared_quant(x, w_static, w_dyn) is x
        assert quant.shared_quant(x, w_dyn, x) is x   # dense member

    @pytest.mark.parametrize("dynamic", [False, True])
    def test_quantized_forward_tracks_dense(self, dynamic):
        """End-to-end through the decoder's shared-quant call sites: the
        fused int8 forward tracks the dense model's readout."""
        cfg = _tiny_cfg(name=f"kernels-q{dynamic}")
        params = decoder.init_params(cfg, jax.random.PRNGKey(2),
                                     dtype=jnp.float32)
        qparams = quant.quantize_decoder_params(params, dynamic=dynamic)
        rng = np.random.default_rng(4)
        toks = jnp.asarray(rng.integers(3, 128, (2, 10)), jnp.int32)
        dense = jax.nn.softmax(
            decoder.forward(params, cfg, toks)[:, -1], axis=-1)
        fused = jax.nn.softmax(
            decoder.forward(qparams, cfg, toks)[:, -1], axis=-1)
        assert np.isfinite(np.asarray(fused)).all()
        assert float(jnp.abs(dense - fused).max()) < 0.06


def _assert_fused_out_close(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        x, y = np.asarray(x), np.asarray(y)
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_allclose(x, y, atol=1e-5)
        else:
            np.testing.assert_array_equal(x, y)


class TestPiggyback:
    def _dispatch(self, seed, B=3, S=16, SA=4, SB=8, V=128):
        rng = np.random.default_rng(seed)
        prefix = jnp.asarray(rng.integers(3, V, (B, S)), jnp.int32)
        pm = np.ones((B, S), np.int32)
        pm[0, S - 4:] = 0
        sa = jnp.asarray(rng.integers(3, V, (B, SA)), jnp.int32)
        sam = np.ones((B, SA), np.int32)
        sam[1, 2:] = 0
        sb = jnp.asarray(rng.integers(3, V, (B, SB)), jnp.int32)
        sbm = np.ones((B, SB), np.int32)
        sbm[2, 5:] = 0
        return (prefix, jnp.asarray(pm), sa, jnp.asarray(sam), sb,
                jnp.asarray(sbm))

    def test_chain_equals_sequential_dispatches(self):
        """prefill -> step -> step -> drain reproduces three sequential
        shared dispatches per row (int readouts exact)."""
        cfg = _tiny_cfg(name="kernels-piggy")
        params = decoder.init_params(cfg, jax.random.PRNGKey(3),
                                     dtype=jnp.float32)
        yes = jnp.asarray([5, 6, 7], jnp.int32)
        no = jnp.asarray([9, 10, 11], jnp.int32)
        d_ids = jnp.arange(10, 30, dtype=jnp.int32)
        d_vals = jnp.arange(0.0, 20.0, dtype=jnp.float32)
        na, nb = 3, 5
        ds = [self._dispatch(s) for s in (1, 2, 3)]
        seq = [generate.greedy_decode_fused_shared(
            params, cfg, *d, yes, no, d_ids, d_vals, max_new_a=na,
            max_new_b=nb) for d in ds]

        carry = generate.shared_piggyback_prefill(params, cfg, *ds[0],
                                                  max_new_a=na, max_new_b=nb)
        outs = []
        for d in ds[1:]:
            oa, ob, carry = generate.shared_piggyback_step(
                params, cfg, carry, *d, yes, no, d_ids, d_vals,
                max_new_a=na, max_new_b=nb)
            outs.append((oa, ob))
        S, SA, SB = 16, 4, 8
        outs.append(generate.shared_piggyback_drain(
            params, cfg, carry, yes, no, d_ids, d_vals, slot0_a=S + SA,
            slot0_b=S + SA + na + SB, max_new_a=na, max_new_b=nb))
        for s, p in zip(seq, outs):
            _assert_fused_out_close(s, p)

    def test_sweep_chains_and_matches_plain(self, tmp_path):
        """The ragged sweep forms piggyback chains (kernel_stats counters
        move) and its rows equal the piggyback-off sweep's."""
        import torch
        import transformers as tf

        from lir_tpu.backends.fake import FakeTokenizer
        from lir_tpu.config import RuntimeConfig
        from lir_tpu.data.prompts import LegalPrompt
        from lir_tpu.engine.runner import ScoringEngine
        from lir_tpu.engine.sweep import run_perturbation_sweep
        from lir_tpu.models.loader import config_from_hf, convert_decoder

        torch.manual_seed(0)
        hf = tf.LlamaForCausalLM(tf.LlamaConfig(
            vocab_size=FakeTokenizer.VOCAB, hidden_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, intermediate_size=128,
            max_position_embeddings=512,
            tie_word_embeddings=False)).eval()
        cfg, fam = config_from_hf(hf.config)
        params = convert_decoder(hf.state_dict(), cfg, fam)
        prompts = (LegalPrompt(
            main="Does a vehicle include a bicycle ?",
            response_format="Answer Covered or Not .",
            target_tokens=("Covered", "Not"),
            confidence_format="Give a number from 0 to 100 ."),)
        perturbations = ([
            f"Would a bicycle number {i} count as a vehicle maybe ?"
            for i in range(11)],)

        def run(piggy, sub):
            rt = RuntimeConfig(batch_size=4, max_new_tokens=8,
                               max_seq_len=256, piggyback_prefill=piggy,
                               sweep_group_min_cells=0)
            eng = ScoringEngine(params, cfg, FakeTokenizer(), rt)
            rows = run_perturbation_sweep(
                eng, "tiny", prompts, perturbations,
                tmp_path / f"r{sub}.xlsx", checkpoint_every=100)
            return rows, eng

        rows_on, eng_on = run(True, "on")
        rows_off, eng_off = run(False, "off")
        assert eng_on.kernel_stats.counters.get("chains_opened", 0) >= 1
        assert eng_on.kernel_stats.counters.get("piggybacked_steps", 0) >= 1
        assert eng_on.kernel_stats.counters.get("chains_drained", 0) >= 1
        assert not eng_off.kernel_stats.counters
        key = lambda r: r.rephrased_main  # noqa: E731
        for a, b in zip(sorted(rows_on, key=key),
                        sorted(rows_off, key=key)):
            assert a.model_response == b.model_response
            assert a.model_confidence_response == b.model_confidence_response
            assert a.confidence_value == b.confidence_value
            assert abs(a.token_1_prob - b.token_1_prob) < 1e-5
            assert abs(a.token_2_prob - b.token_2_prob) < 1e-5
            assert abs(a.weighted_confidence - b.weighted_confidence) < 1e-4

    def test_piggyback_respects_fault_wrapping(self):
        """A fault-wrapped engine (instance-shadowed dispatch methods)
        must not chain — the chain would bypass the injected sites."""
        from lir_tpu.backends.fake import FakeTokenizer
        from lir_tpu.config import RuntimeConfig
        from lir_tpu.engine.runner import ScoringEngine

        cfg = _tiny_cfg(vocab_size=FakeTokenizer.VOCAB)
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        eng = ScoringEngine(params, cfg, FakeTokenizer(),
                            RuntimeConfig(batch_size=2))
        assert eng.piggyback_supported()
        eng.decode_fused_shared = lambda *a, **k: None   # wrap_engine style
        assert not eng.piggyback_supported()
        eng2 = ScoringEngine(params, cfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=2,
                                           piggyback_prefill=False))
        assert not eng2.piggyback_supported()


class TestCostModelAndWatchdogSeed:
    def test_decode_floor_constants(self):
        from lir_tpu.engine import scheduler as sched

        # Fused pricing keeps the historical 1:1 decode-token price
        # (plans byte-identical); the unfused fallback prices higher.
        assert sched.decode_token_cost(True) == sched.DECODE_TOKEN_COST_FUSED
        assert (sched.bucket_cost(4, 64, 4, 12)
                == 4 * 64 + sched.decode_floor(4, 4, 12))
        unfused = sched.bucket_cost(4, 64, 4, 12, fused_decode=False)
        assert unfused > sched.bucket_cost(4, 64, 4, 12)
        assert sched.decode_floor(4, 4, 12, fused_decode=False) == (
            4 * 12 * sched.DECODE_TOKEN_COST_UNFUSED)

    def test_watchdog_seed_reads_scheduler_constants(self):
        from lir_tpu.engine import scheduler as sched
        from lir_tpu.guard.watchdog import DispatchWatchdog

        wd = DispatchWatchdog(multiple=1.0, floor_s=0.0)
        assert wd.seed_headroom == sched.watchdog_seed_headroom()
        wd.observe(cost=10, elapsed=1.0)
        # First sample is inflated by the headroom: a dense-path dispatch
        # at UNFUSED/FUSED x the fused timing stays inside the deadline.
        assert wd.deadline_for(10) == pytest.approx(
            1.0 * sched.watchdog_seed_headroom())
        wd2 = DispatchWatchdog(multiple=1.0, floor_s=0.0, seed_headroom=1.0)
        wd2.observe(cost=10, elapsed=1.0)
        assert wd2.deadline_for(10) == pytest.approx(1.0)


class TestOpsSurface:
    def test_ops_is_the_single_kernel_entry_point(self):
        import lir_tpu.ops as ops

        for name in ("flash_attention", "flash_decode", "pick_split",
                     "reference_attention", "ring_attention",
                     "ulysses_attention", "DEFAULT_BLOCK_Q",
                     "DEFAULT_BLOCK_K"):
            assert hasattr(ops, name), name
        # The re-export IS the parallel implementation, not a copy.
        from lir_tpu.parallel.ring_attention import (reference_attention,
                                                     ring_attention)
        assert ops.ring_attention is ring_attention
        assert ops.reference_attention is reference_attention
