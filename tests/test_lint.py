"""graft-lint (lir_tpu/lint): per-pass positive/negative fixtures,
baseline round-trip, suppression mechanics, and the real-tree pin —
`lir_tpu lint` over this repository must report ZERO findings outside
the checked-in tools/lint_baseline.json, inside the <10 s budget.

The fixtures under tests/lint_fixtures/ are mini source trees that are
PARSED, never imported; each pass has a seeded-violation file it must
flag and a clean twin it must not.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from lir_tpu.lint.core import (ALL_PASSES, Finding, diff_baseline,
                               load_baseline, load_project, run_passes,
                               save_baseline)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def findings_for(subdir: str, pass_name: str):
    project = load_project(FIXTURES / subdir)
    return run_passes(project, only=[pass_name])


def scopes(findings):
    return {f.scope for f in findings}


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

class TestDonationPass:
    def test_flags_seeded_violations(self):
        fs = findings_for("donation", "donation-safety")
        assert scopes(fs) == {"chain_bad", "chain_bad_kw"}
        assert all("donation" in f.pass_name for f in fs)
        assert all("scratch" in f.message for f in fs)

    def test_clean_twins_not_flagged(self):
        fs = findings_for("donation", "donation-safety")
        assert all(f.path.endswith("donation_bad.py") for f in fs)
        # rebind / sibling-branch / identity / **splat idioms stay clean
        assert not {"chain_ok", "branch_ok", "identity_ok",
                    "splat_ok"} & scopes(fs)


# ---------------------------------------------------------------------------
# trace-hazard
# ---------------------------------------------------------------------------

class TestTraceHazardPass:
    def test_flags_seeded_violations(self):
        fs = findings_for("trace", "trace-hazard")
        # branch, coercion, .item(), set iteration, and the taint-
        # propagated helper must each be caught.
        assert scopes(fs) == {"bad_branch", "bad_coerce", "bad_item",
                              "bad_set", "helper"}

    def test_static_idioms_not_flagged(self):
        fs = findings_for("trace", "trace-hazard")
        assert all(f.path.endswith("trace_bad.py") for f in fs)
        assert not {"ok_static_branch", "ok_shape_branch", "ok_identity",
                    "ok_lax_cond", "ok_dict_iteration",
                    "ok_metadata_call"} & scopes(fs)

    def test_set_message_names_desync(self):
        fs = [f for f in findings_for("trace", "trace-hazard")
              if f.scope == "bad_set"]
        assert fs and "desync" in fs[0].message


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

class TestHostSyncPass:
    def test_flags_seeded_violations(self):
        fs = findings_for("hostsync", "host-sync")
        assert scopes(fs) == {"bad_asarray", "bad_float", "bad_truthiness",
                              "bad_iteration", "_decode_row"}

    def test_sanctioned_boundaries_not_flagged(self):
        fs = findings_for("hostsync", "host-sync")
        assert all(f.path == "lir_tpu/engine/hot_bad.py" for f in fs)
        # device_get boundary, @host_readout, allow-comment, shape
        # metadata, pure-host data: all clean.
        assert not {"ok_device_get", "ok_declared_boundary",
                    "ok_allow_comment", "ok_shape_metadata",
                    "ok_host_data"} & scopes(fs)

    def test_cold_modules_out_of_scope(self):
        fs = findings_for("hostsync", "host-sync")
        assert not any("stats/cold" in f.path for f in fs)

    def test_cross_function_taint_reaches_helper(self):
        fs = [f for f in findings_for("hostsync", "host-sync")
              if f.scope == "_decode_row"]
        assert fs and ".tolist()" in fs[0].message


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

class TestLockDisciplinePass:
    def test_flags_seeded_violations(self):
        fs = findings_for("locks", "lock-discipline")
        assert scopes(fs) == {"BadServer.submit", "BadServer.trip",
                              "TypoServer"}

    def test_held_by_caller_and_condition_alias_ok(self):
        fs = findings_for("locks", "lock-discipline")
        assert all(f.path.endswith("locks_bad.py") for f in fs)

    def test_unknown_lock_is_reported(self):
        fs = [f for f in findings_for("locks", "lock-discipline")
              if f.scope == "TypoServer"]
        assert fs and "_missing_lock" in fs[0].message


# ---------------------------------------------------------------------------
# config-drift
# ---------------------------------------------------------------------------

class TestConfigDriftPass:
    def test_flags_drifted_knob_three_ways(self):
        fs = findings_for("configdrift/bad", "config-drift")
        assert {f.scope for f in fs} == {"RuntimeConfig.fancy_knob"}
        msgs = " | ".join(f.message for f in fs)
        assert "no cli.py flag" in msgs
        assert "not mentioned in DEPLOY.md" in msgs
        assert "manifest_key projection" in msgs

    def test_host_only_exempt_from_key(self):
        fs = findings_for("configdrift/bad", "config-drift")
        assert not any(f.scope == "RuntimeConfig.log_level" for f in fs)

    def test_clean_twin(self):
        assert findings_for("configdrift/ok", "config-drift") == []


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------

class TestSuppression:
    def test_allow_comment_waives_named_pass(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0  # guarded-by: _lock\n"
            "    def poke(self):\n"
            "        self._x = 1  # lint: allow(lock-discipline)\n")
        assert run_passes(load_project(tmp_path)) == []

    def test_skip_file_waives_module(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "# lint: skip-file\n"
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0  # guarded-by: _lock\n"
            "    def poke(self):\n"
            "        self._x = 1\n")
        assert run_passes(load_project(tmp_path)) == []


class TestBaseline:
    def _findings(self):
        return [Finding("host-sync", "a.py", 3, "f", "msg one"),
                Finding("host-sync", "a.py", 9, "f", "msg one"),
                Finding("config-drift", "b.py", 1, "C.x", "msg two")]

    def test_round_trip(self, tmp_path):
        path = tmp_path / "baseline.json"
        save_baseline(path, self._findings())
        allowed = load_baseline(path)
        new, stale = diff_baseline(self._findings(), allowed)
        assert new == [] and stale == 0
        # counts survive: the duplicate fingerprint is stored as count=2
        data = json.loads(path.read_text())
        counts = {r["message"]: r["count"] for r in data["findings"]}
        assert counts == {"msg one": 2, "msg two": 1}

    def test_new_finding_detected(self, tmp_path):
        path = tmp_path / "baseline.json"
        save_baseline(path, self._findings())
        extra = self._findings() + [
            Finding("trace-hazard", "c.py", 7, "g", "fresh")]
        new, stale = diff_baseline(extra, load_baseline(path))
        assert [f.message for f in new] == ["fresh"] and stale == 0

    def test_burned_down_entry_reported_stale(self, tmp_path):
        path = tmp_path / "baseline.json"
        save_baseline(path, self._findings())
        new, stale = diff_baseline(self._findings()[:1],
                                   load_baseline(path))
        assert new == [] and stale == 2  # one dup + msg two burned down

    def test_missing_baseline_is_empty(self, tmp_path):
        assert load_baseline(tmp_path / "nope.json") == {}


# ---------------------------------------------------------------------------
# the real tree
# ---------------------------------------------------------------------------

class TestRealTree:
    def test_zero_non_baselined_findings_within_budget(self):
        t0 = time.perf_counter()
        project = load_project(REPO)
        findings = run_passes(project)
        new, _stale = diff_baseline(
            findings, load_baseline(REPO / "tools" / "lint_baseline.json"))
        elapsed = time.perf_counter() - t0
        assert new == [], "new lint findings:\n" + "\n".join(
            f.render() for f in new)
        assert elapsed < 10.0, f"lint budget blown: {elapsed:.1f}s"

    def test_all_six_passes_registered(self):
        assert set(ALL_PASSES) == {"donation-safety", "trace-hazard",
                                   "host-sync", "lock-discipline",
                                   "config-drift", "metrics-drift"}

    def test_annotated_lock_state_is_covered(self):
        """The satellite annotations are live: the lock pass sees the
        breaker/watchdog/queue/cache/server attributes as guarded."""
        from lir_tpu.lint.locks import LockDisciplinePass
        import ast as ast_mod

        project = load_project(REPO)
        p = LockDisciplinePass()
        covered = {}
        for mod in project.modules:
            if "guarded-by:" not in mod.source:
                continue
            for node in ast_mod.walk(mod.tree):
                if isinstance(node, ast_mod.ClassDef):
                    guarded, _created = p._collect(mod, node)
                    if guarded:
                        covered[node.name] = set(guarded)
        assert covered.get("CircuitBreaker") == {"_state", "_consecutive",
                                                 "_opened_at"}
        assert covered.get("DispatchWatchdog") == {"_rate", "_flat"}
        assert "_dq" in covered.get("RequestQueue", set())
        assert "_od" in covered.get("ResultCache", set())
        assert "_target_memo" in covered.get("ScoringServer", set())

    def test_baseline_is_empty_gate_is_strict_zero(self):
        """The config-drift burn-down is COMPLETE (the missing --dtype/
        --logits-dtype/--scan-positions/--topk-match/--remat flags now
        exist): the checked-in baseline must stay EMPTY, so the lint
        gate is strict zero-findings — nobody smuggles a new violation
        in through a baseline entry."""
        allowed = load_baseline(REPO / "tools" / "lint_baseline.json")
        assert allowed == {}, (
            f"baseline must stay empty (strict zero-findings gate), "
            f"found {sorted(allowed)}")


# ---------------------------------------------------------------------------
# CLI entry points
# ---------------------------------------------------------------------------

class TestCli:
    def test_module_entry_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "lir_tpu.lint"], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 new" in proc.stdout

    def test_subcommand_entry_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "lir_tpu", "lint"], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_select_single_pass(self):
        proc = subprocess.run(
            [sys.executable, "-m", "lir_tpu.lint", "--select",
             "donation-safety"], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 finding" in proc.stdout or "0 new" in proc.stdout

    def test_new_violation_fails_gate(self, tmp_path):
        """End to end: a fresh violation in a scratch tree exits 1 and
        names the pass."""
        pkg = tmp_path / "lir_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            "import functools\nimport jax\n\n"
            "@functools.partial(jax.jit, donate_argnames=('c',))\n"
            "def f(c):\n    return c\n\n"
            "def g(c):\n    out = f(c)\n    return out + c\n")
        proc = subprocess.run(
            [sys.executable, "-m", "lir_tpu.lint", "--root",
             str(tmp_path), "--baseline", "none"], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert "donation-safety" in proc.stdout
