"""Sweep-driver tests: D1/D2/D6 row production, manifest resume, checkpoints.

Capability parity under test (SURVEY.md §2.1 C4/C5/C9/C11): grid expansion,
done-set dedup, checkpoint-every-N, append-with-schema-check — all with the
fake backend so no weights or network are needed.
"""

from pathlib import Path

import numpy as np
import pandas as pd
import torch

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.data.prompts import LegalPrompt, WORD_MEANING_QUESTIONS, format_instruct_prompt
from lir_tpu.engine import grid as grid_mod
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_perturbation_sweep, run_word_meaning_sweep
from lir_tpu.models.loader import config_from_hf, convert_decoder
from lir_tpu.utils.manifest import SweepManifest


def _engine(batch_size=4, max_new=8):
    import transformers as tf
    torch.manual_seed(0)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=FakeTokenizer.VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=512, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(batch_size=batch_size,
                                       max_new_tokens=max_new,
                                       max_seq_len=256))


PROMPTS = (
    LegalPrompt(
        main="Does a vehicle include a bicycle ?",
        response_format="Answer Covered or Not .",
        target_tokens=("Covered", "Not"),
        confidence_format="Give a number from 0 to 100 .",
    ),
    LegalPrompt(
        main="Is a drone an aircraft ?",
        response_format="Answer Yes or No .",
        target_tokens=("Yes", "No"),
        confidence_format="Give a number from 0 to 100 .",
    ),
)
PERTURBATIONS = (
    ["Would a bicycle count as a vehicle ?", "Can a bicycle be a vehicle ?"],
    ["Would a drone count as an aircraft ?"],
)


def test_grid_expansion_and_subset():
    cells = grid_mod.build_grid("m", PROMPTS, PERTURBATIONS)
    # original + rephrasings per prompt: (1+2) + (1+1) = 5
    assert len(cells) == 5
    assert cells[0].rephrase_idx == 0
    assert cells[0].rephrased_main == PROMPTS[0].main
    sub = grid_mod.random_subset(cells, 3, seed=42)
    assert len(sub) == 3
    assert grid_mod.random_subset(cells, 3, seed=42) == sub  # deterministic


def test_perturbation_sweep_writes_d6_and_resumes(tmp_path):
    eng = _engine()
    out = tmp_path / "results.xlsx"
    rows = run_perturbation_sweep(eng, "tiny-llama", PROMPTS, PERTURBATIONS,
                                  out, checkpoint_every=2)
    assert len(rows) == 5
    from lir_tpu.data.schemas import read_results_frame
    df = read_results_frame(out)
    assert len(df) == 5
    from lir_tpu.data.schemas import PERTURBATION_COLUMNS
    assert list(df.columns) == list(PERTURBATION_COLUMNS)
    assert df["Token_1_Prob"].between(0, 1).all()
    assert df["Weighted Confidence"].between(0, 100).all()
    # Log Probabilities column holds a parseable top-20 map.
    import json
    lp = json.loads(df["Log Probabilities"].iloc[0])
    assert len(lp) == 20

    # Resume: everything already done -> no new rows, file unchanged.
    rows2 = run_perturbation_sweep(eng, "tiny-llama", PROMPTS, PERTURBATIONS,
                                   out, checkpoint_every=2)
    assert rows2 == []
    assert len(read_results_frame(out)) == 5

    # A new model re-runs the full grid (key includes model).
    rows3 = run_perturbation_sweep(eng, "tiny-llama-2", PROMPTS, PERTURBATIONS,
                                   out, checkpoint_every=2)
    assert len(rows3) == 5
    assert len(read_results_frame(out)) == 10


def test_word_meaning_sweep_rows():
    eng = _engine(batch_size=8)
    questions = list(WORD_MEANING_QUESTIONS[:6])
    rows = run_word_meaning_sweep(eng, "tiny-llama", "instruct", questions,
                                  format_instruct_prompt)
    assert len(rows) == 6
    for q, r in zip(questions, rows):
        assert r.prompt == q
        assert r.model == "tiny-llama"
        assert 0 <= r.yes_prob <= 1 and 0 <= r.no_prob <= 1
