"""Sweep-driver tests: D1/D2/D6 row production, manifest resume, checkpoints.

Capability parity under test (SURVEY.md §2.1 C4/C5/C9/C11): grid expansion,
done-set dedup, checkpoint-every-N, append-with-schema-check — all with the
fake backend so no weights or network are needed.
"""

from pathlib import Path

import numpy as np
import pandas as pd
import pytest
import torch

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.data.prompts import LegalPrompt, WORD_MEANING_QUESTIONS, format_instruct_prompt
from lir_tpu.engine import grid as grid_mod
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_perturbation_sweep, run_word_meaning_sweep
from lir_tpu.models.loader import config_from_hf, convert_decoder
from lir_tpu.utils.manifest import SweepManifest


def _engine(batch_size=4, max_new=8):
    import transformers as tf
    torch.manual_seed(0)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=FakeTokenizer.VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=512, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(batch_size=batch_size,
                                       max_new_tokens=max_new,
                                       max_seq_len=256))


PROMPTS = (
    LegalPrompt(
        main="Does a vehicle include a bicycle ?",
        response_format="Answer Covered or Not .",
        target_tokens=("Covered", "Not"),
        confidence_format="Give a number from 0 to 100 .",
    ),
    LegalPrompt(
        main="Is a drone an aircraft ?",
        response_format="Answer Yes or No .",
        target_tokens=("Yes", "No"),
        confidence_format="Give a number from 0 to 100 .",
    ),
)
PERTURBATIONS = (
    ["Would a bicycle count as a vehicle ?", "Can a bicycle be a vehicle ?"],
    ["Would a drone count as an aircraft ?"],
)


def test_grid_expansion_and_subset():
    cells = grid_mod.build_grid("m", PROMPTS, PERTURBATIONS)
    # original + rephrasings per prompt: (1+2) + (1+1) = 5
    assert len(cells) == 5
    assert cells[0].rephrase_idx == 0
    assert cells[0].rephrased_main == PROMPTS[0].main
    sub = grid_mod.random_subset(cells, 3, seed=42)
    assert len(sub) == 3
    assert grid_mod.random_subset(cells, 3, seed=42) == sub  # deterministic


@pytest.mark.slow
def test_perturbation_sweep_writes_d6_and_resumes(tmp_path):
    eng = _engine()
    out = tmp_path / "results.xlsx"
    rows = run_perturbation_sweep(eng, "tiny-llama", PROMPTS, PERTURBATIONS,
                                  out, checkpoint_every=2)
    assert len(rows) == 5
    from lir_tpu.data.schemas import read_results_frame
    df = read_results_frame(out)
    assert len(df) == 5
    from lir_tpu.data.schemas import PERTURBATION_COLUMNS
    assert list(df.columns) == list(PERTURBATION_COLUMNS)
    assert df["Token_1_Prob"].between(0, 1).all()
    assert df["Weighted Confidence"].between(0, 100).all()
    # Log Probabilities column holds a parseable top-20 map.
    import json
    lp = json.loads(df["Log Probabilities"].iloc[0])
    assert len(lp) == 20

    # Resume: everything already done -> no new rows, file unchanged.
    rows2 = run_perturbation_sweep(eng, "tiny-llama", PROMPTS, PERTURBATIONS,
                                   out, checkpoint_every=2)
    assert rows2 == []
    assert len(read_results_frame(out)) == 5

    # A new model re-runs the full grid (key includes model).
    rows3 = run_perturbation_sweep(eng, "tiny-llama-2", PROMPTS, PERTURBATIONS,
                                   out, checkpoint_every=2)
    assert len(rows3) == 5
    assert len(read_results_frame(out)) == 10


@pytest.mark.slow
def test_word_meaning_sweep_rows():
    eng = _engine(batch_size=8)
    questions = list(WORD_MEANING_QUESTIONS[:6])
    rows = run_word_meaning_sweep(eng, "tiny-llama", "instruct", questions,
                                  format_instruct_prompt)
    assert len(rows) == 6
    for q, r in zip(questions, rows):
        assert r.prompt == q
        assert r.model == "tiny-llama"
        assert 0 <= r.yes_prob <= 1 and 0 <= r.no_prob <= 1


@pytest.mark.slow
def test_reasoning_count_averaging_matches_api_decoder():
    """VERDICT r1 #7: the local n-run averaging must binarize with the same
    if/elif order as the API decoder (perturb_prompts.py:423-426) — a text
    containing BOTH targets ("Not Covered" contains "Covered") counts toward
    token 1 only."""
    from lir_tpu.backends import api
    from lir_tpu.engine.grid import GridCell

    runs = ["Not Covered", "Covered", "Covered", "no idea", "Not"]
    targets = ("Covered", "Not")

    # API side: feed the same run texts through _finalize_reasoning.
    cell = GridCell(prompt_idx=0, rephrase_idx=0, model="m",
                    original_main="o", rephrased_main="r",
                    response_format="f", confidence_format="c",
                    target_tokens=targets)
    score = api.ApiScore(custom_id="p0_r0")
    score.run_responses = list(runs)
    scores = {"p0_r0": score}
    api._finalize_reasoning(scores, {"p0_r0_binary_run0": cell})

    # Local side: scripted sampler returning one run text per call.
    engine = _engine(batch_size=2, max_new=4)
    it = iter(runs)

    def scripted(toks, mask, key, temperature, max_new_tokens):
        return [next(it)] * int(toks.shape[0])

    engine._sample_from_ids = scripted
    res = engine.score_prompts_sampled(
        ["b"], [targets], n_runs=len(runs))[0]

    assert res.token_1_prob == score.token_1_prob == 3 / 5
    assert res.token_2_prob == score.token_2_prob == 1 / 5
    assert res.odds_ratio == score.token_1_prob / score.token_2_prob
    assert res.response == "Covered"  # most common (2x exact)


@pytest.mark.slow
def test_reasoning_sweep_writes_count_fraction_rows(tmp_path):
    """End-to-end reasoning mode on the tiny model: D6 rows carry count
    fractions (multiples of 1/n_runs) and Weighted Confidence equals the
    parsed integer (perturb_prompts.py:459-464)."""
    engine = _engine(batch_size=4, max_new=4)
    out = tmp_path / "results.csv"
    rows = run_perturbation_sweep(
        engine, "tiny-reasoner", PROMPTS, PERTURBATIONS, out,
        reasoning=True, reasoning_runs=4)
    # grid = original + rephrasings per prompt: (1+2) + (1+1) = 5 cells
    assert len(rows) == 5
    for r in rows:
        for p in (r.token_1_prob, r.token_2_prob):
            assert abs(p * 4 - round(p * 4)) < 1e-9
        assert r.log_probabilities == ""
        if r.confidence_value is None:
            assert r.weighted_confidence is None
        else:
            assert r.weighted_confidence == float(r.confidence_value)
    df = pd.read_csv(out)
    assert len(df) == 5


@pytest.mark.slow
def test_reasoning_resume_is_cell_deterministic(tmp_path):
    """PRNG streams are keyed by grid-cell identity, so a resumed sweep
    (different todo/batch composition) samples exactly what the
    uninterrupted run sampled for every remaining cell."""
    engine = _engine(batch_size=4, max_new=4)
    full_rows = run_perturbation_sweep(
        engine, "m", PROMPTS, PERTURBATIONS, tmp_path / "full.csv",
        reasoning=True, reasoning_runs=3)
    by_cell = {(r.original_main, r.rephrased_main): r for r in full_rows}

    # Pre-mark the first three cells done; the "resumed" run scores only the
    # remaining two, in a smaller tail bucket.
    manifest = SweepManifest(tmp_path / "resumed.manifest.jsonl",
                             grid_mod.RESUME_KEY_FIELDS)
    manifest.mark_done_many([
        {"model": "m", "original_main": r.original_main,
         "rephrased_main": r.rephrased_main} for r in full_rows[:3]])
    resumed = run_perturbation_sweep(
        engine, "m", PROMPTS, PERTURBATIONS, tmp_path / "resumed.csv",
        manifest=manifest, reasoning=True, reasoning_runs=3)
    assert len(resumed) == 2
    for r in resumed:
        ref = by_cell[(r.original_main, r.rephrased_main)]
        assert r.token_1_prob == ref.token_1_prob
        assert r.token_2_prob == ref.token_2_prob
        assert r.model_response == ref.model_response
        assert r.model_confidence_response == ref.model_confidence_response


def test_parse_confidence_truncation_guard():
    """A budget-limited decode that never reached EOS must not trust an
    integer whose digits touch the end of the text (possibly cut mid-number:
    '...about 85' truncated to '...about 8')."""
    from lir_tpu.engine.sweep import _parse_confidence

    assert _parse_confidence("I am about 85% sure", complete=False) == 85
    assert _parse_confidence("confidence: 85", complete=True) == 85
    assert _parse_confidence("confidence: 8", complete=False) is None
    assert _parse_confidence("confidence: 85 .", complete=False) == 85
    assert _parse_confidence("no number here", complete=False) is None


@pytest.mark.slow
def test_perturbation_sweep_multihost_shards(tmp_path, monkeypatch):
    """Under a (simulated) 2-process pod, each host sweeps HALF the grid
    into its own .hostN results + manifest (disjoint writes), and the two
    shards partition the cells exactly."""
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.parallel import multihost

    cfg = ModelConfig(name="mh", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4,
                      intermediate_size=64, max_seq_len=128)
    eng = ScoringEngine(decoder.init_params(cfg, jax.random.PRNGKey(0)),
                        cfg, FakeTokenizer(),
                        RuntimeConfig(batch_size=4, max_new_tokens=4))
    lp = (LegalPrompt(main="Is a levee failure a flood ?",
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number 0 to 100 ."),)
    perts = ([f"variant {i} of the levee question ?" for i in range(5)],)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # A real barrier would block: this simulation has one actual process.
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    monkeypatch.setattr(multihost, "liveness_barrier",
                        lambda name, **kw: None)
    seen = []
    for proc in (0, 1):
        monkeypatch.setattr(jax, "process_index", lambda p=proc: p)
        assert multihost.is_multiprocess()
        rows = run_perturbation_sweep(
            eng, "mh-model", lp, perts, tmp_path / "results.xlsx",
            checkpoint_every=3)
        out = tmp_path / f"results.host{proc}.csv"
        assert out.exists(), list(tmp_path.iterdir())
        assert (tmp_path / f"results.host{proc}.manifest.jsonl").exists()
        seen.extend((r.original_main, r.rephrased_main) for r in rows)
    # 6 cells total (original + 5 rephrasings), split 3/3, no overlap.
    assert len(seen) == 6 and len(set(seen)) == 6


def test_multihost_required_single_process_runtime_error_attribution(
        monkeypatch):
    """A launcher that pre-initialized jax.distributed with a SINGLE-process
    topology must get an error naming that state — not a misattributed
    'bring-up failed' (ADVICE r3 #3)."""
    import jax
    import pytest

    from lir_tpu.parallel import multihost

    def boom(*a, **k):
        raise RuntimeError("distributed runtime already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    # raising=False: older jax has no is_initialized at all (multihost
    # probes it defensively), so the patch must not require the attribute.
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    with pytest.raises(RuntimeError, match="SINGLE-process topology"):
        multihost.initialize(required=True)
    # With no runtime at all, the plain bring-up-failed error stands.
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)
    with pytest.raises(RuntimeError, match="bring-up failed"):
        multihost.initialize(required=True)
    # initialize() "succeeding" but finding no peers is the same hazard.
    monkeypatch.setattr(jax.distributed, "initialize", lambda *a, **k: None)
    with pytest.raises(RuntimeError, match="no peers were found"):
        multihost.initialize(required=True)


@pytest.mark.slow
def test_multihost_shard_concat_and_merged_resume(tmp_path, monkeypatch):
    """The gather step: after both hosts sweep their shards, host 0 merges
    the .hostN workbooks + manifests into the FINAL artifact
    (perturb_prompts.py:161-188,975-984 semantics), and a later
    single-process resume against the merged manifest scores nothing."""
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine import grid as grid_mod
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.parallel import multihost
    from lir_tpu.utils.manifest import SweepManifest

    cfg = ModelConfig(name="mhc", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4,
                      intermediate_size=64, max_seq_len=128)
    eng = ScoringEngine(decoder.init_params(cfg, jax.random.PRNGKey(0)),
                        cfg, FakeTokenizer(),
                        RuntimeConfig(batch_size=4, max_new_tokens=4))
    lp = (LegalPrompt(main="Is a levee failure a flood ?",
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number 0 to 100 ."),)
    perts = ([f"variant {i} of the levee question ?" for i in range(5)],)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    monkeypatch.setattr(multihost, "liveness_barrier",
                        lambda name, **kw: None)
    # Host 1 first, then host 0 (whose tail runs the merge).
    for proc in (1, 0):
        monkeypatch.setattr(jax, "process_index", lambda p=proc: p)
        run_perturbation_sweep(eng, "mhc-model", lp, perts,
                               tmp_path / "results.xlsx", checkpoint_every=3)

    final = schemas.resolve_results_path(tmp_path / "results.xlsx")
    assert final.exists()
    df = schemas.read_results_frame(final)
    assert len(df) == 6
    assert list(df.columns) == list(schemas.PERTURBATION_COLUMNS)
    assert len(set(df["Rephrased Main Part"])) == 6
    # Per-host shards/manifests survive (per-host resume keeps working).
    assert (tmp_path / "results.host0.csv").exists()
    assert (tmp_path / "results.host1.manifest.jsonl").exists()
    # Merged manifest covers ALL cells: a single-process resume runs dry.
    merged_manifest = SweepManifest(final.with_suffix(".manifest.jsonl"),
                                    grid_mod.RESUME_KEY_FIELDS)
    cells = grid_mod.build_grid("mhc-model", lp, perts)
    assert grid_mod.pending_cells(cells, merged_manifest) == []


@pytest.mark.slow
def test_multihost_empty_host_still_merges(tmp_path, monkeypatch):
    """A pod larger than the grid: hosts with zero assigned cells write a
    header-only shard, so host 0's merge still produces the final artifact
    instead of mistaking the empty host for a missing filesystem."""
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.parallel import multihost

    cfg = ModelConfig(name="mhe", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4,
                      intermediate_size=64, max_seq_len=128)
    eng = ScoringEngine(decoder.init_params(cfg, jax.random.PRNGKey(0)),
                        cfg, FakeTokenizer(),
                        RuntimeConfig(batch_size=4, max_new_tokens=4))
    lp = (LegalPrompt(main="Is a levee failure a flood ?",
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number 0 to 100 ."),)
    # 2 cells total on a 3-host pod: host 2 gets nothing.
    perts = (["variant zero of the levee question ?"],)

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    monkeypatch.setattr(multihost, "liveness_barrier",
                        lambda name, **kw: None)
    for proc in (2, 1, 0):
        monkeypatch.setattr(jax, "process_index", lambda p=proc: p)
        run_perturbation_sweep(eng, "mhe-model", lp, perts,
                               tmp_path / "results.xlsx", checkpoint_every=3)

    assert (tmp_path / "results.host2.csv").exists()   # header-only shard
    final = schemas.resolve_results_path(tmp_path / "results.xlsx")
    df = schemas.read_results_frame(final)
    assert len(df) == 2
    assert list(df.columns) == list(schemas.PERTURBATION_COLUMNS)


def test_cli_concat_shards(tmp_path, capsys):
    """`lir_tpu concat-shards` merges .hostN shards from the command line
    (the manual gather for pods without a shared filesystem)."""
    from lir_tpu import cli
    from lir_tpu.data import schemas
    from lir_tpu.data.schemas import PerturbationRow

    def rows(tag):
        return [PerturbationRow(
            model="m", original_main="q", response_format="rf",
            confidence_format="cf", rephrased_main=f"{tag}-{i}",
            full_rephrased_prompt="p", full_confidence_prompt="c",
            model_response="Yes", model_confidence_response="85",
            log_probabilities="{}", token_1_prob=0.6, token_2_prob=0.3,
            confidence_value=85, weighted_confidence=80.0) for i in range(2)]

    for h in (0, 1):
        schemas.write_perturbation_results(
            rows(f"h{h}"), tmp_path / f"results.host{h}.csv")
        (tmp_path / f"results.host{h}.manifest.jsonl").write_text(
            "\n".join('{"model": "m", "original_main": "q", '
                      f'"rephrased_main": "h{h}-{i}"}}' for i in range(2))
            + "\n")
    cli.main(["concat-shards", "--results", str(tmp_path / "results.csv"),
              "--hosts", "2"])
    assert "merged 4 rows" in capsys.readouterr().out
    df = schemas.read_results_frame(tmp_path / "results.csv")
    assert len(df) == 4

    with pytest.raises(SystemExit, match="no mergeable shards"):
        cli.main(["concat-shards", "--results",
                  str(tmp_path / "missing.csv"), "--hosts", "2"])


def test_cli_concat_shards_xlsx_request_finds_csv_shards(tmp_path, capsys):
    """Pod hosts without openpyxl write .csv shards; an operator following
    DEPLOY.md with --results results.xlsx must still find them, and a
    merge without shard manifests warns instead of claiming one."""
    from lir_tpu import cli
    from lir_tpu.data import schemas
    from lir_tpu.data.schemas import PerturbationRow

    row = PerturbationRow(
        model="m", original_main="q", response_format="rf",
        confidence_format="cf", rephrased_main="r",
        full_rephrased_prompt="p", full_confidence_prompt="c",
        model_response="Yes", model_confidence_response="85",
        log_probabilities="{}", token_1_prob=0.6, token_2_prob=0.3,
        confidence_value=85, weighted_confidence=80.0)
    for h in (0, 1):
        schemas.write_perturbation_results(
            [row], tmp_path / f"results.host{h}.csv")
    cli.main(["concat-shards", "--results", str(tmp_path / "results.xlsx"),
              "--hosts", "2"])
    out = capsys.readouterr().out
    assert "merged 2 rows" in out
    assert "WARNING: no shard manifests" in out


@pytest.mark.slow
def test_pipelined_writer_failure_preserves_resume(tmp_path, monkeypatch):
    """A flush failure inside the writer thread must re-raise on the
    caller's thread, and the write-ahead guarantee must hold: only rows
    from SUCCESSFUL flushes are marked done, so a resumed sweep re-scores
    exactly the unflushed cells and the final artifact is complete."""
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.engine import sweep as sweep_mod
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="wf", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4,
                      intermediate_size=64, max_seq_len=128)
    eng = ScoringEngine(decoder.init_params(cfg, jax.random.PRNGKey(0)),
                        cfg, FakeTokenizer(),
                        RuntimeConfig(batch_size=2, max_new_tokens=4))
    lp = (LegalPrompt(main="Is a levee failure a flood ?",
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Number 0 to 100 ."),)
    perts = ([f"variant {i} ?" for i in range(5)],)  # 6 cells, batches of 2

    real_write = schemas.write_perturbation_results
    calls = {"n": 0}

    def failing_write(rows, path, append=True):
        calls["n"] += 1
        if calls["n"] == 2:          # second flush dies (disk full, etc.)
            raise OSError("disk full")
        return real_write(rows, path, append=append)

    monkeypatch.setattr(sweep_mod.schemas, "write_perturbation_results",
                        failing_write)
    out = tmp_path / "results.csv"
    with pytest.raises(OSError, match="disk full"):
        run_perturbation_sweep(eng, "wf-model", lp, perts, out,
                               checkpoint_every=2)
    # First flush landed; its rows (and ONLY its rows) are marked done.
    manifest_lines = [
        l for l in (out.with_suffix(".manifest.jsonl")
                    .read_text().splitlines()) if l]
    assert len(manifest_lines) == 2
    assert len(schemas.read_results_frame(out)) == 2

    monkeypatch.setattr(sweep_mod.schemas, "write_perturbation_results",
                        real_write)
    resumed = run_perturbation_sweep(eng, "wf-model", lp, perts, out,
                                     checkpoint_every=2)
    assert len(resumed) == 4         # exactly the unflushed cells
    df = schemas.read_results_frame(out)
    assert len(df) == 6
    assert len(set(df["Rephrased Main Part"])) == 6
