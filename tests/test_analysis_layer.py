"""Tests for the analysis drivers (C20-C30): synthetic-D6 perturbation
analysis, base-vs-instruct deltas vs direct pandas recomputation, kappa
combiner, and the model-graph suite on the committed D2 CSV."""

import json

import numpy as np
import pandas as pd
import pytest

from lir_tpu.analysis import (
    add_relative_prob,
    analyze_model,
    assert_compliance,
    check_confidence_compliance,
    check_output_compliance,
    expected_compliance_tokens,
    family_differences,
    parse_logprob_content,
    perturbation_kappa,
    prepare_model_data,
    prepare_perturbation_data,
    run_kappa_analysis,
    run_model_graph_analysis,
)
from lir_tpu.data.prompts import LEGAL_PROMPTS

import jax

KEY = jax.random.PRNGKey(0)


def synthetic_perturbation_frame(n_per_prompt=120, seed=7) -> pd.DataFrame:
    """A D6-schema frame with known properties: mostly-compliant logprob
    strings, a few non-compliant rows, mixed confidence formats."""
    rng = np.random.default_rng(seed)
    rows = []
    for prompt in LEGAL_PROMPTS:
        t1, t2 = prompt.target_tokens
        for i in range(n_per_prompt):
            p1 = float(np.clip(rng.beta(4, 2), 0.001, 0.999))
            p2 = 1 - p1
            if i % 10 == 0:  # non-compliant first token
                content = [{"token": "I"}, {"token": " think"}]
            elif p1 > 0.5:
                # Compliant: the full expected phrase tokens.
                phrase = prompt.response_format.split("'")[1]
                content = [{"token": phrase.split(" ")[0]}]
                for w in phrase.split(" ")[1:]:
                    content.append({"token": f" {w}"})
            else:
                phrase = prompt.response_format.split("'")[3]
                content = [{"token": phrase.split(" ")[0]}]
                for w in phrase.split(" ")[1:]:
                    content.append({"token": f" {w}"})
            conf_choices = ["85", "42", "100", "3.5", "high", "150"]
            conf = conf_choices[i % len(conf_choices)]
            rows.append(
                {
                    "Model": "synthetic-model",
                    "Original Main Part": prompt.main,
                    "Response Format": prompt.response_format,
                    "Confidence Format": prompt.confidence_format,
                    "Rephrased Main Part": f"{prompt.main[:30]}... v{i}",
                    "Full Rephrased Prompt": f"variant {i}: {prompt.binary_prompt[:60]}",
                    "Full Confidence Prompt": f"variant {i}: {prompt.confidence_prompt[:60]}",
                    "Model Response": content[0]["token"],
                    "Model Confidence Response": conf,
                    "Log Probabilities": json.dumps({"content": content}),
                    "Token_1_Prob": p1,
                    "Token_2_Prob": p2,
                    "Odds_Ratio": p1 / p2,
                    "Confidence Value": None,
                    "Weighted Confidence": float(rng.uniform(0, 100)),
                }
            )
    return pd.DataFrame(rows)


@pytest.fixture(scope="module")
def synthetic_df():
    return synthetic_perturbation_frame()


@pytest.fixture(scope="module")
def instruct_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")


@pytest.fixture(scope="module")
def base_df(reference_data_dir):
    return pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")


@pytest.mark.slow
class TestPerturbationAnalysis:
    def test_relative_prob(self, synthetic_df):
        df = add_relative_prob(synthetic_df)
        expected = synthetic_df["Token_1_Prob"] / (
            synthetic_df["Token_1_Prob"] + synthetic_df["Token_2_Prob"]
        )
        np.testing.assert_allclose(df["Relative_Prob"], expected)

    def test_compliance_counts_local_format_rows(self):
        """A D6 produced by the LOCAL sweep stores 'Log Probabilities' as a
        {token_id: logprob} map — the reference's content parser skips such
        rows, which used to leave the compliance report at 0/0. Local rows
        must classify from 'Model Response' text; reference-style rows
        (content format, or word-keyed maps) keep the executed reference's
        semantics exactly (test_reference_differential pins those)."""
        import json

        from lir_tpu.data.prompts import LEGAL_PROMPTS
        from lir_tpu.analysis.perturbation import (
            add_relative_prob, check_output_compliance)

        main = LEGAL_PROMPTS[0].main
        local_map = json.dumps({"17": -0.5, "348": -1.2})
        word_map = json.dumps({"Covered": -0.5, "Not": -1.5})
        content = json.dumps(
            {"content": [{"token": "Covered", "logprob": -0.1}]})
        rows = [
            # local rows: classified via Model Response
            {"Log Probabilities": local_map, "Model Response": "Covered"},
            {"Log Probabilities": local_map,
             "Model Response": "Not Covered"},
            {"Log Probabilities": local_map, "Model Response": "maybe so"},
            # reference content row: parsed as before
            {"Log Probabilities": content, "Model Response": "ignored"},
            # reference-style word-keyed map: SKIPPED (reference parity)
            {"Log Probabilities": word_map, "Model Response": "Covered"},
        ]
        df = pd.DataFrame([
            dict(r, **{"Original Main Part": main, "Token_1_Prob": 0.6,
                       "Token_2_Prob": 0.3}) for r in rows])
        out = check_output_compliance(add_relative_prob(df), LEGAL_PROMPTS)
        row = out.iloc[0]
        assert int(row["Total_Samples"]) == 5
        assert int(row["First_Token_Compliant"]) == 3   # 2 local + content
        assert int(row["First_Token_Non_Compliant"]) == 1  # "maybe so"
        # 'Not Covered' and 'Covered' full responses are subsequent-ok;
        # content row "Covered" also ok.
        assert int(row["Conditional_Subsequent_Compliant"]) == 3

    def test_relative_prob_zero_mass_is_nan(self):
        df = pd.DataFrame({"Token_1_Prob": [0.0], "Token_2_Prob": [0.0]})
        assert np.isnan(add_relative_prob(df)["Relative_Prob"].iloc[0])

    def test_kappa_matches_direct_pair_loop(self, synthetic_df):
        df = add_relative_prob(synthetic_df)
        kappa, observed, expected = perturbation_kappa(df)

        # Direct O(n^2) reimplementation of the reference's loops.
        finite = df[np.isfinite(df["Relative_Prob"])]
        dec = (finite["Relative_Prob"] > 0.5).astype(int)
        agree = pairs = 0
        for _, group in finite.assign(d=dec).groupby("Original Main Part"):
            vals = group["d"].to_numpy()
            for i in range(len(vals)):
                for j in range(i + 1, len(vals)):
                    pairs += 1
                    agree += int(vals[i] == vals[j])
        obs_direct = agree / pairs
        p1 = dec.mean()
        exp_direct = p1 * p1 + (1 - p1) * (1 - p1)
        assert observed == pytest.approx(obs_direct)
        assert expected == pytest.approx(exp_direct)
        assert kappa == pytest.approx(
            (obs_direct - exp_direct) / (1 - exp_direct)
        )

    def test_logprob_parsing(self):
        raw = json.dumps(
            {"content": [{"token": "Not"}, {"token": " Covered"}]}
        )
        first, full = parse_logprob_content(raw)
        assert first == "Not"
        assert full == "Not Covered"
        # ast fallback for single-quoted dicts.
        first2, full2 = parse_logprob_content(
            "{'content': [{'token': 'Covered'}]}"
        )
        assert first2 == "Covered"
        assert parse_logprob_content("not a dict at all") is None

    def test_expected_tokens_cover_reference_table(self):
        # Prompt 1/5: Covered / Not Covered variants.
        exp = expected_compliance_tokens(LEGAL_PROMPTS[0], 0)
        assert exp["first_tokens"] == ["Covered", "Not"]
        assert "Not Covered" in exp["full_responses"]["Not"]
        assert "Not covered" in exp["full_responses"]["Not"]
        # Prompt 4 extras (reference :1236-1237).
        exp4 = expected_compliance_tokens(LEGAL_PROMPTS[3], 3)
        assert "Monthly Installment Payment" in exp4["full_responses"]["Monthly"]
        assert "Payment Upon" in exp4["full_responses"]["Payment"]

    def test_output_compliance_counts(self, synthetic_df):
        df = add_relative_prob(synthetic_df)
        comp = check_output_compliance(df, LEGAL_PROMPTS)
        assert len(comp) == 5
        # 1 in 10 rows is intentionally non-compliant.
        for _, row in comp.iterrows():
            assert row["First_Token_Non_Compliant"] == row["Total_Samples"] // 10
            # All compliant first tokens carry the full phrase.
            assert row["Conditional_Subsequent_Compliance_Rate"] == pytest.approx(100.0)
        assert_compliance(comp)  # well above the 50% gate

    def test_confidence_compliance_categories(self, synthetic_df):
        conf = check_confidence_compliance(synthetic_df, LEGAL_PROMPTS)
        assert len(conf) == 5
        row = conf.iloc[0]
        n = row["Total_Confidence_Samples"]
        # Choices cycle through 3 valid ints, one float, one text, one
        # out-of-range value.
        assert row["Confidence_Compliant"] == n // 2
        assert row["Float_Errors"] == n // 6
        assert row["Text_Errors"] == n // 6
        assert row["Out_Of_Range_Errors"] == n // 6

    def test_analyze_model_artifacts(self, synthetic_df, tmp_path):
        res = analyze_model(
            synthetic_df, "synthetic-model", tmp_path,
            n_simulations=2000, make_figures=True,
        )
        assert res["status"] == "ok"
        for name in (
            "summary_statistics.csv",
            "normality_test_results.csv",
            "truncated_normal_test_results.csv",
            "cohens_kappa_results.csv",
            "output_compliance_results.csv",
            "confidence_compliance_results.csv",
            "prompt_perturbation_tables.tex",
            "prompt_perturbation_standalone.tex",
            "compliance_summary.tex",
            "confidence_compliance_summary.tex",
            "combined_prompts_visualization.png",
            "combined_confidence_visualization.png",
        ):
            assert (tmp_path / name).exists(), name
        # Figures per prompt.
        for i in range(1, 6):
            assert (tmp_path / "figures" / f"prompt_{i}_distribution.png").exists()
            assert (tmp_path / "figures" / f"prompt_{i}_qq_plot.png").exists()
        summary = pd.read_csv(tmp_path / "summary_statistics.csv")
        assert len(summary) == 5
        assert (summary["95% Interval Width"] > 0).all()
        tex = (tmp_path / "prompt_perturbation_standalone.tex").read_text()
        assert tex.startswith("\\documentclass")
        assert tex.rstrip().endswith("\\end{document}")

    def test_analyze_model_insufficient_data(self, synthetic_df, tmp_path):
        res = analyze_model(
            synthetic_df.head(10), "tiny", tmp_path / "tiny",
            make_figures=False,
        )
        assert res["status"] == "insufficient_data"
        assert (tmp_path / "tiny" / "summary_statistics.csv").exists()


@pytest.mark.slow
class TestBaseVsInstruct:
    def test_family_stats_match_direct(self, base_df):
        res = family_differences(base_df)
        stats = res["statistics"].set_index("Model_Family")
        assert "mistral" not in stats.index

        # Direct recomputation for one family.
        family = stats.index[0]
        fam = base_df[base_df["model_family"] == family]
        base_model = fam.loc[fam["base_or_instruct"] == "base", "model"].iloc[0]
        instr_model = fam.loc[fam["base_or_instruct"] == "instruct", "model"].iloc[0]
        b = base_df[base_df["model"] == base_model].set_index("prompt")
        i = base_df[base_df["model"] == instr_model].set_index("prompt")
        common = b.index.intersection(i.index)
        diffs = []
        for prompt in common:
            yb, nb = b.loc[prompt, "yes_prob"], b.loc[prompt, "no_prob"]
            yi, ni = i.loc[prompt, "yes_prob"], i.loc[prompt, "no_prob"]
            if yb > 0 and nb > 0 and yi > 0 and ni > 0:
                diffs.append(yi / (yi + ni) - yb / (yb + nb))
        assert stats.loc[family, "Num_Samples"] == len(diffs)
        assert stats.loc[family, "Mean"] == pytest.approx(np.mean(diffs))

    def test_artifacts(self, base_df, tmp_path, reference_data_dir):
        from lir_tpu.analysis import run_base_vs_instruct_analysis

        res = run_base_vs_instruct_analysis(
            f"{reference_data_dir}/model_comparison_results.csv",
            tmp_path, make_figures=True,
        )
        for name in (
            "model_rel_prob_statistics.csv",
            "prompt_rel_prob_differences.csv",
            "prompt_rel_prob_heatmap_data.csv",
            "rel_prob_differences.png",
            "prompt_rel_prob_differences.png",
            "prompt_rel_prob_heatmap.png",
        ):
            assert (tmp_path / name).exists(), name
        assert len(res["statistics"]) > 0


@pytest.mark.slow
class TestKappaCombined:
    def test_prepare_model_data(self, instruct_df):
        prepared = prepare_model_data(instruct_df)
        assert len(prepared) == 50
        assert ((prepared["agree_percent"] >= 0.5)
                & (prepared["agree_percent"] <= 1.0)).all()
        assert ((prepared["avg_pairwise_kappa"] >= 0)
                & (prepared["avg_pairwise_kappa"] <= 1)).all()

    def test_prepare_perturbation_data(self, synthetic_df):
        prepared = prepare_perturbation_data(synthetic_df, KEY, n_bootstrap=100)
        assert len(prepared) == 5
        assert (prepared["self_kappa"].abs() <= 1.0).all()
        assert (prepared["n_variations"] == 120).all()

    def test_end_to_end(self, instruct_df, synthetic_df, tmp_path, reference_data_dir):
        pert_path = tmp_path / "combined_results.csv"
        synthetic_df.to_csv(pert_path, index=False)
        res = run_kappa_analysis(
            f"{reference_data_dir}/instruct_model_comparison_results.csv",
            pert_path, tmp_path / "out", n_bootstrap=100, make_figures=True,
        )
        out = tmp_path / "out"
        for name in (
            "model_kappa_metrics.csv",
            "perturbation_kappa_metrics.csv",
            "model_legal_kappas.csv",
            "perturbation_legal_kappas.csv",
            "combined_kappa_results.csv",
            "kappa_analysis_table.tex",
        ):
            assert (out / name).exists(), name
        # The synthetic perturbation prompts ARE the 5 legal prompts, and
        # the word-meaning D2 CSV matches some legal keywords ("company" in
        # prompts etc.) — combined results exist whenever both sides match.
        assert isinstance(res["combined"], dict)


@pytest.mark.slow
class TestModelGraph:
    def test_correlation_matrix_matches_pandas(self, instruct_df, tmp_path):
        res = run_model_graph_analysis(
            _write_csv(instruct_df, tmp_path / "d2.csv"),
            tmp_path / "out", n_bootstrap=50, make_figures=False,
        )
        pivot = res["pivot"]
        ours = res["correlations"]["pearson"]["correlation_matrix"]
        theirs = pivot.corr(method="pearson").to_numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-5)
        # Filtering applied.
        assert not any("mistral" in m.lower() for m in pivot.columns)
        assert not any("opt-iml" in m for m in pivot.columns)

    def test_aggregate_kappa_fields(self, instruct_df, tmp_path):
        res = run_model_graph_analysis(
            _write_csv(instruct_df, tmp_path / "d2.csv"),
            tmp_path / "out", n_bootstrap=50, make_figures=False,
        )
        agg = res["aggregate_kappa"]
        assert -1 <= agg["aggregate_kappa"] <= 1
        assert agg["kappa_ci_lower"] <= agg["kappa_ci_upper"]
        assert agg["n_models"] == len(res["pivot"].columns)

    def test_figures_written(self, instruct_df, tmp_path):
        run_model_graph_analysis(
            _write_csv(instruct_df, tmp_path / "d2.csv"),
            tmp_path / "out", n_bootstrap=20, make_figures=True,
        )
        figs = tmp_path / "out" / "figures"
        for name in (
            "model_comparison_plot.png",
            "model_pearson_correlation_matrix.png",
            "model_spearman_correlation_matrix.png",
            "model_pearson_correlation_distribution.png",
            "model_kappa_distribution.png",
        ):
            assert (figs / name).exists(), name


def _write_csv(df, path):
    df.to_csv(path, index=False)
    return path
