"""Schema writers + resume manifest + retry policy."""

import math

import pandas as pd
import pytest

from lir_tpu.data import LEGAL_PROMPTS, schemas
from lir_tpu.data.schemas import (
    INSTRUCT_COMPARISON_COLUMNS,
    MODEL_COMPARISON_COLUMNS,
    PERTURBATION_COLUMNS,
    PerturbationRow,
    ScoreRow,
    load_perturbations,
    save_perturbations,
    validate_perturbation_cache,
    write_instruct_comparison_csv,
    write_model_comparison_csv,
    write_perturbation_results,
)
from lir_tpu.utils.manifest import SweepManifest, atomic_write_json
from lir_tpu.utils.retry import retry_with_exponential_backoff
from lir_tpu.config import RetryConfig


def _row(prompt="Is a \"tent\" a \"building\"?", model="org/model-7b-instruct"):
    return ScoreRow(
        prompt=prompt,
        model=model,
        base_or_instruct="instruct",
        model_output="Yes.",
        yes_prob=0.6,
        no_prob=0.2,
    )


def test_score_row_readouts():
    r = _row()
    assert r.odds_ratio == pytest.approx(3.0)
    assert r.relative_prob == pytest.approx(0.75)
    assert r.model_family == "model"
    zero = ScoreRow("p", "m", "base", "", 0.0, 0.0)
    assert math.isnan(zero.relative_prob)
    # reference semantics: odds_ratio is inf whenever no_prob == 0
    assert math.isinf(zero.odds_ratio)


def test_csv_schemas(tmp_path):
    d1 = write_model_comparison_csv([_row()], tmp_path / "d1.csv")
    assert tuple(d1.columns) == MODEL_COMPARISON_COLUMNS
    d2 = write_instruct_comparison_csv([_row()], tmp_path / "d2.csv")
    assert tuple(d2.columns) == INSTRUCT_COMPARISON_COLUMNS
    back = pd.read_csv(tmp_path / "d2.csv")
    assert back.loc[0, "relative_prob"] == pytest.approx(0.75)


def test_reference_csv_schema_parity(reference_data_dir):
    d1 = pd.read_csv(f"{reference_data_dir}/model_comparison_results.csv")
    assert tuple(d1.columns) == MODEL_COMPARISON_COLUMNS
    d2 = pd.read_csv(f"{reference_data_dir}/instruct_model_comparison_results.csv")
    assert tuple(d2.columns) == INSTRUCT_COMPARISON_COLUMNS


def _pert_row(i=0):
    p = LEGAL_PROMPTS[0]
    return PerturbationRow(
        model="local/test",
        original_main=p.main,
        response_format=p.response_format,
        confidence_format=p.confidence_format,
        rephrased_main=f"rephrasing {i}",
        full_rephrased_prompt=f"rephrasing {i} " + p.response_format,
        full_confidence_prompt=f"rephrasing {i} " + p.confidence_format,
        model_response="Covered",
        model_confidence_response="80",
        log_probabilities="{}",
        token_1_prob=0.7,
        token_2_prob=0.1,
        confidence_value=80,
        weighted_confidence=78.5,
    )


def test_perturbation_schema_and_append(tmp_path):
    path = tmp_path / "results.csv"
    df1 = write_perturbation_results([_pert_row(0)], path)
    assert tuple(df1.columns) == PERTURBATION_COLUMNS
    write_perturbation_results([_pert_row(1)], path)
    df2 = pd.read_csv(path)       # accumulated artifact (CSV fast-append)
    assert len(df2) == 2
    assert df2.loc[0, "Odds_Ratio"] == pytest.approx(7.0)


def test_perturbation_cache_roundtrip(tmp_path):
    path = tmp_path / "perturbations.json"
    entries = [
        (
            (p.main, p.response_format, tuple(p.target_tokens), p.confidence_format),
            [f"r{i}" for i in range(3)],
        )
        for p in LEGAL_PROMPTS
    ]
    save_perturbations(path, entries)
    loaded = load_perturbations(path)
    assert loaded == entries
    assert validate_perturbation_cache(loaded, LEGAL_PROMPTS)
    assert not validate_perturbation_cache(loaded[:-1], LEGAL_PROMPTS)


def test_manifest_resume(tmp_path):
    path = tmp_path / "manifest.jsonl"
    m = SweepManifest(path, ("model", "orig", "reph"))
    recs = [{"model": "m", "orig": "o", "reph": f"r{i}"} for i in range(5)]
    for r in recs[:3]:
        m.mark_done(r)
    # duplicate mark is a no-op
    m.mark_done(recs[0])
    assert len(m) == 3
    # a fresh instance reloads the done-set from disk
    m2 = SweepManifest(path, ("model", "orig", "reph"))
    assert len(m2) == 3
    assert [r["reph"] for r in m2.pending(recs)] == ["r3", "r4"]


def test_manifest_seed_from_results(tmp_path):
    csv = tmp_path / "prior.csv"
    pd.DataFrame(
        {"Model": ["m1"], "Original Main Part": ["o"], "Rephrased Main Part": ["r"]}
    ).to_csv(csv, index=False)
    m = SweepManifest.from_existing_results(
        tmp_path / "man.jsonl", csv,
        ("Model", "Original Main Part", "Rephrased Main Part"),
    )
    assert m.is_done({"Model": "m1", "Original Main Part": "o", "Rephrased Main Part": "r"})


def test_retry_policy():
    calls = []
    waits = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    cfg = RetryConfig(max_retries=5, initial_delay=1.0, max_delay=4.0)
    out = retry_with_exponential_backoff(
        flaky, (ValueError,), cfg, sleep=waits.append, log=lambda s: None
    )
    assert out == "ok"
    assert len(calls) == 3
    assert len(waits) == 2
    assert waits[1] > waits[0] * 0.5  # backoff grows modulo jitter

    def always_fails():
        raise ValueError("nope")

    with pytest.raises(ValueError):
        retry_with_exponential_backoff(
            always_fails, (ValueError,), cfg, sleep=lambda s: None, log=lambda s: None
        )


def test_atomic_write(tmp_path):
    path = tmp_path / "x.json"
    atomic_write_json(path, {"a": 1})
    atomic_write_json(path, {"a": 2})
    import json

    assert json.loads(path.read_text()) == {"a": 2}


def _demo_row():
    from lir_tpu.data.schemas import PerturbationRow

    return PerturbationRow(
        model="m", original_main="o", response_format="rf",
        confidence_format="cf", rephrased_main="r",
        full_rephrased_prompt="frp", full_confidence_prompt="fcp",
        model_response="Covered", model_confidence_response="85",
        log_probabilities="{}", token_1_prob=0.8, token_2_prob=0.2,
        confidence_value=85, weighted_confidence=84.2)


def test_append_schema_mismatch_backs_up(tmp_path):
    """Column drift between runs: the old artifact is backed up, never
    silently merged (perturb_prompts.py:994-1006)."""
    import pandas as pd

    from lir_tpu.data import schemas

    path = tmp_path / "results.csv"
    pd.DataFrame({"wrong": [1], "columns": [2]}).to_csv(path, index=False)
    schemas.write_perturbation_results([_demo_row()], path, append=True)

    backup = tmp_path / "results_backup.csv"
    assert backup.exists()
    assert list(pd.read_csv(backup).columns) == ["wrong", "columns"]
    fresh = pd.read_csv(path)
    assert list(fresh.columns) == list(schemas.PERTURBATION_COLUMNS)
    assert len(fresh) == 1


def test_append_corrupt_file_writes_sidecar(tmp_path):
    """A truncated/corrupt prior artifact is left in place; new rows land in
    a _new sidecar, and later flushes append to it (perturb_prompts.py:
    1007-1011 semantics)."""
    import pandas as pd

    from lir_tpu.data import schemas

    path = tmp_path / "results.csv"
    path.write_bytes(b"\x00\x01 not a csv \xff")
    schemas.write_perturbation_results([_demo_row()], path, append=True)
    sidecar = tmp_path / "results_new.csv"
    assert sidecar.exists()
    assert path.read_bytes().startswith(b"\x00\x01")  # original untouched
    assert len(pd.read_csv(sidecar)) == 1

    schemas.write_perturbation_results([_demo_row()], path, append=True)
    assert len(pd.read_csv(sidecar)) == 2  # second flush appended


class TestCsvFastAppend:
    """The CSV checkpoint path appends O(new rows) per flush (no
    read-whole-file) while preserving the reference's append semantics
    (perturb_prompts.py:987-1016): schema check, backup-on-mismatch,
    torn-line closure."""

    def _rows(self, tag, n=3):
        return [schemas.PerturbationRow(
            model="m", original_main="q", response_format="rf",
            confidence_format="cf", rephrased_main=f"{tag}-{i}",
            full_rephrased_prompt="p", full_confidence_prompt="c",
            model_response="Yes", model_confidence_response="85",
            log_probabilities='{"1": -0.5}', token_1_prob=0.6,
            token_2_prob=0.3, confidence_value=85,
            weighted_confidence=80.0) for i in range(n)]

    def test_multi_flush_accumulates(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        schemas.write_perturbation_results(self._rows("b"), out)
        schemas.write_perturbation_results(self._rows("c", 2), out)
        df = schemas.read_results_frame(out)
        assert len(df) == 8
        assert list(df.columns) == list(schemas.PERTURBATION_COLUMNS)
        assert df["Rephrased Main Part"].tolist()[:3] == ["a-0", "a-1", "a-2"]
        # Embedded JSON with commas survives the round trip.
        assert df["Log Probabilities"].iloc[0] == '{"1": -0.5}'

    def test_append_does_not_rewrite_existing_bytes(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        first = out.read_bytes()
        schemas.write_perturbation_results(self._rows("b"), out)
        assert out.read_bytes()[:len(first)] == first  # pure append

    def test_torn_last_line_is_truncated(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        with out.open("ab") as f:          # simulate a kill mid-write
            f.write(b"m,q,rf,cf,torn")
        schemas.write_perturbation_results(self._rows("b"), out)
        df = schemas.read_results_frame(out)
        # The torn fragment is TRUNCATED (it was never marked done in the
        # manifest, so resume re-scores it): 3 original + 3 new rows.
        assert len(df) == 6
        assert df["Rephrased Main Part"].tolist()[-3:] == ["b-0", "b-1", "b-2"]

    def test_torn_quoted_field_with_embedded_newline(self, tmp_path):
        """The nasty kill artifact: the file dies INSIDE a quoted field
        whose content contains a newline, so the file's last byte IS a
        newline and the tail parses as an open quote. The known-good
        offset protocol must truncate it anyway; appended rows must not
        be swallowed into the dangling quote."""
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        with out.open("ab") as f:
            f.write(b'm,q,rf,cf,torn,"line one\nline two\n')
        schemas.write_perturbation_results(self._rows("b"), out)
        df = schemas.read_results_frame(out)
        assert len(df) == 6
        assert df["Rephrased Main Part"].tolist() == [
            "a-0", "a-1", "a-2", "b-0", "b-1", "b-2"]

    def test_legacy_file_without_sidecar_validates_once(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        schemas._offset_sidecar(out).unlink()       # pre-sidecar artifact
        schemas.write_perturbation_results(self._rows("b"), out)
        assert schemas._offset_sidecar(out).exists()
        assert len(schemas.read_results_frame(out)) == 6

    def test_merged_artifact_refreshes_offset(self, tmp_path):
        """concat_host_shards rewrites the final file; a later append must
        NOT truncate the merge back to a stale pre-merge offset."""
        schemas.write_perturbation_results(
            self._rows("x"), tmp_path / "r.csv")     # records offset for r.csv
        for h in (0, 1):
            schemas.write_perturbation_results(
                self._rows(f"h{h}"), tmp_path / f"r.host{h}.csv")
        merged = schemas.concat_host_shards(tmp_path / "r.csv", n_hosts=2)
        assert len(merged) == 6
        schemas.write_perturbation_results(self._rows("z"),
                                           tmp_path / "r.csv")
        assert len(schemas.read_results_frame(tmp_path / "r.csv")) == 9

    def test_torn_quoted_field_does_not_swallow_rows(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        with out.open("ab") as f:      # kill mid-QUOTED field (open quote)
            f.write(b'm,q,rf,cf,torn,"partial prompt, with comma and open quo')
        schemas.write_perturbation_results(self._rows("b"), out)
        df = schemas.read_results_frame(out)
        assert len(df) == 6
        assert df["Rephrased Main Part"].tolist() == [
            "a-0", "a-1", "a-2", "b-0", "b-1", "b-2"]

    def test_schema_mismatch_backs_up(self, tmp_path):
        out = tmp_path / "r.csv"
        out.write_text("wrong,cols\n1,2\n")
        schemas.write_perturbation_results(self._rows("a"), out)
        assert (tmp_path / "r_backup.csv").exists()
        df = schemas.read_results_frame(out)
        assert len(df) == 3


class TestLegacyTornArtifacts:
    """Pre-sidecar artifacts (no .offset file) with kill damage: a torn
    plain tail is truncated before certification; a torn quoted tail
    routes to the corrupt-file sidecar path, never backup-and-fresh
    (which would drop manifest-marked rows from the artifact)."""

    def _rows(self, tag, n=3):
        return [schemas.PerturbationRow(
            model="m", original_main="q", response_format="rf",
            confidence_format="cf", rephrased_main=f"{tag}-{i}",
            full_rephrased_prompt="p", full_confidence_prompt="c",
            model_response="Yes", model_confidence_response="85",
            log_probabilities="{}", token_1_prob=0.6, token_2_prob=0.3,
            confidence_value=85, weighted_confidence=80.0) for i in range(n)]

    def test_legacy_torn_plain_tail_truncated(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        schemas._offset_sidecar(out).unlink()
        with out.open("ab") as f:
            f.write(b"m,q,rf,cf,torn-fragment")     # pre-sidecar kill
        schemas.write_perturbation_results(self._rows("b"), out)
        df = schemas.read_results_frame(out)
        assert len(df) == 6
        assert df["Rephrased Main Part"].tolist() == [
            "a-0", "a-1", "a-2", "b-0", "b-1", "b-2"]

    def test_legacy_torn_quoted_tail_goes_to_sidecar(self, tmp_path):
        out = tmp_path / "r.csv"
        schemas.write_perturbation_results(self._rows("a"), out)
        schemas._offset_sidecar(out).unlink()
        with out.open("ab") as f:
            f.write(b'm,q,rf,cf,torn,"open quote never closed\n')
        schemas.write_perturbation_results(self._rows("b"), out)
        # Damaged main file PRESERVED (its 3 good rows are manifest-marked
        # and must not vanish); new rows land in the _new sidecar.
        assert not (tmp_path / "r_backup.csv").exists()
        sidecar = tmp_path / "r_new.csv"
        assert sidecar.exists()
        assert len(pd.read_csv(sidecar)) == 3
