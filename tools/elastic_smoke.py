#!/usr/bin/env python
"""Elastic-serving smoke: the failover-router + shard-lease invariants
the `make elastic-smoke` CI target guards:

- 3 in-process replica servers (config-identical tiny engines) behind
  a ReplicaRouter serve an open-loop request stream; a seeded
  ``replica_kill`` schedule kills replica r1 mid-run — ZERO requests
  dropped (every future resolves ok) and ZERO double-resolved (unique
  request ids, resolve-once futures, zombie payloads dropped);
- the killed replica's router-side breaker walks the survivor path:
  open on the kill -> half_open after the cooldown once the replica
  rejoins -> closed on the probe success;
- a shard lease abandoned by a dead holder is STOLEN by a live holder
  within one TTL of expiry, double-claims are refused while the lease
  is live, and the stolen shard's re-folded rows merge bitwise
  (identical-overlap union) with the dead holder's partial lattice.

Runs hermetically on CPU (FakeTokenizer + tiny random decoders);
prints the router/lease summaries as JSON on success.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BATCH = 4
N_WAVES = 6
PER_WAVE = 4


def _tiny_server(cfg_serve, seed=2):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer

    cfg = ModelConfig(name="elastic-smoke",
                      vocab_size=FakeTokenizer.VOCAB, hidden_size=32,
                      n_layers=1, n_heads=2, intermediate_size=64,
                      max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=BATCH,
                                         max_seq_len=256))
    return ScoringServer(engine, "elastic-smoke", cfg_serve)


def router_smoke(failures):
    from lir_tpu import faults
    from lir_tpu.config import RouterConfig, ServeConfig
    from lir_tpu.serve import ReplicaRouter, ServeRequest

    serve_cfg = ServeConfig(queue_depth=64, classes=(("smoke", 600.0),),
                            default_class="smoke", linger_s=0.0)
    servers = [_tiny_server(serve_cfg).start() for _ in range(3)]
    router = ReplicaRouter(
        [(f"r{i}", s) for i, s in enumerate(servers)],
        config=RouterConfig(replica_failure_threshold=1,
                            replica_cooldown_s=0.3,
                            cache_entries=0)).start()
    # Seeded kill: r1's SECOND dispatch dies (mid-run, with the router
    # loaded) — the router observes the death first, then the dispatch
    # raises, exactly like an abrupt host loss.
    plan = faults.FaultPlan(seed=7, schedules={
        "replica": faults.SiteSchedule.replica_kill_at(1, "r1")})
    faults.wrap_replica(router, "r1", plan)

    def request(i):
        body = f"clause {i} covers wind damage under policy {i * 7}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=f"q{i}")

    results = []
    revived = False
    try:
        for w in range(N_WAVES):
            futs = [router.submit(request(w * PER_WAVE + j))
                    for j in range(PER_WAVE)]
            results += [f.result(timeout=60) for f in futs]
            if plan.injected("replica") and not revived:
                # The kill has fired: the replica is out of placement
                # (alive=False, breaker tripped). Let it rejoin for
                # the recovery half; the breaker's full
                # open -> half_open -> closed walk is asserted from
                # its transition log below.
                if "r1" in router.alive_replicas():
                    failures.append("r1 still alive after the kill")
                router.revive_replica("r1")
                revived = True
                time.sleep(0.35)      # past the cooldown -> half-open
    finally:
        router.stop()

    if not plan.injected("replica"):
        failures.append("scheduled replica_kill never fired")
    # Zero dropped: every request resolved ok. Zero duplicated: ids
    # unique and the router completed exactly len(results).
    bad = [r for r in results if r.status != "ok"]
    if bad:
        failures.append(f"{len(bad)} requests not served ok after the "
                        f"kill: {[r.status for r in bad[:4]]}")
    ids = [r.request_id for r in results]
    if len(set(ids)) != len(ids) or len(ids) != N_WAVES * PER_WAVE:
        failures.append(f"dropped/duplicated requests: {len(ids)} "
                        f"results, {len(set(ids))} unique")
    if router.stats.completed != N_WAVES * PER_WAVE:
        failures.append(f"router completed {router.stats.completed} != "
                        f"{N_WAVES * PER_WAVE}")
    # Survivor-path breaker story: open (kill) -> half_open (cooldown
    # after rejoin) -> closed (probe success).
    transitions = [f"{a}->{b}"
                   for a, b in router.breaker_of("r1").stats.transitions]
    for want in ("closed->open", "open->half_open",
                 "half_open->closed"):
        if want not in transitions:
            failures.append(f"r1 breaker transition {want} missing "
                            f"({transitions})")
    for s in servers:
        s.stop()
    return {"router": router.stats.summary(),
            "r1_breaker_transitions": transitions}


def lease_smoke(failures):
    import tempfile

    import numpy as np

    from lir_tpu.engine import lease as lease_mod
    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.stats import streaming

    ttl = 10.0
    with tempfile.TemporaryDirectory() as td:
        log = Path(td) / "sweep.leases.jsonl"
        now_a, now_b = {"t": 0.0}, {"t": 0.0}
        a = lease_mod.LeaseManager(log, "hostA", ttl_s=ttl,
                                   clock=lambda: now_a["t"])
        b = lease_mod.LeaseManager(log, "hostB", ttl_s=ttl,
                                   clock=lambda: now_b["t"])
        if not a.claim(0):
            failures.append("hostA could not claim an unclaimed shard")
        now_b["t"] = 1.0
        if b.claim(0, steal=True):
            failures.append("live lease was double-claimed")
        # hostA dies (no renewals). Within ONE TTL of expiry, hostB's
        # steal succeeds.
        now_b["t"] = ttl + 1.0
        if not b.claim(0, steal=True):
            failures.append("expired lease was not stolen within one "
                            "TTL")
        if b.stats.steals != 1:
            failures.append(f"steal counter {b.stats.steals} != 1")
        stolen_after = now_b["t"] - ttl     # seconds past expiry
        if stolen_after > ttl:
            failures.append("steal took longer than one TTL")

        # The stolen shard's re-folded rows: hostA folded rows 0-3
        # before dying; hostB re-scores the WHOLE shard (0-5). The
        # identical-overlap union equals an uninterrupted fold.
        import jax.numpy as jnp

        class _Cell:
            def __init__(self, p, r):
                self.prompt_idx, self.rephrase_idx = p, r

        def fold(sink, rng_rows):
            for r in rng_rows:
                yes = np.float32(0.2 + 0.1 * r)
                sink.fold(jnp.asarray([yes]),
                          jnp.asarray([1 - yes], jnp.float32),
                          jnp.asarray([10.0 * r], jnp.float32),
                          jnp.zeros((1, 1), jnp.float32),
                          [_Cell(0, r)], topk=1)

        full = stream_mod.StreamSink(1, 6, seed=1)
        fold(full, range(6))
        sa = stream_mod.StreamSink(1, 6, seed=1)
        fold(sa, range(4))
        sb = stream_mod.StreamSink(1, 6, seed=1)
        fold(sb, range(6))
        merged = streaming.merge_accums(
            [sa.snapshot(), sb.snapshot()],
            allow_identical_overlap=True)
        want = full.snapshot()
        same = (np.array_equal(merged.filled, want.filled)
                and np.array_equal(merged.rel, want.rel, equal_nan=True)
                and np.array_equal(merged.conf, want.conf,
                                   equal_nan=True)
                and np.array_equal(merged.dec, want.dec))
        if not same:
            failures.append("stolen-shard merge is not bitwise equal "
                            "to the uninterrupted lattice")
        return {"lease_a": a.stats.summary(),
                "lease_b": b.stats.summary(),
                "stolen_s_after_expiry": stolen_after}


def main() -> int:
    failures = []
    router_summary = router_smoke(failures)
    lease_summary = lease_smoke(failures)
    if failures:
        for f in failures:
            print(f"ELASTIC-SMOKE FAIL: {f}")
        return 1
    print(json.dumps({"router": router_summary, "lease": lease_summary}))
    print("elastic smoke: OK (replica killed mid-run with zero "
          "dropped/duplicated requests; breaker open->half_open->closed"
          " across the rejoin; expired lease stolen within one TTL; "
          "stolen-shard lattice merge bitwise-identical)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
