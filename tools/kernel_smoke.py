#!/usr/bin/env python
"""Kernel smoke (`make kernel-smoke`): prove the PR-7 fused kernel layer
on CPU, no chip needed.

Asserts, under Pallas interpret mode where a kernel is involved:
1. flash_decode == dense decode attention (argmax exact through a greedy
   loop, logits within tolerance) across masked/padded rows, GQA, ALiBi,
   and non-power-of-two cache extents;
2. int8 fused matmul == the dequantized reference for static AND dynamic
   QuantTensors, with quant.shared_quant bit-identical to per-matrix
   activation quantization;
3. a piggybacked dispatch chain == the sequential dispatches per row, and
   an actual sweep on the fake backend chains (counters move) with rows
   identical to the piggyback-off sweep.

Exit 0 = all parity holds; any assertion failure is a real regression in
the fused paths.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import tempfile
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax                  # noqa: E402
import jax.numpy as jnp     # noqa: E402
import numpy as np          # noqa: E402


def check_flash_decode() -> None:
    from lir_tpu.engine import generate
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="ksmoke", vocab_size=256, hidden_size=32,
                      n_layers=2, n_heads=4, n_kv_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(3, 256, (3, 14)), jnp.int32)
    mask = np.ones((3, 14), np.int32)
    mask[0, :6] = 0
    mask = jnp.asarray(mask)
    gen_d, lg_d = generate.greedy_decode(
        params, dataclasses.replace(cfg, fused_decode=False), toks, mask,
        max_new_tokens=6)
    old = decoder.FUSED_DECODE_INTERPRET_ON_CPU
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True
    try:
        gen_f, lg_f = generate.greedy_decode(params, cfg, toks, mask,
                                             max_new_tokens=6)
    finally:
        decoder.FUSED_DECODE_INTERPRET_ON_CPU = old
    assert (np.asarray(gen_d) == np.asarray(gen_f)).all(), \
        "fused decode changed the greedy argmax"
    err = float(jnp.abs(lg_d - lg_f).max())
    assert err < 2e-5, f"fused decode logits drifted: {err}"
    print(f"  flash-decode greedy parity: argmax exact, "
          f"logits max err {err:.2e}")


def check_int8_fusion() -> None:
    from lir_tpu.models import quant

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(5, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 48)), jnp.float32)
    qt = quant.quantize(w)
    np.testing.assert_allclose(np.asarray(quant.matmul(x, qt)),
                               np.asarray(x @ qt.dequant()),
                               rtol=1e-5, atol=1e-5)
    qd = dataclasses.replace(qt, dynamic=True)
    xq, xs = quant.dynamic_quant(x)
    ref = ((np.asarray(xq, np.float32) * np.asarray(xs)[:, None])
           @ np.asarray(qd.dequant()))
    np.testing.assert_allclose(np.asarray(quant.matmul(x, qd)), ref,
                               rtol=1e-5, atol=1e-5)
    shared = quant.shared_quant(x, qd, qd)
    np.testing.assert_array_equal(np.asarray(quant.matmul(shared, qd)),
                                  np.asarray(quant.matmul(x, qd)))
    print("  int8 fused matmul parity: static + dynamic + shared-quant ok")


def check_piggyback() -> None:
    import torch
    import transformers as tf

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models.loader import config_from_hf, convert_decoder

    torch.manual_seed(0)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=FakeTokenizer.VOCAB, hidden_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        intermediate_size=128, max_position_embeddings=512,
        tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)
    prompts = (LegalPrompt(
        main="Does a vehicle include a bicycle ?",
        response_format="Answer Covered or Not .",
        target_tokens=("Covered", "Not"),
        confidence_format="Give a number from 0 to 100 ."),)
    perts = ([f"Would a bicycle number {i} count as a vehicle maybe ?"
              for i in range(11)],)

    def run(piggy, td):
        rt = RuntimeConfig(batch_size=4, max_new_tokens=8, max_seq_len=256,
                           piggyback_prefill=piggy, sweep_group_min_cells=0)
        eng = ScoringEngine(params, cfg, FakeTokenizer(), rt)
        rows = run_perturbation_sweep(eng, "ksmoke", prompts, perts,
                                      Path(td) / "r.xlsx",
                                      checkpoint_every=100)
        return rows, eng

    with tempfile.TemporaryDirectory() as td:
        rows_on, eng_on = run(True, td)
    with tempfile.TemporaryDirectory() as td:
        rows_off, _ = run(False, td)
    c = eng_on.kernel_stats.counters
    assert c.get("chains_opened", 0) >= 1, c
    assert c.get("piggybacked_steps", 0) >= 1, c
    assert c.get("chains_drained", 0) >= 1, c
    key = lambda r: r.rephrased_main  # noqa: E731
    for a, b in zip(sorted(rows_on, key=key), sorted(rows_off, key=key)):
        assert a.model_response == b.model_response
        assert a.confidence_value == b.confidence_value
        assert abs(a.token_1_prob - b.token_1_prob) < 1e-5
        assert abs(a.weighted_confidence - b.weighted_confidence) < 1e-4
    print(f"  piggyback chain: {c.get('piggybacked_steps')} piggybacked "
          f"steps, rows identical to the sequential sweep")


def main() -> int:
    print("kernel smoke: fused paths vs their references (CPU interpret)")
    check_flash_decode()
    check_int8_fusion()
    check_piggyback()
    print("kernel smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
