"""In-scan sweep-bucket profile at the CURRENT budgets (VERDICT r4 #3).

The r3 "definitive sweep-bucket profile" (SCALE.md) was measured at the
old conf=16 budget; r4 cut the confidence decode to 8 tokens and the
profile went stale — nothing measured said where the e2e-vs-isolated gap
(31.7 vs 41.0 p/s) now comes from or what the new device-bound ceiling
is. This tool re-measures the components of one production sweep bucket
(the shared-prefix two-format scorer, generate.greedy_decode_fused_shared)
the only way that is trustworthy under tunneled dispatch: repeats INSIDE
one jitted lax.scan, so per-iteration time contains zero host/dispatch
overhead. Differencing two scan lengths cancels the fixed entry cost.

Components reported:
- full bucket (prefill 256 + 2 suffix extends + bin and conf fused tails)
  at the production budgets -> the device-work floor and p/s ceiling
- the same bucket at conf+8 -> ms per confidence decode step (slope)
- the same bucket at bin+4 -> ms per binary decode step (slope)
- shared prefill alone
- residual = extends + in-scan readout overhead

Run on the TPU:  python tools/bucket_profile.py [--batch 40] [--no-record]
"""

from __future__ import annotations

import argparse
import datetime
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

SCALE_MD = REPO / "SCALE.md"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=40)
    ap.add_argument("--bucket", type=int, default=256)
    ap.add_argument("--sfx", type=int, default=16)
    ap.add_argument("--model", default="llama2_7b")
    ap.add_argument("--bin-tokens", type=int, default=4)
    ap.add_argument("--conf-tokens", type=int, default=8)
    ap.add_argument("--reps", type=int, default=8,
                    help="long scan length (short is 2; per-iter = diff/6)")
    ap.add_argument("--no-record", action="store_true")
    args = ap.parse_args()

    import functools
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # env alone is too late under the axon sitecustomize (conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from lir_tpu.engine import generate
    from lir_tpu.models import decoder, quant

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("# no accelerator: tiny CPU smoke variant")
        from lir_tpu.models.registry import ModelConfig
        cfg = ModelConfig(name="profile-smoke", vocab_size=512,
                          hidden_size=64, n_layers=2, n_heads=4,
                          intermediate_size=128, max_seq_len=1024)
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        mode = "0.2M-smoke fp32"
    else:
        import dataclasses
        from tools.scale_validation import resolve_preset
        cfg = dataclasses.replace(resolve_preset(args.model),
                                  kv_cache_int8=True)
        params = quant.random_quantized_params(
            cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16, dynamic=True)
        mode = f"{cfg.name} int8-dyn+kvq8"

    B, S, S2 = args.batch, args.bucket, args.sfx
    rng = np.random.default_rng(0)
    prefix = jnp.asarray(rng.integers(5, cfg.vocab_size - 5, (B, S)),
                         jnp.int32)
    pmask = jnp.ones((B, S), jnp.int32)
    sfx = jnp.asarray(rng.integers(5, cfg.vocab_size - 5, (B, S2)),
                      jnp.int32)
    smask = jnp.ones((B, S2), jnp.int32)
    yes_ids = jnp.full((B,), 7, jnp.int32)
    no_ids = jnp.full((B,), 9, jnp.int32)
    digit_ids = jnp.asarray(rng.integers(5, cfg.vocab_size - 5, (32,)),
                            jnp.int32)
    digit_vals = jnp.asarray(np.linspace(0, 100, 32), jnp.float32)

    # params MUST be a traced argument: closing over a 7B tree embeds it
    # as multi-GB compile-time constants.
    def _vary(prefix, carry):
        # The body must be LOOP-CARRIED or XLA hoists the (otherwise
        # loop-invariant) model computation out of the scan and every
        # length times the same single execution. A carry-dependent token
        # offset (0 on iter 0, 1 after — cost-identical) forces true
        # per-iteration execution.
        off = jnp.clip(jnp.abs(carry).astype(jnp.int32), 0, 1)
        return jnp.minimum(prefix + off, cfg.vocab_size - 1)

    @functools.partial(jax.jit, static_argnames=("reps", "bin_t", "conf_t"))
    def scan_full(params, prefix, reps, bin_t, conf_t):
        def body(carry, _):
            out_a, out_b = generate.greedy_decode_fused_shared(
                params, cfg, _vary(prefix, carry), pmask, sfx, smask, sfx,
                smask, yes_ids, no_ids, digit_ids, digit_vals,
                max_new_a=bin_t, max_new_b=conf_t)
            # Consume every output so nothing is dead-code-eliminated.
            chk = (out_a.p_yes.sum() + out_b.weighted_confidence.sum()
                   + out_a.generated.sum() + out_b.generated.sum())
            return carry + chk.astype(jnp.float32), ()
        total, _ = lax.scan(body, jnp.float32(0), None, length=reps)
        return total

    @functools.partial(jax.jit, static_argnames=("reps",))
    def scan_prefill(params, prefix, reps):
        T0 = S + S2 + 16
        def body(carry, _):
            logits, cache, pos = decoder.prefill(
                params, cfg, _vary(prefix, carry), pmask, T0)
            chk = logits.sum() + jax.tree_util.tree_leaves(cache)[0].sum(
                dtype=jnp.float32)
            return carry + chk.astype(jnp.float32), ()
        total, _ = lax.scan(body, jnp.float32(0), None, length=reps)
        return total

    def per_iter_ms(fn, *static) -> float:
        short, long_ = 2, args.reps
        for reps in (short, long_):          # compile both lengths
            fn(params, prefix, reps, *static).block_until_ready()
        t = {}
        for reps in (short, long_):
            t0 = time.perf_counter()
            fn(params, prefix, reps, *static).block_until_ready()
            t[reps] = time.perf_counter() - t0
        return (t[long_] - t[short]) / (long_ - short) * 1000.0

    bt, ct = args.bin_tokens, args.conf_tokens
    full_ms = per_iter_ms(scan_full, bt, ct)
    full_conf_ms = per_iter_ms(scan_full, bt, ct + 8)
    full_bin_ms = per_iter_ms(scan_full, bt + 4, ct)
    prefill_ms = per_iter_ms(scan_prefill)

    conf_step = (full_conf_ms - full_ms) / 8.0
    bin_step = (full_bin_ms - full_ms) / 4.0
    decode_ms = bt * bin_step + ct * conf_step
    resid_ms = full_ms - prefill_ms - decode_ms
    ceiling = B / (full_ms / 1000.0)

    stamp = datetime.date.today().isoformat()
    lines = [
        "",
        f"## r4-budget sweep-bucket profile — TPU v5 lite, {stamp} "
        "(in-scan timed)",
        "",
        f"{mode}, batch {B}, bucket {S}, suffixes {S2}, budgets "
        f"bin={bt}/conf={ct} (tools/bucket_profile.py; per-iter = scan-"
        f"length differencing, zero dispatch overhead):",
        "",
        "| component | ms/bucket | share |",
        "|---|---|---|",
        f"| shared prefill ({S} tok) | {prefill_ms:.0f} | "
        f"{prefill_ms / full_ms:.0%} |",
        f"| {bt} binary decode steps ({bin_step:.1f} ms/step) | "
        f"{bt * bin_step:.0f} | {bt * bin_step / full_ms:.0%} |",
        f"| {ct} confidence decode steps ({conf_step:.1f} ms/step) | "
        f"{ct * conf_step:.0f} | {ct * conf_step / full_ms:.0%} |",
        f"| 2 suffix extends + in-scan readouts (residual) | "
        f"{resid_ms:.0f} | {resid_ms / full_ms:.0%} |",
        f"| **device-work floor** | **{full_ms:.0f}** | -> "
        f"{ceiling:.1f} p/s ceiling |",
        "",
    ]
    print("\n".join(lines))
    if not args.no_record and dev.platform != "cpu":
        with SCALE_MD.open("a") as f:
            f.write("\n".join(lines) + "\n")
        print(f"# appended to {SCALE_MD}")


if __name__ == "__main__":
    main()
