#!/usr/bin/env python
"""Tier-1 guard: run the ROADMAP tier-1 suite and fail if DOTS_PASSED
drops below the recorded floor.

The repo's hard constraint is "tier-1 tests no worse than the seed", and
the floor only ratchets UP as PRs add coverage. This script is the one
place the current floor is recorded; `make verify` (or a pre-push hook —
`make install-hooks`) runs it so a regression is caught before it ships,
not by the next session's baseline run.

The pass count is derived exactly the way ROADMAP.md's tier-1 command
derives it (dot-counting over pytest's progress lines), so the two can
never disagree about what "passed" means. pytest's exit code is NOT the
gate: the suite may contain known-failing seed tests; the invariant is
the pass COUNT never regressing.

Usage:
    python tools/check_tier1.py [--floor N] [--timeout SECS]
Env:
    LIR_TPU_TIER1_FLOOR overrides the recorded floor (CI experiments).
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from pathlib import Path

# The recorded floor. Update DELIBERATELY (with the PR that raises
# coverage), never to paper over a regression.
TIER1_FLOOR = 517

PYTEST_ARGS = [
    "-m", "pytest", "tests/", "-q", "-m", "not slow",
    "--continue-on-collection-errors", "-p", "no:cacheprovider",
    "-p", "no:xdist", "-p", "no:randomly",
]

# ROADMAP.md's dot-counting rule: progress lines are runs of outcome
# characters, optionally followed by a percent marker.
PROGRESS_RE = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")


def count_passed(output: str) -> int:
    return sum(line.count(".") for line in output.splitlines()
               if PROGRESS_RE.match(line.strip()))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--floor", type=int,
                    default=int(os.environ.get("LIR_TPU_TIER1_FLOOR",
                                               TIER1_FLOOR)))
    ap.add_argument("--timeout", type=int, default=870,
                    help="suite timeout in seconds (ROADMAP's budget)")
    args = ap.parse_args()

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    print(f"tier-1 guard: running the suite (floor {args.floor}) ...",
          flush=True)
    try:
        proc = subprocess.run(
            [sys.executable, *PYTEST_ARGS], cwd=repo, env=env,
            capture_output=True, text=True, timeout=args.timeout)
    except subprocess.TimeoutExpired:
        print(f"TIER-1 FAIL: suite exceeded {args.timeout}s", flush=True)
        return 1
    output = proc.stdout + proc.stderr
    passed = count_passed(output)
    tail = "\n".join(output.strip().splitlines()[-3:])
    print(tail)
    print(f"DOTS_PASSED={passed} (floor {args.floor})")
    if passed < args.floor:
        print(f"TIER-1 FAIL: {passed} < floor {args.floor} — a test that "
              "passed at the recorded baseline no longer does.")
        return 1
    print("tier-1 guard: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
