#!/usr/bin/env python
"""Cascade-decode smoke: the trunk-aware flash-decode split dedup
(ops/flash_decode trunk kernels + engine/runner decode routing) on the
fake backend — the `make cascade-decode-smoke` CI target.

Serves a shared-trunk grid (waves of requests that rephrase the SAME
long legal-prompt trunk, varying only a short tail) on two servers
sharing nothing but the request trace: cascade decode ON (the default)
and OFF (--no-cascade-decode, the flat split-K baseline). Prefill runs
dense on BOTH servers (the cascade-prefill interpret hook stays
unarmed), so the only difference under test is the decode-phase trunk
dedup. Asserts the PR's load-bearing claims:

- the dedup actually engaged: nonzero cascade-decode dispatches AND
  nonzero analytic trunk bytes deduped (the trunk covered at least one
  whole key split — a zero here means the ladder never dedup'd);
- payload parity is BITWISE: every field of every request's payload —
  argmax-derived strings AND float probabilities — is identical
  between the two servers (the trunk kernels compute the flat kernels'
  exact partials; the merge is the same reduction);
- the flat server never counted a cascade-decode dispatch.

Runs hermetically on CPU with the FakeTokenizer + a tiny random decoder
(the trunk kernels under the Pallas interpreter via the tier-1
fused-decode hook); prints the CascadeStats summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_BASES = 3
WAVE = 8           # requests per shared-trunk wave (one batch's worth)
# Long trunks: the prefix must land in a bucket whose decode cache
# extent splits into more than one key tile (pick_split), with the
# quantized trunk covering at least one whole tile — ~120 words puts
# the prefix in the 128 bucket (cache extent 144 -> split 72, trunk
# 112 -> one whole tile deduped). 90 words lands in the 96 bucket,
# whose 112-slot cache is a SINGLE split: zero dedup by design.
BASE_WORDS = 120


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    # Tier-1 hook: the fused decode kernels (and their trunk-aware
    # siblings) run under the Pallas interpreter on CPU. The cascade
    # PREFILL hook stays unarmed — prefill runs dense on both servers,
    # isolating the decode-phase dedup as the only variable.
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True

    cfg = ModelConfig(name="cascdec-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder.init_params(cfg, jax.random.PRNGKey(13))

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    rng = np.random.default_rng(31)
    bases = [" ".join(rng.choice(words) for _ in range(BASE_WORDS))
             for _ in range(N_BASES)]

    def request(b: int, i: int) -> ServeRequest:
        main_text = f"{bases[b]} case {i} maybe ?"
        return ServeRequest(
            binary_prompt=f"{main_text} Answer Yes or No .",
            confidence_prompt=f"{main_text} Give a number from 0 to 100 .",
            klass="smoke", request_id=f"{b}-{i}")

    def serve(decode_on: bool):
        rt = RuntimeConfig(batch_size=WAVE, max_seq_len=512,
                           cascade_decode=decode_on)
        engine = ScoringEngine(params, cfg, FakeTokenizer(), rt)
        sc = ServeConfig(queue_depth=2 * WAVE, classes=(("smoke", 600.0),),
                         default_class="smoke", linger_s=0.01)
        server = ScoringServer(engine, "cascdec-smoke", sc).start()
        payloads = []
        for b in range(N_BASES):
            futs = [server.submit(request(b, i)) for i in range(WAVE)]
            payloads.extend(f.result(timeout=600) for f in futs)
        server.stop()
        return engine, payloads

    eng_on, res_on = serve(True)
    eng_off, res_off = serve(False)

    failures = []
    bad = [r.request_id for r in res_on + res_off if r.status != "ok"]
    if bad:
        failures.append(f"non-ok results: {bad}")
    stats = eng_on.cascade_stats
    if stats.cascade_decode_dispatches <= 0:
        failures.append("the shared-trunk grid never took the trunk-aware "
                        "decode path (zero cascade-decode dispatches)")
    if stats.trunk_bytes_deduped <= 0:
        failures.append("zero trunk bytes deduped — the trunk never "
                        "covered a whole key split (check the bucket "
                        "ladder vs the trunk extent)")
    if eng_off.cascade_stats.cascade_decode_dispatches != 0:
        failures.append("--no-cascade-decode engine still counted "
                        "cascade-decode dispatches")
    fields = ("status", "model_response", "model_confidence_response",
              "confidence_value", "token_1_prob", "token_2_prob",
              "weighted_confidence")
    for a, b in zip(res_on, res_off):
        diff = [f for f in fields
                if getattr(a, f, None) != getattr(b, f, None)]
        if diff:
            failures.append(f"payload fields {diff} differ for request "
                            f"{a.request_id} — trunk decode must be "
                            f"BITWISE the flat kernel")
            break
    if failures:
        for f in failures:
            print(f"CASCADE-DECODE-SMOKE FAIL: {f}")
        return 1
    print(json.dumps(stats.summary()))
    print(f"cascade decode smoke: OK ({N_BASES * WAVE} requests over "
          f"{N_BASES} shared trunks, "
          f"{stats.cascade_decode_dispatches} trunk-aware decode "
          f"dispatches, {stats.trunk_bytes_deduped:.2e} trunk bytes "
          f"deduped, payloads bitwise ON vs OFF)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
