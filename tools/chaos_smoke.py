#!/usr/bin/env python
"""Chaos smoke: the failure path exercised end-to-end on the fake
backend, with a seeded fault schedule (lir_tpu/faults.FaultPlan). The
`make chaos-smoke` CI target asserts the three recovery mechanisms the
robustness PR ships:

1. SWEEP CRASH CONSISTENCY — a perturbation sweep runs under injected
   transient device errors plus a mid-sweep kill (simulated preemption
   raised between checkpoints), then the manifest tail is torn the way a
   real kill mid-append tears it; the RESUMED sweep must complete with
   output rows bitwise identical to a fault-free run over the same grid:
   zero lost, zero duplicated.
2. CIRCUIT BREAKER — a serve session under a scheduled device outage
   must trip the breaker (queue drained, submits shed), then recover to
   healthy through the half-open probe once the outage ends, and serve
   every post-recovery request "ok".
3. DEGRADATION LADDER + CHECKPOINT — a poison request must be isolated
   by bisection (its neighbors scored, only it errors), and a SIGTERM-
   style shutdown checkpoint must hand every unresolved request to a
   fresh server with zero lost and zero double-served.

The guard layer (lir_tpu/guard) adds the SILENT failure modes:

4. WATCHDOG vs HANG — a sweep dispatch that sleeps far past its
   watchdog deadline must be detected within ~one deadline, abandoned,
   and recovered through the ladder: zero lost/duplicated rows, output
   bitwise identical to a fault-free run, wall time nowhere near the
   hang duration.
5. NUMERICS GUARD vs NaN — injected NaN logits (SDC stand-in) must
   quarantine exactly the corrupt rows as error:numerics while every
   clean row stays bitwise identical to the fault-free run — zero
   corrupted rows recorded; GuardStats counters match the injections.
   Same contract online: the serve request carrying the corrupt row
   resolves "error" with a numerics note, its neighbors "ok".
6. MULTIHOST LIVENESS — a simulated dead peer (collectives that never
   complete) must raise HostDesyncError on the survivor within the
   liveness timeout (resumable exit) instead of hanging forever.
7. STREAMING ACCUMULATOR — a mid-sweep kill with rows folded but not
   checkpointed must resume to an accumulator bitwise-identical to an
   uninterrupted run (idempotent slot folds).
8. ELASTIC — a LEASED sweep killed mid-run is finished by a different
   holder stealing the expired leases (accumulator bitwise vs the
   static run), and a straggler replica behind the failover router
   loses the hedge race with its late payload dropped: zero requests
   lost or double-resolved (lir_tpu/serve/router.py +
   lir_tpu/engine/lease.py).
9. SPECULATIVE DRAFT CORRUPTION — seeded garbage drafts must only cost
   re-verification: rows bitwise, rejections counted (spec_chaos).
10. OOM SQUEEZE — a seeded ``hbm_squeeze`` shrinks the HBM governor's
   budget mid-sweep and mid-serve (lir_tpu/engine/hbm.py): zero
   crashed dispatches, every degradation rung reversible (down AND up
   counters), rows/payloads bitwise vs unpressured runs, governor
   gauges in the metrics snapshot, and an injected device OOM
   reclaim-and-retried without feeding the circuit breaker.

11. MIGRATION STALL/CORRUPT — disaggregated serving's page-transfer
   chaos (lir_tpu/serve/migrate.py): a seeded ``migration_corrupt``
   flips transferred chunk bytes under the export checksums (the
   import must refuse to land any page, destination tree/refcounts
   rolled back untouched) and a ``migration_stall`` wedges the wire
   hop past the chain deadline — BOTH fall back to local re-prefill
   on the decode replica: every request resolves ok with payloads
   bitwise a colocated server's, fallbacks == injections, never a
   wrong answer.

12. TIER CORRUPT/STALL — the tiered KV ladder's promote chaos
   (lir_tpu/serve/tiers.py): a seeded ``tier_corrupt`` flips a demoted
   prefix's bytes under its chunk checksums (the promote must refuse
   BEFORE any page enters the radix tree and drop the poisoned entry)
   and a ``disk_stall`` wedges a disk-tier read past ``disk_timeout_s``
   (the promote is abandoned but the entry KEPT — a transient stall is
   not corruption) — BOTH requests fall back to local re-prefill and
   resolve ok with payloads bitwise an untiered server's: refusals and
   stalls counted == injections, never a wrong answer.

Runs hermetically on CPU (FakeTokenizer + tiny random decoder); prints
the FaultStats/GuardStats summaries as JSON on success.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_CELLS = 12
BATCH = 4


def _make_engine(batch=BATCH, seed=11):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="chaos-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    # piggyback OFF: chaos scenarios compare fault-injected passes
    # BITWISE against fault-free passes, and fault wrapping disables the
    # piggyback chain by design (it must not bypass the injected
    # dispatch sites) — so both sides of every comparison here must run
    # the plain path. Piggyback-vs-plain parity has its own gate
    # (make kernel-smoke; float-tolerance, not bitwise — the chain's
    # cache extent reassociates reductions by a few ulps).
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(batch_size=batch, max_seq_len=256,
                                       piggyback_prefill=False))


def _grid(n_cells, seed=21):
    import numpy as np

    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    # Two length populations so the ragged planner forms several buckets
    # (the kill should land between checkpoints of a real multi-dispatch
    # schedule, not inside one trivial batch).
    perts = ([text(10 if i % 2 else 24) for i in range(n_cells - 1)],)
    return lp, perts


_VALUE_COLUMNS = ("Token_1_Prob", "Token_2_Prob", "Confidence Value",
                  "Weighted Confidence", "Model Response",
                  "Model Confidence Response", "Log Probabilities")


def sweep_chaos(failures):
    """Mechanism 1: transient faults + mid-sweep kill + torn manifest
    tail -> resumed output bitwise equal to the fault-free run."""
    import tempfile

    from lir_tpu import faults
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid(N_CELLS)

    from lir_tpu.data import schemas

    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        clean = run_perturbation_sweep(
            _make_engine(), "chaos", lp, perts, td / "clean.csv",
            checkpoint_every=4)
        if len(clean) != N_CELLS:
            failures.append(f"fault-free sweep produced {len(clean)} rows")
            return {}
        # Compare ARTIFACT to ARTIFACT: both runs pass through the same
        # CSV encoding, so cell values must match exactly (bitwise after
        # identical decoding) — any recovery-path divergence shows up.
        clean_df = schemas.read_results_frame(td / "clean.csv")
        clean_by_key = {
            (row["Rephrased Main Part"], row["Response Format"],
             row["Confidence Format"]): tuple(
                row[c] for c in _VALUE_COLUMNS)
            for _, row in clean_df.iterrows()}

        # Chaos pass: dispatch call 1 fails once (transient; the
        # recovery ladder retries through it) and the SECOND manifest
        # checkpoint is a kill — fired AFTER that checkpoint's rows hit
        # the results file but BEFORE they are marked done, the exact
        # window where a naive resume would duplicate them.
        plan = faults.FaultPlan(seed=7, schedules={
            "dispatch": faults.SiteSchedule(fail_calls=(1,)),
            "manifest_write": faults.SiteSchedule.kill_at(1),
        })
        engine = _make_engine()
        faults.wrap_engine(engine, plan)
        out = td / "chaos.csv"
        from lir_tpu.engine import grid as grid_mod
        from lir_tpu.utils.manifest import SweepManifest

        manifest = SweepManifest(out.with_suffix(".manifest.jsonl"),
                                 grid_mod.RESUME_KEY_FIELDS)
        manifest.mark_done_many = plan.wrap("manifest_write",
                                            manifest.mark_done_many)
        preempted = False
        try:
            run_perturbation_sweep(engine, "chaos", lp, perts, out,
                                   manifest=manifest, checkpoint_every=4)
        except faults.InjectedPreemption:
            preempted = True
        if not preempted:
            failures.append("scheduled preemption never fired")
            return {}
        if engine.fault_stats.recovered_dispatches < 1:
            failures.append("transient dispatch fault was not recovered")
        # The kill landed mid-manifest-append: tear the tail.
        manifest = out.with_suffix(".manifest.jsonl")
        if manifest.exists():
            faults.tear_jsonl_tail(manifest)

        resumed_engine = _make_engine()
        run_perturbation_sweep(resumed_engine, "chaos", lp, perts, out,
                               checkpoint_every=4)
        df = schemas.read_results_frame(out)
        keys = list(zip(df["Rephrased Main Part"], df["Response Format"],
                        df["Confidence Format"]))
        if len(keys) != N_CELLS:
            failures.append(
                f"resumed sweep artifact has {len(keys)} rows, expected "
                f"{N_CELLS} (lost {N_CELLS - len(set(keys))}, "
                f"dup {len(keys) - len(set(keys))})")
        if len(set(keys)) != len(keys):
            failures.append("resumed sweep artifact holds duplicated rows")
        for _, row in df.iterrows():
            k = (row["Rephrased Main Part"], row["Response Format"],
                 row["Confidence Format"])
            want = clean_by_key.get(k)
            if want is None:
                failures.append(f"resumed sweep invented a row: {k[0][:40]}")
                continue
            got = tuple(row[c] for c in _VALUE_COLUMNS)
            for g, w in zip(got, want):
                import pandas as pd

                if pd.isna(g) and pd.isna(w):
                    continue
                if g != w:
                    failures.append(
                        f"resumed row differs from fault-free run: "
                        f"{g!r} != {w!r} for {k[0][:40]}")
                    break
        return {"injected": plan.stats.summary(),
                "sweep_recovered": engine.fault_stats.summary()}


def stream_accum_chaos(failures):
    """Mechanism 7 (streaming statistics): a mid-sweep kill — fired with
    rows DISPATCHED (folded into the device accumulator) but not yet
    checkpointed/marked — must leave a partial accumulator flushed on
    the kill path, and the RESUMED sweep's accumulator must be
    bitwise-identical to an uninterrupted run's: re-folds of the
    inflight rows are idempotent per cell, never double-counted,
    never lost."""
    import tempfile

    import numpy as np

    from lir_tpu import faults
    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid(N_CELLS)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        run_perturbation_sweep(_make_engine(), "chaos", lp, perts,
                               td / "clean.csv", checkpoint_every=4)
        acc_clean = stream_mod.load_accum(
            (td / "clean.csv").with_suffix(stream_mod.ACCUM_SUFFIX))
        if acc_clean is None or acc_clean.rows_folded != N_CELLS:
            failures.append("stream: fault-free accumulator incomplete")
            return {}

        engine = _make_engine()
        plan = faults.FaultPlan(seed=9, schedules={
            "dispatch": faults.SiteSchedule.kill_at(1)},
            stats=engine.fault_stats)
        faults.wrap_engine(engine, plan)
        out = td / "chaos.csv"
        try:
            run_perturbation_sweep(engine, "chaos", lp, perts, out,
                                   checkpoint_every=4)
            failures.append("stream: scheduled kill never fired")
            return {}
        except faults.InjectedPreemption:
            pass
        partial = stream_mod.load_accum(
            out.with_suffix(stream_mod.ACCUM_SUFFIX))
        if partial is None:
            failures.append("stream: partial accumulator not flushed "
                            "on the preemption exit path")
            return {}
        if not 0 < partial.rows_folded < N_CELLS:
            failures.append(
                f"stream: partial accumulator folded "
                f"{partial.rows_folded} rows (expected mid-sweep)")

        run_perturbation_sweep(_make_engine(), "chaos", lp, perts, out,
                               checkpoint_every=4)
        acc = stream_mod.load_accum(
            out.with_suffix(stream_mod.ACCUM_SUFFIX))
        same = (acc is not None
                and np.array_equal(acc_clean.filled, acc.filled)
                and np.array_equal(acc_clean.rel, acc.rel,
                                   equal_nan=True)
                and np.array_equal(acc_clean.conf, acc.conf,
                                   equal_nan=True)
                and np.array_equal(acc_clean.dec, acc.dec)
                and acc_clean.seed == acc.seed)
        if not same:
            failures.append("stream: resume-merged accumulator is NOT "
                            "bitwise-identical to the uninterrupted run")
        return {"partial_rows_folded": int(partial.rows_folded),
                "resumed_rows_folded": int(acc.rows_folded
                                           if acc else -1)}


def serve_chaos(failures):
    """Mechanisms 2+3: breaker trip -> half-open probe -> recovery;
    poison-row isolation; SIGTERM checkpoint resume with zero lost."""
    from lir_tpu import faults
    from lir_tpu.config import RetryConfig, ServeConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    def request(i, rid=None):
        body = f"clause {i} covers wind damage under policy {i * 7}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=rid or str(i))

    import dataclasses

    cfg = ServeConfig(
        queue_depth=64, classes=(("smoke", 600.0),),
        default_class="smoke", linger_s=0.0,
        max_consecutive_failures=2, breaker_cooldown_s=0.3,
        retry=RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5))

    # --- breaker: a transient outage of exactly 4 injections = two
    # whole failed dispatches (2 attempts each, ladder off so the
    # accounting is exact) -> the breaker opens on the second; the
    # schedule is then exhausted, so the half-open probe succeeds.
    cfg_nb = dataclasses.replace(cfg, degrade_ladder=False)
    server = ScoringServer(_make_engine(), "chaos", cfg_nb)
    plan = faults.FaultPlan(seed=3, schedules={
        "dispatch": faults.SiteSchedule(rate=1.0, max_failures=4)})
    faults.wrap_server(server, plan)
    server.start()
    results = []
    for wave in range(2):       # two waves -> at least two dispatches
        futs = [server.submit(request(10 * wave + i)) for i in range(2)]
        results += [f.result(timeout=60) for f in futs]
    deadline = time.monotonic() + 10
    while server.healthy and time.monotonic() < deadline:
        time.sleep(0.01)     # breaker must OPEN
    if server.healthy:
        failures.append("breaker never opened under the outage")
    if not all(r.status in ("error", "shed") for r in results):
        failures.append("outage requests resolved with an OK status")
    # Shed-while-open: a submit inside the cooldown resolves shed.
    shed = server.submit(request(99, "shed")).result(timeout=5)
    if shed.status != "shed":
        failures.append(f"open breaker admitted a request: {shed.status}")
    time.sleep(cfg.breaker_cooldown_s + 0.05)   # cooldown -> half-open
    probe = server.submit(request(100, "probe")).result(timeout=60)
    if probe.status != "ok":
        failures.append(f"half-open probe did not serve: {probe.status}")
    if not server.healthy:
        failures.append("breaker did not close after the probe success")
    post = [server.submit(request(200 + i)).result(timeout=60)
            for i in range(4)]
    if not all(r.status == "ok" for r in post):
        failures.append("post-recovery requests did not all serve ok")
    server.stop()
    transitions = [f"{a}->{b}" for a, b in server.faults.transitions]
    for want in ("closed->open", "open->half_open", "half_open->closed"):
        if want not in transitions:
            failures.append(f"breaker transition {want} missing "
                            f"({transitions})")
    breaker_summary = server.faults.summary()

    # --- ladder: one poison request fails in any company; neighbors
    # must still score and only the culprit errors.
    server2 = ScoringServer(_make_engine(), "chaos", cfg)
    real_score = server2.batcher.score

    def poisoned_score(bucket, rows):
        if any(p.request.request_id == "poison" for p in rows):
            raise RuntimeError("poison row crash")
        return real_score(bucket, rows)

    server2.batcher.score = poisoned_score
    reqs = [request(i) for i in range(3)] + [request(66, "poison")]
    futs = [server2.submit(r) for r in reqs]
    server2.start()
    res = [f.result(timeout=60) for f in futs]
    server2.stop()
    by_id = {r.request_id: r for r in res}
    if by_id["poison"].status != "error":
        failures.append("poison request did not resolve as error")
    if not all(by_id[str(i)].status == "ok" for i in range(3)):
        failures.append("poison row took its neighbors down")
    if server2.faults.degraded_rows != 1:
        failures.append(
            f"ladder degraded {server2.faults.degraded_rows} rows, "
            "expected exactly the poison row")
    if not server2.healthy:
        failures.append("breaker tripped on a recoverable poison row")

    # --- checkpoint: SIGTERM-style shutdown with a backlog; a fresh
    # server resumes it with zero lost, zero double-served.
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ckpt = Path(td) / "serve-state.json"
        server3 = ScoringServer(_make_engine(), "chaos", cfg)
        backlog = [server3.submit(request(300 + i)) for i in range(6)]
        n = server3.shutdown_checkpoint(ckpt)   # never started: all pend
        if n != 6:
            failures.append(f"checkpoint held {n} requests, expected 6")
        done_before = {f.result(0).request_id for f in backlog
                       if f.done()}
        server4 = ScoringServer(_make_engine(), "chaos", cfg).start()
        resumed = server4.resume_from_checkpoint(ckpt)
        res4 = [f.result(timeout=60) for f in resumed]
        server4.stop()
        ids = [r.request_id for r in res4]
        if sorted(ids) != sorted(str(300 + i) for i in range(6)):
            failures.append(f"resume lost/invented requests: {ids}")
        if done_before & set(ids):
            failures.append("a request was both served and checkpointed")
        if not all(r.status == "ok" for r in res4):
            failures.append("a resumed request did not serve ok")

    return {"breaker": breaker_summary,
            "ladder": server2.faults.summary()}


def guard_chaos(failures):
    """Mechanisms 4+5 offline: one sweep under an injected HANG (call 1)
    and injected NaN corruption (a later dispatch) — the stall must be
    detected within ~one watchdog deadline and recovered by the ladder,
    the NaN row quarantined as error:numerics, everything else bitwise
    identical to a fault-free run. Zero lost, zero dup, zero corrupted
    rows recorded."""
    import tempfile

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.guard import NUMERICS_ERROR
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    import jax

    cfg = ModelConfig(name="guard-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(11))
    # One engine for both passes: the clean sweep calibrates the
    # watchdog, so the chaos pass runs under tight, price-model-derived
    # deadlines with no hand tuning.
    # piggyback OFF: the clean pass must run the same (plain) path the
    # fault-wrapped chaos pass runs, or the bitwise clean-vs-chaos
    # comparison measures the chain's ulp-level reduction drift instead
    # of recovery correctness (see _engine above). spec OFF for the
    # same reason serve_guard_chaos sets it: the hair-trigger deadline
    # is calibrated from the clean pass's handful of dispatches, and
    # the speculative executables' first-trace time (x the widened
    # spec seed headroom) would inflate that one-shot calibration past
    # the injected hang — smoke-scale compile noise, not a recovery
    # property. Speculative chaos is scenario 9 (spec_chaos).
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                                         watchdog_multiple=2.0,
                                         watchdog_floor_s=0.2,
                                         piggyback_prefill=False,
                                         spec_decode=False))
    lp, perts = _grid(N_CELLS)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        clean = run_perturbation_sweep(engine, "guard", lp, perts,
                                       td / "clean.csv",
                                       checkpoint_every=100)
        if not engine.watchdog.calibrated:
            failures.append("watchdog did not calibrate on the clean sweep")
        clean_by_key = {r.rephrased_main: (
            r.token_1_prob, r.token_2_prob, r.confidence_value,
            r.weighted_confidence, r.model_response,
            r.model_confidence_response, r.log_probabilities)
            for r in clean}

        hang_s = 60.0
        plan_hang = faults.FaultPlan(seed=5, schedules={
            "dispatch": faults.SiteSchedule.hang_at(1, seconds=hang_s)})
        plan_nan = faults.FaultPlan(seed=6, schedules={
            # Call index on the OUTER wrap: 0 clean, 1 hang->stall,
            # 2 the stalled dispatch's retry, 3 the NaN dispatch.
            "dispatch": faults.SiteSchedule.nan_at(3, rows=(0,))})
        faults.wrap_engine(engine, plan_hang)
        faults.wrap_engine(engine, plan_nan)
        t0 = time.monotonic()
        rows = run_perturbation_sweep(engine, "guard", lp, perts,
                                      td / "chaos.csv",
                                      checkpoint_every=100)
        elapsed = time.monotonic() - t0

        if plan_hang.stats.injected.get("dispatch", 0) != 1:
            failures.append("scheduled hang never fired")
        if engine.guard_stats.stalls.get("sweep", 0) < 1:
            failures.append("watchdog never detected the injected hang")
        if engine.fault_stats.recovered_dispatches < 1:
            failures.append("stalled dispatch was not recovered")
        if elapsed > hang_s / 2:
            failures.append(
                f"stall recovery took {elapsed:.1f}s — the sweep waited "
                f"out the hang instead of abandoning at its deadline")
        keys = [r.rephrased_main for r in rows]
        if len(keys) != N_CELLS or len(set(keys)) != N_CELLS:
            failures.append(
                f"hang+nan sweep lost/duplicated rows ({len(keys)} rows, "
                f"{len(set(keys))} unique, expected {N_CELLS})")
        quarantined = [r for r in rows if r.model_response == NUMERICS_ERROR]
        if len(quarantined) != 1:
            failures.append(
                f"{len(quarantined)} rows quarantined, expected exactly "
                f"the injected-NaN row")
        if engine.guard_stats.quarantined.get("sweep", 0) != 1:
            failures.append("GuardStats quarantine counter != 1 injection")
        for r in rows:
            if r.model_response == NUMERICS_ERROR:
                if r.token_1_prob is not None or r.confidence_value is not None:
                    failures.append("quarantined row still carries values")
                continue
            got = (r.token_1_prob, r.token_2_prob, r.confidence_value,
                   r.weighted_confidence, r.model_response,
                   r.model_confidence_response, r.log_probabilities)
            if got != clean_by_key.get(r.rephrased_main):
                failures.append(
                    f"clean row differs from fault-free run under "
                    f"hang+nan chaos: {r.rephrased_main[:40]}")
    return {"guard": engine.guard_stats.summary(),
            "recovered": engine.fault_stats.summary(),
            "stall_recovery_s": round(elapsed, 2)}


def serve_guard_chaos(failures):
    """Mechanism 5 online: the serve request whose dispatch row was
    NaN-corrupted resolves error:numerics; neighbors ok; an injected
    serve hang is stalled-out and recovered to ok."""
    import dataclasses

    from lir_tpu import faults
    from lir_tpu.config import RetryConfig, RuntimeConfig, ServeConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    def request(i, rid=None):
        body = f"clause {i} covers wind damage under policy {i * 7}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=rid or str(i))

    cfg = ServeConfig(
        queue_depth=64, classes=(("smoke", 600.0),),
        default_class="smoke", linger_s=0.0,
        max_consecutive_failures=3,
        retry=RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5))
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    mcfg = ModelConfig(name="guard-serve", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(13))
    # spec OFF: this scenario calibrates the watchdog from a SINGLE warm
    # dispatch and then requires its hair-trigger deadline (floor 0.3s,
    # multiple 3) to shoot a 60s hang well inside the request window.
    # The speculative executables' first-trace time would land in that
    # one calibration sample (multiplied by the widened spec seed
    # headroom), inflating the deadline past the hang — a compile
    # artifact of the smoke's tiny scale, not a recovery property.
    # Speculative chaos has its own scenario (spec_chaos, #9).
    engine = ScoringEngine(params, mcfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                                         watchdog_multiple=3.0,
                                         watchdog_floor_s=0.3,
                                         spec_decode=False))
    server = ScoringServer(engine, "guard-serve", cfg)
    plan = faults.FaultPlan(seed=9, schedules={
        "dispatch": faults.SiteSchedule(fail_calls=(1,), kind="hang",
                                        hang_s=60.0)})
    plan_nan = faults.FaultPlan(seed=10, schedules={
        # Outer wrap call index: 0 warm, 1 hang, 2 its retry, 3 nan.
        "dispatch": faults.SiteSchedule.nan_at(3, rows=(0,))})
    faults.wrap_server(server, plan)
    faults.wrap_server(server, plan_nan)
    server.start()
    try:
        warm = [server.submit(request(i, f"w{i}")) for i in range(BATCH)]
        if not all(f.result(timeout=60).status == "ok" for f in warm):
            failures.append("serve warm requests did not all serve ok")
        t0 = time.monotonic()
        hung = [server.submit(request(100 + i, f"h{i}"))
                for i in range(BATCH)]
        hres = [f.result(timeout=60) for f in hung]
        stall_t = time.monotonic() - t0
        if not all(r.status == "ok" for r in hres):
            failures.append(
                f"hung serve dispatch not recovered: "
                f"{[r.status for r in hres]}")
        if stall_t > 30.0:
            failures.append(f"serve stall recovery took {stall_t:.1f}s")
        if engine.guard_stats.stalls.get("serve", 0) < 1:
            failures.append("serve watchdog never detected the hang")
        nfut = [server.submit(request(200 + i, f"n{i}"))
                for i in range(BATCH)]
        nres = [f.result(timeout=60) for f in nfut]
        quarantined = [r for r in nres
                       if r.status == "error" and "numerics" in r.note]
        if len(quarantined) != 1:
            failures.append(
                f"{len(quarantined)} serve rows quarantined, expected "
                f"exactly the NaN row")
        if sum(r.status == "ok" for r in nres) != BATCH - 1:
            failures.append("NaN row took serve neighbors down")
        if not server.healthy:
            failures.append("row-local NaN tripped the serve breaker")
    finally:
        server.stop()
    return {"serve_guard": engine.guard_stats.summary()}


def multihost_chaos(failures):
    """Mechanism 6: a dead peer (collectives that never complete) must
    fail the survivor fast with HostDesyncError — resumable exit — not
    park it in the collective forever. Simulated by patching the jax
    multihost utils; restored before returning."""
    import jax
    from jax.experimental import multihost_utils

    from lir_tpu.parallel import multihost

    saved = (jax.process_count, jax.process_index,
             multihost_utils.sync_global_devices,
             multihost_utils.process_allgather)

    def parked(*a, **k):
        time.sleep(60)

    jax.process_count = lambda: 2
    jax.process_index = lambda: 0
    multihost_utils.sync_global_devices = parked
    multihost_utils.process_allgather = parked
    try:
        t0 = time.monotonic()
        try:
            multihost.liveness_barrier("chaos-shard-done", timeout_s=0.5,
                                       payload=3)
            failures.append("dead-peer barrier returned instead of "
                            "raising HostDesyncError")
        except multihost.HostDesyncError:
            pass
        elapsed = time.monotonic() - t0
        if elapsed > 10.0:
            failures.append(
                f"dead-peer detection took {elapsed:.1f}s — survivor "
                f"nearly hung")
    finally:
        (jax.process_count, jax.process_index,
         multihost_utils.sync_global_devices,
         multihost_utils.process_allgather) = saved
    return {"desync_detect_s": round(elapsed, 2)}


def elastic_chaos(failures):
    """Scenario 8 (elastic serving): (a) a LEASED sweep killed mid-run
    is finished by a DIFFERENT holder stealing the expired leases, and
    the final accumulator is bitwise-identical to an uninterrupted
    static run; (b) a straggler replica behind the router
    (replica_lag) loses the hedge race and its late payload is dropped
    — zero requests lost or double-resolved."""
    import tempfile

    import jax
    import numpy as np

    from lir_tpu import faults
    from lir_tpu.config import RouterConfig, RuntimeConfig, ServeConfig
    from lir_tpu.engine import lease as lease_mod
    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.serve import ReplicaRouter, ServeRequest

    lp, perts = _grid(N_CELLS)
    out = {}
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        # (a) static baseline, then a leased run killed mid-sweep; a
        # SECOND holder (host1 via a patched process_index) resumes
        # after the short TTL expired and STEALS the dead holder's
        # shards.
        run_perturbation_sweep(_make_engine(), "elastic", lp, perts,
                               td / "static.csv", checkpoint_every=4)
        acc_static = stream_mod.load_accum(
            (td / "static.csv").with_suffix(stream_mod.ACCUM_SUFFIX))

        def leased_engine():
            from lir_tpu.backends.fake import FakeTokenizer
            from lir_tpu.engine.runner import ScoringEngine
            from lir_tpu.models import decoder
            from lir_tpu.models.registry import ModelConfig

            mcfg = ModelConfig(name="chaos-smoke",
                               vocab_size=FakeTokenizer.VOCAB,
                               hidden_size=32, n_layers=1, n_heads=2,
                               intermediate_size=64, max_seq_len=256)
            params = decoder.init_params(mcfg, jax.random.PRNGKey(11))
            return ScoringEngine(
                params, mcfg, FakeTokenizer(),
                RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                              piggyback_prefill=False,
                              lease_shards=True, lease_ttl_s=0.05,
                              lease_cells_per_shard=3))

        engine = leased_engine()
        plan = faults.FaultPlan(seed=9, schedules={
            "dispatch": faults.SiteSchedule.kill_at(1)})
        faults.wrap_engine(engine, plan)
        leased_out = td / "leased.csv"
        try:
            run_perturbation_sweep(engine, "elastic", lp, perts,
                                   leased_out, checkpoint_every=4)
            failures.append("elastic: scheduled kill never fired")
            return {}
        except faults.InjectedPreemption:
            pass
        time.sleep(0.06)            # the dead holder's leases expire
        saved_idx = jax.process_index
        jax.process_index = lambda: 1       # the stealing holder
        try:
            run_perturbation_sweep(leased_engine(), "elastic", lp,
                                   perts, leased_out,
                                   checkpoint_every=4)
        finally:
            jax.process_index = saved_idx
        acc = stream_mod.load_accum(
            leased_out.with_suffix(stream_mod.ACCUM_SUFFIX))
        same = (acc is not None and acc_static is not None
                and np.array_equal(acc_static.filled, acc.filled)
                and np.array_equal(acc_static.rel, acc.rel,
                                   equal_nan=True)
                and np.array_equal(acc_static.conf, acc.conf,
                                   equal_nan=True)
                and np.array_equal(acc_static.dec, acc.dec))
        if not same:
            failures.append("elastic: leased steal-resumed accumulator "
                            "NOT bitwise-identical to the static run")
        check = lease_mod.LeaseManager(
            leased_out.with_suffix(lease_mod.LEASE_SUFFIX), "checker")
        n_shards = -(-N_CELLS // 3)
        if not all(check.is_done(s) for s in range(n_shards)):
            failures.append("elastic: lease log does not show every "
                            "shard done after the steal-resume")
        holders = {(check.record(s) or {}).get("holder")
                   for s in range(n_shards)}
        if "host1" not in holders:
            failures.append(f"elastic: no shard finished by the "
                            f"stealing holder ({holders})")
        out["lease_holders"] = sorted(h for h in holders if h)

    # (b) straggler replica: r0 lags 1.5s on a dispatch; the hedge
    # fires within the deadline whisker, the fast replica wins, and
    # the straggler's late payload is dropped.
    serve_cfg = ServeConfig(queue_depth=64, classes=(("smoke", 600.0),),
                            default_class="smoke", linger_s=0.0)
    servers = [_serve_server(serve_cfg, seed) for seed in (11, 11)]
    for s in servers:
        s.start()
    body = "clause 9 covers wind damage under policy 63"

    def lag_req(tag, i, deadline_s=None):
        return ServeRequest(
            binary_prompt=f"{body} {i} Answer Yes or No .",
            confidence_prompt=f"{body} {i} Give a number from 0 to "
                              f"100 .",
            klass="smoke", deadline_s=deadline_s,
            request_id=f"{tag}{i}")

    # Warm both replicas DIRECTLY — two requests each, so BOTH
    # cache-handoff variants (cold + warm donated) compile before the
    # timed phase and the lagged run measures the lag, not a trace.
    for si, s in enumerate(servers):
        for w in (97, 99):
            if s.submit(lag_req(f"warm{si}-", w)).result(60) \
                    .status != "ok":
                failures.append("elastic: straggler warmup failed")
    router = ReplicaRouter(
        [("r0", servers[0]), ("r1", servers[1])],
        config=RouterConfig(hedge_s=1.9, tick_s=0.01,
                            cache_entries=0)).start()
    lag_plan = faults.FaultPlan(seed=4, schedules={
        "replica": faults.SiteSchedule.replica_lag_at(0, 1.5, "r0")})
    faults.wrap_replica(router, "r0", lag_plan)
    try:
        futs = [router.submit(lag_req("lag", i, deadline_s=2.0))
                for i in range(4)]
        res = [f.result(timeout=60) for f in futs]
        # Wait for the straggler to finish and resolve LATE (observed
        # and dropped), bounded well past the lag.
        deadline = time.monotonic() + 10.0
        while (router.stats.hedge_losses + router.stats.zombie_payloads
               < 1 and time.monotonic() < deadline):
            time.sleep(0.05)
    finally:
        router.stop()
        for s in servers:
            s.stop()
    if not all(r.status == "ok" for r in res):
        failures.append(f"elastic: straggler run statuses "
                        f"{[r.status for r in res]}")
    if len({r.request_id for r in res}) != 4:
        failures.append("elastic: duplicated straggler results")
    if lag_plan.injected("replica") != 1:
        failures.append("elastic: replica_lag never fired")
    if router.stats.hedged < 1:
        failures.append("elastic: straggler was never hedged")
    if router.stats.hedge_losses + router.stats.zombie_payloads < 1:
        failures.append("elastic: the straggler's late payload was "
                        "never observed-and-dropped")
    if router.stats.completed != 4:
        failures.append(f"elastic: router completed "
                        f"{router.stats.completed} != 4")
    out["router"] = router.stats.summary()
    return out


def _serve_server(cfg, seed):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    mcfg = ModelConfig(name="elastic-serve",
                       vocab_size=FakeTokenizer.VOCAB, hidden_size=32,
                       n_layers=1, n_heads=2, intermediate_size=64,
                       max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(seed))
    from lir_tpu.serve import ScoringServer

    engine = ScoringEngine(params, mcfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=BATCH,
                                         max_seq_len=256))
    return ScoringServer(engine, "elastic-serve", cfg)


def spec_chaos(failures):
    """Mechanism 9 (speculative decode): a seeded ``draft_corrupt``
    fault poisons the tree-probed draft tokens BEFORE verification —
    a bad draft must only cost re-verification: sweep rows stay
    bitwise equal to the fault-free run, and SpecStats.rejected_tokens
    counts the injected garbage."""
    import tempfile

    import jax

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="chaos-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(11))

    def spec_engine():
        # prefix cache ON so the tree-continuation drafter has a token
        # history to draft (and corrupt) from; piggyback OFF as in
        # _make_engine (bitwise comparisons need the plain path).
        return ScoringEngine(params, cfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=BATCH,
                                           max_seq_len=256,
                                           piggyback_prefill=False,
                                           prefix_cache=True,
                                           prefix_cache_pages=128))

    lp, perts = _grid(N_CELLS)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        eng_clean = spec_engine()
        run_perturbation_sweep(eng_clean, "chaos", lp, perts,
                               td / "warm.csv", checkpoint_every=4)
        # Same engine, same grid again: the tree now drafts every row's
        # whole continuation — the speculation-friendly repeat pass.
        clean = run_perturbation_sweep(eng_clean, "chaos", lp, perts,
                                       td / "clean.csv",
                                       checkpoint_every=4)
        eng_clean.spec_flush()
        if eng_clean.spec_stats.accepted_tokens <= 0:
            failures.append("spec: warm repeat pass accepted no drafts")
            return {}
        clean_df = schemas.read_results_frame(td / "clean.csv")
        clean_by_key = {
            (row["Rephrased Main Part"], row["Response Format"],
             row["Confidence Format"]): tuple(
                row[c] for c in _VALUE_COLUMNS)
            for _, row in clean_df.iterrows()}

        eng = spec_engine()
        run_perturbation_sweep(eng, "chaos", lp, perts, td / "warm2.csv",
                               checkpoint_every=4)
        plan = faults.FaultPlan(seed=31, schedules={
            "draft": faults.SiteSchedule.draft_corrupt_at(0, rows=(0, 1)),
        }, stats=eng.fault_stats)
        faults.wrap_engine(eng, plan)
        chaos = run_perturbation_sweep(eng, "chaos", lp, perts,
                                       td / "chaos.csv",
                                       checkpoint_every=4)
        eng.spec_flush()
        if plan.injected("draft") < 1:
            failures.append("spec: scheduled draft_corrupt never fired")
            return {}
        if eng.spec_stats.rejected_tokens < 1:
            failures.append("spec: corrupted drafts were never rejected")
        if len(chaos) != len(clean):
            failures.append(
                f"spec: corrupted run produced {len(chaos)} rows vs "
                f"{len(clean)} clean")
        df = schemas.read_results_frame(td / "chaos.csv")
        import pandas as pd

        for _, row in df.iterrows():
            k = (row["Rephrased Main Part"], row["Response Format"],
                 row["Confidence Format"])
            want = clean_by_key.get(k)
            if want is None:
                failures.append(f"spec: invented row {k[0][:40]}")
                continue
            got = tuple(row[c] for c in _VALUE_COLUMNS)
            for g, w in zip(got, want):
                if pd.isna(g) and pd.isna(w):
                    continue
                if g != w:
                    failures.append(
                        f"spec: corrupted-draft row differs from the "
                        f"fault-free run: {g!r} != {w!r} for {k[0][:40]}")
                    break
        return {"injected_draft": plan.injected("draft"),
                "rejected_tokens": int(eng.spec_stats.rejected_tokens),
                "accept_rate": round(eng.spec_stats.accept_rate, 4)}


def hbm_chaos(failures):
    """Scenario 10 (OOM squeeze — engine/hbm.py): a seeded
    ``hbm_squeeze`` shrinks the HBM governor's ledger budget mid-sweep
    AND mid-serve. The contract: zero crashed dispatches, every
    engaged degradation rung REVERSIBLE (counters show down AND up
    transitions, ladder back at level 0), consumed rows and serve
    payloads bitwise-identical to an unpressured run, and the governor
    gauges visible in the metrics snapshot. A real-OOM stand-in
    (RESOURCE_EXHAUSTED raised once mid-serve) must route through the
    governor's reclaim-and-retry: the request still serves ok and the
    circuit breaker never hears about it."""
    import tempfile

    import jax
    import pandas as pd

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import (GovernorConfig, RetryConfig,
                                RuntimeConfig, ServeConfig)
    from lir_tpu.data import schemas
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    mcfg = ModelConfig(name="chaos-smoke", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(11))

    def gov_engine():
        # piggyback OFF: squeeze-vs-clean comparisons are bitwise (see
        # _make_engine); sustain 1 so the smoke's handful of dispatch
        # ticks is enough ladder walking.
        return ScoringEngine(
            params, mcfg, FakeTokenizer(),
            RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                          piggyback_prefill=False),
            governor_config=GovernorConfig(sustain_ticks=1))

    def drain(gov, max_ticks=16):
        # the ticks a longer-running session's next dispatches supply
        for _ in range(max_ticks):
            if gov.level == 0:
                return
            gov.tick()

    def check_reversible(gov, leg):
        if not gov.stats.rung_downs:
            failures.append(f"hbm[{leg}]: squeeze never walked the "
                            f"ladder down")
        drain(gov)
        if gov.level != 0:
            failures.append(f"hbm[{leg}]: ladder stuck at level "
                            f"{gov.level}")
        if gov.stats.rung_ups != gov.stats.rung_downs:
            failures.append(
                f"hbm[{leg}]: rungs not reversible (downs "
                f"{gov.stats.rung_downs} vs ups {gov.stats.rung_ups})")

    out = {}
    lp, perts = _grid(N_CELLS)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        run_perturbation_sweep(gov_engine(), "chaos", lp, perts,
                               td / "clean.csv", checkpoint_every=4)
        clean_df = schemas.read_results_frame(td / "clean.csv")
        clean_by_key = {
            (r["Rephrased Main Part"], r["Response Format"],
             r["Confidence Format"]): tuple(
                r[c] for c in _VALUE_COLUMNS)
            for _, r in clean_df.iterrows()}

        engine = gov_engine()
        plan = faults.FaultPlan(seed=19, schedules={
            "hbm": faults.SiteSchedule.hbm_squeeze_at(1, frac=0.05,
                                                      calls=3)})
        faults.wrap_governor(engine.governor, plan)
        run_perturbation_sweep(engine, "chaos", lp, perts,
                               td / "squeezed.csv", checkpoint_every=4)
        if plan.injected("hbm") != 1:
            failures.append("hbm: scheduled mid-sweep squeeze never "
                            "fired")
        check_reversible(engine.governor, "sweep")
        df = schemas.read_results_frame(td / "squeezed.csv")
        keys = list(zip(df["Rephrased Main Part"],
                        df["Response Format"], df["Confidence Format"]))
        if len(keys) != N_CELLS or len(set(keys)) != N_CELLS:
            failures.append(
                f"hbm: squeezed sweep crashed/duplicated dispatch rows "
                f"({len(keys)} rows, {len(set(keys))} unique)")
        for _, row in df.iterrows():
            k = (row["Rephrased Main Part"], row["Response Format"],
                 row["Confidence Format"])
            want = clean_by_key.get(k)
            got = tuple(row[c] for c in _VALUE_COLUMNS)
            if want is None:
                failures.append(f"hbm: invented row {k[0][:40]}")
                continue
            for g, w in zip(got, want):
                if pd.isna(g) and pd.isna(w):
                    continue
                if g != w:
                    failures.append(
                        f"hbm: squeezed row differs from the "
                        f"unpressured run: {g!r} != {w!r} for "
                        f"{k[0][:40]}")
                    break
        # Governor gauges in the per-sweep metrics snapshot — the same
        # canonical document the serve metrics endpoint answers.
        from lir_tpu.observe import registry as metrics_mod

        snap = metrics_mod.engine_registry(engine).snapshot(
            device_memory=False)
        if snap["sources"].get("mem", {}).get("type") != "MemStats":
            failures.append("hbm: governor gauges missing from the "
                            "sweep metrics snapshot")
        out["sweep_mem"] = engine.governor.summary()

    # -- mid-serve squeeze + one real-OOM stand-in ---------------------------
    cfg = ServeConfig(
        queue_depth=64, classes=(("smoke", 600.0),),
        default_class="smoke", linger_s=0.0, cache_entries=0,
        max_consecutive_failures=2,
        retry=RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5))

    def request(i, rid=None):
        body = f"clause {i} covers wind damage under policy {i * 7}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=rid or str(i))

    fields = ("model_response", "model_confidence_response",
              "token_1_prob", "token_2_prob", "log_probabilities",
              "confidence_value", "weighted_confidence")

    def serve_all(server, tag):
        payloads = {}
        for i in range(10):
            r = server.submit(request(i, f"{tag}{i}")).result(timeout=60)
            if r.status != "ok":
                failures.append(f"hbm[serve]: request {i} resolved "
                                f"{r.status} ({r.note!r})")
                continue
            payloads[i] = tuple(getattr(r, f) for f in fields)
        return payloads

    base = ScoringServer(gov_engine(), "chaos", cfg).start()
    try:
        baseline = serve_all(base, "b")
    finally:
        base.stop()

    engine = gov_engine()
    plan = faults.FaultPlan(seed=29, schedules={
        "hbm": faults.SiteSchedule.hbm_squeeze_at(2, frac=0.05,
                                                  calls=3)})
    faults.wrap_governor(engine.governor, plan)
    server = ScoringServer(engine, "chaos", cfg)
    # One real-OOM stand-in on dispatch call 6 (after the squeeze
    # cleared): must reclaim-and-retry, never feed the breaker.
    real_score = server.batcher.score
    state = {"n": 0}

    def oom_once(bucket, rows):
        state["n"] += 1
        if state["n"] == 7:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected device OOM (chaos 10)")
        return real_score(bucket, rows)

    server.batcher.score = oom_once
    server.start()
    try:
        squeezed = serve_all(server, "s")
        snap = server.metrics.snapshot(device_memory=False)
    finally:
        server.stop()
    gov = engine.governor
    if plan.injected("hbm") != 1:
        failures.append("hbm: scheduled mid-serve squeeze never fired")
    if gov.stats.oom_events.get("serve", 0) != 1:
        failures.append("hbm: injected serve OOM never reached the "
                        "governor")
    if gov.stats.oom_reclaims != 1:
        failures.append("hbm: serve OOM was not reclaim-and-retried")
    if server.breaker.consecutive_failures != 0 or not server.healthy:
        failures.append("hbm: a device OOM fed the circuit breaker")
    check_reversible(gov, "serve")
    if "mem" not in snap.get("sources", {}):
        failures.append("hbm: governor gauges missing from the serve "
                        "metrics snapshot")
    for i, want in baseline.items():
        got = squeezed.get(i)
        if got is not None and got != want:
            failures.append(f"hbm: squeezed serve payload {i} differs "
                            f"from the unpressured server")
    out["serve_mem"] = gov.summary()
    return out


def disagg_chaos(failures):
    """Scenario 11 (migration stall/corrupt — serve/migrate.py): a
    1-prefill + 2-decode disaggregated router under seeded transfer
    chaos. ``migration_corrupt`` flips chunk bytes under the export's
    checksums — the import must detect the mismatch and land ZERO
    pages (destination refcounts/tree rolled back); ``migration_stall``
    wedges the wire hop past the chain deadline — the tick must
    abandon it. Both requests fall back to LOCAL re-prefill on the
    decode replica and resolve ok with payloads bitwise-identical to a
    colocated server's: fallbacks == injections, never a wrong
    answer."""
    import jax

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import (MigrationConfig, RouterConfig,
                                RuntimeConfig, ServeConfig)
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ReplicaRouter, ScoringServer, ServeRequest

    mcfg = ModelConfig(name="chaos-smoke", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(11))
    scfg = ServeConfig(classes=(("chaos", 600.0),),
                       default_class="chaos", linger_s=0.002)

    def server():
        return ScoringServer(
            ScoringEngine(params, mcfg, FakeTokenizer(),
                          RuntimeConfig(batch_size=BATCH,
                                        max_seq_len=256)),
            "chaos-smoke", scfg)

    words = ("coverage policy flood water damage claim insurer "
             "premium").split()

    def req(seed, rid):
        import numpy as np

        rng = np.random.default_rng(seed)
        body = " ".join(rng.choice(words) for _ in range(55)) + f" q{rid}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="chaos", request_id=str(rid))

    reqs = [req(101, "corrupt"), req(202, "stall")]
    colo = server().start()
    base = [colo.submit(r).result(300) for r in reqs]
    colo.stop()

    servers = [server().start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        # Generous chain deadline: the stall kind RAISES on release, so
        # the fallback is exercised deterministically even on a loaded
        # CI box (the deadline-abandonment variant is pinned by
        # tests/test_migrate.py with a tight timeout).
        migrate=MigrationConfig(min_prefix_tokens=16, chunk_pages=2,
                                timeout_s=30.0)).start()
    fields = ("model_response", "model_confidence_response",
              "token_1_prob", "token_2_prob", "log_probabilities",
              "confidence_value", "weighted_confidence")
    try:
        plan_c = faults.FaultPlan(seed=3, schedules={
            "migrate": faults.SiteSchedule.migration_corrupt_at(0)})
        faults.wrap_migrator(router.migrator, plan_c)
        got = router.submit(reqs[0]).result(300)
        if got.status != "ok":
            failures.append(f"disagg: corrupt-transfer request "
                            f"resolved {got.status}")
        for f in fields:
            if getattr(got, f) != getattr(base[0], f):
                failures.append(f"disagg: corrupt-fallback payload "
                                f"field {f} differs from colocated")
        if router.migrate_stats.corrupt_chunks != 1:
            failures.append("disagg: corrupt chunk not detected")
        # every decode replica's refcounts stayed sane (rollback)
        for s in servers[1:]:
            rc = s.engine.prefix_cache.pool.refcount
            if not (rc >= 0).all():
                failures.append("disagg: negative refcount after "
                                "corrupt-import rollback")

        # Unwrap the corrupt schedule before arming the stall one so
        # each phase fires exactly its own kind.
        router.migrator.transfer = getattr(
            router.migrator.transfer, "__wrapped__",
            router.migrator.transfer)
        plan_s = faults.FaultPlan(seed=4, schedules={
            "migrate": faults.SiteSchedule.migration_stall_at(
                0, seconds=0.8)})
        faults.wrap_migrator(router.migrator, plan_s)
        got2 = router.submit(reqs[1]).result(300)
        if got2.status != "ok":
            failures.append(f"disagg: stalled-transfer request "
                            f"resolved {got2.status}")
        for f in fields:
            if getattr(got2, f) != getattr(base[1], f):
                failures.append(f"disagg: stall-fallback payload "
                                f"field {f} differs from colocated")
        ms = router.migrate_stats
        injected = (plan_c.injected("migrate")
                    + plan_s.injected("migrate"))
        if injected != 2:
            failures.append(f"disagg: expected 2 injections, "
                            f"got {injected}")
        if ms.refetch_fallbacks != injected:
            failures.append(f"disagg: fallbacks {ms.refetch_fallbacks} "
                            f"!= injections {injected}")
        if ms.stalls < 1:
            failures.append("disagg: stall never counted")
        return ms.summary()
    finally:
        router.stop()
        for s in servers:
            s.stop()


def tiers_chaos(failures):
    """Scenario 12 (tier corrupt/stall — serve/tiers.py): a tiered
    server whose whole radix tree was demoted down the ladder, under
    seeded promote chaos. ``tier_corrupt`` flips the demoted bytes
    under the export's checksums — the promote must refuse before any
    page lands and DROP the entry; ``disk_stall`` wedges the disk read
    past ``disk_timeout_s`` — the promote is abandoned but the entry
    KEPT. Both re-asks fall back to local re-prefill and resolve ok
    with payloads bitwise an untiered server's: never a wrong
    answer."""
    import tempfile

    import jax

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig, TierConfig
    from lir_tpu.engine import tokens as tok
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    mcfg = ModelConfig(name="chaos-smoke", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(11))
    # cache_entries=0: exact-dedup would answer the chaos re-asks from
    # the result cache and the tier promote would never run.
    scfg = ServeConfig(classes=(("chaos", 600.0),), default_class="chaos",
                       prefix_cache=True, cache_entries=0, linger_s=0.002)

    def engine():
        return ScoringEngine(params, mcfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=BATCH,
                                           max_seq_len=256,
                                           prefix_cache=True))

    words = ("coverage policy flood water damage claim insurer "
             "premium").split()

    def req(seed, rid):
        import numpy as np

        rng = np.random.default_rng(seed)
        body = " ".join(rng.choice(words) for _ in range(55)) + f" q{rid}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="chaos", request_id=str(rid))

    reqs = [req(101, "tier-corrupt"), req(202, "disk-stall")]
    colo = ScoringServer(engine(), "chaos-smoke", scfg).start()
    base = [colo.submit(r).result(300) for r in reqs]
    colo.stop()

    fields = ("model_response", "model_confidence_response",
              "token_1_prob", "token_2_prob", "log_probabilities",
              "confidence_value", "weighted_confidence")
    with tempfile.TemporaryDirectory(prefix="tiers_chaos_") as tmp:
        # Tiny host pool: every demotion spills through to the disk
        # tier, so the stall leg exercises the disk deadline. Generous
        # timeout vs a 2 s injected stall: a healthy few-KB read never
        # takes 500 ms, the wedged one always abandons.
        srv = ScoringServer(
            engine(), "chaos-smoke", scfg,
            tiers=TierConfig(enabled=True, disk_dir=tmp,
                             host_budget_mb=0.0001,
                             disk_timeout_s=0.5)).start()
        store = srv.tiers
        try:
            cold = [srv.submit(r).result(300) for r in reqs]
            if any(r.status != "ok" for r in cold):
                failures.append("tiers: cold pass not all ok")
            srv.submit_page_op(
                lambda eng: [store.demote(eng, n_pages=999)
                             for _ in range(8)]).result(60)
            if not store.summary()["pages_demoted"]:
                failures.append("tiers: nothing demoted — chaos legs "
                                "have no ladder to attack")

            plan_c = faults.FaultPlan(seed=3, schedules={
                "tiers": faults.SiteSchedule.tier_corrupt_at(0)})
            faults.wrap_tiers(store, plan_c)
            got = srv.submit(reqs[0]).result(300)
            if got.status != "ok":
                failures.append(f"tiers: corrupt-promote request "
                                f"resolved {got.status}")
            for f in fields:
                if getattr(got, f) != getattr(base[0], f):
                    failures.append(f"tiers: corrupt-fallback payload "
                                    f"field {f} differs from untiered")
            if store.summary()["checksum_refusals"] != 1:
                failures.append("tiers: corrupt promote not refused")

            # Unwrap the corrupt schedule before arming the stall one
            # so each phase fires exactly its own kind.
            store.transfer = getattr(store.transfer, "__wrapped__",
                                     store.transfer)
            plan_s = faults.FaultPlan(seed=4, schedules={
                "tiers": faults.SiteSchedule.disk_stall_at(
                    0, seconds=2.0)})
            faults.wrap_tiers(store, plan_s)
            got2 = srv.submit(reqs[1]).result(300)
            if got2.status != "ok":
                failures.append(f"tiers: stalled-promote request "
                                f"resolved {got2.status}")
            for f in fields:
                if getattr(got2, f) != getattr(base[1], f):
                    failures.append(f"tiers: stall-fallback payload "
                                    f"field {f} differs from untiered")
            summary = store.summary()
            if summary["disk_stalls"] != 1:
                failures.append("tiers: disk stall never counted")
            injected = (plan_c.injected("tiers")
                        + plan_s.injected("tiers"))
            if injected != 2:
                failures.append(f"tiers: expected 2 injections, "
                                f"got {injected}")
            # The stalled entry survived (kept); the corrupt one is
            # gone (dropped) — a wedged read is not corruption.
            e = srv.engine
            bi = tuple(int(i) for i in e.tokenizer(
                reqs[1].binary_prompt).input_ids)
            ci = tuple(int(i) for i in e.tokenizer(
                reqs[1].confidence_prompt).input_ids)
            lcp = tok.shared_prefix_len(bi, ci)
            bucket = tok.assign_bucket(max(lcp, 1), e.buckets)
            if store.match_len(bucket, bi[:lcp]) <= 0:
                failures.append("tiers: stalled entry was dropped — "
                                "a transient stall is not corruption")
            return summary
        finally:
            srv.stop()


def main() -> int:
    failures = []
    sweep_summary = sweep_chaos(failures)
    serve_summary = serve_chaos(failures)
    guard_summary = guard_chaos(failures)
    serve_guard_summary = serve_guard_chaos(failures)
    mh_summary = multihost_chaos(failures)
    stream_summary = stream_accum_chaos(failures)
    elastic_summary = elastic_chaos(failures)
    spec_summary = spec_chaos(failures)
    hbm_summary = hbm_chaos(failures)
    disagg_summary = disagg_chaos(failures)
    tiers_summary = tiers_chaos(failures)
    if failures:
        for f in failures:
            print(f"CHAOS-SMOKE FAIL: {f}")
        return 1
    print(json.dumps({"sweep": sweep_summary, "serve": serve_summary,
                      "guard": guard_summary,
                      "serve_guard": serve_guard_summary,
                      "multihost": mh_summary,
                      "stream": stream_summary,
                      "elastic": elastic_summary,
                      "spec": spec_summary,
                      "hbm": hbm_summary,
                      "disagg": disagg_summary,
                      "tiers": tiers_summary}))
    print("chaos smoke: OK (sweep resumed bitwise-identical after "
          "injected kill + torn manifest; breaker tripped and recovered "
          "via half-open probe; poison row isolated; checkpoint resume "
          "lost nothing; injected hang stalled-out within its deadline "
          "and recovered; NaN rows quarantined as error:numerics with "
          "clean rows bitwise-identical; dead peer detected within the "
          "liveness timeout; resume-merged streaming accumulators "
          "bitwise-identical to an uninterrupted run; leased shards "
          "stolen by a live holder converge bitwise on the static run "
          "and a straggler replica's late payload is dropped, never "
          "double-resolved; corrupted speculative drafts cost only "
          "re-verification — rows bitwise, rejections counted; an "
          "hbm_squeeze walked the degradation ladder down and back up "
          "mid-sweep and mid-serve with zero crashed dispatches, rows "
          "and payloads bitwise vs unpressured runs, and a device OOM "
          "reclaim-and-retried without feeding the breaker; a "
          "corrupted page migration was refused at import and a "
          "stalled one abandoned at the chain deadline, both falling "
          "back to local re-prefill with payloads bitwise a colocated "
          "server's; a corrupted tier promote was refused under its "
          "checksums with the poisoned entry dropped and a stalled "
          "disk-tier read abandoned past its deadline with the entry "
          "kept, both re-asks re-prefilled bitwise an untiered "
          "server's)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
