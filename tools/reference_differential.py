"""Capture golden outputs by EXECUTING the reference's analysis scripts.

VERDICT r1 #2: the strongest parity evidence available in a zero-egress
environment is to actually run the reference's CPU-runnable analysis code on
the committed data CSVs and diff our artifacts against its outputs. This
tool does that:

  1. Builds a sandbox under /tmp, copies four reference scripts into it and
     applies ONLY mechanical environment patches (the patched copies stay in
     /tmp — nothing from the reference tree enters this repo):
       - hard-coded personal paths ("G:/My Drive/...") -> "."
         (SURVEY.md §5 config: the reference has no path flags)
       - pd.read_excel -> pd.read_csv + the .xlsx filename -> .csv
         (this image has no openpyxl; values are unaffected)
  2. Stages identical inputs for both sides:
       - the committed D2/D3 CSVs from /root/reference/data
       - a deterministic synthetic D6 (lir_tpu.data.synthetic — the real D6
         is a generated artifact the upstream repo never committed)
       - D7 (survey_analysis_detailed.json) regenerated from D3 by OUR
         loader — both the reference bootstrap script and our D9 writer
         consume this same file
  3. Runs each script (subprocess, cwd=sandbox, Agg backend), collects every
     numeric artifact they write plus full-precision values from direct
     function calls, and writes tests/golden/reference_executed.json.

tests/test_reference_differential.py then diffs lir_tpu's own outputs
against that JSON under the ≤1% gate (BASELINE.json north star).

Run:  python tools/reference_differential.py
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
REF = Path("/root/reference")
SANDBOX = Path("/tmp/lir_ref_differential")
GOLDEN = REPO / "tests" / "golden" / "reference_executed.json"

SCRIPTS = {
    "model_comparison_graph.py": REF / "analysis/model_comparison_graph.py",
    "calculate_cohens_kappa.py": REF / "analysis/calculate_cohens_kappa.py",
    "survey_analysis_consolidated.py":
        REF / "survey_analysis/survey_analysis_consolidated.py",
    "analyze_llm_agreement_simple_bootstrap.py":
        REF / "survey_analysis/analyze_llm_agreement_simple_bootstrap.py",
    "analyze_perturbation_results.py":
        REF / "analysis/analyze_perturbation_results.py",
    "analyze_results_base_versus_instruct.py":
        REF / "analysis/analyze_results_base_versus_instruct.py",
    "analyze_llm_human_agreement.py":
        REF / "survey_analysis/analyze_llm_human_agreement.py",
    "analyze_model_family_differences.py":
        REF / "survey_analysis/analyze_model_family_differences.py",
    "calculate_correlation_pvalues.py":
        REF / "survey_analysis/calculate_correlation_pvalues.py",
    "analyze_base_vs_instruct_vs_human.py":
        REF / "survey_analysis/analyze_base_vs_instruct_vs_human.py",
    "bootstrap_confidence_intervals.py":
        REF / "survey_analysis/bootstrap_confidence_intervals.py",
}

_GDRIVE_DIR = re.compile(r"G:/My Drive/Computational/llm_interpretation/")
_GDRIVE = re.compile(r"G:/My Drive/Computational/llm_interpretation")


def _patch(text: str) -> str:
    text = _GDRIVE_DIR.sub("./", text)
    text = _GDRIVE.sub(".", text)
    text = text.replace("pd.read_excel", "pd.read_csv")
    text = text.replace(".to_excel(", ".to_csv(")
    text = text.replace("combined_results.xlsx", "combined_results.csv")
    text = text.replace("results_30_multi_model.xlsx", "combined_results.csv")
    return text


def stage_sandbox() -> None:
    if SANDBOX.exists():
        shutil.rmtree(SANDBOX)
    SANDBOX.mkdir(parents=True)
    for name, src in SCRIPTS.items():
        (SANDBOX / name).write_text(_patch(src.read_text()))
    for csv in ("instruct_model_comparison_results.csv",
                "model_comparison_results.csv",
                "word_meaning_survey_results.csv"):
        shutil.copy(REF / "data" / csv, SANDBOX / csv)

    from lir_tpu.data import synthetic
    synthetic.write_synthetic_d6(SANDBOX / "combined_results.csv")

    # D7 from OUR loader — the same file our D9 pipeline consumes.
    from lir_tpu.survey import loader
    survey_df, qcols = loader.load_survey(SANDBOX / "word_meaning_survey_results.csv")
    clean_df, _ = loader.apply_exclusions(survey_df, qcols)
    loader.write_survey_detailed(
        clean_df, qcols, SANDBOX / "survey_analysis_detailed.json")


def _run(script: str, timeout: int = 3600) -> str:
    env = dict(os.environ, MPLBACKEND="Agg", PYTHONHASHSEED="0")
    proc = subprocess.run(
        [sys.executable, script], cwd=SANDBOX, env=env, timeout=timeout,
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{script} failed rc={proc.returncode}\n--- stdout\n"
            f"{proc.stdout[-4000:]}\n--- stderr\n{proc.stderr[-4000:]}")
    return proc.stdout


_GRAPH_DRIVER = """
import json, sys
import numpy as np, pandas as pd
sys.path.insert(0, ".")
import model_comparison_graph as g

df = pd.read_csv("instruct_model_comparison_results.csv")
df = df[~df["model"].str.contains("opt-iml-1.3b")]
df = df[~df["model"].str.contains("mistral", case=False)]

out = {}
for corr_type in ("pearson", "spearman"):
    s = g.calculate_model_correlations(df, correlation_type=corr_type,
                                       n_bootstrap=1000)
    out[corr_type] = {
        "mean_correlation": s["mean_correlation"],
        "median_correlation": s["median_correlation"],
        "std_correlation": s["std_correlation"],
        "min_correlation": s["min_correlation"],
        "max_correlation": s["max_correlation"],
        "mean_ci": list(s["mean_ci"]),
        "median_ci": list(s["median_ci"]),
        "std_ci": list(s["std_ci"]),
        "correlation_matrix": s["correlation_matrix"].values.tolist(),
        "models": list(s["correlation_matrix"].columns),
    }
k = g.calculate_aggregate_cohens_kappa(df)
out["aggregate_kappa"] = {key: (float(val) if np.isscalar(val) else val)
                          for key, val in k.items()
                          if isinstance(val, (int, float, np.floating, np.integer))}
json.dump(out, open("graph_golden.json", "w"), indent=1)
print("graph driver ok")
"""


def capture() -> dict:
    golden: dict = {"_provenance": {
        "generated_by": "tools/reference_differential.py",
        "reference_snapshot": "/root/reference @ 2025-09-12",
        "inputs": {
            "instruct_csv": "reference data/instruct_model_comparison_results.csv",
            "base_csv": "reference data/model_comparison_results.csv",
            "survey_csv": "reference data/word_meaning_survey_results.csv",
            "perturbation_d6": "lir_tpu.data.synthetic (seed 20260730)",
            "survey_detailed_d7": "lir_tpu.survey.loader.write_survey_detailed",
        },
        "patches": "paths G:/->. ; read_excel->read_csv (no openpyxl)",
    }}

    (SANDBOX / "graph_driver.py").write_text(_GRAPH_DRIVER)
    _run("graph_driver.py")
    golden["model_comparison_graph"] = json.loads(
        (SANDBOX / "graph_golden.json").read_text())

    _run("calculate_cohens_kappa.py")
    kdir = SANDBOX / "output/kappa_analysis"
    import pandas as pd
    golden["calculate_cohens_kappa"] = {
        stem: pd.read_csv(kdir / f"{stem}.csv").to_dict(orient="list")
        for stem in ("model_kappa_metrics", "perturbation_kappa_metrics",
                     "model_legal_kappas", "perturbation_legal_kappas",
                     "combined_kappa_results")
    }

    _run("survey_analysis_consolidated.py")
    golden["survey_consolidated"] = json.loads(
        (SANDBOX / "consolidated_analysis_results.json").read_text())

    _run("analyze_llm_agreement_simple_bootstrap.py")
    golden["llm_human_agreement_bootstrap"] = json.loads(
        (SANDBOX / "llm_human_agreement_bootstrap.json").read_text())

    # The 2,025-line perturbation analyzer (C20-C27 in one script): per-model
    # summary stats, KS/AD normality, the zero/one-inflated truncated-normal
    # MC fit, within-prompt kappa, and both compliance checkers — run on the
    # synthetic D6 whose edge model exercises every hairy branch.
    from lir_tpu.data.synthetic import SYNTH_EDGE_MODEL, SYNTH_MODEL
    _run("analyze_perturbation_results.py")
    pert = {}
    for model in (SYNTH_MODEL, SYNTH_EDGE_MODEL):
        safe = model.replace(".", "_").replace("-", "_")
        mdir = SANDBOX / "output" / safe
        pert[model] = {
            stem: pd.read_csv(mdir / f"{stem}.csv").to_dict(orient="list")
            for stem in ("summary_statistics", "normality_test_results",
                         "truncated_normal_test_results",
                         "cohens_kappa_results",
                         "output_compliance_results",
                         "confidence_compliance_results")
        }
    golden["analyze_perturbation_results"] = pert

    # C28: base-vs-instruct family deltas on the committed D2.
    _run("analyze_results_base_versus_instruct.py")
    adir = SANDBOX / "analysis_results"
    golden["base_versus_instruct"] = {
        stem: pd.read_csv(adir / f"{stem}.csv").to_dict(orient="list")
        for stem in ("model_rel_prob_statistics",
                     "prompt_rel_prob_differences",
                     "prompt_rel_prob_heatmap_data")
    }

    # C39: per-model human-LLM agreement (MAE/MSE/correlation suite).
    _run("analyze_llm_human_agreement.py")
    golden["llm_human_agreement"] = json.loads(
        (SANDBOX / "llm_human_agreement_analysis.json").read_text())

    # C42: family differences — a print-only script; its stdout IS the
    # artifact, so the numeric report is parsed into structure.
    out = _run("analyze_model_family_differences.py")
    golden["family_differences"] = _parse_family_differences(out)

    # C43: correlation p-value suite. The full human pairwise list is tens
    # of thousands of rows; keep the distribution-level comparison (every
    # statistic the report prints) plus the complete LLM pair list.
    _run("calculate_correlation_pvalues.py")
    pv = json.loads(
        (SANDBOX / "correlation_pvalues_analysis.json").read_text())
    golden["correlation_pvalues"] = {
        "comparison": pv["comparison"],
        "llm_correlations": pv["llm_correlations"],
        "n_human_correlations": len(pv["human_correlations"]),
    }

    # Base vs instruct vs human correlations (survey-side C28 companion).
    _run("analyze_base_vs_instruct_vs_human.py")
    golden["base_vs_instruct_vs_human"] = pd.read_csv(
        SANDBOX / "model_human_correlations.csv").to_dict(orient="list")

    # C38: the simulated-individual bootstrap (10,000 iterations of a
    # pure-Python resampling loop — by far the slowest capture; hours).
    if os.environ.get("LIR_SKIP_SLOW_BOOTSTRAP") != "1":
        _run("bootstrap_confidence_intervals.py", timeout=6 * 3600)
        golden["bootstrap_confidence_intervals"] = json.loads(
            (SANDBOX / "bootstrap_confidence_intervals.json").read_text())

    return golden


_FAMILY_ROW = re.compile(
    r"^(\w+)\s+(MAE|MSE|MAPE)\s+([+\-\d.]+)%?\s+([+\-\d.]+)%?\s+"
    r"([+\-\d.]+)%?\s+\[([+\-\d.]+)%?, ([+\-\d.]+)%?\]\s+(Yes|No)\s*$",
    re.MULTILINE)
_MC_FAMILY = re.compile(r"^([A-Z]+)\n-{60}", re.MULTILINE)
_MC_ROW = re.compile(
    r"^(MAE|MSE|MAPE): ([+\-\d.]+)%? \[([+\-\d.]+)%?, ([+\-\d.]+)%?\], "
    r"p = ([\d.]+)\s*$", re.MULTILINE)


def _parse_family_differences(stdout: str) -> dict:
    """Structure analyze_model_family_differences.py's printed report:
    the CI-combination summary table and the seed-42 Monte-Carlo section
    (its only outputs — the script writes no files)."""
    table = {}
    for m in _FAMILY_ROW.finditer(stdout):
        fam, metric = m.group(1), m.group(2)
        table.setdefault(fam, {})[metric] = {
            "base": float(m.group(3)), "instruct": float(m.group(4)),
            "diff": float(m.group(5)),
            "ci": [float(m.group(6)), float(m.group(7))],
            "significant": m.group(8) == "Yes",
        }
    mc_section = stdout.split("BOOTSTRAP-BASED DIFFERENCE ANALYSIS", 1)[-1]
    mc: dict = {}
    fams = list(_MC_FAMILY.finditer(mc_section))
    for i, fm in enumerate(fams):
        seg = mc_section[fm.end():
                         fams[i + 1].start() if i + 1 < len(fams) else None]
        mc[fm.group(1)] = {
            r.group(1): {"diff": float(r.group(2)),
                         "ci": [float(r.group(3)), float(r.group(4))],
                         "p": float(r.group(5))}
            for r in _MC_ROW.finditer(seg)
        }
    return {"summary_table": table, "mc_differences": mc}


def main() -> None:
    # Statistics-only work: keep jax (used by lir_tpu.survey.loader) off the
    # tunneled TPU. The axon sitecustomize ignores JAX_PLATFORMS, so force
    # the backend programmatically before any lir_tpu import initializes it.
    import jax
    jax.config.update("jax_platforms", "cpu")
    stage_sandbox()
    golden = capture()
    GOLDEN.parent.mkdir(parents=True, exist_ok=True)
    GOLDEN.write_text(json.dumps(golden, indent=1, sort_keys=True))
    print(f"golden written: {GOLDEN} ({GOLDEN.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
