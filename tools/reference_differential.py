"""Capture golden outputs by EXECUTING the reference's analysis scripts.

VERDICT r1 #2: the strongest parity evidence available in a zero-egress
environment is to actually run the reference's CPU-runnable analysis code on
the committed data CSVs and diff our artifacts against its outputs. This
tool does that:

  1. Builds a sandbox under /tmp, copies four reference scripts into it and
     applies ONLY mechanical environment patches (the patched copies stay in
     /tmp — nothing from the reference tree enters this repo):
       - hard-coded personal paths ("G:/My Drive/...") -> "."
         (SURVEY.md §5 config: the reference has no path flags)
       - pd.read_excel -> pd.read_csv + the .xlsx filename -> .csv
         (this image has no openpyxl; values are unaffected)
  2. Stages identical inputs for both sides:
       - the committed D2/D3 CSVs from /root/reference/data
       - a deterministic synthetic D6 (lir_tpu.data.synthetic — the real D6
         is a generated artifact the upstream repo never committed)
       - D7 (survey_analysis_detailed.json) regenerated from D3 by OUR
         loader — both the reference bootstrap script and our D9 writer
         consume this same file
  3. Runs each script (subprocess, cwd=sandbox, Agg backend), collects every
     numeric artifact they write plus full-precision values from direct
     function calls, and writes tests/golden/reference_executed.json.

tests/test_reference_differential.py then diffs lir_tpu's own outputs
against that JSON under the ≤1% gate (BASELINE.json north star).

Run:  python tools/reference_differential.py
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
REF = Path("/root/reference")
SANDBOX = Path("/tmp/lir_ref_differential")
GOLDEN = REPO / "tests" / "golden" / "reference_executed.json"

SCRIPTS = {
    "model_comparison_graph.py": REF / "analysis/model_comparison_graph.py",
    "calculate_cohens_kappa.py": REF / "analysis/calculate_cohens_kappa.py",
    "survey_analysis_consolidated.py":
        REF / "survey_analysis/survey_analysis_consolidated.py",
    "analyze_llm_agreement_simple_bootstrap.py":
        REF / "survey_analysis/analyze_llm_agreement_simple_bootstrap.py",
}

_GDRIVE = re.compile(r"G:/My Drive/Computational/llm_interpretation/?")


def _patch(text: str) -> str:
    text = _GDRIVE.sub(".", text)
    text = text.replace("pd.read_excel", "pd.read_csv")
    text = text.replace("combined_results.xlsx", "combined_results.csv")
    text = text.replace("results_30_multi_model.xlsx", "combined_results.csv")
    return text


def stage_sandbox() -> None:
    if SANDBOX.exists():
        shutil.rmtree(SANDBOX)
    SANDBOX.mkdir(parents=True)
    for name, src in SCRIPTS.items():
        (SANDBOX / name).write_text(_patch(src.read_text()))
    for csv in ("instruct_model_comparison_results.csv",
                "model_comparison_results.csv",
                "word_meaning_survey_results.csv"):
        shutil.copy(REF / "data" / csv, SANDBOX / csv)

    from lir_tpu.data import synthetic
    synthetic.write_synthetic_d6(SANDBOX / "combined_results.csv")

    # D7 from OUR loader — the same file our D9 pipeline consumes.
    from lir_tpu.survey import loader
    survey_df, qcols = loader.load_survey(SANDBOX / "word_meaning_survey_results.csv")
    clean_df, _ = loader.apply_exclusions(survey_df, qcols)
    loader.write_survey_detailed(
        clean_df, qcols, SANDBOX / "survey_analysis_detailed.json")


def _run(script: str, timeout: int = 3600) -> str:
    env = dict(os.environ, MPLBACKEND="Agg", PYTHONHASHSEED="0")
    proc = subprocess.run(
        [sys.executable, script], cwd=SANDBOX, env=env, timeout=timeout,
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{script} failed rc={proc.returncode}\n--- stdout\n"
            f"{proc.stdout[-4000:]}\n--- stderr\n{proc.stderr[-4000:]}")
    return proc.stdout


_GRAPH_DRIVER = """
import json, sys
import numpy as np, pandas as pd
sys.path.insert(0, ".")
import model_comparison_graph as g

df = pd.read_csv("instruct_model_comparison_results.csv")
df = df[~df["model"].str.contains("opt-iml-1.3b")]
df = df[~df["model"].str.contains("mistral", case=False)]

out = {}
for corr_type in ("pearson", "spearman"):
    s = g.calculate_model_correlations(df, correlation_type=corr_type,
                                       n_bootstrap=1000)
    out[corr_type] = {
        "mean_correlation": s["mean_correlation"],
        "median_correlation": s["median_correlation"],
        "std_correlation": s["std_correlation"],
        "min_correlation": s["min_correlation"],
        "max_correlation": s["max_correlation"],
        "mean_ci": list(s["mean_ci"]),
        "median_ci": list(s["median_ci"]),
        "std_ci": list(s["std_ci"]),
        "correlation_matrix": s["correlation_matrix"].values.tolist(),
        "models": list(s["correlation_matrix"].columns),
    }
k = g.calculate_aggregate_cohens_kappa(df)
out["aggregate_kappa"] = {key: (float(val) if np.isscalar(val) else val)
                          for key, val in k.items()
                          if isinstance(val, (int, float, np.floating, np.integer))}
json.dump(out, open("graph_golden.json", "w"), indent=1)
print("graph driver ok")
"""


def capture() -> dict:
    golden: dict = {"_provenance": {
        "generated_by": "tools/reference_differential.py",
        "reference_snapshot": "/root/reference @ 2025-09-12",
        "inputs": {
            "instruct_csv": "reference data/instruct_model_comparison_results.csv",
            "base_csv": "reference data/model_comparison_results.csv",
            "survey_csv": "reference data/word_meaning_survey_results.csv",
            "perturbation_d6": "lir_tpu.data.synthetic (seed 20260730)",
            "survey_detailed_d7": "lir_tpu.survey.loader.write_survey_detailed",
        },
        "patches": "paths G:/->. ; read_excel->read_csv (no openpyxl)",
    }}

    (SANDBOX / "graph_driver.py").write_text(_GRAPH_DRIVER)
    _run("graph_driver.py")
    golden["model_comparison_graph"] = json.loads(
        (SANDBOX / "graph_golden.json").read_text())

    _run("calculate_cohens_kappa.py")
    kdir = SANDBOX / "output/kappa_analysis"
    import pandas as pd
    golden["calculate_cohens_kappa"] = {
        stem: pd.read_csv(kdir / f"{stem}.csv").to_dict(orient="list")
        for stem in ("model_kappa_metrics", "perturbation_kappa_metrics",
                     "model_legal_kappas", "perturbation_legal_kappas",
                     "combined_kappa_results")
    }

    _run("survey_analysis_consolidated.py")
    golden["survey_consolidated"] = json.loads(
        (SANDBOX / "consolidated_analysis_results.json").read_text())

    _run("analyze_llm_agreement_simple_bootstrap.py")
    golden["llm_human_agreement_bootstrap"] = json.loads(
        (SANDBOX / "llm_human_agreement_bootstrap.json").read_text())

    return golden


def main() -> None:
    # Statistics-only work: keep jax (used by lir_tpu.survey.loader) off the
    # tunneled TPU. The axon sitecustomize ignores JAX_PLATFORMS, so force
    # the backend programmatically before any lir_tpu import initializes it.
    import jax
    jax.config.update("jax_platforms", "cpu")
    stage_sandbox()
    golden = capture()
    GOLDEN.parent.mkdir(parents=True, exist_ok=True)
    GOLDEN.write_text(json.dumps(golden, indent=1, sort_keys=True))
    print(f"golden written: {GOLDEN} ({GOLDEN.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
