"""Measure the on-pod rephraser (C3) at real size (VERDICT r4 #4).

The zero-external-API pipeline replaces the reference's Step 1 — 100
Claude sessions x 20 numbered rephrasings per legal prompt, temperature
0.9, ~500-token responses (perturb_prompts.py:787-835) — with a local 7B
sampler (engine/rephrase.py). r4 shipped it parser-parity-tested but
never MEASURED: no rephrasings/s/chip, no sampling-decode profile, no
parser yield.

This bench runs the PRODUCTION path (rephraser_from_engine ->
generate_rephrasings -> parse_numbered_rephrasings) on the TPU with the
offline-trained byte-BPE tokenizer and a 7B-dimension programmed-chain
model (tools/chain7b.py: zero attention/MLP at full matmul cost) whose
sampled output is a numbered-list cycle — every generated line is a
parseable "N text?" item, so parser yield is measured on REAL text, and
the 512-token sampled responses match the reference's session shape. The
real legal prompts are the rephrasing subjects (450-token requests in
this vocab -> the 512 bucket).

Run on the TPU:  python tools/rephrase_bench.py [--sessions 16 --batch 8]
"""

from __future__ import annotations

import argparse
import datetime
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "tools") not in sys.path:
    sys.path.insert(0, str(REPO / "tools"))

SCALE_MD = REPO / "SCALE.md"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sessions", type=int, default=16,
                    help="sessions per prompt (reference runs 100)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=512,
                    help="sampled tokens per session (reference responses "
                         "are ~500 tokens)")
    ap.add_argument("--prompts", type=int, default=2,
                    help="how many of the 5 legal prompts to rephrase")
    ap.add_argument("--one-line-sessions", action="store_true",
                    help="rewire the chain so every session EOSes after "
                         "its first numbered line (~23 tokens): measures "
                         "the sampler's HF-parity EOS stop through the "
                         "production path — session cost should track "
                         "actual response length, not --max-new. Implies "
                         "--no-record (prints a comparison line instead)")
    ap.add_argument("--no-record", action="store_true")
    args = ap.parse_args()

    from chain7b import (bench_setup, last_token_id, ship_quantized_chain,
                         single_token_id, vocab_word_pieces)
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LEGAL_PROMPTS, rephrase_request
    from lir_tpu.engine.rephrase import (generate_rephrasings,
                                         rephraser_from_engine)
    from lir_tpu.engine.runner import ScoringEngine

    jax, dev, on_accel, fast, cfg, mode = bench_setup(
        max_seq_len=1024, smoke_name="rephrase-smoke")
    if not on_accel:
        args.max_new = min(args.max_new, 64)

    # --- chain: a numbered-list CYCLE the parser can score ---------------
    # "1 w1 w2 ... w20?\n" repeating: every ~23-token line is a parseable
    # "N text" item (the no-dot numbered form, perturb_prompts.py:826-828).
    anchor = last_token_id(fast, rephrase_request(LEGAL_PROMPTS[0].main))
    one = single_token_id(fast, "1")
    qm = single_token_id(fast, "?")
    nl = fast(chr(10), add_special_tokens=False).input_ids[-1]
    words = vocab_word_pieces(fast, 20, {anchor, one, qm, nl})
    cycle = [one] + words + [qm, nl]
    chain = {}
    for a, b in zip(cycle, cycle[1:] + cycle[:1]):
        chain[a] = (b, b)               # (argmax == runner-up: sampling at
        # temperature 0.9 cannot leave the cycle)
    chain[anchor] = (one, one)
    if args.one_line_sessions:
        # First newline -> EOS: sessions are ~one-line long; with the
        # sampler's EOS stop armed (rephraser_from_engine), the remaining
        # --max-new budget must be refunded, not decoded.
        eos = fast.eos_token_id
        chain[nl] = (eos, eos)
        chain[eos] = (eos, eos)
    # Every other request token also enters the cycle, so all legal
    # prompts anchor identically regardless of their final BPE piece.
    params = ship_quantized_chain(jax, dev, cfg, chain, junk_next=one,
                                  junk_second=one)

    rt = RuntimeConfig(batch_size=args.batch, max_seq_len=1024)
    engine = ScoringEngine(params, cfg, fast, rt)
    gen_text = rephraser_from_engine(engine, temperature=0.9,
                                     max_new_tokens=args.max_new)

    prompts = LEGAL_PROMPTS[:args.prompts]
    key = jax.random.PRNGKey(0)

    # Warmup (compiles the 512-bucket sampling decode).
    generate_rephrasings(gen_text, prompts[:1], key,
                         sessions_per_prompt=args.batch,
                         sessions_per_batch=args.batch)

    t0 = time.perf_counter()
    results = generate_rephrasings(gen_text, prompts, key,
                                   sessions_per_prompt=args.sessions,
                                   sessions_per_batch=args.batch)
    dt = time.perf_counter() - t0

    n_sessions = args.sessions * len(prompts)
    total = sum(len(r) for _, r in results)
    per_session = total / n_sessions
    line_len = len(cycle)

    if args.one_line_sessions:
        # ~line_len-token sessions under a --max-new budget: the EOS stop
        # makes session cost track content length. The generic full-budget
        # figures would be ~budget/line_len x inflated here (tokens the
        # stop never decoded), so print only content-priced numbers.
        print(f"one-line sessions (~{line_len + 1} decoded tokens + EOS "
              f"fill) under a {args.max_new}-token budget: {n_sessions} "
              f"sessions in {dt:.1f}s = {n_sessions / dt:.2f} sessions/s "
              f"({dt / n_sessions:.2f} s/session), {total} lines parsed — "
              f"the EOS stop refunds the unused budget; compare the "
              f"full-budget cycle run in SCALE.md", flush=True)
        return

    ceiling = args.max_new / line_len
    toks_s = n_sessions * args.max_new / dt
    print(f"{n_sessions} sessions x {args.max_new} sampled tokens in "
          f"{dt:.1f}s")
    print(f"rephrasings: {total} parsed = {per_session:.1f}/session "
          f"(line ceiling {ceiling:.1f}) -> {total / dt:.2f} "
          f"rephrasings/s/chip")
    print(f"sampling decode: {toks_s:.0f} tok/s at batch {args.batch} "
          f"(seq 512 prompt + {args.max_new} sampled)")
    ref_total = 5 * 100 * 20            # reference Step-1 volume
    eta_min = ref_total / max(total / dt, 1e-9) / 60
    print(f"reference Step-1 volume (5x100x20 = {ref_total}) ETA on one "
          f"chip: {eta_min:.1f} min")

    if args.no_record or not on_accel:
        return
    date = datetime.date.today().isoformat()
    SCALE_MD.write_text(SCALE_MD.read_text() + f"""
## on-pod rephraser (C3) MEASURED — {dev.device_kind}, {date}

{mode}, batch {args.batch}, temperature 0.9, {args.max_new}-token sampled
sessions over the REAL legal-prompt requests (450-token -> 512 bucket),
production path rephraser_from_engine -> generate_rephrasings -> parser
(tools/rephrase_bench.py; programmed-chain weights emit parseable
numbered lines at full 7B matmul cost):

- {n_sessions} sessions in {dt:.1f}s -> **{total / dt:.2f}
  rephrasings/s/chip** ({per_session:.1f} parsed/session against a
  {ceiling:.1f}-line ceiling — parser yield
  {per_session / ceiling:.0%})
- sampling decode: **{toks_s:.0f} tok/s** at batch {args.batch}
- the reference's full Step-1 volume (5 prompts x 100 sessions x 20 =
  {ref_total} rephrasings) lands in **~{eta_min:.0f} min on one chip** —
  the zero-external-API pipeline's Step 1 now has a measured cost next
  to its Step 2.
""")
    print("recorded to SCALE.md")


if __name__ == "__main__":
    main()
