"""TPU-vs-CPU logit parity check (SURVEY.md §7 hard part 3, §4 test plan).

Runs the same fp32 forward on the real TPU chip and on the host CPU backend
and compares logits + softmax readout probabilities. The acceptance gate is
on the *relative* readout (probabilities), matching the ≤1% statistic
deviation criterion — raw logits may differ at bf16-pass magnitudes.

Usage (needs a TPU-visible `python`):  python tools/tpu_parity_check.py
Last recorded (v5e-1, 2026-07-30): max |Δlogit| 2.8e-3, max |Δp| 4.2e-6.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

PROB_GATE = 1e-3  # softmax probability deviation allowed (well under 1%)


def main() -> int:
    sys.path.insert(0, ".")
    from __graft_entry__ import _flagship_cfg
    from lir_tpu.models import decoder

    tpu = jax.devices()[0]
    if tpu.platform == "cpu":
        print("no accelerator present; parity check skipped")
        return 0
    cpu = jax.devices("cpu")[0]

    cfg = _flagship_cfg(tiny=True)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(3, cfg.vocab_size, (2, 24)), jnp.int32
    )

    fwd = lambda p, t: decoder.forward(p, cfg, t)
    out_tpu = jax.device_get(jax.jit(fwd, device=tpu)(params, toks))
    out_cpu = jax.device_get(
        jax.jit(fwd, device=cpu)(jax.device_put(params, cpu), toks)
    )

    logit_diff = float(np.abs(out_tpu - out_cpu).max())
    p_tpu = np.asarray(jax.nn.softmax(jnp.asarray(out_tpu[:, -1]), axis=-1))
    p_cpu = np.asarray(jax.nn.softmax(jnp.asarray(out_cpu[:, -1]), axis=-1))
    prob_diff = float(np.abs(p_tpu - p_cpu).max())

    print(f"max |logit_tpu - logit_cpu| = {logit_diff:.3e}")
    print(f"max |p_tpu - p_cpu|         = {prob_diff:.3e} (gate {PROB_GATE})")
    if prob_diff > PROB_GATE:
        print("FAIL: readout probabilities diverge beyond the gate")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
