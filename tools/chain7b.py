"""Programmed-chain parameter trees at FULL model size.

Same trick as tools/tiny_checkpoints.build_chain_gpt2, scaled to 7B: all
attention and MLP matrices are ZERO (they still execute at full matmul
cost — timing is identical to real weights for a given dtype/quant mode),
token embeddings are one-hot basis vectors, and an untied lm_head encodes
a token -> (argmax_next, runner_up) transition table with +10/+5 margins.
The model's output text is then a designed pure function of the last
prompt token, at genuine 7B compute cost — which makes REAL-tokenizer,
real-content measurements possible on random-initialized infrastructure:
the digit early-stop bench needs responses that actually contain
standalone integers, and the rephraser bench needs responses the
numbered-list parser can score for yield (VERDICT r4 #4/#5).

Margins survive int8 weight-only quantization exactly (0/5/10 per column
quantize to 0/64/127 at scale 10/127) and dominate temperature-0.9
sampling (logit gap ~320 after the rmsnorm sqrt(D) gain)."""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, Tuple

import numpy as np

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def _chain_content_leaves(cfg, chain: Dict[int, Tuple[int, int]],
                          junk_next: int, junk_second: int):
    """(tok_embed, lm_head) numpy fp32 — the only value-bearing leaves of
    a chain tree: one-hot basis embeddings + the transition-table head.
    Shared by the host builder (chain_param_tree) and the on-device
    builder (ship_quantized_chain) so their designed outputs agree."""
    D, V = cfg.hidden_size, cfg.vocab_size
    basis: Dict[int, int] = {}
    for t in chain:
        basis[t] = len(basis)
    junk_axis = len(basis)
    assert junk_axis < D, "chain larger than hidden size"

    tok_embed = np.zeros((V, D), np.float32)
    tok_embed[:, junk_axis] = 4.0
    for t, b in basis.items():
        tok_embed[t, junk_axis] = 0.0
        tok_embed[t, b] = 4.0

    lm_head = np.zeros((D, V), np.float32)
    for t, (nxt, second) in chain.items():
        lm_head[basis[t], nxt] += 10.0
        lm_head[basis[t], second] += 5.0
    lm_head[junk_axis, junk_next] += 10.0
    lm_head[junk_axis, junk_second] += 5.0
    return tok_embed, lm_head


def _chain_layout(cfg, dtype, jnp, linear):
    """The decoder param layout (models/decoder.init_params flag cascade)
    with every big linear built by ``linear(*shape)`` — dense zeros on
    the host path, zero QuantTensors on the on-device path. Single source
    so the two chain builders cannot drift; the content leaves
    (tok_embed / lm_head) are attached by the callers."""
    D, H, K, hd, F, L = (cfg.hidden_size, cfg.n_heads, cfg.n_kv_heads,
                         cfg.head_dim, cfg.intermediate_size, cfg.n_layers)

    def zeros(*shape):
        return jnp.zeros(shape, dtype)

    layers = {
        "ln1": {"scale": jnp.ones((L, D), dtype)},
        "wq": linear(L, D, H * hd), "wk": linear(L, D, K * hd),
        "wv": linear(L, D, K * hd), "wo": linear(L, H * hd, D),
        "w_up": linear(L, D, F), "w_down": linear(L, F, D),
    }
    if not cfg.shared_block_ln:
        layers["ln2"] = {"scale": jnp.ones((L, D), dtype)}
    if cfg.norm == "layernorm":
        layers["ln1"]["bias"] = zeros(L, D)
        if "ln2" in layers:
            layers["ln2"]["bias"] = zeros(L, D)
    if cfg.gated_mlp:
        layers["w_gate"] = linear(L, D, F)
    if cfg.qkv_bias:
        layers["bq"] = zeros(L, H * hd)
        layers["bk"] = zeros(L, K * hd)
        layers["bv"] = zeros(L, K * hd)
    if cfg.attn_out_bias:
        layers["bo"] = zeros(L, D)
    if cfg.mlp_bias:
        layers["b_up"] = zeros(L, F)
        layers["b_down"] = zeros(L, D)

    params = {"layers": layers}
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = zeros(cfg.max_seq_len + cfg.learned_pos_offset,
                                    D)
    if cfg.embedding_norm:
        params["embed_ln"] = {"scale": jnp.ones((D,), dtype),
                              "bias": zeros(D)}
    if cfg.final_norm:
        fl = {"scale": jnp.ones((D,), dtype)}
        if cfg.norm == "layernorm":
            fl["bias"] = zeros(D)
        params["final_ln"] = fl
    return params


def chain_param_tree(cfg, chain: Dict[int, Tuple[int, int]],
                     junk_next: int, junk_second: int, dtype=None):
    """Build the decoder param tree (models/decoder.init_params layout)
    realizing ``chain``; unlisted tokens all map to (junk_next,
    junk_second). cfg must have tie_embeddings=False."""
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    assert not cfg.tie_embeddings, "chain tree needs an untied lm_head"
    tok_embed, lm_head = _chain_content_leaves(cfg, chain, junk_next,
                                               junk_second)
    params = _chain_layout(cfg, dtype, jnp,
                           linear=lambda *s: jnp.zeros(s, dtype))
    params["tok_embed"] = jnp.asarray(tok_embed, dtype)
    params["lm_head"] = jnp.asarray(lm_head, dtype)
    return params


def single_token_id(tokenizer, text: str) -> int:
    ids = tokenizer(text, add_special_tokens=False).input_ids
    assert len(ids) == 1, (text, ids)
    return int(ids[0])


def last_token_id(tokenizer, text: str) -> int:
    return int(tokenizer(text, add_special_tokens=False).input_ids[-1])


def vocab_word_pieces(tokenizer, n: int, taken) -> list:
    """First ``n`` distinct space-prefixed alpha vocab pieces not in
    ``taken`` — chain preamble/cycle words. Picked straight from the
    vocab because BPE word TAILS collide across words (' nearly' and
    ' roughly' both end in 'y')."""
    import re

    out = []
    for tid in range(len(tokenizer)):
        piece = tokenizer.convert_ids_to_tokens(tid)
        if re.fullmatch(r"Ġ[a-z]{3,}", piece or "") and tid not in taken:
            out.append(tid)
            if len(out) == n:
                return out
    raise SystemExit(f"vocab too small: found {len(out)}/{n} word pieces")


# The two production-sweep format strings the chain anchors on (their LAST
# token is each response's transition trigger). Shared by bench.py and
# earlystop_bench so the recorded headline and the early-stop study stay
# apples-to-apples: editing one side only would silently anchor the two
# chains on different tokens.
CHAIN_RESPONSE_FORMAT = "Respond with either Yes or No only please"
CHAIN_CONFIDENCE_FORMAT = "Give a confidence number from 0 to 100"

# The chain's measured-response constants, owned HERE so bench.py derives
# its printed "answer at decode step N" provenance and its per-row
# expected-confidence assertion from the same source that programs the
# weights — changing the answer step or value can then never silently
# desync the headline JSON from what the chain actually emits (ADVICE r5,
# bench.py:133). CHAIN_ANSWER_STEP is one-two steps PAST the
# corpus-median answer word position of 0-1 (SCALE.md "confidence decode
# budget"), i.e. a conservative stop point.
CHAIN_ANSWER_STEP = 3
CHAIN_CONFIDENCE_VALUE = 85


def confidence_chain(fast, response_format: str, confidence_format: str,
                     answer_step: int = CHAIN_ANSWER_STEP):
    """Transition table realizing the production sweep's two response
    shapes on tokenizer ``fast``: the binary prompt (ending in
    ``response_format``'s last token) answers " Yes."-style, and the
    confidence prompt (ending in ``confidence_format``'s last token)
    emits ``answer_step - 1`` non-digit preamble words, then the
    single-token integer " 85", then ".", then EOS — the shape the digit
    early stop (engine/tokens.digit_stop_classes) halts on, at the
    corpus-measured answer position (SCALE.md "confidence decode budget":
    median answer word 0-1 across 1,382 committed reference rows).

    Returns ``(chain, junk_next, junk_second)`` for
    :func:`chain_param_tree` / :func:`ship_quantized_chain`."""
    conf_anchor = last_token_id(fast, confidence_format)
    bin_anchor = last_token_id(fast, response_format)
    eos = fast.eos_token_id
    digit = single_token_id(fast, f" {CHAIN_CONFIDENCE_VALUE}")
    dot = single_token_id(fast, ".")
    yes = single_token_id(fast, " Yes")
    # Preamble words (never digits): emitted before the integer so the
    # stop has real work to do at answer-step > 0.
    taken = {conf_anchor, bin_anchor, eos, digit, dot, yes}
    # vocab_word_pieces returns exactly this many pieces or raises.
    pre = vocab_word_pieces(fast, max(answer_step - 1, 1), taken)
    chain = {}
    seq = [conf_anchor] + pre[:max(answer_step - 1, 0)] + [digit, dot, eos]
    for a, b in zip(seq, seq[1:]):
        chain.setdefault(a, (b, dot))
    chain[bin_anchor] = (yes, dot)
    chain.setdefault(yes, (dot, eos))
    chain[eos] = (eos, dot)
    cast = [conf_anchor, bin_anchor, eos, digit, dot, yes] + pre
    assert len(set(cast)) == len(cast), "chain token collision"
    return chain, dot, eos


def bucket_sized_words(fast, rng, target_tokens: int = 205):
    """(word list, words-per-text) sizing rephrased mains to land in the
    256-token bucket under tokenizer ``fast`` — corpus words are
    multi-piece in a small trained vocab, so a fixed word count would
    spill into the 512 bucket and OOM the measured batch."""
    from lir_tpu.data.prompts import WORD_MEANING_QUESTIONS

    words = sorted({w for q in WORD_MEANING_QUESTIONS for w in q.split()
                    if w.isalpha()})
    sample = " ".join(rng.choice(words) for _ in range(50))
    per_word = len(fast(sample, add_special_tokens=False).input_ids) / 50
    return words, max(int(target_tokens / per_word), 8)


def bench_setup(max_seq_len: int, smoke_name: str):
    """Shared 7B-chain bench scaffolding: pin the backend (env alone is
    too late under the axon sitecustomize — tests/conftest.py), build the
    offline BPE tokenizer, and pick the 7B preset (vocab rounded to 128)
    on an accelerator or a tiny smoke config on CPU. Returns
    (jax, dev, on_accel, fast, cfg, mode)."""
    import dataclasses
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from tiny_checkpoints import build_bpe_tokenizer

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    fast = build_bpe_tokenizer()
    vocab = (len(fast) + 127) // 128 * 128
    if on_accel:
        from tools.scale_validation import resolve_preset
        cfg = dataclasses.replace(
            resolve_preset("llama2_7b"), vocab_size=vocab,
            tie_embeddings=False, kv_cache_int8=True)
        mode = f"{cfg.name} int8-dyn+kvq8, real BPE tokenizer"
    else:
        print("# no accelerator: tiny CPU smoke variant")
        from lir_tpu.models.registry import ModelConfig
        cfg = ModelConfig(name=smoke_name, vocab_size=vocab,
                          hidden_size=64, n_layers=2, n_heads=4,
                          intermediate_size=128, max_seq_len=max_seq_len,
                          tie_embeddings=False)
        mode = "0.2M-smoke"
    return jax, dev, on_accel, fast, cfg, mode


def ship_quantized_chain(jax, dev, cfg, chain, junk_next, junk_second):
    """Assemble the dynamic-int8 chain tree DIRECTLY on the accelerator.

    Every layer matrix of a chain tree is zeros, and ``quant.quantize`` of
    a zero matrix is exactly ``q = 0`` with the zero-safe scale floor
    ``1e-8 / 127`` — so those QuantTensors are constructed on-device with
    no host build and no transfer. Only the content-bearing leaves
    (one-hot tok_embed bf16 + the transition-table lm_head, quantized
    weight-only on device like quantize_decoder_params does) ship over
    the wire: ~0.4 GiB instead of the full 6.7 GiB int8 tree, which at
    tunnel bandwidth dominated bench start-up (~6 min host quantize +
    transfer measured before this path)."""
    import jax.numpy as jnp

    from lir_tpu.models import quant

    assert not cfg.tie_embeddings, "chain tree needs an untied lm_head"
    tok_embed, lm_head = _chain_content_leaves(cfg, chain, junk_next,
                                               junk_second)
    dtype = jnp.bfloat16

    with jax.default_device(dev):
        def zq(*shape):
            # quantize(zeros) == zero payload + the 1e-8/127 scale floor
            # (quant.quantize); dynamic matches random_quantized_params.
            return quant.QuantTensor(
                q=jnp.zeros(shape, jnp.int8),
                scale=jnp.full(shape[:-2] + shape[-1:], 1e-8 / 127.0,
                               jnp.float32),
                dynamic=True)

        params = _chain_layout(cfg, dtype, jnp, linear=zq)
        params["tok_embed"] = jnp.asarray(tok_embed, dtype)
        params["lm_head"] = quant.quantize(jnp.asarray(lm_head, dtype))
    return params
