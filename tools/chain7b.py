"""Programmed-chain parameter trees at FULL model size.

Same trick as tools/tiny_checkpoints.build_chain_gpt2, scaled to 7B: all
attention and MLP matrices are ZERO (they still execute at full matmul
cost — timing is identical to real weights for a given dtype/quant mode),
token embeddings are one-hot basis vectors, and an untied lm_head encodes
a token -> (argmax_next, runner_up) transition table with +10/+5 margins.
The model's output text is then a designed pure function of the last
prompt token, at genuine 7B compute cost — which makes REAL-tokenizer,
real-content measurements possible on random-initialized infrastructure:
the digit early-stop bench needs responses that actually contain
standalone integers, and the rephraser bench needs responses the
numbered-list parser can score for yield (VERDICT r4 #4/#5).

Margins survive int8 weight-only quantization exactly (0/5/10 per column
quantize to 0/64/127 at scale 10/127) and dominate temperature-0.9
sampling (logit gap ~320 after the rmsnorm sqrt(D) gain)."""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, Tuple

import numpy as np

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def chain_param_tree(cfg, chain: Dict[int, Tuple[int, int]],
                     junk_next: int, junk_second: int, dtype=None):
    """Build the decoder param tree (models/decoder.init_params layout)
    realizing ``chain``; unlisted tokens all map to (junk_next,
    junk_second). cfg must have tie_embeddings=False."""
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    assert not cfg.tie_embeddings, "chain tree needs an untied lm_head"
    D, H, K, hd, F, L, V = (cfg.hidden_size, cfg.n_heads, cfg.n_kv_heads,
                            cfg.head_dim, cfg.intermediate_size,
                            cfg.n_layers, cfg.vocab_size)

    basis: Dict[int, int] = {}
    for t in chain:
        basis[t] = len(basis)
    junk_axis = len(basis)
    assert junk_axis < D, "chain larger than hidden size"

    tok_embed = np.zeros((V, D), np.float32)
    tok_embed[:, junk_axis] = 4.0
    for t, b in basis.items():
        tok_embed[t, junk_axis] = 0.0
        tok_embed[t, b] = 4.0

    lm_head = np.zeros((D, V), np.float32)
    for t, (nxt, second) in chain.items():
        lm_head[basis[t], nxt] += 10.0
        lm_head[basis[t], second] += 5.0
    lm_head[junk_axis, junk_next] += 10.0
    lm_head[junk_axis, junk_second] += 5.0

    def zeros(*shape):
        return jnp.zeros(shape, dtype)

    layers = {
        "ln1": {"scale": jnp.ones((L, D), dtype)},
        "wq": zeros(L, D, H * hd), "wk": zeros(L, D, K * hd),
        "wv": zeros(L, D, K * hd), "wo": zeros(L, H * hd, D),
        "w_up": zeros(L, D, F), "w_down": zeros(L, F, D),
    }
    if not cfg.shared_block_ln:
        layers["ln2"] = {"scale": jnp.ones((L, D), dtype)}
    if cfg.norm == "layernorm":
        layers["ln1"]["bias"] = zeros(L, D)
        if "ln2" in layers:
            layers["ln2"]["bias"] = zeros(L, D)
    if cfg.gated_mlp:
        layers["w_gate"] = zeros(L, D, F)
    if cfg.qkv_bias:
        layers["bq"] = zeros(L, H * hd)
        layers["bk"] = zeros(L, K * hd)
        layers["bv"] = zeros(L, K * hd)
    if cfg.attn_out_bias:
        layers["bo"] = zeros(L, D)
    if cfg.mlp_bias:
        layers["b_up"] = zeros(L, F)
        layers["b_down"] = zeros(L, D)

    params = {"tok_embed": jnp.asarray(tok_embed, dtype), "layers": layers}
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = zeros(cfg.max_seq_len + cfg.learned_pos_offset,
                                    D)
    if cfg.embedding_norm:
        params["embed_ln"] = {"scale": jnp.ones((D,), dtype),
                              "bias": zeros(D)}
    if cfg.final_norm:
        fl = {"scale": jnp.ones((D,), dtype)}
        if cfg.norm == "layernorm":
            fl["bias"] = zeros(D)
        params["final_ln"] = fl
    params["lm_head"] = jnp.asarray(lm_head, dtype)
    return params


def single_token_id(tokenizer, text: str) -> int:
    ids = tokenizer(text, add_special_tokens=False).input_ids
    assert len(ids) == 1, (text, ids)
    return int(ids[0])


def last_token_id(tokenizer, text: str) -> int:
    return int(tokenizer(text, add_special_tokens=False).input_ids[-1])


def vocab_word_pieces(tokenizer, n: int, taken) -> list:
    """First ``n`` distinct space-prefixed alpha vocab pieces not in
    ``taken`` — chain preamble/cycle words. Picked straight from the
    vocab because BPE word TAILS collide across words (' nearly' and
    ' roughly' both end in 'y')."""
    import re

    out = []
    for tid in range(len(tokenizer)):
        piece = tokenizer.convert_ids_to_tokens(tid)
        if re.fullmatch(r"Ġ[a-z]{3,}", piece or "") and tid not in taken:
            out.append(tid)
            if len(out) == n:
                return out
    raise SystemExit(f"vocab too small: found {len(out)}/{n} word pieces")


def bench_setup(max_seq_len: int, smoke_name: str):
    """Shared 7B-chain bench scaffolding: pin the backend (env alone is
    too late under the axon sitecustomize — tests/conftest.py), build the
    offline BPE tokenizer, and pick the 7B preset (vocab rounded to 128)
    on an accelerator or a tiny smoke config on CPU. Returns
    (jax, dev, on_accel, fast, cfg, mode)."""
    import dataclasses
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from tiny_checkpoints import build_bpe_tokenizer

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    fast = build_bpe_tokenizer()
    vocab = (len(fast) + 127) // 128 * 128
    if on_accel:
        from tools.scale_validation import resolve_preset
        cfg = dataclasses.replace(
            resolve_preset("llama2_7b"), vocab_size=vocab,
            tie_embeddings=False, kv_cache_int8=True)
        mode = f"{cfg.name} int8-dyn+kvq8, real BPE tokenizer"
    else:
        print("# no accelerator: tiny CPU smoke variant")
        from lir_tpu.models.registry import ModelConfig
        cfg = ModelConfig(name=smoke_name, vocab_size=vocab,
                          hidden_size=64, n_layers=2, n_heads=4,
                          intermediate_size=128, max_seq_len=max_seq_len,
                          tie_embeddings=False)
        mode = "0.2M-smoke"
    return jax, dev, on_accel, fast, cfg, mode


def ship_quantized_chain(jax, dev, cfg, chain, junk_next, junk_second):
    """Build + quantize the chain tree on HOST CPU (a bf16 7B tree
    on-device is ~12.6 GiB and OOMs beside its own int8 copy), then ship
    only the int8 tree to the accelerator."""
    from lir_tpu.models import quant

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = chain_param_tree(cfg, chain, junk_next=junk_next,
                                  junk_second=junk_second)
        params = quant.quantize_decoder_params(params, dynamic=True)
    return jax.device_put(params, dev)
