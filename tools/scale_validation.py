"""7B-at-real-size validation (VERDICT r1 #3).

Materializes the two extreme 7B-class presets at FULL size with random
weights and proves the claims the round-1 docstrings only asserted:

  tpu mode (default when a real accelerator is present):
    - llama2_7b() weight-only int8 on ONE chip: measure init, compile and
      warm fused-scoring-step time (host-read synced), prompts/s, implied
      TFLOPS/MFU, and the empirical HBM-fit boundary (which batch OOMs).
    - falcon_7b() int8 (MQA: 71 q heads / 1 kv head, shared-LN parallel
      block) — the degenerate-sharding family — one fused scoring step.

  mesh-bf16 mode (--mesh-bf16; any platform, uses 8 virtual CPU devices via
  XLA_FLAGS=--xla_force_host_platform_device_count=8 when no pod exists):
    - llama2_7b() bf16 at full size sharded over an 8-device (1, 8, 1) mesh
      with the production NamedSharding rules: compile + run ONE fused
      scoring step on tiny batch/seq. This is the "bf16 needs 8-way TP"
      fit story executed end to end.

Appends measured numbers to SCALE.md. Run:
    python tools/scale_validation.py            # on the TPU
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/scale_validation.py --mesh-bf16
"""

from __future__ import annotations

import argparse
import datetime
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

SCALE_MD = REPO / "SCALE.md"

HEADER = """# SCALE.md — 7B-at-real-size validation log

Measured on-device numbers for the real-size model claims (VERDICT r1 #3).
Each section is appended by `tools/scale_validation.py`; nothing here is
estimated or asserted without a run behind it.
"""


def _append(text: str) -> None:
    if not SCALE_MD.exists():
        SCALE_MD.write_text(HEADER)
    SCALE_MD.write_text(SCALE_MD.read_text() + text)
    print(text)


def _fused_step(params, cfg, batch, seq, new_tokens):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from lir_tpu.engine import generate, score

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones_like(toks)
    yes = jnp.full((batch,), 1, jnp.int32)
    no = jnp.full((batch,), 2, jnp.int32)

    def step():
        fused = generate.greedy_decode_fused(
            params, cfg, toks, mask, yes, no,
            jnp.arange(10, 110, dtype=jnp.int32),
            jnp.arange(0, 100, dtype=jnp.float32),
            max_new_tokens=new_tokens)
        res = score.readout_from_fused(fused, yes, no)
        # Host read = the only trustworthy sync under tunneled dispatch.
        return float(jnp.sum(res.yes_prob) + jnp.sum(res.no_prob))

    t0 = time.perf_counter()
    chk = step()
    compile_s = time.perf_counter() - t0
    assert np.isfinite(chk), f"non-finite checksum {chk}"
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        chk = step()
        best = min(best, time.perf_counter() - t0)
    assert np.isfinite(chk), f"non-finite checksum {chk}"
    return compile_s, best


def resolve_preset(name: str, *, allow_t5: bool = False):
    """Resolve a registry PRESET name to a config, restricted to the
    zero-arg preset factories (class names like ModelConfig would
    construct a default config; tiny() needs an argument; modules are
    not callable). SystemExit with the valid names on any miss."""
    import inspect
    import types as _types

    from lir_tpu.models import registry

    presets = {
        n: v for n, v in vars(registry).items()
        if isinstance(v, _types.FunctionType)
        and v.__module__ == registry.__name__
        and not n.startswith("_")
        and all(p.default is not inspect.Parameter.empty
                for p in inspect.signature(v).parameters.values())
    }
    mk = presets.get(name)
    if mk is None:
        raise SystemExit(f"no registry preset {name!r} "
                         f"(try one of: {', '.join(sorted(presets))})")
    cfg = mk()
    if isinstance(cfg, registry.T5Config) and not allow_t5:
        raise SystemExit(
            f"{name} is an encoder-decoder preset; this tool runs "
            f"decoder-only models (use scale_validation.py --t5)")
    if getattr(cfg, "name", "unnamed") == "unnamed":
        # An unlabeled section header ("### unnamed (...)") is impossible
        # to cite later (VERDICT r3 weak #5) — refuse before any append.
        raise SystemExit(
            f"preset {name!r} resolved to a config with the default "
            f"name='unnamed'; give it a real name before recording "
            f"measurements")
    return cfg


def run_tpu_int8(models: str | None = None,
                 fast_path: bool = False,
                 batches: tuple | None = None) -> None:
    import jax
    import jax.numpy as jnp
    from lir_tpu.models import registry, quant
    from lir_tpu.utils import profiling

    import gc

    dev = jax.devices()[0]
    seq, new_tokens = 256, 10
    names = [n.strip() for n in (models or "llama2_7b,falcon_7b").split(",")
             if n.strip()]
    # Resolve every preset BEFORE the first _append: a typo'd name must
    # fail fast, not leave an orphaned section header in SCALE.md.
    cfgs = [resolve_preset(n) for n in names]
    # The section header is appended TOGETHER with the first model section:
    # a run that dies in init must not leave an orphaned empty "## ..."
    # header in the log (VERDICT r3 weak #5). Naming the models also keeps
    # repeated runs distinguishable.
    header_pending = (
        f"\n## int8 single-chip ({', '.join(c.name for c in cfgs)}) — "
        f"{dev.device_kind} ({dev.platform}), {datetime.date.today()}\n\n")

    import dataclasses as _dc

    for cfg in cfgs:
        if fast_path:
            cfg = _dc.replace(cfg, kv_cache_int8=True)
        t0 = time.perf_counter()
        params = quant.random_quantized_params(cfg, jax.random.PRNGKey(0),
                                               dtype=jnp.bfloat16,
                                               dynamic=fast_path)
        jax.block_until_ready(params)
        _ = float(params["layers"]["wq"].scale.reshape(-1)[0])  # real sync
        init_s = time.perf_counter() - t0
        gib = quant.param_bytes(params) / 2**30

        batch_results = []
        oom_at = None
        ladder = batches or ((16, 32, 48) if fast_path else (8, 16, 32))
        for batch in ladder:
            try:
                compile_s, step_s = _fused_step(params, cfg, batch, seq,
                                                new_tokens)
            except Exception as err:  # noqa: BLE001
                from lir_tpu.utils.profiling import is_oom_error

                if is_oom_error(err):
                    oom_at = batch
                    break
                raise
            flops = profiling.scoring_step_flops(cfg, batch, seq, new_tokens)
            tflops = flops / step_s / 1e12
            peak = profiling.chip_peak_flops(dev, int8=fast_path)
            mfu = f"{tflops * 1e12 / peak:.1%}" if peak else "n/a"
            batch_results.append(
                f"| {batch} | {compile_s:.1f} | {step_s:.3f} | "
                f"{batch / step_s:.2f} | {tflops:.1f} | {mfu} |")

        kv_bytes = 1 if fast_path else 2     # int8 cache vs bf16
        kv_gib = (cfg.n_layers * (seq + new_tokens) * cfg.n_kv_heads
                  * cfg.head_dim * 2 * kv_bytes) / 2**30
        _append(
            header_pending +
            f"### {cfg.name} ({'int8-dyn+kvq8' if fast_path else 'int8'}, "
            f"{gib:.2f} GiB params, "
            f"KV {kv_gib:.3f} GiB/row @ seq {seq + new_tokens})\n\n"
            f"- random-init (on device): {init_s:.0f} s\n"
            f"- fused scoring step (prefill {seq} + {new_tokens} decode):\n\n"
            "| batch | compile s | step s | prompts/s | impl TFLOPS | MFU |\n"
            "|---|---|---|---|---|---|\n"
            + "\n".join(batch_results) + "\n"
            + (f"\n- HBM-fit boundary: batch {oom_at} OOMs on this chip "
               f"(largest fitting batch above)\n" if oom_at else
               f"\n- no OOM up to batch {ladder[-1]}\n"))
        # Free this model's HBM before materializing the next 7B tree —
        # two resident int8 trees (6.3 + 6.9 GiB) plus caches exhaust a
        # 16 GiB chip.
        header_pending = ""
        del params
        gc.collect()


def run_tpu_t5() -> None:
    """T0-3B (the reference's largest enc-dec,
    compare_instruct_models.py:145-166,471-475) at FULL size on the chip:
    bf16 and int8, batch ladder over the seq2seq scoring step
    (t5_greedy_decode: encode once + 10 teacher-forced decoder re-runs).
    VERDICT r2 missing #4: no T5 had ever been materialized at real size.
    """
    import gc

    import jax
    import jax.numpy as jnp
    import numpy as np
    from lir_tpu.engine import generate
    from lir_tpu.models import encdec, quant
    from lir_tpu.models.registry import t0_3b

    dev = jax.devices()[0]
    seq, new_tokens = 256, 10
    cfg = t0_3b()
    _append(f"\n## T5 at real size — {dev.device_kind} ({dev.platform}), "
            f"{datetime.date.today()}\n\n")

    def step_fn(params, batch):
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        mask = jnp.ones_like(toks)
        t0 = time.perf_counter()
        gen, logits = generate.t5_greedy_decode(params, cfg, toks, mask,
                                                max_new_tokens=new_tokens)
        chk = float(jnp.sum(logits[:, 0, :2]))  # host read = real sync
        compile_s = time.perf_counter() - t0
        assert np.isfinite(chk)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            gen, logits = generate.t5_greedy_decode(
                params, cfg, toks, mask, max_new_tokens=new_tokens)
            chk = float(jnp.sum(logits[:, 0, :2]))
            best = min(best, time.perf_counter() - t0)
        assert np.isfinite(chk)
        return compile_s, best

    import os
    modes = tuple(os.environ.get("T5_MODES", "bf16,int8").split(","))
    for mode in modes:
        t0 = time.perf_counter()
        params = encdec.init_params(cfg, jax.random.PRNGKey(0),
                                    dtype=jnp.bfloat16)
        if mode == "int8":
            params = quant.quantize_encdec_params(params)
        jax.block_until_ready(params)
        # Host read of one leaf = the only trustworthy sync (tunneled axon).
        leaf = jax.tree.leaves(params)[0]
        _ = float(jnp.asarray(leaf).reshape(-1)[0].astype(jnp.float32))
        init_s = time.perf_counter() - t0
        gib = quant.param_bytes(params) / 2**30

        rows, oom_at = [], None
        for batch in (8, 16, 32):
            try:
                compile_s, step_s = step_fn(params, batch)
            except Exception as err:  # noqa: BLE001
                from lir_tpu.utils.profiling import is_oom_error

                if is_oom_error(err):
                    oom_at = batch
                    break
                raise
            rows.append(f"| {batch} | {compile_s:.1f} | {step_s:.3f} | "
                        f"{batch / step_s:.2f} |")
        _append(
            f"### {cfg.name} ({mode}, {gib:.2f} GiB params)\n\n"
            f"- random-init + {'quantize ' if mode == 'int8' else ''}"
            f"(on device): {init_s:.0f} s\n"
            f"- seq2seq scoring step (encode {seq} + {new_tokens} "
            f"teacher-forced decoder passes):\n\n"
            "| batch | compile s | step s | prompts/s |\n"
            "|---|---|---|---|\n" + "\n".join(rows) + "\n"
            + (f"\n- HBM-fit boundary: batch {oom_at} OOMs\n" if oom_at
               else "\n- no OOM up to batch 32\n"))
        del params
        gc.collect()


def run_mesh_bf16() -> None:
    import os
    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import jax
    import jax.numpy as jnp
    from lir_tpu.config import MeshConfig
    from lir_tpu.models import decoder, quant
    from lir_tpu.models.registry import llama2_7b
    from lir_tpu.parallel import sharding

    n_dev = len(jax.devices())
    assert n_dev >= 8, f"need 8 devices (virtual ok), have {n_dev}"
    cfg = llama2_7b()
    mesh = sharding.build_mesh(MeshConfig(data=1, model=8))

    t0 = time.perf_counter()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.bfloat16)
    params = sharding.shard_params(params, cfg, mesh)
    jax.block_until_ready(params)
    init_s = time.perf_counter() - t0
    gib = quant.param_bytes(params) / 2**30

    # Per-device shard of the largest matrix proves 8-way placement.
    wq = params["layers"]["wq"]
    shard_gib = (wq.addressable_shards[0].data.size
                 * wq.dtype.itemsize) / 2**30

    compile_s, step_s = _fused_step(params, cfg, batch=2, seq=16, new_tokens=4)
    _append(
        f"\n## bf16 8-way tensor-parallel — {jax.devices()[0].platform} x "
        f"{n_dev} devices, {datetime.date.today()}\n\n"
        f"### {cfg.name} (bf16, {gib:.2f} GiB params, mesh (1, 8, 1))\n\n"
        f"- init + shard (full size): {init_s:.0f} s\n"
        f"- wq per-device shard: {shard_gib:.3f} GiB "
        f"(= 1/8 of {shard_gib * 8:.2f} GiB)\n"
        f"- fused scoring step, batch 2 / seq 16 / 4 decode: "
        f"compile {compile_s:.0f} s, warm step {step_s:.2f} s\n"
        f"- bf16/chip at 8-way TP: ~{gib / 8:.2f} GiB params/device -> fits "
        f"a 16 GiB v5e chip with room for cache+activations\n")


def run_12b_fit() -> None:
    """h2ogpt-12b (the zoo's largest) sharding fit proof on the virtual
    8-device mesh: materialize the FULL-SIZE int8 tree, shard it with the
    production rules over model=2, and measure the per-device bytes — the
    must-shard recipe for a model whose 11.3 GiB int8 tree is borderline
    on a 16 GiB chip. Run with
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8.
    """
    import jax
    import jax.numpy as jnp

    from lir_tpu.config import MeshConfig
    from lir_tpu.models import quant
    from lir_tpu.parallel import sharding
    from lir_tpu.models.registry import h2ogpt_12b

    cfg = h2ogpt_12b()
    n_dev = len(jax.devices())
    assert n_dev >= 8, f"need the virtual 8-device mesh, got {n_dev}"
    t0 = time.perf_counter()
    # Spec-level fit computation: the PRODUCTION sharding rules applied to
    # the full-size quantized tree's abstract shapes (NamedSharding.
    # shard_shape gives the exact per-device slab without materializing
    # 11 GiB on the 1-core host; the same rules' runtime correctness is
    # pinned by the dryrun's composed-mesh phases and
    # tests/test_preset_sharding.py).
    shapes = jax.eval_shape(
        lambda k: quant.random_quantized_params(cfg, k, dtype=jnp.bfloat16,
                                                dynamic=True),
        jax.random.PRNGKey(0))
    mesh = sharding.build_mesh(MeshConfig(data=4, model=2))
    specs = sharding.decoder_param_specs(cfg, mesh)

    total = 0
    worst_b = 0
    flat_shapes, _ = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, quant.QuantTensor))
    flat_specs = dict(jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))[0])

    def _bytes(shape, dtype):
        import math
        return math.prod(shape) * jnp.dtype(dtype).itemsize

    for path, leaf in flat_shapes:
        if isinstance(leaf, quant.QuantTensor):
            parts = [(leaf.q.shape, leaf.q.dtype, flat_specs.get(path)),
                     (leaf.scale.shape, leaf.scale.dtype, None)]
        else:
            parts = [(leaf.shape, leaf.dtype, flat_specs.get(path))]
        for shape, dtype, spec in parts:
            total += _bytes(shape, dtype)
            ns = jax.sharding.NamedSharding(
                mesh, spec if spec is not None else
                jax.sharding.PartitionSpec())
            worst_b += _bytes(ns.shard_shape(shape), dtype)
    total_gib = total / 2**30
    worst = worst_b / 2**30
    init_s = time.perf_counter() - t0
    seq = 266
    kv_row = (cfg.n_layers * seq * cfg.n_kv_heads * cfg.head_dim * 2) / 2**30
    _append(f"""
## h2ogpt-12b must-shard fit proof — virtual {n_dev}-device mesh, {datetime.date.today()}

The zoo's largest model ({cfg.hidden_size}h x {cfg.n_layers}L, vocab
{cfg.vocab_size}): int8-dyn tree = **{total_gib:.2f} GiB** — borderline on a
16 GiB chip (one single-chip init measured OK at 11.28 GiB; repeat
attempts hit RESOURCE_EXHAUSTED on this shared dev chip, so single-chip
12B is NOT a dependable deployment). The robust recipe — per-device
slabs computed with NamedSharding.shard_shape from the PRODUCTION
sharding rules over the full-size tree's shapes, data=4 x model=2 mesh:

- per-device param bytes, worst device: **{worst:.2f} GiB** (vs
  {total_gib:.2f} GiB unsharded) — comfortable on a 16 GiB chip with
  int8 KV ({kv_row:.3f} GiB per cache row @ seq {seq}, batch ~32 fits)
- correctness of the sharded scorer at this mesh shape is pinned by the
  dryrun (2x4 composed mesh phases) and tests/test_preset_sharding.py;
  quantized trees shard by the same rules (QuantTensor payload on the
  weight spec, scales on the output axis).
""")


SUMMARY_START = "<!-- SUMMARY:START (generated by scale_validation.py --summarize) -->"
SUMMARY_END = "<!-- SUMMARY:END -->"


def run_summarize() -> None:
    """Regenerate the summary table at the top of SCALE.md: one row per
    (model, config) with its best measured prompts/s and the section that
    evidence lives in — every DEPLOY.md number becomes traceable to one
    named section (VERDICT r3 #6)."""
    import re as _re

    text = SCALE_MD.read_text()
    # Strip any previous generated block INCLUDING adjacent blank lines, so
    # regeneration is a fixed point (blank padding must not accumulate).
    text = _re.sub(
        r"\n*" + _re.escape(SUMMARY_START) + r".*?"
        + _re.escape(SUMMARY_END) + r"\n*",
        "\n\n", text, flags=_re.DOTALL)

    rows = []
    section = ""
    model = mode = None
    header_cells = None
    best: float = 0.0

    def _flush():
        nonlocal model, mode, best
        if model is not None and best > 0:
            rows.append((model, mode, best, section))
        model = mode = None
        best = 0.0

    sweep_re = _re.compile(r"\*\*([\d.]+)\s*(?:prompts/s|p/s)")
    for line in text.splitlines():
        if line.startswith("## "):
            _flush()
            header_cells = None
            section = line[3:].strip()
            # End-to-end sweep sections record bolded p/s lines directly.
        elif line.startswith("### "):
            _flush()
            header_cells = None
            m = _re.match(r"### ([^\s(]+) \(([^,)]+)", line)
            if m:
                model, mode = m.group(1), m.group(2)
        elif model is not None and line.startswith("|"):
            cells = [c.strip() for c in line.strip("|").split("|")]
            # Locate the prompts/s column from the table HEADER (a cell
            # naming the unit without carrying a number), never a fixed
            # index — reordered/added columns must not silently record a
            # wrong best (ADVICE r4).
            if all(_re.fullmatch(r"[-: ]*", c) for c in cells):
                pass                    # separator row keeps current header
            elif not _re.search(r"\d", cells[0]):
                # Header row: the label column has no digit, while every
                # model-section data row leads with a batch size. A header
                # WITHOUT a p/s column starts a non-throughput table and
                # must invalidate the stale header so its rows aren't read
                # at the old column index.
                if any("p/s" in c or "prompts/s" in c for c in cells):
                    header_cells = cells
                else:
                    header_cells = None
            elif header_cells:
                col = next((k for k, h in enumerate(header_cells)
                            if "p/s" in h or "prompts/s" in h), None)
                if col is not None and len(cells) > col:
                    try:
                        best = max(best, float(cells[col].strip("*")))
                    except ValueError:
                        pass
        elif model is None and line.startswith("|"):
            cells = [c.strip() for c in line.strip("|").split("|")]
            if any("p/s" in c or "prompts/s" in c for c in cells):
                header_cells = cells         # e.g. cross-architecture table
            elif header_cells and len(cells) == len(header_cells):
                col = next((k for k, h in enumerate(header_cells)
                            if "p/s" in h or "prompts/s" in h), None)
                if col is not None and not cells[0].replace(".", "").isdigit():
                    try:
                        val = float(cells[col].strip("*"))
                    except ValueError:
                        continue
                    rows.append((cells[0].split(" (")[0], "e2e sweep table",
                                 val, section))
        elif model is None:
            m = sweep_re.search(line)
            if m:
                rows.append(("(end-to-end sweep)", "see section",
                             float(m.group(1)), section))
    _flush()

    if not rows:
        raise SystemExit("no measured sections found in SCALE.md")
    # Dedup repeated (model, config, section) measurements: keep the best.
    dedup: dict = {}
    for model_, mode_, val, sec in rows:
        k = (model_, mode_, sec)
        dedup[k] = max(dedup.get(k, 0.0), val)
    rows = [(m, c, v, s) for (m, c, s), v in dedup.items()]
    table = [SUMMARY_START,
             "",
             "| model / table row | config | best prompts/s | "
             "evidence section |",
             "|---|---|---|---|"]
    for model_, mode_, val, sec in rows:
        table.append(f"| {model_} | {mode_} | {val:.2f} | {sec} |")
    table += ["", SUMMARY_END, ""]

    lines = text.splitlines()
    # Insert after the prose header (before the first "## ").
    for i, line in enumerate(lines):
        if line.startswith("## "):
            break
    else:
        i = len(lines)
    out = "\n".join(lines[:i] + table + lines[i:]) + "\n"
    SCALE_MD.write_text(out)
    print(f"summary: {len(rows)} rows regenerated at the top of SCALE.md")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--fit-12b", action="store_true",
                    help="h2ogpt-12b full-size sharded fit proof on the "
                         "virtual 8-device CPU mesh")
    ap.add_argument("--summarize", action="store_true",
                    help="regenerate the summary table at the top of "
                         "SCALE.md from the measured sections (no device "
                         "work)")
    ap.add_argument("--mesh-bf16", action="store_true",
                    help="run the full-size bf16 8-device-mesh validation")
    ap.add_argument("--fast-path", action="store_true",
                    help="int8 single-chip run with the FULL fast path "
                         "(dynamic activations + int8 KV cache), batch "
                         "ladder 16/32/48")
    ap.add_argument("--models", default=None,
                    help="comma-separated registry preset names for the "
                         "int8 single-chip run (default: llama2_7b,"
                         "falcon_7b)")
    ap.add_argument("--batches", default=None,
                    help="comma-separated batch ladder override for the "
                         "int8 single-chip run (e.g. 4,8,16 for 12B-class "
                         "models)")
    ap.add_argument("--t5", action="store_true",
                    help="materialize T0-3B at full size (bf16 + int8) on "
                         "the chip and measure the seq2seq scoring step")
    args = ap.parse_args()
    if (args.models or args.fast_path) and (args.mesh_bf16 or args.t5):
        ap.error("--models/--fast-path only apply to the int8 "
                 "single-chip run")
    if args.summarize:
        run_summarize()
        return
    if args.fit_12b:
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        run_12b_fit()
        return
    if args.mesh_bf16:
        run_mesh_bf16()
    elif args.t5:
        run_tpu_t5()
    else:
        ladder = (tuple(int(b) for b in args.batches.split(","))
                  if args.batches else None)
        run_tpu_int8(args.models, fast_path=args.fast_path, batches=ladder)


if __name__ == "__main__":
    main()
