#!/usr/bin/env python
"""Prefix-cache smoke: the cross-request radix prefix cache
(engine/prefix_tree.py over models/paged.py) on the fake backend — the
`make prefix-smoke` CI target.

Serves the production-shaped workload (variations of 5 long legal-prompt
bases) twice on each of two servers sharing nothing but the request
trace: prefix cache OFF (the exact-dedup-only baseline) and prefix cache
ON (the serving default). Asserts the PR's two load-bearing claims:

- nonzero prefill-tokens-avoided: warm dispatches resumed shared
  prefixes from the page pool instead of re-prefilling them (and the
  radix hit rate is nonzero);
- bitwise parity with the unpaged path: every request's payload fields
  are identical between the two servers — the cache is a pure perf
  lever, invisible in results;
- allocator sanity: page refcounts never went negative and, with every
  dispatch drained, only the tree's own references remain.

Runs hermetically on CPU with the FakeTokenizer + a tiny random decoder
(the same stand-in the test suite uses); prints the PrefixCacheStats
summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_BASES = 5
N_REQUESTS = 30
BASE_WORDS = 120   # long legal bases: prefill dominates, as in production


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    cfg = ModelConfig(name="prefix-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder.init_params(cfg, jax.random.PRNGKey(11))

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    rng = np.random.default_rng(17)
    bases = [" ".join(rng.choice(words) for _ in range(BASE_WORDS))
             for _ in range(N_BASES)]

    def request(i: int) -> ServeRequest:
        main_text = f"{bases[i % N_BASES]} case {i} ?"
        return ServeRequest(
            binary_prompt=f"{main_text} Answer Yes or No .",
            confidence_prompt=f"{main_text} Give a number from 0 to 100 .",
            klass="smoke", request_id=str(i))

    def serve(prefix_on: bool):
        engine = ScoringEngine(params, cfg, FakeTokenizer(),
                               RuntimeConfig(batch_size=8, max_seq_len=512))
        sc = ServeConfig(queue_depth=N_REQUESTS + 8, prefix_cache=prefix_on,
                         classes=(("smoke", 600.0),), default_class="smoke",
                         linger_s=0.01)
        payloads = []
        for _ in range(2):          # pass 2 is the warm pass
            server = ScoringServer(engine, "prefix-smoke", sc).start()
            futs = [server.submit(request(i)) for i in range(N_REQUESTS)]
            payloads = [f.result(timeout=600) for f in futs]
            server.stop()
        return engine, payloads

    eng_off, base = serve(False)
    eng_on, warm = serve(True)

    failures = []
    bad = [r.request_id for r in base + warm if r.status != "ok"]
    if bad:
        failures.append(f"non-ok results: {bad}")
    stats = eng_on.prefix_stats
    if stats.hit_tokens <= 0:
        failures.append("zero prefill tokens avoided — the warm pass "
                        "never resumed from the page pool")
    if stats.hits <= 0:
        failures.append("zero radix hits on the warm pass")
    fields = ("status", "token_1_prob", "token_2_prob",
              "log_probabilities", "confidence_value",
              "weighted_confidence", "model_response",
              "model_confidence_response")
    mismatches = [a.request_id for a, b in zip(base, warm)
                  if any(getattr(a, f, None) != getattr(b, f, None)
                         for f in fields)]
    if mismatches:
        failures.append(f"paged payloads differ from the unpaged "
                        f"baseline: requests {mismatches}")
    pool = eng_on.prefix_cache.pool
    if not (pool.refcount >= 0).all():
        failures.append("a page refcount went negative")
    if pool.refcount[1:].sum() != pool.pages_in_use:
        failures.append("dangling dispatch pins after drain (references "
                        "beyond the tree's own remain)")
    if failures:
        for f in failures:
            print(f"PREFIX-SMOKE FAIL: {f}")
        return 1
    print(json.dumps(stats.summary()))
    print(f"prefix smoke: OK ({N_REQUESTS} requests over {N_BASES} shared "
          f"bases, {stats.hit_tokens} prefill tokens avoided "
          f"({100 * stats.avoided_frac:.0f}%), radix hit rate "
          f"{stats.hit_rate:.2f}, paged == unpaged bitwise)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
