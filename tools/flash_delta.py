"""Measure the flash-attention delta on the real chip (VERDICT r1 #4).

Times the fused scoring step of llama2_7b (int8) with
``use_flash_attention`` on vs off at seq 512 and 1024 — the lengths where
the dense (B, H, S, S) score tensor starts to dominate HBM — and appends
the measured delta to SCALE.md. Host-read-synced timing (same discipline as
bench.py). Run on the TPU:  python tools/flash_delta.py
"""

from __future__ import annotations

import dataclasses
import datetime
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.scale_validation import SCALE_MD, _append, _fused_step  # noqa: E402


def main() -> None:
    import argparse
    import gc

    import jax
    import jax.numpy as jnp
    from lir_tpu.models import quant
    from lir_tpu.models.registry import llama2_7b

    ap = argparse.ArgumentParser()
    ap.add_argument("--long", action="store_true",
                    help="long-context points the int8 KV cache unlocked "
                         "(seq 1024 batch 8 / seq 2048 batch 4, int8-dyn + "
                         "kvq8) — VERDICT r2 weak #4")
    ap.add_argument("--points", default=None,
                    help="override measurement points as SEQ:BATCH[,...] "
                         "(e.g. 256:40 — the production sweep's prefill "
                         "shape)")
    ap.add_argument("--dyn-kvq8", action="store_true",
                    help="measure in the production int8-dyn+kvq8 mode "
                         "(what the sweep headline runs) instead of "
                         "weight-only int8")
    args = ap.parse_args()

    dev = jax.devices()[0]
    assert dev.platform != "cpu", "run on the TPU (Pallas does not lower on CPU)"

    base = llama2_7b()
    fast_path = args.long or args.dyn_kvq8
    if fast_path:
        base = dataclasses.replace(base, kv_cache_int8=True)
    params = quant.random_quantized_params(base, jax.random.PRNGKey(0),
                                           dtype=jnp.bfloat16,
                                           dynamic=fast_path)
    jax.block_until_ready(params)
    _ = float(params["layers"]["wq"].scale.reshape(-1)[0])

    mode = ("int8-dyn + int8 KV cache" if fast_path else "int8")
    points = ([(1024, 8), (2048, 4)] if args.long
              else [(512, 8), (1024, 8)])
    if args.points:
        try:
            points = [(int(s), int(b)) for s, b in
                      (p.split(":") for p in args.points.split(","))]
        except ValueError:
            points = []
        if not points or any(s < 1 or b < 1 for s, b in points):
            ap.error(f"--points {args.points!r} must be "
                     "SEQ:BATCH[,SEQ:BATCH...] with positive ints")
    lines = [f"\n## flash-attention prefill delta — {dev.device_kind}, "
             f"{datetime.date.today()}"
             # The long-context label belongs to --long's OWN points; a
             # --points override replaces them, so the permanent record
             # must not claim shapes that were not measured.
             f"{' (long-context, int8 KV)' if args.long and not args.points else ''}\n\n"
             f"llama-2-7b {mode}, fused scoring step (prefill + 10 "
             "decode):\n\n"
             "| seq | batch | dense step s | flash step s | speedup |\n"
             "|---|---|---|---|---|\n"]
    for seq, batch in points:
        results = {}
        for flash in (False, True):
            cfg = dataclasses.replace(base, use_flash_attention=flash)
            try:
                _, step_s = _fused_step(params, cfg, batch=batch, seq=seq,
                                        new_tokens=10)
                results[flash] = step_s
            except Exception as err:  # noqa: BLE001
                from lir_tpu.utils.profiling import is_oom_error

                if is_oom_error(err):
                    results[flash] = None  # OOM: the delta IS the fit
                else:
                    raise
            gc.collect()
        dense, flash_t = results[False], results[True]
        dense_s = f"{dense:.3f}" if dense else "OOM"
        flash_s = f"{flash_t:.3f}" if flash_t else "OOM"
        if dense and flash_t:
            ratio = f"{dense / flash_t:.2f}x"
        elif flash_t and not dense:
            ratio = "flash fits, dense OOMs"
        else:
            ratio = "n/a"
        lines.append(f"| {seq} | {batch} | {dense_s} | {flash_s} | {ratio} |\n")
    _append("".join(lines))
    print(f"appended flash delta to {SCALE_MD}")


if __name__ == "__main__":
    main()
