"""Deterministic canned API responses for the perturb_prompts oracle.

Shared between the sandbox stub clients (tools/reference_perturb_oracle.py
writes stubs that import this module) and the lir_tpu-side differential
(tests/test_reference_perturb_oracle.py) so both sides replay IDENTICAL
payloads. Every payload is a pure function of the request, no RNG.

The response variants are chosen to exercise every branch of the
reference's decoder (perturb_prompts.py:398-549): clean target answers,
answers matching neither target, targets missing from top_logprobs
(division-by-zero -> inf odds), leading-space token lookalikes that must
NOT match the exact-equality rule, multi-position confidence logprobs,
out-of-range integers (>100) excluded from E[v], integers embedded in
non-digit tokens ("85%"), unparseable confidence text, and the reasoning
if/elif counting quirk where a "Not Covered" run counts as "Covered"
(:422-426 — substring containment, first branch wins).
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Tuple

# target_tokens keyed by a stable marker: the response_format text is
# appended verbatim to every rephrased binary prompt, so it identifies the
# original prompt inside any request (perturb_prompts.py:215).
PROMPT_TARGETS: List[Tuple[str, Tuple[str, str]]] = [
    ("Answer only 'Covered' if insurance covers the loss",
     ("Covered", "Not")),
    ("Answer only 'First Petition' if the first filing date",
     ("Ultimate", "First")),
    ("Answer only 'Existing Affiliates' or 'Future Affiliates'",
     ("Existing", "Future")),
    ("Answer only 'Monthly Installment Payments' or 'Payment Upon Completion'",
     ("Monthly", "Payment")),
]
# Prompts 0 and 4 share a response format; target lookup falls through to
# the first match, which is correct (same targets).

CONFIDENCE_MARKER = "How confident are you"


def targets_for(full_prompt: str) -> Tuple[str, str]:
    for marker, targets in PROMPT_TARGETS:
        if marker in full_prompt:
            return targets
    return ("Covered", "Not")          # confidence prompts: unused


def _variant(custom_id: str) -> int:
    m = re.search(r"(\d+)", custom_id)
    return int(m.group(1)) if m else 0


def claude_rephrasings(call_idx: int, main_prompt: str) -> str:
    """Canned Claude message text for one rephrasing session: numbered
    list with the parser's edge cases (preamble line, 'N.' and 'N '
    forms, an unnumbered continuation line)."""
    stem = main_prompt.split("?")[0][:40].strip()
    k = call_idx
    return (
        "Here are 20 rephrasings of the question:\n"
        "\n"
        f"1. Could you analyze (v{k}a) whether {stem}?\n"
        f"2 In your view (v{k}b), {stem}?\n"
        f"3. Considering the terms (v{k}c),\n"
        f"   does the provision discussed in {stem}\n"
        f"   apply here?\n"
    )


def parsed_rephrasings(call_idx: int, main_prompt: str) -> List[str]:
    """What the reference's parser (perturb_prompts.py:812-835) extracts
    from claude_rephrasings — kept next to the generator so drift between
    the canned text and expectations is impossible."""
    stem = main_prompt.split("?")[0][:40].strip()
    k = call_idx
    return [
        f"Could you analyze (v{k}a) whether {stem}?",
        f"In your view (v{k}b), {stem}?",
        f"Considering the terms (v{k}c), does the provision discussed in "
        f"{stem} apply here?",
    ]


def _top(entries: List[Tuple[str, float]]) -> List[Dict[str, object]]:
    return [{"token": t, "logprob": lp} for t, lp in entries]


def binary_logprob_content(variant: int, t1: str, t2: str
                           ) -> List[Dict[str, object]]:
    v = variant % 4
    if v == 0:        # both targets present; leading-space lookalikes too
        top = _top([(t1, -0.1054), (t2, -2.3026), (" " + t1, -3.0),
                    (" " + t2, -3.5), ("The", -4.0)])
    elif v == 1:      # reversed preference
        top = _top([(t2, -0.3567), (t1, -1.2040), ("Answer", -5.0)])
    elif v == 2:      # neither target in top-20 -> probs 0, odds inf
        top = _top([("I", -0.5), ("cannot", -1.0), ("tell", -1.5)])
    else:             # target_1 only -> token_2_prob 0 -> odds inf
        top = _top([(t1, -0.2231), ("perhaps", -2.0)])
    return [{"token": top[0]["token"], "logprob": top[0]["logprob"],
             "top_logprobs": top}]


def binary_text(variant: int, t1: str, t2: str) -> str:
    return [t1, t2, "I cannot tell from the term alone.", t1][variant % 4]


def confidence_payload(variant: int) -> Tuple[str, List[Dict[str, object]]]:
    """(message text, logprobs content) for a confidence request. The
    content spans MULTIPLE positions — the reference's E[v] accumulates
    top_logprobs across every generated position (:513-526) — and
    includes >100 integers (excluded) and digits embedded in non-digit
    tokens like '85%' (included via the \\b(\\d+)\\b search)."""
    v = variant % 4
    if v == 0:
        text = "85"
        content = [
            {"token": "85", "logprob": -0.2231, "top_logprobs": _top(
                [("85", -0.2231), ("90", -2.3026), ("150", -1.0),
                 ("eighty", -3.0)])},
            {"token": ".", "logprob": -0.1, "top_logprobs": _top(
                [(".", -0.1), ("100", -4.6052), ("0", -5.0)])},
        ]
    elif v == 1:
        text = "I am 72% confident in this reading."
        content = [
            {"token": "I", "logprob": -0.3, "top_logprobs": _top(
                [("I", -0.3), ("72", -1.6094)])},
            {"token": " am", "logprob": -0.2, "top_logprobs": _top(
                [(" am", -0.2), ("85%", -2.9957)])},
        ]
    elif v == 2:
        text = "Unable to quantify."
        content = [
            {"token": "Unable", "logprob": -0.4, "top_logprobs": _top(
                [("Unable", -0.4), ("to", -1.2)])},
        ]
    else:
        text = "Confidence: 60 out of 100"
        content = [
            {"token": "Confidence", "logprob": -0.5, "top_logprobs": _top(
                [("Confidence", -0.5), ("60", -0.9163)])},
            {"token": " 60", "logprob": -0.3, "top_logprobs": _top(
                [(" 60", -0.3), ("101", -0.5), ("40", -2.5257)])},
        ]
    return text, content


def reasoning_binary_text(run_idx: int, t1: str, t2: str) -> str:
    """Run texts for the 10-run average: 5 plain target_1, 3 'Not
    <target_1>'-style texts CONTAINING target_1 (the if/elif containment
    quirk counts these as target_1), 1 target_2-only, 1 neither."""
    if run_idx < 5:
        return f"{t1}."
    if run_idx < 8:
        return f"Not {t1}" if t2 == "Not" else f"{t2} {t1} mix"
    if run_idx < 9:
        return f"{t2} side prevails" if t1 not in t2 else t2
    return "No clear answer."


def openai_batch_result_line(request: Dict[str, object]) -> str:
    """One JSONL result line for one batch request, as the OpenAI Batch
    API would return it (the shapes the reference reads at :386-396 and
    :472-526)."""
    custom_id = str(request["custom_id"])
    body = request["body"]
    content_text = str(body["messages"][0]["content"])
    is_reasoning = "max_completion_tokens" in body
    wants_logprobs = bool(body.get("logprobs"))
    t1, t2 = targets_for(content_text)
    v = _variant(custom_id)

    # Non-reasoning grids alternate binary/confidence on even/odd counters;
    # v // 2 walks each format through ALL its variants.
    if CONFIDENCE_MARKER in content_text:
        if is_reasoning:
            text = str(40 + (v % 5) * 10)          # "40".."80"
            choice: Dict[str, object] = {"message": {"content": text}}
        else:
            text, content = confidence_payload(v // 2)
            choice = {"message": {"content": text},
                      "logprobs": {"content": content}}
    else:
        if is_reasoning:
            # runs are consecutive counters within one rephrase's block
            text = reasoning_binary_text(v % 10, t1, t2)
            choice = {"message": {"content": text}}
        else:
            text = binary_text(v // 2, t1, t2)
            choice = {"message": {"content": text}}
            if wants_logprobs:
                choice["logprobs"] = {
                    "content": binary_logprob_content(v // 2, t1, t2)}

    result = {
        "id": f"batch_req_{custom_id}",
        "custom_id": custom_id,
        "response": {
            "status_code": 200,
            "body": {
                "choices": [choice],
                "usage": {"prompt_tokens": max(len(content_text) // 4, 1),
                          "completion_tokens": 7},
            },
        },
        "error": None,
    }
    return json.dumps(result)
