#!/usr/bin/env python
"""Cascade-prefill smoke: the shared-prefix cascade dispatch path
(ops/cascade_prefill + engine/runner routing) on the fake backend — the
`make cascade-smoke` CI target.

Serves a shared-trunk grid (waves of requests that rephrase the SAME
long legal-prompt trunk, varying only a short tail — the paper's axis-1
workload shape) on two servers sharing nothing but the request trace:
cascade prefill ON (the default) and OFF (--no-cascade-prefill, the
dense baseline). Asserts the PR's load-bearing claims:

- the cascade actually engaged: nonzero cascade dispatches, deduped
  trunk rows, and analytic prefix FLOPs saved (CascadeStats);
- parity at the PR-7 bar: every request's argmax-derived payload fields
  (model responses, parsed confidence) are IDENTICAL between the two
  servers, float probabilities agree to tolerance — the cascade is a
  pure perf lever, invisible in results;
- the dense server never took the cascade path.

Runs hermetically on CPU with the FakeTokenizer + a tiny random decoder
(the cascade kernel under the Pallas interpreter, the tier-1 hook);
prints the CascadeStats summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_BASES = 3
WAVE = 8           # requests per shared-trunk wave (one batch's worth)
BASE_WORDS = 90    # long trunks: trunk prefill dominates, as in production
FLOAT_TOL = 5e-4


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    decoder.CASCADE_INTERPRET_ON_CPU = True   # tier-1 hook: kernel on CPU

    cfg = ModelConfig(name="cascade-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder.init_params(cfg, jax.random.PRNGKey(13))

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    rng = np.random.default_rng(29)
    bases = [" ".join(rng.choice(words) for _ in range(BASE_WORDS))
             for _ in range(N_BASES)]

    def request(b: int, i: int) -> ServeRequest:
        # The shared-trunk grid cell: one base trunk, a short varying tail.
        main_text = f"{bases[b]} case {i} maybe ?"
        return ServeRequest(
            binary_prompt=f"{main_text} Answer Yes or No .",
            confidence_prompt=f"{main_text} Give a number from 0 to 100 .",
            klass="smoke", request_id=f"{b}-{i}")

    def serve(cascade_on: bool):
        rt = RuntimeConfig(batch_size=WAVE, max_seq_len=512,
                           cascade_prefill=cascade_on)
        engine = ScoringEngine(params, cfg, FakeTokenizer(), rt)
        sc = ServeConfig(queue_depth=2 * WAVE, classes=(("smoke", 600.0),),
                         default_class="smoke", linger_s=0.01)
        server = ScoringServer(engine, "cascade-smoke", sc).start()
        payloads = []
        # One wave per base: every dispatch's rows share that base's
        # trunk (mixed-trunk dispatches would fall back dense — the
        # fallback counter asserts the grid actually cascaded).
        for b in range(N_BASES):
            futs = [server.submit(request(b, i)) for i in range(WAVE)]
            payloads.extend(f.result(timeout=600) for f in futs)
        server.stop()
        return engine, payloads

    eng_on, res_on = serve(True)
    eng_off, res_off = serve(False)

    failures = []
    bad = [r.request_id for r in res_on + res_off if r.status != "ok"]
    if bad:
        failures.append(f"non-ok results: {bad}")
    stats = eng_on.cascade_stats
    if stats.cascade_dispatches <= 0:
        failures.append("the shared-trunk grid never took the cascade "
                        "path (zero cascade dispatches)")
    if stats.trunk_rows_deduped <= 0:
        failures.append("zero trunk rows deduped")
    if stats.prefix_flops_saved <= 0:
        failures.append("zero prefix FLOPs saved — the cascade bought "
                        "no prefill work")
    if eng_off.cascade_stats.cascade_dispatches != 0:
        failures.append("--no-cascade-prefill engine still cascaded")
    exact = ("status", "model_response", "model_confidence_response",
             "confidence_value")
    close = ("token_1_prob", "token_2_prob", "weighted_confidence")
    for a, b in zip(res_on, res_off):
        if any(getattr(a, f, None) != getattr(b, f, None) for f in exact):
            failures.append(f"argmax-derived payload fields differ for "
                            f"request {a.request_id}")
            break
        if any(abs((getattr(a, f, 0.0) or 0.0) - (getattr(b, f, 0.0) or 0.0))
               > FLOAT_TOL for f in close):
            failures.append(f"float payload fields drift past {FLOAT_TOL} "
                            f"for request {a.request_id}")
            break
    if failures:
        for f in failures:
            print(f"CASCADE-SMOKE FAIL: {f}")
        return 1
    print(json.dumps(stats.summary()))
    print(f"cascade smoke: OK ({N_BASES * WAVE} requests over {N_BASES} "
          f"shared trunks, {stats.cascade_dispatches} cascade dispatches, "
          f"{stats.trunk_rows_deduped} trunk rows deduped, "
          f"{stats.prefix_flops_saved:.2e} prefix FLOPs saved, "
          f"cascade == dense at the PR-7 parity bar)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
