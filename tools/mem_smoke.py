#!/usr/bin/env python
"""Memory-governance smoke: the unified HBM governor exercised
end-to-end on the fake backend (`make mem-smoke`).

A seeded ``hbm_squeeze`` fault (faults/plan.py via wrap_governor)
shrinks the governor's ledger budget mid-run and auto-restores it. The
smoke asserts the §1o contract (DEPLOY.md):

1. OFFLINE — one perturbation grid swept twice on config-identical
   engines: squeeze OFF (baseline) and squeeze ON (budget cut to 5%
   for a few dispatch ticks mid-sweep). The ladder must walk DOWN
   under the squeeze (rung_downs nonzero) and back UP after it
   (rung_ups == rung_downs, level 0), zero dispatches may crash (row
   count intact, no quarantines), and every row must be BITWISE
   identical to the unpressured run — no degradation rung is allowed
   to change results.
2. ONLINE — the same squeeze against a ScoringServer mid-traffic:
   every request resolves "ok" (the ladder absorbs the squeeze;
   nothing is shed or errored at this depth), payloads bitwise vs an
   unpressured server over the same engine params, and the governor's
   gauges are visible in the server's metrics snapshot.

Runs hermetically on CPU (FakeTokenizer + tiny random decoder); prints
the MemStats summaries as JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_CELLS = 24
BATCH = 4

_VALUE_COLUMNS = ("Token_1_Prob", "Token_2_Prob", "Confidence Value",
                  "Weighted Confidence", "Model Response",
                  "Model Confidence Response", "Log Probabilities")


def _make_engine(seed=11):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import GovernorConfig, RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="mem-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    # piggyback OFF: the squeeze pass is compared BITWISE against the
    # baseline, so both must run the plain dispatch path (the chain's
    # cache extent reassociates reductions by a few ulps — same rule
    # as chaos_smoke). sustain_ticks=1 so the smoke's handful of
    # dispatches is enough for the ladder to move.
    return ScoringEngine(
        params, cfg, FakeTokenizer(),
        RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                      piggyback_prefill=False),
        governor_config=GovernorConfig(sustain_ticks=1))


def _grid(n_cells, seed=21):
    import numpy as np

    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([text(10 if i % 2 else 24) for i in range(n_cells - 1)],)
    return lp, perts


def _drain_ladder(governor, max_ticks=16) -> None:
    """The dispatches that would follow in a longer session: keep
    ticking until the ladder is fully re-armed (the smoke's grid is
    finite; a real serving session keeps dispatching)."""
    for _ in range(max_ticks):
        if governor.level == 0:
            return
        governor.tick()


def sweep_smoke(failures):
    import tempfile

    from lir_tpu import faults
    from lir_tpu.data import schemas
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid(N_CELLS)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        run_perturbation_sweep(_make_engine(), "mem", lp, perts,
                               td / "off.csv", checkpoint_every=4)
        off = schemas.read_results_frame(td / "off.csv")
        if len(off) != N_CELLS:
            failures.append(f"baseline sweep produced {len(off)} rows")
            return {}
        off_by_key = {
            (r["Rephrased Main Part"], r["Response Format"],
             r["Confidence Format"]): tuple(r[c] for c in _VALUE_COLUMNS)
            for _, r in off.iterrows()}

        engine = _make_engine()
        plan = faults.FaultPlan(seed=17, schedules={
            "hbm": faults.SiteSchedule.hbm_squeeze_at(1, frac=0.05,
                                                      calls=4)})
        faults.wrap_governor(engine.governor, plan)
        run_perturbation_sweep(engine, "mem", lp, perts, td / "on.csv",
                               checkpoint_every=4)
        gov = engine.governor
        if plan.injected("hbm") != 1:
            failures.append("sweep: scheduled hbm_squeeze never fired")
        if gov.stats.squeezes != 1:
            failures.append("sweep: governor never saw the squeeze")
        if not gov.stats.rung_downs:
            failures.append("sweep: the squeeze never walked the "
                            "ladder down")
        _drain_ladder(gov)
        if gov.level != 0:
            failures.append(f"sweep: ladder stuck at level {gov.level} "
                            f"after the squeeze cleared")
        if gov.stats.rung_ups != gov.stats.rung_downs:
            failures.append(
                f"sweep: ladder not fully reversible "
                f"(downs {gov.stats.rung_downs} vs ups "
                f"{gov.stats.rung_ups})")

        on = schemas.read_results_frame(td / "on.csv")
        keys = list(zip(on["Rephrased Main Part"], on["Response Format"],
                        on["Confidence Format"]))
        if len(keys) != N_CELLS or len(set(keys)) != N_CELLS:
            failures.append(
                f"squeezed sweep lost/duplicated rows ({len(keys)} "
                f"rows, {len(set(keys))} unique, expected {N_CELLS})")
        import pandas as pd

        for _, row in on.iterrows():
            k = (row["Rephrased Main Part"], row["Response Format"],
                 row["Confidence Format"])
            want = off_by_key.get(k)
            if want is None:
                failures.append(f"squeezed sweep invented a row: "
                                f"{k[0][:40]}")
                continue
            got = tuple(row[c] for c in _VALUE_COLUMNS)
            for g, w in zip(got, want):
                if pd.isna(g) and pd.isna(w):
                    continue
                if g != w:
                    failures.append(
                        f"squeezed row differs from baseline: {g!r} != "
                        f"{w!r} for {k[0][:40]}")
                    break
        return {"sweep_mem": gov.summary(),
                "injected": plan.stats.summary()}


def serve_smoke(failures):
    from lir_tpu import faults
    from lir_tpu.config import RetryConfig, ServeConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    cfg = ServeConfig(
        queue_depth=64, classes=(("smoke", 600.0),),
        default_class="smoke", linger_s=0.0, cache_entries=0,
        retry=RetryConfig(max_retries=1, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5))

    def request(i, rid=None):
        body = f"clause {i} covers wind damage under policy {i * 7}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=rid or str(i))

    fields = ("model_response", "model_confidence_response",
              "token_1_prob", "token_2_prob", "log_probabilities",
              "confidence_value", "weighted_confidence")

    def serve_all(server, tag):
        out = {}
        for i in range(12):
            r = server.submit(request(i, f"{tag}{i}")).result(timeout=60)
            if r.status != "ok":
                failures.append(
                    f"serve[{tag}]: request {i} resolved {r.status} "
                    f"({r.note!r}) — a squeeze at this depth must "
                    f"degrade, not refuse")
                continue
            out[i] = tuple(getattr(r, f) for f in fields)
        return out

    base_server = ScoringServer(_make_engine(), "mem-smoke", cfg).start()
    try:
        baseline = serve_all(base_server, "b")
    finally:
        base_server.stop()

    engine = _make_engine()
    plan = faults.FaultPlan(seed=23, schedules={
        "hbm": faults.SiteSchedule.hbm_squeeze_at(2, frac=0.05,
                                                  calls=4)})
    faults.wrap_governor(engine.governor, plan)
    server = ScoringServer(engine, "mem-smoke", cfg).start()
    try:
        squeezed = serve_all(server, "s")
        snap = server.metrics.snapshot(device_memory=False)
    finally:
        server.stop()
    gov = engine.governor
    if plan.injected("hbm") != 1:
        failures.append("serve: scheduled hbm_squeeze never fired")
    if not gov.stats.rung_downs:
        failures.append("serve: the squeeze never walked the ladder")
    _drain_ladder(gov)
    if gov.stats.rung_ups != gov.stats.rung_downs:
        failures.append(f"serve: ladder not reversible (downs "
                        f"{gov.stats.rung_downs} vs ups "
                        f"{gov.stats.rung_ups})")
    if "mem" not in snap.get("sources", {}):
        failures.append("serve: governor gauges missing from the "
                        "metrics snapshot")
    for i, want in baseline.items():
        got = squeezed.get(i)
        if got is None:
            continue        # already reported above
        if got != want:
            failures.append(
                f"serve: squeezed payload {i} differs from the "
                f"unpressured server")
    return {"serve_mem": gov.summary()}


def main() -> int:
    failures = []
    sweep_summary = sweep_smoke(failures)
    serve_summary = serve_smoke(failures)
    if failures:
        for f in failures:
            print(f"MEM-SMOKE FAIL: {f}")
        return 1
    print(json.dumps({"sweep": sweep_summary, "serve": serve_summary}))
    print("mem smoke: OK (seeded hbm_squeeze walked the degradation "
          "ladder down and back up in both the sweep and serve paths; "
          "zero crashed dispatches; rows and payloads bitwise-identical "
          "to unpressured runs; governor gauges live in the metrics "
          "snapshot)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
