#!/usr/bin/env python
"""Observatory smoke: the reliability-observatory + telemetry-spine
invariants the `make observe-smoke` CI target guards:

- a 2-model fake fleet re-scores a sentinel grid across 3 time
  windows; windows 1-2 are clean and raise NO alert (deterministic
  greedy decode -> identical clean windows -> zero false alarms);
- a seeded fault-plan NaN injection on ONE model's dispatches during
  window 3 raises EXACTLY ONE drift alert, carrying window 3's
  identity (the injected model's valid fraction collapses and the
  alert names it);
- per-window fleet kappa is BITWISE the analysis layer's
  within_group_kappa over the same decisions (one contingency code
  path everywhere);
- the unified metrics snapshot ({"op": "metrics"} schema) is non-empty
  for EVERY registered stats source and JSON round-trips.

Runs hermetically on CPU with FakeTokenizer + tiny random decoders;
prints the observatory summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_MODELS = 2
SENTINELS = ["Is a cat an animal", "Is rain considered weather",
             "Is a contract binding"]
WINDOW_S = 100.0


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import ObserveConfig, RuntimeConfig, ServeConfig
    from lir_tpu.engine.fleet import ModelFleet
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.faults.plan import FaultPlan, SiteSchedule
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.observe import SentinelScheduler
    from lir_tpu.serve import FleetScoringServer, ServeRequest
    from lir_tpu.stats.kappa import within_group_kappa

    names = [f"org/obs-m{i}" for i in range(N_MODELS)]

    def _cfg(name):
        return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                           hidden_size=32, n_layers=1, n_heads=2,
                           intermediate_size=64, max_seq_len=256)

    engines = [
        (n, ScoringEngine(
            decoder.init_params(_cfg(n), jax.random.PRNGKey(i)),
            _cfg(n), FakeTokenizer(),
            RuntimeConfig(batch_size=4, max_seq_len=256)))
        for i, n in enumerate(names)]
    fleet = ModelFleet.from_engines(engines)
    server = FleetScoringServer(
        fleet, ServeConfig(linger_s=0.005)).start()

    failures = []
    now = {"t": WINDOW_S}          # start inside window 1
    cfg = ObserveConfig(sentinel_interval_s=1.0,
                        sentinel_window_s=WINDOW_S,
                        drift_sigma=3.0, drift_min_windows=2)
    sched = SentinelScheduler(
        server,
        [ServeRequest(binary_prompt=f"{q} Answer Yes or No.",
                      confidence_prompt=f"{q} Give a confidence 0-100.",
                      request_id=f"s{i}")
         for i, q in enumerate(SENTINELS)],
        cfg=cfg, clock=lambda: now["t"])
    server.attach_observatory(sched)

    # Windows 1 and 2: two clean sweeps each.
    for w in (1, 2):
        now["t"] = w * WINDOW_S + 1.0
        assert sched.tick() is not None
        now["t"] += 2.0
        assert sched.tick() is not None

    # Window 3: seeded NaN corruption on model 0's dispatches — the
    # numerics guard quarantines every row, the model's sentinel
    # decisions go invalid, valid_frac collapses.
    plan = FaultPlan(seed=7, schedules={
        "dispatch": SiteSchedule(rate=1.0, kind="nan",
                                 nan_rows=(0, 1, 2, 3))})
    victim = server.batcher.batchers[names[0]]
    original_score = victim.score
    victim.score = plan.wrap("dispatch", victim.score)
    now["t"] = 3 * WINDOW_S + 1.0
    assert sched.tick() is not None
    now["t"] += 2.0
    assert sched.tick() is not None
    victim.score = original_score

    # Cross into window 4 so window 3 finalizes, then close the books.
    now["t"] = 4 * WINDOW_S + 1.0
    sched.finalize_closed()
    sched.finalize_all()
    obs = sched.summary()

    if len(obs["windows"]) != 3:
        failures.append(f"expected 3 finalized windows, got "
                        f"{len(obs['windows'])}")
    alerts = obs["alerts"]
    if len(alerts) != 1:
        failures.append(f"expected exactly 1 drift alert, got "
                        f"{len(alerts)}: {alerts}")
    elif alerts[0]["window"] != 3:
        failures.append(f"alert names window {alerts[0]['window']}, "
                        f"expected 3")
    elif not any(m.get("model") == names[0]
                 for m in alerts[0]["metrics"]):
        failures.append(f"alert does not name the injected model: "
                        f"{alerts[0]['metrics']}")
    for w in obs["windows"][:2]:
        if w.get("drifted"):
            failures.append(f"clean window {w['window']} false-alarmed")

    # Per-window kappa bitwise vs the analysis layer on the same counts.
    for w in obs["windows"]:
        n_g = np.asarray(w["counts"]["n_g"], np.int64)
        s_g = np.asarray(w["counts"]["s_g"], np.int64)
        decisions, groups = [], []
        for g, (n, s) in enumerate(zip(n_g, s_g)):
            decisions += [1] * int(s) + [0] * int(n - s)
            groups += [g] * int(n)
        ref = within_group_kappa(np.asarray(decisions, int),
                                 np.asarray(groups, int))
        if w["kappa"]["kappa"] != ref["kappa"] and not (
                np.isnan(w["kappa"]["kappa"])
                and np.isnan(ref["kappa"])):
            failures.append(
                f"window {w['window']} kappa {w['kappa']['kappa']} != "
                f"within_group_kappa {ref['kappa']}")

    # Metrics snapshot: non-empty fields for every registered source,
    # and the document survives a strict-JSON round trip.
    snap = server.metrics.snapshot()
    if not snap["sources"]:
        failures.append("metrics snapshot has no sources")
    for name, src in snap["sources"].items():
        if not src.get("fields"):
            failures.append(f"metrics source {name} has empty fields")
    if json.loads(json.dumps(snap)) != snap:
        failures.append("metrics snapshot does not JSON round-trip")
    if snap["counters"].get("sentinel_sweeps") != 6:
        failures.append(f"expected 6 sentinel_sweeps in the registry, "
                        f"got {snap['counters'].get('sentinel_sweeps')}")

    server.stop()
    fleet.shutdown()
    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("observe smoke OK")
    print(json.dumps({"windows": len(obs["windows"]),
                      "alerts": alerts,
                      "sweeps": obs["sweeps"]}, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
