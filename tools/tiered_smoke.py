#!/usr/bin/env python
"""Tiered-memory smoke: the HBM -> host DRAM -> disk KV ladder
(serve/tiers.py, DEPLOY.md §1s) on the fake backend — the
`make tiered-smoke` CI target.

Serves a shared-prefix working set LARGER than the HBM page budget on a
tiered server (tiny host pool, so demotions spill through to the disk
tier), demotes the whole radix tree between passes the way the
governor's ``evict_pages`` rung would, and asserts the PR's
load-bearing claims:

- NONZERO demotions AND promotions: the warm pass resumed prefixes
  from the host/disk ladder through the paged-warm import path instead
  of re-prefilling them;
- every payload is BITWISE-identical to the same stream served with
  tiering OFF — the ladder is a pure capacity lever, invisible in
  results;
- restart-warm: after the process "dies" (server + engine discarded,
  only the disk directory survives), a fresh server on the same
  ``disk_dir`` re-seeds its radix tree from the index, serves the same
  stream with nonzero prefill-tokens-avoided, and stays bitwise.

Runs hermetically on CPU; prints the TierStats summaries as JSON on
success.
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_BASES = 4
N_REQUESTS = 12
BASE_WORDS = 90    # long trunks: the working set outgrows the page pool
POOL_PAGES = 48    # HBM page budget — smaller than the 4-base working set

PAYLOAD_FIELDS = ("status", "model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig, TierConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    cfg = ModelConfig(name="tiered-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder.init_params(cfg, jax.random.PRNGKey(5))

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    rng = np.random.default_rng(29)
    bases = [" ".join(rng.choice(words) for _ in range(BASE_WORDS))
             for _ in range(N_BASES)]

    def request(i: int) -> ServeRequest:
        body = f"{bases[i % N_BASES]} case {i} ?"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=str(i))

    def fresh_engine() -> ScoringEngine:
        return ScoringEngine(params, cfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=4, max_seq_len=512,
                                           prefix_cache=True,
                                           prefix_cache_pages=POOL_PAGES))

    # cache_entries=0: exact-dedup would answer the warm re-asks from
    # the result cache and the tier probe would never run — this smoke
    # is about the KV ladder, not dedup.
    serve_cfg = ServeConfig(queue_depth=N_REQUESTS + 8, prefix_cache=True,
                            cache_entries=0, classes=(("smoke", 600.0),),
                            default_class="smoke", linger_s=0.01)

    def serve_stream(server) -> list:
        futs = [server.submit(request(i)) for i in range(N_REQUESTS)]
        return [f.result(timeout=600) for f in futs]

    failures = []

    # Baseline: tiering OFF, same stream, same params.
    base_srv = ScoringServer(fresh_engine(), "tiered-smoke",
                             serve_cfg).start()
    base = serve_stream(base_srv)
    base_srv.stop()

    with tempfile.TemporaryDirectory(prefix="tiered_smoke_") as tmp:
        tiers = TierConfig(enabled=True, disk_dir=tmp,
                           host_budget_mb=0.05,   # tiny: spill to disk
                           disk_timeout_s=30.0, restart_warm=True)
        srv = ScoringServer(fresh_engine(), "tiered-smoke", serve_cfg,
                            tiers=tiers).start()
        cold = serve_stream(srv)
        store = srv.tiers

        # Demote the whole tree (the evict_pages rung under sustained
        # pressure) on the supervisor thread, then re-ask everything:
        # the promote probe must warm the trunks back from the ladder.
        def demote_all(eng):
            while store.demote(eng, n_pages=POOL_PAGES):
                pass
        srv.submit_page_op(demote_all).result(timeout=60)
        warm = serve_stream(srv)
        summary_live = store.summary()
        srv.stop()

        if not summary_live.get("pages_demoted"):
            failures.append("zero demotions — nothing left HBM for the "
                            "ladder")
        if not summary_live.get("pages_promoted"):
            failures.append("zero promotions — the warm pass never "
                            "resumed from the host/disk tiers")
        if summary_live.get("checksum_refusals"):
            failures.append("checksum refusals on a healthy ladder: "
                            f"{summary_live}")

        # Restart-warm: the process dies; only the disk dir survives.
        del srv, store
        srv2 = ScoringServer(fresh_engine(), "tiered-smoke", serve_cfg,
                             tiers=tiers).start()
        reseeded = srv2.tiers.summary().get("restart_pages_reseeded", 0)
        rewarm = serve_stream(srv2)
        hit_tokens = srv2.engine.prefix_stats.hit_tokens
        summary_restart = srv2.tiers.summary()
        srv2.stop()

        if not reseeded:
            failures.append("restart-warm re-seeded zero pages from the "
                            "disk tier")
        if hit_tokens <= 0:
            failures.append("zero prefill tokens avoided after restart — "
                            "the re-seeded tree never served a hit")
        for name, got in (("tiered-cold", cold), ("tiered-warm", warm),
                          ("restart-warm", rewarm)):
            bad = [r.request_id for r, ref in zip(got, base)
                   if any(getattr(r, f, None) != getattr(ref, f, None)
                          for f in PAYLOAD_FIELDS)]
            if bad:
                failures.append(f"{name} payloads differ from the "
                                f"untiered baseline: requests {bad}")

        if failures:
            for f in failures:
                print(f"TIERED-SMOKE FAIL: {f}")
            return 1
        print(json.dumps({"tiered_smoke": "ok",
                          "live": summary_live,
                          "restart": summary_restart}, indent=2))
        print(f"tiered smoke: OK ({3 * N_REQUESTS} tiered requests over "
              f"{N_BASES} shared bases, "
              f"{summary_live['pages_demoted']} pages demoted, "
              f"{summary_live['pages_promoted']} promoted, "
              f"{reseeded} re-seeded after restart, "
              f"{hit_tokens} prefill tokens avoided restart-warm, "
              f"tiered == untiered bitwise)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
