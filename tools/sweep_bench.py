"""End-to-end PERTURBATION-SWEEP throughput at 7B scale — the literal
BASELINE.json metric ("prompts/sec/chip on the perturbation sweep").

bench.py measures the fused scoring step in isolation (in-scan, checksum-
synced). This tool measures the whole production loop around it:
grid build -> manifest resume filter -> length bucketing/padding ->
tokenization -> fused binary + confidence decodes -> top-20 logprob map ->
D6 Excel append + manifest write-ahead — `engine.sweep.run_perturbation_
sweep` exactly as the CLI runs it, on a full-size registry preset
(--model, default llama-2-7b; random weights, dynamic int8 + int8 KV
cache) with long rephrasings that
land in the 256-token bucket at the default N_WORDS, as the real legal
prompts do (SURVEY.md §6:
prompt + format <= ~700 tokens).

A warmup sweep (separate results dir) triggers the two jit compiles; the
timed sweep then runs all-warm, matching steady-state operation where one
compile serves ~20k grid cells. Appends measured numbers to SCALE.md.

Run on the TPU:  python tools/sweep_bench.py [--cells 192] [--batch 48]
"""

from __future__ import annotations

import argparse
import datetime
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

SCALE_MD = REPO / "SCALE.md"

WORDS = ("coverage policy flood water damage claim insurer holder premium "
         "exclusion endorsement rider peril deductible adjuster settle "
         "liability clause binding interpret statute ordinary meaning").split()


N_WORDS = 170  # + format lines -> the 256-token bucket for FakeTokenizer


def _long_text(rng, n_words: int) -> str:
    return " ".join(rng.choice(WORDS) for _ in range(n_words)) + " ?"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=192)
    # batch 40 is the measured sweet spot for the shared-prefix path (48
    # OOMs: the shared cache carries suffix+gen slack slots; SCALE.md r3).
    ap.add_argument("--batch", type=int, default=40)
    ap.add_argument("--words", type=int, default=N_WORDS,
                    help="rephrasing length in words (~tokens for the fake "
                         "tokenizer): 170 -> 256-token bucket, 700 -> 1024 "
                         "(long-context sweep)")
    ap.add_argument("--model", default="llama2_7b",
                    help="registry preset for the full-size run "
                         "(default llama2_7b)")
    ap.add_argument("--conf-tokens", type=int, default=None,
                    help="override RuntimeConfig.sweep_confidence_tokens "
                         "(budget x throughput table for SCALE.md)")
    ap.add_argument("--decode-tokens", type=int, default=None,
                    help="override RuntimeConfig.sweep_decode_tokens")
    ap.add_argument("--no-record", action="store_true",
                    help="print only; do not append to SCALE.md")
    args = ap.parse_args()
    if args.cells < args.batch:
        raise SystemExit(
            f"--cells {args.cells} < --batch {args.batch}: the timed run "
            f"would measure a tail-bucket jit compile, not throughput — "
            f"pass --cells >= --batch (a multiple of it)")
    if args.cells % args.batch:
        # A ragged cell count leaves a tail bucket whose power-of-two
        # batch compiles INSIDE the timed run (~17 s at 7B) — measuring
        # compile, not steady state. Snap down to full buckets.
        snapped = args.cells - args.cells % args.batch
        print(f"# snapping --cells {args.cells} -> {snapped} "
              f"(multiple of batch {args.batch}; a tail bucket would time "
              f"an extra jit compile)")
        args.cells = snapped

    import dataclasses

    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import quant

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    if not on_accel:
        print("# no accelerator: running the tiny CPU smoke variant")

    if on_accel:
        from tools.scale_validation import resolve_preset
        cfg = dataclasses.replace(resolve_preset(args.model),
                                  kv_cache_int8=True)
        params = quant.random_quantized_params(
            cfg, jax.random.PRNGKey(0), dtype=jax.numpy.bfloat16,
            dynamic=True)
        mode = f"{cfg.name} int8-dyn+kvq8"
    else:
        from lir_tpu.models import decoder
        from lir_tpu.models.registry import ModelConfig
        cfg = ModelConfig(name="sweep-smoke", vocab_size=1024, hidden_size=64,
                          n_layers=2, n_heads=4, intermediate_size=128,
                          max_seq_len=512)
        params = decoder.init_params(cfg, jax.random.PRNGKey(0))
        mode = "0.2M-smoke fp32"

    rt = RuntimeConfig(batch_size=args.batch,
                       max_seq_len=max(512, 2 * args.words))
    if args.conf_tokens is not None:
        rt = dataclasses.replace(rt, sweep_confidence_tokens=args.conf_tokens)
    if args.decode_tokens is not None:
        rt = dataclasses.replace(rt, sweep_decode_tokens=args.decode_tokens)
    engine = ScoringEngine(params, cfg, FakeTokenizer(), rt)
    mode += (f", budgets bin={rt.sweep_decode_tokens}"
             f"/conf={rt.sweep_confidence_tokens}")

    rng = np.random.default_rng(7)
    lp = (LegalPrompt(
        main=_long_text(rng, args.words),
        response_format="Respond with either ' Yes' or ' No' only .",
        target_tokens=("Yes", "No"),
        confidence_format="Give a confidence number from 0 to 100 ."),)

    def run(n_cells: int, tag: str) -> float:
        perts = ([_long_text(rng, args.words)
                  for _ in range(n_cells - 1)],)
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            rows = run_perturbation_sweep(
                engine, f"sweep-bench-{tag}", lp, perts,
                Path(td) / "results.xlsx", checkpoint_every=100)
            dt = time.perf_counter() - t0
        assert len(rows) == n_cells, (len(rows), n_cells)
        assert all(np.isfinite(r.token_1_prob) for r in rows)
        return dt

    warm_cells = args.batch  # one full bucket: triggers both compiles
    t_warm = run(warm_cells, "warmup")
    print(f"# warmup ({warm_cells} cells incl. compiles): {t_warm:.1f}s")
    t = run(args.cells, "timed")
    rate = args.cells / t
    print(f"sweep_bench: {args.cells} grid cells in {t:.1f}s -> "
          f"{rate:.2f} prompts/s/chip end-to-end ({mode}, batch "
          f"{args.batch}, ~{args.words}-word rephrasings, "
          f"binary+confidence per cell)")

    if args.no_record or not on_accel:
        return
    date = datetime.date.today().isoformat()
    SCALE_MD.write_text(SCALE_MD.read_text() + f"""
## end-to-end sweep throughput — {dev.device_kind}, {date}

`run_perturbation_sweep` exactly as the CLI runs it (grid + manifest +
bucketing + tokenize + binary & confidence fused decodes + top-20 logprob
maps + D6 Excel/manifest writes), {mode}, batch {args.batch},
~{args.words}-word rephrasings:

- {args.cells} grid cells in {t:.1f}s = **{rate:.2f} prompts/s/chip
  end-to-end** (warm; compile-inclusive warmup bucket took {t_warm:.1f}s)
- vs bench.py's isolated scoring step at the same batch: the gap is the
  real orchestration overhead (host readback, Excel append, manifest).
""")
    print("recorded to SCALE.md")


if __name__ == "__main__":
    main()
