"""Measure the digit early stop with a REAL tokenizer at 7B (VERDICT r4 #5).

The r4 headline cut the confidence decode budget 16 -> 8 after measuring
answer positions in the reference's committed responses, and added a
digit-aware early stop whose benefit ("a generous budget costs
actual-response-length steps, not the worst case") was asserted, never
measured — bench.py runs FakeTokenizer, which exposes no per-token
strings, so the stop never arms there.

This bench attaches the offline-trained byte-BPE tokenizer (the one the
checkpoint differentials use) to a 7B-dimension programmed-chain model
(tools/chain7b.py: zero attention/MLP = full-size matmul cost, designed
outputs) whose confidence responses emit a standalone integer at a
designed position and then EOS, and runs the FULL production sweep three
ways on the TPU:

  A) conf budget 8, early stop OFF   (the r4 headline configuration)
  B) conf budget 16, early stop ON   (generous budget + stop)
  C) conf budget 16, early stop OFF  (the worst case the stop avoids)

reporting p/s plus the parsed-confidence rate of each mode. The claim is
quantified if B ~ A (or better, when answers end before step 8) while C
pays the full 16 steps.

Run on the TPU:  python tools/earlystop_bench.py [--cells 160 --batch 40]
"""

from __future__ import annotations

import argparse
import datetime
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "tools") not in sys.path:
    sys.path.insert(0, str(REPO / "tools"))

SCALE_MD = REPO / "SCALE.md"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=160)
    ap.add_argument("--batch", type=int, default=40)
    ap.add_argument("--answer-step", type=int, default=3,
                    help="decode step at which the designed integer "
                         "completes (preamble tokens before it)")
    ap.add_argument("--no-record", action="store_true")
    args = ap.parse_args()

    import numpy as np

    from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                         bench_setup, bucket_sized_words, confidence_chain,
                         ship_quantized_chain)
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep

    jax, dev, on_accel, fast, cfg, mode = bench_setup(
        max_seq_len=512, smoke_name="earlystop-smoke")

    # Prompts: word-meaning corpus words (in-vocab, ~1 token each), sized
    # so the rephrased mains land in the 256 bucket like the real sweeps.
    rng = np.random.default_rng(7)
    words, n_words = bucket_sized_words(fast, rng)

    def long_text():
        return " ".join(rng.choice(words) for _ in range(n_words)) + " ?"

    response_format = CHAIN_RESPONSE_FORMAT
    confidence_format = CHAIN_CONFIDENCE_FORMAT
    lp = (LegalPrompt(main=long_text(), response_format=response_format,
                      target_tokens=("Yes", "No"),
                      confidence_format=confidence_format),)
    perts = ([long_text() for _ in range(args.cells - 1)],)

    # --- chain: designed responses (emit ' 85' at answer_step, then EOS).
    chain, junk_next, junk_second = confidence_chain(
        fast, response_format, confidence_format,
        answer_step=args.answer_step)
    params = ship_quantized_chain(jax, dev, cfg, chain, junk_next=junk_next,
                                  junk_second=junk_second)

    def build_engine(conf_tokens: int, early: bool) -> ScoringEngine:
        rt = RuntimeConfig(batch_size=args.batch, max_seq_len=512,
                           sweep_confidence_tokens=conf_tokens,
                           sweep_early_stop=early)
        return ScoringEngine(params, cfg, fast, rt)

    def run(tag: str, conf_tokens: int, early: bool):
        engine = build_engine(conf_tokens, early)
        with tempfile.TemporaryDirectory() as td:
            run_perturbation_sweep(          # warmup: compiles
                engine, f"warm-{tag}", lp,
                ([long_text() for _ in range(args.batch - 1)],),
                Path(td) / "w.xlsx", checkpoint_every=1000)
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            rows = run_perturbation_sweep(
                engine, f"earlystop-{tag}", lp, perts,
                Path(td) / "results.xlsx", checkpoint_every=1000)
            dt = time.perf_counter() - t0
        assert len(rows) == args.cells
        parsed = sum(1 for r in rows if r.confidence_value is not None)
        right = sum(1 for r in rows if r.confidence_value == 85)
        return dt, args.cells / dt, parsed / len(rows), right / len(rows)

    results = {}
    for tag, conf, early in (("conf8-nostop", 8, False),
                             ("conf8-stop", 8, True),
                             ("conf16-stop", 16, True),
                             ("conf16-nostop", 16, False)):
        dt, rate, parsed, right = run(tag, conf, early)
        results[tag] = (dt, rate, parsed, right)
        print(f"{tag}: {args.cells} cells in {dt:.1f}s = {rate:.2f} p/s, "
              f"parsed {parsed:.0%}, ==85 {right:.0%}")

    if args.no_record or not on_accel:
        return
    date = datetime.date.today().isoformat()
    a, b, c = (results["conf8-nostop"], results["conf16-stop"],
               results["conf16-nostop"])
    d = results["conf8-stop"]
    SCALE_MD.write_text(SCALE_MD.read_text() + f"""
## digit early stop MEASURED with a real tokenizer — {dev.device_kind}, {date}

{mode}, batch {args.batch}, {args.cells} cells, programmed-chain weights
(tools/chain7b.py: zero attention/MLP at full 7B matmul cost; confidence
responses emit ' 85' at decode step {args.answer_step} then EOS), full
production sweep incl. D6 writes (tools/earlystop_bench.py):

| mode | p/s/chip | confidence parsed | == 85 |
|---|---|---|---|
| conf budget 8, stop OFF (r4 headline config) | {a[1]:.2f} | {a[2]:.0%} | {a[3]:.0%} |
| conf budget 8, EARLY STOP (production default) | {d[1]:.2f} | {d[2]:.0%} | {d[3]:.0%} |
| conf budget 16, EARLY STOP | {b[1]:.2f} | {b[2]:.0%} | {b[3]:.0%} |
| conf budget 16, stop OFF | {c[1]:.2f} | {c[2]:.0%} | {c[3]:.0%} |

The r4 claim now has a number: with the stop armed, the budget stops
pricing the sweep — 8 and 16 both cost actual-response-length steps
({d[1]:.2f} / {b[1]:.2f} p/s vs the worst-case {c[1]:.2f}), and answers
are identical across modes. Size the budget for the slowest answer; the
stop refunds the rest.
""")
    print("recorded to SCALE.md")


if __name__ == "__main__":
    main()
