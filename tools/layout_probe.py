"""Attack the batch-64 OOM wall with AUTO input layouts (VERDICT r2 #6).

The round-2/3 OOM dumps blame HLO-temp layout copies: XLA materializes
relaid-out copies of the int8 weight stacks (3-4 x 512 MiB) and of the KV
cache when the layout a producer (prefill scan) prefers differs from what
the decode while-loop wants. Chasing the preferred layout by hand failed in
round 2 (the preference MOVES). This probe lets XLA pick the INPUT layouts
itself: compile the fused scoring step with `Format(Layout.AUTO)` on the
params, then device_put the params into the compiled executable's chosen
formats — if the copies were input-layout-induced, they disappear and the
fit boundary moves.

Measures, on the real chip (llama-2-7b int8-dyn + int8 KV, seq 256):
  A. plain fused step, default layouts:  batch 48 (r2 knee), batch 64 (OOM?)
  B. plain fused step, AUTO layouts:     batch 48, batch 64
Appends results to SCALE.md.  Run:  python tools/layout_probe.py
"""

from __future__ import annotations

import dataclasses
import datetime
import gc
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.scale_validation import SCALE_MD, _append  # noqa: E402


def run_one(mode: str, batch: int) -> str:
    """One (layout-mode, batch) measurement in THIS process — modes run in
    separate processes so the default-layout tree and the relaid-out copy
    never co-reside in HBM (6.4 GiB each; both at once OOMs the probe
    itself)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.layout import Format, Layout

    from lir_tpu.engine import generate, score
    from lir_tpu.models import quant
    from lir_tpu.models.registry import llama2_7b

    dev = jax.devices()[0]
    assert dev.platform != "cpu", "run on the TPU"

    cfg = dataclasses.replace(llama2_7b(), kv_cache_int8=True)
    params = quant.random_quantized_params(cfg, jax.random.PRNGKey(0),
                                           dtype=jnp.bfloat16, dynamic=True)
    jax.block_until_ready(params)
    seq, new_tokens = 256, 10

    def build(batch):
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        mask = jnp.ones_like(toks)
        yes = jnp.full((batch,), 1, jnp.int32)
        no = jnp.full((batch,), 2, jnp.int32)

        def f(params, toks, mask, yes, no):
            fused = generate.greedy_decode_fused.__wrapped__(
                params, cfg, toks, mask, yes, no,
                jnp.arange(10, 110, dtype=jnp.int32),
                jnp.arange(0, 100, dtype=jnp.float32),
                max_new_tokens=new_tokens)
            res = score.readout_from_fused(fused, yes, no)
            return jnp.sum(res.yes_prob) + jnp.sum(res.no_prob)

        return f, (toks, mask, yes, no)

    def timed(run, *args):
        t0 = time.perf_counter()
        chk = float(run(*args))
        compile_s = time.perf_counter() - t0
        assert np.isfinite(chk)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            chk = float(run(*args))
            best = min(best, time.perf_counter() - t0)
        assert np.isfinite(chk)
        return compile_s, best

    from lir_tpu.utils.profiling import is_oom_error as is_oom

    f, args = build(batch)
    try:
        if mode == "default":
            _, step_s = timed(jax.jit(f), params, *args)
        else:
            auto = Format(Layout.AUTO)
            jf = jax.jit(f, in_shardings=(auto,) + (None,) * 4)
            compiled = jf.lower(params, *args).compile()
            fmts = compiled.input_formats[0][0]
            # Relayout IN PLACE leaf-by-leaf: drop each default-layout leaf
            # as soon as its AUTO-format copy lands, so peak extra HBM is
            # one weight stack, not a whole second tree.
            leaves, treedef = jax.tree.flatten(params)
            fmt_leaves = jax.tree.flatten(fmts)[0]
            for i in range(len(leaves)):
                leaves[i] = jax.device_put(leaves[i], fmt_leaves[i])
            p_opt = jax.tree.unflatten(treedef, leaves)
            del leaves
            gc.collect()
            jax.block_until_ready(p_opt)
            _, step_s = timed(compiled, p_opt, *args)
        return f"{step_s:.3f}s ({batch / step_s:.1f} p/s)"
    except Exception as err:  # noqa: BLE001
        if not is_oom(err):
            raise
        return "OOM"


def main() -> None:
    import argparse
    import datetime as _dt
    import subprocess
    import sys as _sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--one", nargs=2, metavar=("MODE", "BATCH"),
                    help="internal: run a single (mode, batch) measurement")
    args = ap.parse_args()
    if args.one:
        print("RESULT::" + run_one(args.one[0], int(args.one[1])), flush=True)
        return

    results = {}
    for batch in (48, 64):
        for mode in ("default", "auto"):
            proc = subprocess.run(
                [_sys.executable, __file__, "--one", mode, str(batch)],
                capture_output=True, text=True, timeout=560)
            out = [l for l in proc.stdout.splitlines()
                   if l.startswith("RESULT::")]
            results[(mode, batch)] = (out[0][8:] if out
                                      else f"FAILED rc={proc.returncode}")
            print(mode, batch, results[(mode, batch)], flush=True)
            if not out and proc.returncode != 0:
                from lir_tpu.utils.profiling import is_oom_error

                tail = (proc.stderr or "")[-1500:]
                if is_oom_error(tail):
                    results[(mode, batch)] = "OOM"
                else:
                    print(tail, flush=True)
    rows = [f"| {b} | {results[('default', b)]} | {results[('auto', b)]} |"
            for b in (48, 64)]

    _append(
        f"\n## AUTO-layout probe (batch-64 wall) — "
        f"{_dt.date.today()}\n\n"
        "llama-2-7b int8-dyn + int8 KV, fused scoring step (prefill 256 + "
        "10 decode), params device_put into the executable's "
        "Layout.AUTO-chosen input formats vs default layouts:\n\n"
        "| batch | default layouts | AUTO input layouts |\n"
        "|---|---|---|\n" + "\n".join(rows) + "\n")
    print(f"appended to {SCALE_MD}")


if __name__ == "__main__":
    main()
