#!/usr/bin/env python
"""Speculative-decode smoke: prompt-lookup drafting + fused verification
on the fake backend — the `make spec-smoke` CI target.

Runs the production-shaped confidence-tail workload (variations of a few
long legal bases, each scored under the binary + confidence formats)
through the shared dispatch path twice per engine — the second pass is
the speculation-friendly one (the radix tree's token history holds every
prompt's observed continuation after pass 1). Asserts the PR's
load-bearing claims:

- nonzero accepted tokens: the tree-continuation drafts actually land
  (pass 2 accept rate is high on a repeat grid by construction);
- >= 2x fewer decode dispatches per row on pass 2: verify forwards vs
  the forwards the sequential scan would have run (SpecStats
  decode_forwards vs seq_forwards), the headline target;
- ON == OFF payloads bitwise: every consumed readout (position-0
  probabilities, top-20 logprob map, weighted confidence, generated
  token streams) is identical between the speculative and sequential
  engines, cold and warm — speculation is a pure perf lever.

Runs hermetically on CPU with the FakeTokenizer + a tiny random decoder;
prints the SpecStats summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_BASES = 3
N_VARIANTS = 4
BASE_WORDS = 60
NEW_TOKENS = 4
CONF_TOKENS = 8
SPEC_K = 4


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine import tokens as tok
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="spec-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder.init_params(cfg, jax.random.PRNGKey(13))
    tokz = FakeTokenizer()

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    rng = np.random.default_rng(29)
    bases = [" ".join(rng.choice(words) for _ in range(BASE_WORDS))
             for _ in range(N_BASES)]
    cells = [(f"{b} case {v} Answer Yes or No .",
              f"{b} case {v} Give your confidence 0 to 100 .")
             for b in bases for v in range(N_VARIANTS)]
    B = len(cells)

    def make_engine(spec_on: bool) -> ScoringEngine:
        rt = RuntimeConfig(spec_decode=spec_on, spec_k=SPEC_K,
                           batch_size=B, piggyback_prefill=False,
                           prefix_cache=True, prefix_cache_pages=256)
        return ScoringEngine(params, cfg, tokz, runtime=rt)

    def dispatch(eng: ScoringEngine, record: bool):
        bps = [c[0] for c in cells]
        cps = [c[1] for c in cells]
        yes = np.full((B,), eng.yes_id, np.int32)
        no = np.full((B,), eng.no_id, np.int32)
        fused, cfused = eng.decode_fused_shared(
            bps, cps, yes, no, new_tokens=NEW_TOKENS,
            conf_tokens=CONF_TOKENS, reuse_cache=True)
        fused, cfused = jax.device_get((fused, cfused))
        if record:
            with eng._tok_lock:
                bin_ids = [tokz(p).input_ids for p in bps]
                conf_ids = [tokz(p).input_ids for p in cps]
            lcp = [tok.shared_prefix_len(a, b)
                   for a, b in zip(bin_ids, conf_ids)]
            bucket = tok.pick_bucket([max(n, 1) for n in lcp], eng.buckets)
            eng.spec_record(bucket, bin_ids, np.asarray(fused.generated), B)
            eng.spec_record(bucket, conf_ids, np.asarray(cfused.generated),
                            B)
        return fused, cfused

    eng_on = make_engine(True)
    eng_off = make_engine(False)

    on1 = dispatch(eng_on, record=True)
    eng_on.spec_flush()
    pass1_fwd = eng_on.spec_stats.decode_forwards
    on2 = dispatch(eng_on, record=False)
    eng_on.spec_flush()
    off1 = dispatch(eng_off, record=False)
    off2 = dispatch(eng_off, record=False)

    # -- claim 3: ON == OFF payloads bitwise, cold and warm ------------------
    def assert_consumed_bitwise(tag, on, off):
        for pair_name, a, b in (("binary", on[0], off[0]),
                                ("confidence", on[1], off[1])):
            for field in ("generated", "top2_ids", "topk_logprobs",
                          "topk_ids", "weighted_confidence"):
                av = np.asarray(getattr(a, field))
                bv = np.asarray(getattr(b, field))
                assert np.array_equal(av, bv), \
                    f"{tag}/{pair_name}.{field} diverged ON vs OFF"
            for field in ("p_yes", "p_no"):
                av = np.asarray(getattr(a, field))[:, 0]
                bv = np.asarray(getattr(b, field))[:, 0]
                assert np.array_equal(av, bv), \
                    f"{tag}/{pair_name}.{field}[pos0] diverged ON vs OFF"

    assert_consumed_bitwise("cold", on1, off1)
    assert_consumed_bitwise("warm", on2, off2)

    s = eng_on.spec_stats
    summary = s.summary()
    print(json.dumps(summary, indent=2))

    # -- claim 1: drafts landed ----------------------------------------------
    assert s.accepted_tokens > 0, "no draft token was ever accepted"
    assert s.draft_tree > 0, "the tree-continuation drafter never fired"

    # -- claim 2: >= 2x fewer decode forwards on the warm pass ---------------
    warm_fwd = s.decode_forwards - pass1_fwd
    warm_seq = s.seq_forwards - pass1_fwd  # pass 1 ran ~sequential counts
    ratio = warm_seq / max(warm_fwd, 1)
    print(f"warm decode forwards: {warm_fwd} vs sequential {warm_seq} "
          f"({ratio:.2f}x fewer)")
    assert ratio >= 2.0, \
        f"expected >= 2x fewer decode dispatches, got {ratio:.2f}x"

    print("spec smoke OK: drafts accepted, >= 2x fewer decode dispatches, "
          "ON == OFF payloads bitwise")
    return 0


if __name__ == "__main__":
    sys.exit(main())
