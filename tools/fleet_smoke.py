#!/usr/bin/env python
"""Fleet smoke: the multi-model fleet layer on the fake backend — the
invariants the `make fleet-smoke` CI target guards:

- the prefetch pipeline genuinely overlaps: a 3-model sweep books
  nonzero swap_s_hidden (model i+1's weights streamed while model i
  scored) with exactly one fully-exposed load (the first);
- per-model results are BITWISE identical to three separate
  single-model engines scoring the same questions (weights are moved by
  the cache/streamer, never transformed);
- a fleet_score serve fan-out answers per-model P(yes)/P(no) plus a
  kappa that matches the analysis layer's within_group_kappa on the
  same decisions EXACTLY (the serve path routes through
  stats/streaming.kappa_from_counts — one contingency code path
  everywhere).

Runs hermetically on CPU with FakeTokenizer + tiny random decoders (the
test suite's stand-ins); prints the FleetStats summary JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_MODELS = 3
QUESTIONS = ["Is a cat an animal", "Is a rock an animal",
             "Is rain considered weather", "Is a contract binding"]


def main() -> int:
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.fleet import ModelFleet
    from lir_tpu.engine.multi import ModelSpec, format_for, \
        run_model_comparison_sweep
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_word_meaning_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import (FleetScoringServer, ScoringServer,
                               ServeRequest)
    from lir_tpu.stats.kappa import within_group_kappa

    from lir_tpu.models import weights

    names = [f"org/fleet-m{i}" for i in range(N_MODELS)]

    def _cfg(name: str) -> ModelConfig:
        return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                           hidden_size=32, n_layers=1, n_heads=2,
                           intermediate_size=64, max_seq_len=256)

    # Host staging built up front (the checkpoint stand-in): every
    # factory call then pays the fleet's REAL load path — a chunked
    # host->device stream of the staged tree — which is what the
    # prefetch worker overlaps behind compute.
    staged = {name: weights.host_stage(
        decoder.init_params(_cfg(name), jax.random.PRNGKey(i)))
        for i, name in enumerate(names)}

    def make_engine(name: str) -> ScoringEngine:
        params = weights.stream_params(staged[name])
        jax.block_until_ready(jax.tree.leaves(params)[0])
        return ScoringEngine(params, _cfg(name), FakeTokenizer(),
                             RuntimeConfig(batch_size=4, max_seq_len=256))

    failures = []
    specs = [ModelSpec(n, "instruct") for n in names]

    # 1+2: fleet sweep — prefetch overlap + bitwise parity.
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        res = run_model_comparison_sweep(specs, make_engine, Path(td),
                                         questions=QUESTIONS)
    fleet_stats = res["fleet"]
    if fleet_stats["swap_s_hidden"] <= 0.0:
        failures.append(f"prefetch overlap is zero: {fleet_stats}")
    if fleet_stats["prefetch_hits"] != N_MODELS - 1 \
            or fleet_stats["prefetch_misses"] != 1:
        failures.append(f"prefetch pipeline misbehaved: {fleet_stats}")
    df = res["model_comparison_csv"]
    for name in names:
        ref = run_word_meaning_sweep(
            make_engine(name), name, "instruct", QUESTIONS,
            format_for(ModelSpec(name, "instruct")))
        got = df[df["model"] == name]
        if (list(got["yes_prob"]) != [r.yes_prob for r in ref]
                or list(got["no_prob"]) != [r.no_prob for r in ref]):
            failures.append(f"{name}: fleet rows != standalone engine")

    # 3: fleet_score serving — per-model probs + kappa parity.
    fleet = ModelFleet.from_engines([(n, make_engine(n)) for n in names])
    cfg = ServeConfig(queue_depth=64, classes=(("smoke", 600.0),),
                      default_class="smoke", linger_s=0.01)
    server = FleetScoringServer(fleet, cfg, fleet_deadline_s=600.0).start()
    body = "clause nine covers flood damage under the endorsement"
    req = ServeRequest(binary_prompt=f"{body} Answer Yes or No .",
                       confidence_prompt=f"{body} Give a number from "
                                         f"0 to 100 .",
                       klass="smoke", request_id="q0")
    agg = server.submit_fleet(req).result(timeout=600)
    server.stop()
    fleet.shutdown()
    if agg["status"] != "ok" or agg["n_valid"] != N_MODELS:
        failures.append(f"fleet_score did not answer cleanly: {agg}")
    decs = [m["decision"] for m in agg["per_model"].values()
            if m["decision"] is not None]
    ref_kappa = within_group_kappa(np.asarray(decs, int),
                                   np.zeros(len(decs), int))
    for k in ("kappa", "observed_agreement", "expected_agreement"):
        a, b = agg["kappa"][k], float(ref_kappa[k])
        same = (np.isnan(a) and np.isnan(b)) or a == b
        if not same:
            failures.append(f"kappa[{k}] {a} != within_group_kappa {b}")
    for mid in names:
        single = ScoringServer(make_engine(mid), mid, cfg).start()
        ref = single.submit(ServeRequest(
            binary_prompt=req.binary_prompt,
            confidence_prompt=req.confidence_prompt,
            klass="smoke", request_id="ref")).result(timeout=600)
        single.stop()
        got = agg["per_model"][mid]
        if (got["token_1_prob"] != ref.token_1_prob
                or got["token_2_prob"] != ref.token_2_prob):
            failures.append(f"{mid}: fleet_score probs != single-model "
                            f"server")

    if failures:
        for f in failures:
            print(f"FLEET-SMOKE FAIL: {f}")
        return 1
    print(json.dumps(fleet_stats))
    print(f"fleet smoke: OK ({N_MODELS} models x {len(QUESTIONS)} "
          f"questions swept with {fleet_stats['prefetch_hits']} "
          f"prefetched loads, swap hidden "
          f"{fleet_stats['swap_s_hidden']:.3f}s vs exposed "
          f"{fleet_stats['swap_s_exposed']:.3f}s; fleet_score kappa "
          f"{agg['kappa']['kappa']:.3f} == within_group_kappa)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
