"""Execute the REFERENCE's own yes/no scorer as the C13 oracle (VERDICT r4 #1).

The measurement layer of the two inference scripts —
`get_yes_no_logprobs` in compare_base_vs_instruct.py:185-305 and its
variant in compare_instruct_models.py:171-293 — was previously pinned only
by a torch REIMPLEMENTATION of the scan rule. This tool stages both
scripts in a sandbox with purely mechanical patches (drop the `dotenv`
import, truncate before the model-download driver loop), imports the
reference's actual functions, and runs them on CPU torch against the
deterministic tiny LOCAL checkpoints from tools/tiny_checkpoints.py:

- byte-BPE GPT-2 and Unigram/Metaspace Llama (both tokenizer families)
- Unigram/Metaspace T5 (the enc-dec branch, :188-237)
- the programmed-chain GPT-2, which forces the scan to find Yes/No at
  positions 0, 2, 5, as top-2 runner-up at 3, and never (pos-0 fallback,
  :280-285)
- a bos-prepending Llama tokenizer variant that pins, by execution, the
  reference's `tokenizer(" Yes").input_ids[0]` grabbing the <s> special
  when the tokenizer adds one (:244-247) — the quirk lir_tpu fixes by
  resolving with add_special_tokens=False (PARITY.md)

Every returned field is captured into the "scorer_oracle" group of
tests/golden/reference_executed.json (merged, preserving the analysis
groups); tests/test_reference_scorer_oracle.py rebuilds the identical
checkpoints and diffs lir_tpu's engine/score.py row-by-row. The C13
oracle is thereby the reference's EXECUTED code, not a reimplementation.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "tools") not in sys.path:
    sys.path.insert(0, str(REPO / "tools"))

REF = Path("/root/reference/analysis")
SANDBOX = Path("/tmp/lir_ref_scorer_oracle")
GOLDEN = REPO / "tests" / "golden" / "reference_executed.json"

SCRIPTS = {
    # module key -> (source file, driver-loop line that truncation cuts at)
    "ref_cbvi": (REF / "compare_base_vs_instruct.py",
                 "for base_name, instruct_name in model_pairs:"),
    "ref_cim": (REF / "compare_instruct_models.py",
                "for model_name in models:"),
}


def _stage(name: str, src: Path, cut_marker: str):
    """Mechanically patch + import one reference script: drop dotenv (not
    in the image), truncate everything from the model-download driver loop
    on (the scorer function and prompt list stay verbatim)."""
    text = src.read_text()
    lines = []
    for line in text.splitlines():
        if line.startswith(cut_marker):
            break
        if line.strip() == "from dotenv import load_dotenv":
            line = "load_dotenv = lambda: None  # dotenv not in image"
        lines.append(line)
    else:
        raise SystemExit(f"driver loop marker not found in {src}")
    SANDBOX.mkdir(parents=True, exist_ok=True)
    staged = SANDBOX / f"{name}.py"
    staged.write_text("\n".join(lines) + "\n")
    spec = importlib.util.spec_from_file_location(name, staged)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _native(obj):
    import numpy as np
    if isinstance(obj, dict):
        return {k: _native(v) for k, v in obj.items()}
    if isinstance(obj, (np.floating, np.integer)):
        obj = obj.item()                 # numpy scalars join the float path
    if isinstance(obj, float):
        if obj != obj:                   # NaN
            return "nan"
        if obj in (float("inf"), float("-inf")):
            return str(obj)
    return obj


def capture() -> dict:
    import torch
    import transformers as tf

    from lir_tpu.data.prompts import (format_base_prompt,
                                      format_instruct_prompt)
    from tiny_checkpoints import (CHAIN_PROMPTS, build_bpe_gpt2,
                                  build_chain_gpt2, build_chain_t5,
                                  build_sp_llama, build_sp_t5)

    mods = {name: _stage(name, src, cut)
            for name, (src, cut) in SCRIPTS.items()}
    for name, mod in mods.items():
        assert callable(mod.get_yes_no_logprobs), name

    ck = SANDBOX / "ckpts"
    questions = [
        'Is a "screenshot" a "photograph"?',
        'Is a "drone" an "aircraft"?',
        'Is a "tomato" a "vegetable"?',
    ]
    group: dict = {"transformers_version": tf.__version__,
                   "torch_version": torch.__version__}

    def run_cases(ckpt_key, model, tok, prompts):
        entry = {"cases": []}
        for pkey, prompt in prompts:
            case = {"key": pkey, "prompt": prompt}
            for mname, mod in mods.items():
                with torch.no_grad():
                    case[mname] = _native(mod.get_yes_no_logprobs(
                        model, tok, prompt, "cpu"))
            entry["cases"].append(case)
        group[ckpt_key] = entry
        return entry

    # --- decoder family checkpoints, both prompt formats -----------------
    _, model, tok = build_bpe_gpt2(ck / "bpe-gpt2")
    run_cases("bpe-gpt2", model, tok,
              [(f"instruct{i}", format_instruct_prompt(q))
               for i, q in enumerate(questions)]
              + [(f"base{i}", format_base_prompt(q))
                 for i, q in enumerate(questions[:2])])
    group["bpe-gpt2"]["yes_id"] = tok(" Yes").input_ids[0]   # :244-247
    group["bpe-gpt2"]["no_id"] = tok(" No").input_ids[0]

    _, model, tok = build_sp_llama(ck / "sp-llama")
    run_cases("sp-llama", model, tok,
              [(f"instruct{i}", format_instruct_prompt(q))
               for i, q in enumerate(questions)])
    group["sp-llama"]["yes_id"] = tok(" Yes").input_ids[0]
    group["sp-llama"]["no_id"] = tok(" No").input_ids[0]

    # --- enc-dec branch --------------------------------------------------
    _, model, tok = build_sp_t5(ck / "sp-t5")
    run_cases("sp-t5", model, tok,
              [(f"instruct{i}", format_instruct_prompt(q))
               for i, q in enumerate(questions)])
    group["sp-t5"]["yes_id"] = tok("Yes").input_ids[0]       # :208-209
    group["sp-t5"]["no_id"] = tok("No").input_ids[0]

    # --- programmed-chain checkpoint: exact scan positions ---------------
    _, model, tok, expected = build_chain_gpt2(ck / "chain-gpt2")
    entry = run_cases("chain-gpt2", model, tok,
                      sorted(CHAIN_PROMPTS.items()))
    entry["designed"] = {k: list(v) for k, v in expected.items()}
    entry["yes_id"] = tok(" Yes").input_ids[0]
    entry["no_id"] = tok(" No").input_ids[0]
    # The designed positions must be what the REFERENCE actually measured.
    for case in entry["cases"]:
        want_pos, want_found = expected[case["key"]]
        for mname in mods:
            assert case[mname]["position_found"] == want_pos, case
            assert case[mname]["yes_no_found"] == want_found, case

    # --- programmed-chain T5: non-fallback positions on the ENC-DEC
    # branch (cross-attention zeroed -> input-independent designed output)
    for key, never in (("chain-t5-pos2", False), ("chain-t5-never", True)):
        _, model, tok, expected = build_chain_t5(ck / key, never=never)
        entry = run_cases(key, model, tok,
                          [("instruct0",
                            format_instruct_prompt(questions[0]))])
        entry["designed"] = list(expected)
        entry["yes_id"] = tok("Yes").input_ids[0]
        entry["no_id"] = tok("No").input_ids[0]
        for case in entry["cases"]:
            for mname in mods:
                assert case[mname]["position_found"] == expected[0], case
                assert case[mname]["yes_no_found"] == expected[1], case

    # --- bos-prepending tokenizer: the special-token grab, executed ------
    _, model, tok = build_sp_llama(ck / "sp-llama-bos", add_bos=True)
    entry = run_cases("sp-llama-bos", model, tok,
                      [("instruct0", format_instruct_prompt(questions[0]))])
    entry["yes_id"] = tok(" Yes").input_ids[0]
    entry["no_id"] = tok(" No").input_ids[0]
    entry["bos_id"] = tok.bos_token_id
    # Executed fact: with a bos-adding tokenizer the reference's target id
    # IS the <s> special (both "yes" and "no" collapse onto it).
    assert entry["yes_id"] == tok.bos_token_id
    assert entry["no_id"] == tok.bos_token_id

    return group


def main() -> None:
    group = capture()
    golden = json.loads(GOLDEN.read_text()) if GOLDEN.exists() else {}
    golden["scorer_oracle"] = group
    GOLDEN.write_text(json.dumps(golden, indent=1, sort_keys=True))
    n = sum(len(v.get("cases", [])) for v in group.values()
            if isinstance(v, dict))
    print(f"scorer_oracle: {n} cases captured into {GOLDEN}")


if __name__ == "__main__":
    main()
