"""Answer-position percentiles from the reference's committed responses.

The r4 budget cut (SCALE.md "confidence decode budget") recorded the
corpus MEDIAN answer word position (0-1) plus within-4/within-8 rates;
ADVICE r5 (bench.py:380) pointed out the headline's conservatism claim
("answer_step=3 is past the median") is median-only — a right-skewed
answer-length distribution would refund less budget in production than
the bench measures. This tool recomputes the full percentile set —
median, MEAN, and P90 — from the same rows (the only real-model text in
the zero-egress image: `model_comparison_results.csv` +
`instruct_model_comparison_results.csv`), so SCALE.md can record the
skew-robust numbers next to the median.

Run where the reference data is mounted (tests/conftest.py
REFERENCE_DATA, default /root/reference/data):

    python tools/answer_position_stats.py [--data-dir DIR]

Prints one markdown table row per corpus; paste into SCALE.md "answer
position mean / p90". Without the mount it exits 2 with a pointer
(the percentile BOUNDS derivable from the recorded within-4 rates are
already in SCALE.md).
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

CSVS = ("model_comparison_results.csv",
        "instruct_model_comparison_results.csv")
# First standalone Yes/No (either case) — the same first-match rule the
# sweep's binarizer applies to responses.
ANSWER = re.compile(r"\b(yes|no)\b", re.IGNORECASE)


def answer_word_pos(text: str):
    """0-based word index of the first Yes/No token in ``text``, or None
    when the response never answers (those rows are excluded, matching
    the r4 'rows found' accounting)."""
    if not isinstance(text, str):
        return None
    m = ANSWER.search(text)
    if m is None:
        return None
    return len(text[:m.start()].split())


def corpus_stats(csv_path: Path):
    import numpy as np
    import pandas as pd

    df = pd.read_csv(csv_path)
    pos = [p for p in (answer_word_pos(t) for t in df["model_output"])
           if p is not None]
    if not pos:
        return None
    a = np.asarray(pos)
    return {
        "rows": int(a.size),
        "median": float(np.median(a)),
        "mean": float(a.mean()),
        "p90": float(np.percentile(a, 90)),
        "within4": float((a <= 4).mean()),
        "within8": float((a <= 8).mean()),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", type=Path,
                    default=Path("/root/reference/data"),
                    help="directory holding the reference CSVs "
                         "(tests/conftest.py REFERENCE_DATA)")
    args = ap.parse_args()
    if not args.data_dir.is_dir():
        print(f"reference data not mounted at {args.data_dir} — see "
              "SCALE.md 'answer position mean / p90' for the bounds "
              "derivable without it", file=sys.stderr)
        sys.exit(2)

    print("| corpus | rows | median | mean | p90 | within 4 | within 8 |")
    print("|---|---|---|---|---|---|---|")
    for name in CSVS:
        path = args.data_dir / name
        if not path.exists():
            print(f"| {name} | MISSING | | | | | |")
            continue
        s = corpus_stats(path)
        if s is None:
            print(f"| {name} | 0 answered | | | | | |")
            continue
        print(f"| {name.removesuffix('_results.csv')} | {s['rows']} "
              f"| {s['median']:.1f} | {s['mean']:.2f} | {s['p90']:.1f} "
              f"| {s['within4']:.1%} | {s['within8']:.1%} |")


if __name__ == "__main__":
    main()
