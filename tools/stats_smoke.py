#!/usr/bin/env python
"""Streaming-statistics smoke: the grid -> CIs device pipeline exercised
end-to-end on the fake backend (`make stats-smoke`). Asserts the ISSUE-9
acceptance criteria hermetically on CPU:

1. PARITY — one sweep with streaming ON + the row artifact ON: the
   accumulator finalize (moments / percentiles / bootstrap CIs / kappa /
   contingency counts) must match the csv-reload pipeline on the same
   rows — counts and kappa BITWISE, moments and CIs within
   stats.streaming.FLOAT_TOL.
2. NO PER-ROW HOST TRANSFER — a streaming-only pass (row artifact off)
   must fold every grid row on device (rows_folded == grid size), write
   zero result rows, and report nonzero host_bytes_avoided; statically,
   the host-sync lint pass over the sink module (engine/stream_stats.py
   is hot-path scanned) must report ZERO findings — the dispatch hot
   loop contains no implicit device->host sync.
3. LIVE ESTIMATES — a serve session's `stats` endpoint returns
   in-progress percentile/kappa estimates mid-workload, and the
   StreamStats counters move.

Prints the streaming summaries as JSON on success; exits 1 on the first
violated invariant.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_CELLS = 16
BATCH = 4


def _make_engine(**rt_kw):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="stats-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(13))
    rt_kw.setdefault("batch_size", BATCH)
    rt_kw.setdefault("max_seq_len", 256)
    return ScoringEngine(params, cfg, FakeTokenizer(),
                         RuntimeConfig(**rt_kw))


def _grid(n_cells=N_CELLS, seed=31):
    import numpy as np

    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([text(10 if i % 2 else 22) for i in range(n_cells - 1)],)
    return lp, perts


def parity(failures):
    """Invariant 1: streaming finalize == csv-reload pipeline."""
    import tempfile

    from lir_tpu.data import schemas
    from lir_tpu.engine import grid as grid_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.stats import streaming as st

    lp, perts = _grid()
    engine = _make_engine()
    with tempfile.TemporaryDirectory() as td:
        out = Path(td) / "results.csv"
        rows = run_perturbation_sweep(engine, "smoke", lp, perts, out)
        sink = engine.stream_sink
        acc = sink.snapshot()
        streamed = st.summarize(acc, n_boot=300)
        cells = grid_mod.build_grid("smoke", lp, perts)
        df = schemas.read_results_frame(out)
        reloaded = st.summarize(
            st.accum_from_rows(df, st.slot_map_from_cells(cells), 1,
                               len(rows), acc.seed), n_boot=300)
        try:
            st.assert_parity(streamed, reloaded)
        except AssertionError as err:
            failures.append(f"parity: streaming != csv-reload: {err}")
            return
        if acc.rows_folded != len(rows):
            failures.append(
                f"parity: rows folded {acc.rows_folded} != {len(rows)}")
        print("parity: streaming == csv-reload "
              f"(counts/kappa bitwise, CIs within {st.FLOAT_TOL}); "
              f"kappa: {json.dumps(streamed['kappa'])}")


def no_host_rows(failures):
    """Invariant 2: streaming-only pass — zero rows materialized, every
    row folded on device, and the host-sync lint pass clean over the
    sink module."""
    import tempfile

    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid()
    engine = _make_engine(row_artifact=False)
    with tempfile.TemporaryDirectory() as td:
        out = Path(td) / "results.csv"
        rows = run_perturbation_sweep(engine, "smoke", lp, perts, out)
        sink = engine.stream_sink
        if rows:
            failures.append(f"no-host-rows: {len(rows)} rows built")
        if out.exists():
            failures.append("no-host-rows: row artifact was written")
        if sink.stats.rows_folded != N_CELLS:
            failures.append(
                f"no-host-rows: rows_folded {sink.stats.rows_folded} "
                f"!= grid {N_CELLS}")
        if sink.stats.dispatch_folds <= 0:
            failures.append("no-host-rows: zero dispatch folds")
        if sink.stats.host_bytes_avoided <= 0:
            failures.append("no-host-rows: host_bytes_avoided is zero")
        acc = stream_mod.load_accum(
            out.with_suffix(stream_mod.ACCUM_SUFFIX))
        if acc is None or acc.rows_folded != N_CELLS:
            failures.append("no-host-rows: accumulator checkpoint "
                            "missing or incomplete")
        print(f"no-host-rows: {sink.stats.rows_folded} rows folded on "
              f"device, {sink.stats.host_bytes_avoided} host bytes "
              f"avoided, counters: {json.dumps(sink.stats.summary())}")

    # Static half: the host-sync pass over the sink module must be
    # clean — the dispatch hot loop performs no implicit sync.
    from lir_tpu.lint.core import load_project
    from lir_tpu.lint.hostsync import HostSyncPass

    repo = Path(__file__).resolve().parent.parent
    project = load_project(repo)
    findings = [f for f in HostSyncPass().run(project)
                if "stream_stats" in f.path or "sweep" in f.path]
    if findings:
        failures.append(
            "no-host-rows: host-sync findings in the sink/sweep hot "
            f"loop: {[(f.path, f.line, f.message) for f in findings]}")
    else:
        print("no-host-rows: host-sync lint clean over the sink module "
              "and sweep hot loop")


def live_endpoint(failures):
    """Invariant 3: mid-run serve `stats` endpoint returns estimates."""
    from lir_tpu.config import ServeConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    engine = _make_engine()
    cfg = ServeConfig(queue_depth=64, classes=(("t", 600.0),),
                      default_class="t", linger_s=0.005,
                      prefix_cache=False, stream_window=64)
    server = ScoringServer(engine, "smoke", cfg).start()
    try:
        futs = []
        for i in range(10):
            futs.append(server.submit(ServeRequest(
                binary_prompt=f"claim {i} ? Answer Yes or No .",
                confidence_prompt=(f"claim {i} ? Give a number from 0 "
                                   "to 100 ."),
                targets=("Yes", "No"), klass="t", request_id=f"s{i}")))
            if i == 5:
                mid = server.stream_summary()  # LIVE: mid-workload read
        for f in futs:
            if f.result(timeout=300).status != "ok":
                failures.append("live: request not ok")
        final = server.stream_summary()
    finally:
        server.stop()
    if final.get("rows_folded") != 10:
        failures.append(f"live: rows_folded {final.get('rows_folded')} "
                        "!= 10")
    if "kappa" not in final or "per_group" not in final:
        failures.append("live: summary missing kappa/per_group")
    if mid.get("rows_folded", 0) > 10:
        failures.append("live: mid-run fold count insane")
    print(f"live: mid-run estimate at {mid.get('rows_folded')} rows, "
          f"final {json.dumps(final)[:200]}...")


def main() -> int:
    failures: list = []
    for step in (parity, no_host_rows, live_endpoint):
        step(failures)
    if failures:
        print("\nSTATS SMOKE FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("\nstats smoke: all invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
