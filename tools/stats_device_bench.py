"""Measure the vectorized statistics kernels on the REAL TPU vs host CPU.

VERDICT r1 weak #3: the CLI pins statistics to CPU (`ensure_cpu_backend`)
on the argument that tunneled-TPU dispatch latency swamps tiny kernels —
but BASELINE.json config 2 ("10k resamples -> vmap on single TPU core")
had never actually been measured. This tool runs the production stats
kernels — the same ones the survey/analysis layers call, at the
reference's own problem sizes (SURVEY.md §6 bootstrap budgets) — on both
backends and appends the numbers to SCALE.md, so the backend-pinning
policy is a measurement, not an assertion.

Every kernel result is a host-side float (BootstrapResult / dict), so the
timings are host-materialization-synced by construction — the same
verified-timing discipline as bench.py.

Run (parent orchestrates both backends as subprocesses):
    python tools/stats_device_bench.py
"""

from __future__ import annotations

import argparse
import datetime
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

SCALE_MD = REPO / "SCALE.md"

# (name, reference sizing note)
KERNELS = [
    ("pearson_boot_1k", "C34: bootstrap Pearson CI, n=50, 1000 resamples"),
    ("corr_matrix_boot_1k",
     "C30: 10-model correlation matrix, 50 prompts, 1000 resamples"),
    ("aggregate_kappa_1k", "C30: pooled kappa, 10x50 binary, 1000-fold CI"),
    ("truncnorm_mc_100k",
     "C22: truncated-normal MC fit, n=2000, 100k samples/iter"),
]


def _build_and_time(name: str):
    import jax
    import numpy as np

    rng = np.random.default_rng(42)
    key = jax.random.PRNGKey(0)

    if name == "pearson_boot_1k":
        from lir_tpu.stats.bootstrap import bootstrap_correlation
        x = rng.uniform(size=50)
        y = 0.6 * x + 0.4 * rng.uniform(size=50)
        fn = lambda: bootstrap_correlation(x, y, key, n_boot=1000).estimate
    elif name == "corr_matrix_boot_1k":
        from lir_tpu.stats.correlations import bootstrap_correlation_matrix
        piv = rng.uniform(size=(50, 10))
        fn = lambda: bootstrap_correlation_matrix(
            piv, key, n_bootstrap=1000)["mean_correlation"]
    elif name == "aggregate_kappa_1k":
        from lir_tpu.stats.kappa import aggregate_kappa
        binary = (rng.uniform(size=(10, 50)) > 0.5).astype(np.int32)
        fn = lambda: aggregate_kappa(binary, key, n_boot=1000)["aggregate_kappa"]
    elif name == "truncnorm_mc_100k":
        from lir_tpu.stats.fits import truncated_normal_mc_fit
        data = np.clip(rng.normal(0.6, 0.25, size=2000), 0.0, 1.0)
        fn = lambda: truncated_normal_mc_fit(
            data, key, n_simulations=100_000)[0]["KS Statistic"]
    else:
        raise KeyError(name)

    t0 = time.perf_counter()
    first = float(np.asarray(fn()))
    cold = time.perf_counter() - t0
    warm = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        v = float(np.asarray(fn()))
        warm = min(warm, time.perf_counter() - t0)
    assert np.isfinite(v), (name, v)
    return {"cold_s": round(cold, 4), "warm_s": round(warm, 4),
            "value": round(first, 6)}


def child(backend: str) -> None:
    import jax
    if backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    out = {"backend": backend, "platform": dev.platform,
           "device_kind": getattr(dev, "device_kind", "?")}
    for name, _ in KERNELS:
        out[name] = _build_and_time(name)
        print(f"# {backend}: {name} {out[name]}", file=sys.stderr)
    print(json.dumps(out))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--child", choices=["cpu", "tpu"])
    args = parser.parse_args()
    if args.child:
        child(args.child)
        return

    results = {}
    for backend in ("cpu", "tpu"):
        proc = subprocess.run(
            [sys.executable, __file__, "--child", backend],
            capture_output=True, text=True, cwd=REPO, timeout=1800)
        sys.stderr.write(proc.stderr[-2000:])
        if proc.returncode != 0:
            print(f"{backend} child failed rc={proc.returncode}")
            sys.exit(1)
        results[backend] = json.loads(proc.stdout.strip().splitlines()[-1])

    # Refuse to record a "TPU vs CPU" table measured on two CPU backends
    # (e.g. no reachable chip and jax silently fell back) — the whole point
    # of this tool is honest data.
    if results["tpu"]["platform"] == "cpu":
        print("ABORT: the 'tpu' child ran on the CPU backend "
              f"({results['tpu']['device_kind']}); no table written.")
        sys.exit(1)
    if results["cpu"]["platform"] != "cpu":
        print("ABORT: the 'cpu' child did not run on CPU "
              f"({results['cpu']['platform']}); no table written.")
        sys.exit(1)

    date = datetime.date.today().isoformat()
    kind = results["tpu"]["device_kind"]
    lines = [
        f"\n## stats kernels: TPU vs host CPU — {kind}, {date}\n",
        "\nBASELINE config 2 measured (VERDICT r1 weak #3). Warm best-of-3,",
        "\nhost-materialization-synced; reference problem sizes.\n",
        "\n| kernel (reference sizing) | cpu warm s | tpu warm s |"
        " tpu/cpu | tpu cold s |\n",
        "|---|---|---|---|---|\n",
    ]
    for name, note in KERNELS:
        c, t = results["cpu"][name], results["tpu"][name]
        ratio = t["warm_s"] / max(c["warm_s"], 1e-9)
        lines.append(f"| {note} | {c['warm_s']:.3f} | {t['warm_s']:.3f} | "
                     f"{ratio:.1f}x | {t['cold_s']:.1f} |\n")
        dv = abs(results["cpu"][name]["value"] - results["tpu"][name]["value"])
        if dv > 1e-2:
            lines.append(f"|   (value drift {dv:.3g} — inspect!) | | | | |\n")
    text = "".join(lines)
    SCALE_MD.write_text(SCALE_MD.read_text() + text)
    print(text)


if __name__ == "__main__":
    main()
