#!/usr/bin/env python
"""Serve smoke: boot the scoring server on the fake backend, push 50
requests (40 unique + 10 duplicate re-asks), and assert the serving
invariants the `make serve-smoke` CI target guards:

- zero sheds (the queue is sized for the burst — admission control must
  not fire on a healthy, correctly sized deployment),
- a nonzero dedup hit rate (the duplicate re-asks hit the
  content-addressed result cache instead of the device),
- every request resolves "ok" and the server stays healthy.

Runs hermetically on CPU with the FakeTokenizer + a tiny random decoder
(the same stand-in the test suite uses); prints the ServeStats summary
JSON on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_UNIQUE = 40
N_DUP = 10


def main() -> int:
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    cfg = ModelConfig(name="serve-smoke", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(7))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=8, max_seq_len=256))
    server = ScoringServer(
        engine, "serve-smoke",
        ServeConfig(queue_depth=N_UNIQUE + N_DUP,
                    classes=(("smoke", 600.0),), default_class="smoke",
                    linger_s=0.01)).start()

    def request(i: int, rid: str) -> ServeRequest:
        body = f"clause {i} covers flood damage under policy {i * 3}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=rid)

    futures = [server.submit(request(i, str(i))) for i in range(N_UNIQUE)]
    # Wait for the originals so the duplicate re-asks hit a warm cache.
    results = [f.result(timeout=600) for f in futures]
    dup_results = [server.submit(request(i, f"dup{i}")).result(timeout=600)
                   for i in range(N_DUP)]
    server.stop()

    stats = server.stats
    failures = []
    bad = [r.request_id for r in results + dup_results if r.status != "ok"]
    if bad:
        failures.append(f"non-ok results: {bad}")
    if stats.shed != 0:
        failures.append(f"sheds under a sized queue: {stats.shed}")
    if stats.dedup_hits == 0 or stats.dedup_hit_rate <= 0.0:
        failures.append("duplicate re-asks produced zero dedup hits")
    if not all(r.cached for r in dup_results):
        failures.append("a duplicate re-ask was scored on the device")
    if not server.healthy:
        failures.append("health flag tripped during the smoke")
    if failures:
        for f in failures:
            print(f"SERVE-SMOKE FAIL: {f}")
        return 1
    print(json.dumps(stats.summary()))
    print(f"serve smoke: OK ({N_UNIQUE} unique + {N_DUP} duplicate "
          f"requests, {stats.dispatches} dispatches, dedup hit rate "
          f"{stats.dedup_hit_rate:.2f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
