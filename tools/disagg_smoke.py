#!/usr/bin/env python
"""Disaggregated-serving smoke: the prefill/decode split + KV-page
migration invariants the `make disagg-smoke` CI target guards
(DEPLOY.md §1p):

- 1 prefill-role + 2 decode-role replica servers (config-identical
  tiny engines) behind a ReplicaRouter serve a prefill-heavy request
  stream on the fake backend: every request resolves ok, scoring
  dispatches land ONLY on decode replicas, and a NONZERO number of
  pages migrates (prefill → export → transfer → import);
- every payload is BITWISE-identical to the same request scored on a
  colocated single server — migrated-page decode cannot differ from
  local-prefill decode;
- a replica KILLED mid-migration recovers: the chain falls back to
  local re-prefill on a survivor, the request still resolves ok and
  bitwise, and nothing is dropped or double-resolved.

Runs hermetically on CPU; prints the migrate/router summaries as JSON
on success.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BATCH = 4

PAYLOAD_FIELDS = ("model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")


def _tiny_server(cfg_serve, seed=2):
    import jax

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer

    cfg = ModelConfig(name="disagg-smoke",
                      vocab_size=FakeTokenizer.VOCAB, hidden_size=32,
                      n_layers=1, n_heads=2, intermediate_size=64,
                      max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    engine = ScoringEngine(params, cfg, FakeTokenizer(),
                           RuntimeConfig(batch_size=BATCH,
                                         max_seq_len=256))
    return ScoringServer(engine, "disagg-smoke", cfg_serve)


def _requests(n, seed=7, tag=""):
    import numpy as np

    from lir_tpu.serve import ServeRequest

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer "
             "premium exclusion endorsement").split()
    trunks = [" ".join(rng.choice(words) for _ in range(60))
              for _ in range(2)]
    reqs = []
    for i in range(n):
        body = f"{trunks[i % 2]} case {i}"
        reqs.append(ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="smoke", request_id=f"{tag}{i}"))
    return reqs


def main() -> int:
    from lir_tpu import faults
    from lir_tpu.config import (MigrationConfig, RouterConfig,
                                ServeConfig)
    from lir_tpu.serve import ReplicaRouter

    serve_cfg = ServeConfig(classes=(("smoke", 600.0),),
                            default_class="smoke", linger_s=0.002)
    reqs = _requests(10)

    # Colocated baseline: one ordinary server scores everything.
    colo = _tiny_server(serve_cfg).start()
    base = [colo.submit(r).result(300) for r in reqs]
    colo.stop()
    assert all(r.status == "ok" for r in base)

    servers = [_tiny_server(serve_cfg).start() for _ in range(3)]
    router = ReplicaRouter(
        [("pre", servers[0]), ("d0", servers[1]), ("d1", servers[2])],
        config=RouterConfig(cache_entries=0, tick_s=0.01),
        roles={"pre": "prefill", "d0": "decode", "d1": "decode"},
        migrate=MigrationConfig(min_prefix_tokens=16, chunk_pages=2,
                                timeout_s=5.0)).start()
    try:
        futs = [router.submit(r) for r in reqs]
        res = [f.result(300) for f in futs]
        assert all(r.status == "ok" for r in res), \
            [r.status for r in res]
        ids = [r.request_id for r in res]
        assert len(set(ids)) == len(reqs), "dropped/double-resolved"
        for got, ref in zip(res, base):
            for f in PAYLOAD_FIELDS:
                assert getattr(got, f) == getattr(ref, f), (
                    f"payload field {f} differs from the colocated "
                    f"baseline on request {got.request_id}")
        ms = router.migrate_stats
        assert ms.pages_migrated > 0, "no pages migrated"
        assert ms.prefill_ops > 0, "no prefill-role dispatches"
        # Scoring traffic never landed on the prefill replica.
        assert router.stats.per_replica.get("pre", 0) == 0, \
            router.stats.per_replica

        # Kill-mid-migration: stall the wire hop so the chain is alive
        # when the SOURCE replica dies — the request must fall back to
        # local re-prefill on a survivor, still ok and bitwise.
        plan = faults.FaultPlan(seed=11, schedules={
            "migrate": faults.SiteSchedule.migration_stall_at(
                0, seconds=1.0)})
        faults.wrap_migrator(router.migrator, plan)
        # A brand-new trunk (different seed): COLD everywhere, so the
        # submit must start a real migration chain for the kill to hit.
        kill_req = _requests(1, seed=23, tag="k")[0]
        colo2 = _tiny_server(serve_cfg).start()
        ref2 = colo2.submit(kill_req).result(300)
        colo2.stop()
        fut = router.submit(kill_req)
        router.kill_replica("pre")            # dies mid-chain
        got2 = fut.result(300)
        assert got2.status == "ok", got2.status
        for f in PAYLOAD_FIELDS:
            assert getattr(got2, f) == getattr(ref2, f), f
        assert ms.refetch_fallbacks >= 1, ms.summary()
        print(json.dumps({
            "disagg_smoke": "ok",
            "requests": len(reqs) + 1,
            "migrate": ms.summary(),
            "router": router.stats.summary(),
        }, indent=2))
    finally:
        router.stop()
        for s in servers:
            s.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
