"""Execute the reference's perturb_prompts.py against stub API clients
(VERDICT r4 #2) — the L1a/L2 leg of the executed-reference differential.

perturb_prompts.py needs live OpenAI/Anthropic keys, so it had never been
RUN; its grid builder (create_batch_requests, :190-269), batch decoder
(extract_results_from_batch, :398-549), rephrasing parser (:812-835),
random subset sampler (:109-159) and 15-column workbook (:964-1016) were
pinned only by reimplementation. This tool stages the script with
mechanical patches (gdrive paths -> sandbox, xlsx -> csv, two models, no
thread pool) plus stub `openai`/`anthropic`/`config` modules that replay
the DETERMINISTIC canned payloads from tools/perturb_oracle_data.py, and
executes it twice:

- scenario A: no perturbations file -> Step 1 runs against the stub
  Claude (100 sessions x 5 prompts, numbered-list parsing with
  continuation lines), then PROCESS_RANDOM_SUBSET=True cuts the grid to
  the seed-42 subset of 20; reasoning model in its default
  SKIP_REASONING_MODEL_LOGPROBS=True confidence-only mode.
- scenario B: canned perturbations.json (4 rephrasings/prompt, loaded
  via the reference's own verification path), full grid,
  SKIP_REASONING_MODEL_LOGPROBS=False -> the 10-run reasoning averaging
  and containment-counting quirk execute.

Captured into tests/golden/reference_perturb_oracle.json: every uploaded
batch request (grid + custom_id mapping + bodies), the final workbook
rows, the saved perturbations (hash + samples; the canned generator is
shared so tests regenerate the full list), and the stdout log tail.
tests/test_reference_perturb_oracle.py diffs lir_tpu's backends/api +
engine/rephrase + engine/grid against this captured execution.
"""

from __future__ import annotations

import hashlib
import json
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
REF_SCRIPT = Path("/root/reference/analysis/perturb_prompts.py")
SANDBOX = Path("/tmp/lir_ref_perturb_oracle")
GOLDEN = REPO / "tests" / "golden" / "reference_perturb_oracle.json"

GDRIVE = "gdrive/My Drive/Computational/llm_interpretation"

OPENAI_STUB = '''\
"""Stub OpenAI client: batches complete instantly with deterministic
payloads from tools/perturb_oracle_data.py; every upload is copied to
captured/ before the reference deletes its input file."""
import json
from pathlib import Path

from perturb_oracle_data import openai_batch_result_line

_CAPTURE = Path(__file__).parent / "captured"


class _Obj:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class _Files:
    def __init__(self, store):
        self._s = store

    def create(self, file=None, purpose=None):
        data = file.read()
        if isinstance(data, bytes):
            data = data.decode("utf-8")
        fid = "file-%d" % len(self._s["uploads"])
        self._s["uploads"][fid] = data
        _CAPTURE.mkdir(exist_ok=True)
        (_CAPTURE / ("upload_%s.jsonl" % fid)).write_text(data)
        return _Obj(id=fid)

    def content(self, file_id):
        return _Obj(content=self._s["outputs"][file_id].encode("utf-8"))


class _Batches:
    def __init__(self, store):
        self._s = store

    def create(self, input_file_id=None, endpoint=None,
               completion_window=None, metadata=None):
        bid = "batch-%d" % len(self._s["batches"])
        lines = self._s["uploads"][input_file_id].strip().splitlines()
        out = "\\n".join(openai_batch_result_line(json.loads(ln))
                         for ln in lines if ln)
        ofid = "out-%s" % bid
        self._s["outputs"][ofid] = out
        self._s["batches"][bid] = _Obj(
            id=bid, status="completed", output_file_id=ofid, errors=None)
        return self._s["batches"][bid]

    def retrieve(self, batch_id):
        return self._s["batches"][batch_id]


class OpenAI:
    def __init__(self, api_key=None):
        store = {"uploads": {}, "outputs": {}, "batches": {}}
        self.files = _Files(store)
        self.batches = _Batches(store)
'''

ANTHROPIC_STUB = '''\
"""Stub Anthropic client: messages.create returns the canned numbered
rephrasing lists (call-indexed, deterministic)."""
import re

from perturb_oracle_data import claude_rephrasings

HUMAN_PROMPT = "\\n\\nHuman:"
AI_PROMPT = "\\n\\nAssistant:"


class _Content:
    def __init__(self, text):
        self.text = text


class _Response:
    def __init__(self, text):
        self.content = [_Content(text)]


class _Messages:
    def __init__(self):
        self.calls = 0

    def create(self, model=None, max_tokens=None, temperature=None,
               messages=None):
        prompt = messages[0]["content"]
        m = re.search(r'###"(.*)"###', prompt, re.DOTALL)
        main = m.group(1) if m else prompt
        text = claude_rephrasings(self.calls, main)
        self.calls += 1
        return _Response(text)


class Anthropic:
    def __init__(self, api_key=None):
        self.messages = _Messages()
'''

ANTHROPIC_EXC = '''\
class OverloadedError(Exception):
    pass


class RateLimitError(Exception):
    pass


class APIError(Exception):
    pass


class APIStatusError(Exception):
    pass
'''


def _patch(text: str, scenario: str) -> str:
    text = text.replace(GDRIVE, "work")
    text = text.replace("pd.read_excel", "pd.read_csv")
    text = text.replace(".to_excel(", ".to_csv(")
    text = text.replace(".xlsx", ".csv")
    # Two models: one regular + one reasoning (config-list trim; every
    # model runs the identical code path).
    old_models = text[text.index("MODELS_TO_TEST = ["):]
    old_models = old_models[:old_models.index("]") + 1]
    text = text.replace(
        old_models,
        'MODELS_TO_TEST = ["gpt-4.1-2025-04-14", "o3-2025-04-16"]')
    text = text.replace("PROCESS_BATCHES_IN_PARALLEL = True",
                        "PROCESS_BATCHES_IN_PARALLEL = False")
    if scenario == "A":
        text = text.replace("PROCESS_RANDOM_SUBSET = False",
                            "PROCESS_RANDOM_SUBSET = True")
    else:
        text = text.replace("SKIP_REASONING_MODEL_LOGPROBS = True",
                            "SKIP_REASONING_MODEL_LOGPROBS = False")
    return text


def _canned_perturbations() -> list:
    """Scenario B's pre-existing perturbations.json, built from lir_tpu's
    LEGAL_PROMPTS — the reference verifies each loaded tuple against its
    own hardcoded prompts (:747-760), so a successful load also proves
    byte-parity of our prompt data."""
    from lir_tpu.data.prompts import LEGAL_PROMPTS

    data = []
    for p in LEGAL_PROMPTS:
        data.append({
            "original_main": p.main,
            "response_format": p.response_format,
            "target_tokens": list(p.target_tokens),
            "confidence_format": p.confidence_format,
            "rephrasings": [
                f"(B{j}) {p.main.split('?')[0][:60].strip()} — restated?"
                for j in range(4)
            ],
        })
    return data


def _run_scenario(scenario: str) -> dict:
    box = SANDBOX / scenario
    if box.exists():
        shutil.rmtree(box)
    (box / "anthropic").mkdir(parents=True)
    (box / "work").mkdir()
    (box / "openai.py").write_text(OPENAI_STUB)
    (box / "anthropic" / "__init__.py").write_text(ANTHROPIC_STUB)
    (box / "anthropic" / "_exceptions.py").write_text(ANTHROPIC_EXC)
    (box / "config.py").write_text(
        'ANTHROPIC_API_KEY = "stub"\nOPENAI_API_KEY = "stub"\n')
    (box / "perturb_staged.py").write_text(
        _patch(REF_SCRIPT.read_text(), scenario))
    if scenario == "B":
        (box / "work" / "perturbations.json").write_text(
            json.dumps(_canned_perturbations(), indent=2,
                       ensure_ascii=False))

    env = {
        "PYTHONPATH": f"{box}:{REPO / 'tools'}:{REPO}",
        "PYTHONHASHSEED": "0",
        "PATH": "/usr/bin:/bin",
        "HOME": str(box),
    }
    proc = subprocess.run(
        [sys.executable, "perturb_staged.py"], cwd=box, env=env,
        capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-4000:])
        sys.stderr.write(proc.stderr[-4000:])
        raise SystemExit(f"scenario {scenario} failed")

    # Collect: uploaded grids (grouped by model), workbook, perturbations.
    uploads: dict = {}
    for f in sorted((box / "captured").glob("upload_*.jsonl"),
                    key=lambda p: int(p.stem.rsplit("-", 1)[1])):
        reqs = [json.loads(ln) for ln in f.read_text().splitlines() if ln]
        model = reqs[0]["body"]["model"]
        uploads.setdefault(model, []).extend(reqs)

    import pandas as pd
    workbook = pd.read_csv(box / "work" / "results_30_multi_model.csv")
    columns = list(workbook.columns)        # golden is sort_keys=True;
    rows = json.loads(workbook.to_json(orient="records"))

    pert_file = box / "work" / "perturbations.json"
    pert = json.loads(pert_file.read_text())
    pert_summary = {
        "sha256": hashlib.sha256(
            json.dumps(pert, sort_keys=True, ensure_ascii=False)
            .encode()).hexdigest(),
        "counts": [len(item["rephrasings"]) for item in pert],
        "samples": [item["rephrasings"][:3] for item in pert],
    }

    return {
        "stdout_tail": proc.stdout[-2500:],
        "uploads": uploads,
        "workbook": rows,
        "workbook_columns": columns,
        "perturbations": pert_summary,
    }


def main() -> None:
    golden = {
        "scenario_a": _run_scenario("A"),
        "scenario_b": _run_scenario("B"),
    }
    GOLDEN.write_text(json.dumps(golden, indent=1, sort_keys=True))
    for key, g in golden.items():
        n_req = {m: len(v) for m, v in g["uploads"].items()}
        print(f"{key}: requests={n_req} workbook_rows={len(g['workbook'])}")
    print(f"captured into {GOLDEN}")


if __name__ == "__main__":
    main()
