"""Deterministic tiny LOCAL HF checkpoints for executed-reference oracles.

The zero-egress image ships no pretrained weights, so every differential
that wants to run real HF code (ours AND the reference's staged scripts —
tools/reference_scorer_oracle.py) builds genuine checkpoints here: real
tokenizers (trained byte-BPE, constructed Unigram/Metaspace), real
`save_pretrained` safetensors, fixed torch seeds. The SAME builders back
the capture tool and the pytest differentials, so both sides always score
the identical weights (VERDICT r4 #1).

Builders:
- byte-BPE + GPT-2 (seed 0) — the GPT-2-style byte-level family
- Unigram/Metaspace + Llama (seed 1) — the sentencepiece family ("▁Yes")
- Unigram/Metaspace + T5 (seed 2) — the enc-dec branch
  (compare_base_vs_instruct.py:188-237)
- programmed-chain GPT-2 — a Markov-chain LM whose next token is a pure
  function of the current token (all attention/MLP weights zero, untied
  one-hot embeddings, +10/+5 logit margins). This gives EXACT control of
  where "Yes"/"No" first enters the top-2, so the reference's scan rule
  (compare_base_vs_instruct.py:264-285) is exercised at chosen positions
  1-9, as runner-up-of-top-2, and in the never-found position-0 fallback —
  outcomes random weights cannot pin.
- bos-adding Unigram/Metaspace + Llama — same pieces with a
  TemplateProcessing post-processor that prepends <s>, reproducing real
  llama tokenizers, to pin the reference's `tokenizer(" Yes").input_ids[0]`
  special-token grab (compare_base_vs_instruct.py:244-247) by execution.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _sp_tokenizer(add_bos: bool = False, with_pad: bool = False):
    """Unigram + Metaspace fast tokenizer (the llama/t5 scheme), built from
    the word-meaning corpus with explicit piece scores so resolution is
    deterministic."""
    import transformers as tf
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    from tokenizers.processors import TemplateProcessing

    from lir_tpu.data.prompts import WORD_MEANING_QUESTIONS

    corpus = list(WORD_MEANING_QUESTIONS) + [
        "Yes", "No", "Answer either 'Yes' or 'No'.",
        "Question: Answer:", "Is a tomato a vegetable?",
        "Give a confidence number from 0 to 100",
    ]
    words = sorted({w for line in corpus for w in line.split()})
    chars = sorted({c for line in corpus for c in line} | {"▁"})
    pieces = {"<unk>": 0.0, "<s>": 0.0, "</s>": 0.0}
    if with_pad:
        pieces["<pad>"] = 0.0       # T5 needs a real pad (reference
        # enc-dec branch tokenizes with padding=True, :194)
    for w in words:
        pieces.setdefault("▁" + w, -8.0)
    for v in range(101):
        pieces.setdefault("▁" + str(v), -8.0)
        pieces.setdefault(str(v), -9.0)
    for c in chars:
        pieces.setdefault(c, -12.0)
    tok = Tokenizer(models.Unigram(list(pieces.items()), unk_id=0))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    if add_bos:
        # Real LlamaTokenizer behavior: every encode() prepends <s>.
        bos_id = tok.token_to_id("<s>")
        tok.post_processor = TemplateProcessing(
            single="<s> $A", pair="<s> $A <s> $B",
            special_tokens=[("<s>", bos_id)])
    kw = {"pad_token": "<pad>"} if with_pad else {}
    return tf.PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token="<s>", eos_token="</s>",
        unk_token="<unk>", **kw)


def build_bpe_tokenizer():
    """Train the byte-level BPE tokenizer (real merges, real leading-space
    " Yes" semantics) — shared by the random and chain GPT-2 builders."""
    import transformers as tf
    from tokenizers import (Tokenizer, decoders, models, pre_tokenizers,
                            trainers)

    from lir_tpu.data.prompts import WORD_MEANING_QUESTIONS

    corpus = list(WORD_MEANING_QUESTIONS) + [
        "Yes", "No", " Yes", " No", "Answer either 'Yes' or 'No'.",
        "Question: Answer:", "Is a tomato a vegetable?",
        " ".join(str(i) for i in range(101)),
    ]
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=1024, special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(corpus, trainer)
    return tf.PreTrainedTokenizerFast(
        tokenizer_object=tok, eos_token="<|endoftext|>")


def build_bpe_gpt2(path: Path):
    """Trained byte-level BPE tokenizer + random GPT-2 (seed 0) — byte-for-
    byte the construction tests/test_real_tokenizer_end_to_end.py uses."""
    import torch
    import transformers as tf

    fast = build_bpe_tokenizer()
    torch.manual_seed(0)
    # n_positions 512: the engine conservatively trims length buckets to
    # table_rows - max_new_tokens for learned-position models, and the
    # formatted few-shot prompts (~134 tokens) + a 50-token reference
    # generation budget need the 256 bucket to survive that trim.
    model = tf.GPT2LMHeadModel(tf.GPT2Config(
        vocab_size=len(fast), n_embd=64, n_layer=2, n_head=4,
        n_positions=512)).eval()
    path.mkdir(parents=True, exist_ok=True)
    model.save_pretrained(path, safe_serialization=True)
    fast.save_pretrained(path)
    return path, model, fast


def build_sp_llama(path: Path, add_bos: bool = False, seed: int = 1):
    """Unigram/Metaspace tokenizer + random Llama (seed 1) — byte-for-byte
    the tests/test_real_tokenizer_end_to_end.py construction; add_bos=True
    swaps in the bos-prepending variant (real-llama encode semantics)."""
    import torch
    import transformers as tf

    fast = _sp_tokenizer(add_bos=add_bos)
    torch.manual_seed(seed)
    model = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=len(fast), hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=256, tie_word_embeddings=False)).eval()
    path.mkdir(parents=True, exist_ok=True)
    model.save_pretrained(path, safe_serialization=True)
    fast.save_pretrained(path)
    return path, model, fast


def build_sp_t5(path: Path):
    """Unigram/Metaspace tokenizer + random tiny T5 (seed 2) for the
    enc-dec scorer branch (compare_base_vs_instruct.py:188-237: ids from
    tokenizer("Yes"), scores scanned from decoder steps)."""
    import torch
    import transformers as tf

    fast = _sp_tokenizer(with_pad=True)
    torch.manual_seed(2)
    model = tf.T5ForConditionalGeneration(tf.T5Config(
        vocab_size=len(fast), d_model=64, d_kv=16, d_ff=128,
        num_layers=2, num_decoder_layers=2, num_heads=4,
        decoder_start_token_id=fast.pad_token_id,
        pad_token_id=fast.pad_token_id,
        eos_token_id=fast.eos_token_id,
        tie_word_embeddings=False)).eval()
    path.mkdir(parents=True, exist_ok=True)
    model.save_pretrained(path, safe_serialization=True)
    fast.save_pretrained(path)
    return path, model, fast


def build_chain_t5(path: Path, never: bool = False):
    """Programmed-chain T5 for the ENC-DEC scorer branch
    (compare_base_vs_instruct.py:188-237): all attention (self + cross) and
    FFN weights zeroed, one-hot shared embeddings, untied programmed
    lm_head. Cross-attention zero makes the decoder input-INDEPENDENT: the
    chain runs from decoder_start (pad), so every prompt produces the same
    designed completion — "w1 w2 Yes </s>" (top-2 find at position 2) or,
    with ``never=True``, a 3-word cycle whose top-2 never contains
    Yes/No inside the 10-position scan (the pos-0 fallback, :228-233).
    Returns (path, model, fast, (expected_position, expected_found))."""
    import torch
    import transformers as tf

    fast = _sp_tokenizer(with_pad=True)

    def pid(piece: str) -> int:
        # The backing tokenizer's token_to_id returns None for a missing
        # piece (the fast wrapper would silently fall back to <unk>).
        i = fast._tokenizer.token_to_id(piece)
        assert i is not None, f"piece {piece!r} not in vocab"
        return int(i)

    yes = pid("▁Yes")
    w = [pid("▁" + t) for t in ("a", "form", "of")]
    pad, eos = fast.pad_token_id, fast.eos_token_id
    if never:
        chain = {pad: (w[0], w[1]), w[0]: (w[1], w[2]), w[1]: (w[2], w[0]),
                 w[2]: (w[0], w[1])}
        expected = (0, False)
    else:
        chain = {pad: (w[0], w[1]), w[0]: (w[1], w[2]),
                 w[1]: (yes, w[2]), yes: (eos, w[0]), eos: (eos, w[0])}
        expected = (2, True)

    torch.manual_seed(4)
    model = tf.T5ForConditionalGeneration(tf.T5Config(
        vocab_size=len(fast), d_model=64, d_kv=16, d_ff=128,
        num_layers=1, num_decoder_layers=1, num_heads=4,
        decoder_start_token_id=pad, pad_token_id=pad, eos_token_id=eos,
        tie_word_embeddings=False)).eval()
    sd = model.state_dict()
    with torch.no_grad():
        for k, v in sd.items():
            if any(s in k for s in ("SelfAttention", "EncDecAttention",
                                    "DenseReluDense")):
                v.zero_()
            elif "layer_norm" in k or "final_layer_norm" in k:
                v.fill_(1.0)
        basis = {t: i for i, t in enumerate(chain)}
        junk = len(basis)
        assert junk < 64
        model.shared.weight.zero_()
        model.shared.weight[:, junk] = 4.0
        for t, b in basis.items():
            model.shared.weight[t, junk] = 0.0
            model.shared.weight[t, b] = 4.0
        model.lm_head.weight.zero_()           # (V, D)
        for t, (nxt, second) in chain.items():
            model.lm_head.weight[nxt, basis[t]] += 10.0
            model.lm_head.weight[second, basis[t]] += 5.0
        model.lm_head.weight[w[0], junk] += 10.0
        model.lm_head.weight[w[1], junk] += 5.0

    path.mkdir(parents=True, exist_ok=True)
    model.save_pretrained(path, safe_serialization=True)
    fast.save_pretrained(path)
    return path, model, fast, expected


# ---------------------------------------------------------------------------
# Programmed-chain GPT-2: argmax sequence is a designed function of the
# last prompt token, with +10/+5 margins so top-2 membership is exact on
# both torch and XLA.
# ---------------------------------------------------------------------------

# Chain prompts: each ends in a distinct anchor word whose LAST token seeds
# its chain. Kept single-word-ish so the BPE last token is stable.
CHAIN_PROMPTS = {
    # position 2: two preamble steps, then " Yes" as argmax
    "pos2_yes": 'Is a "screenshot" a "photograph"? photograph',
    # position 0: " No" immediately as argmax
    "pos0_no": 'Is a "drone" an "aircraft"? aircraft',
    # position 5: five preamble steps, then " Yes"
    "pos5_yes": 'Is a "tomato" a "vegetable"? vegetable',
    # runner-up: " No" enters top-2 at position 3 as the +5 SECOND token
    "runnerup_no": 'Is "humming" "singing"? singing',
    # never: 12-cycle of junk tokens, no Yes/No in any top-2 -> fallback
    "never": 'Is a "screenshot" a "quotation"? quotation',
}


def build_chain_gpt2(path: Path):
    """GPT-2 whose logits depend ONLY on the current token: zero attention
    and MLP outputs + zero positional embeddings leave h = ln_f(wte[t]);
    untied one-hot wte rows and a designed lm_head make
    logits[next(t)] ~ +10 and logits[second(t)] ~ +5. Returns
    (path, model, fast, expected) where expected maps CHAIN_PROMPTS keys to
    the designed (position_found, yes_no_found, argmax token text)."""
    import torch
    import transformers as tf

    # Reuse the trained BPE tokenizer so ids match the bpe-gpt2 family.
    fast = build_bpe_tokenizer()

    V = len(fast)
    D = 64

    def one(text: str) -> int:
        ids = fast(text, add_special_tokens=False).input_ids
        return ids[-1]

    yes_id = one(" Yes")
    no_id = one(" No")
    eos_id = fast.eos_token_id
    # Preamble/junk vocabulary (never Yes/No/eos):
    w = [one(t) for t in [" I", " think", " the", " answer", " is",
                          " clearly", " a", " b", " c", " d", " e", " f",
                          " g", " h"]]
    dot = one(".")
    anchors = [one(CHAIN_PROMPTS[k]) for k in CHAIN_PROMPTS]
    # Chain links use setdefault; any id collision would silently rewire a
    # designed position, so the whole cast must be distinct.
    cast = anchors + w + [dot, yes_id, no_id, eos_id]
    assert len(set(cast)) == len(cast), "chain token collision"

    chain: dict = {}          # token -> (argmax_next, second)

    def link(seq, second=None):
        for a, b in zip(seq, seq[1:]):
            chain.setdefault(a, (b, second or dot))

    # pos2_yes: anchor -> w0 -> w1 -> Yes -> . -> eos
    a1 = one(CHAIN_PROMPTS["pos2_yes"])
    link([a1, w[0], w[1], yes_id, dot, eos_id])
    # pos0_no: anchor -> No -> . -> eos
    a2 = one(CHAIN_PROMPTS["pos0_no"])
    link([a2, no_id])
    link([no_id, dot, eos_id])
    # pos5_yes: anchor -> w2..w6 -> Yes
    a3 = one(CHAIN_PROMPTS["pos5_yes"])
    link([a3, w[2], w[3], w[4], w[5], w[6], yes_id])
    # runnerup_no: anchor -> w7 -> w8 -> w9(second=No) -> w10 -> . -> eos;
    # at position 3 the argmax is w10 but the +5 runner-up is " No".
    a4 = one(CHAIN_PROMPTS["runnerup_no"])
    link([a4, w[7], w[8]])
    chain.setdefault(w[8], (w[9], dot))
    chain[w[9]] = (w[10], no_id)          # top-2 = {w10, No} here
    link([w[10], dot, eos_id])
    # never: anchor cycles junk for >10 steps
    a5 = one(CHAIN_PROMPTS["never"])
    link([a5, w[11], w[12], w[13]])
    chain[w[13]] = (w[11], dot)           # 3-cycle, never Yes/No
    chain.setdefault(yes_id, (dot, w[0]))
    chain.setdefault(dot, (eos_id, w[0]))
    chain[eos_id] = (eos_id, dot)         # eos self-loop: post-eos steps inert

    torch.manual_seed(3)
    cfg = tf.GPT2Config(vocab_size=V, n_embd=D, n_layer=1, n_head=1,
                        n_positions=256, tie_word_embeddings=False)
    model = tf.GPT2LMHeadModel(cfg).eval()
    sd = model.state_dict()
    with torch.no_grad():
        for k, v in sd.items():
            if any(s in k for s in ("attn", "mlp")) and k.endswith(
                    ("weight", "bias")):
                v.zero_()
        model.transformer.wpe.weight.zero_()
        # ln_1/ln_2 irrelevant (their block outputs are zeroed); ln_f = id-ish
        model.transformer.ln_f.weight.fill_(1.0)
        model.transformer.ln_f.bias.zero_()
        # One-hot-ish embeddings: chain tokens get unique basis vectors.
        model.transformer.wte.weight.zero_()
        basis = {}
        for t in chain:
            basis[t] = len(basis)
        assert len(basis) < D, "chain too large for hidden size"
        junk_axis = len(basis)            # shared axis for non-chain tokens
        for t in range(V):
            model.transformer.wte.weight[t, basis.get(t, junk_axis)] = 4.0
        # lm_head columns realize the transitions.
        model.lm_head.weight.zero_()
        for t, (nxt, second) in chain.items():
            model.lm_head.weight[nxt, basis[t]] += 10.0
            model.lm_head.weight[second, basis[t]] += 5.0
        # Non-chain tokens (every random prompt token) deterministically
        # enter the pos0_no chain so behavior is total.
        model.lm_head.weight[no_id, junk_axis] += 10.0
        model.lm_head.weight[dot, junk_axis] += 5.0

    path.mkdir(parents=True, exist_ok=True)
    model.save_pretrained(path, safe_serialization=True)
    fast.save_pretrained(path)
    expected = {
        "pos2_yes": (2, True),
        "pos0_no": (0, True),
        "pos5_yes": (5, True),
        "runnerup_no": (3, True),
        "never": (0, False),
    }
    return path, model, fast, expected
