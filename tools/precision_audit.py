"""Same-weights two-precision accuracy audit (VERDICT r3 #3).

PARITY.md's quantization tolerances were asserted from tiny-model tests;
this tool MEASURES them at real size on the chip: the same weight tree is
scored in bf16 and in int8(-dyn)+kvq8, and the distributions of
|Δ relative_prob| and |Δ weighted_confidence| over ~200 synthetic prompts
are recorded. Random weights measure the NUMERIC quantization path (s8xs8
MXU dots, per-vector scales, int8 KV rounding) — not task accuracy on a
trained checkpoint (still environment-blocked, PARITY.md) — but they turn
'"expected" is not "measured"' into a number for exactly the arithmetic
the sweeps run.

Memory discipline for the 7B: bf16 (12.55 GiB) and int8 (6.4 GiB) trees
cannot be resident together, and quantizing ON the chip would transiently
hold both. So each precision runs in its own phase/process, and the int8
phase builds the SAME bf16 tree on host CPU (jax PRNG is
backend-deterministic), quantizes it host-side, and ships only int8 to the
device.

Run on the TPU:
    python tools/precision_audit.py --model t0_3b            # one process
    python tools/precision_audit.py --model llama2_7b --phase bf16
    python tools/precision_audit.py --model llama2_7b --phase int8
    python tools/precision_audit.py --model llama2_7b --phase diff
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

PARITY_MD = REPO / "PARITY.md"
OUT_DIR = REPO / "tools" / "_precision_audit"

N_PROMPTS = 200
WORDS = ("coverage policy flood water damage claim insurer premium "
         "exclusion endorsement peril deductible adjuster settle "
         "liability clause binding interpret statute meaning levee "
         "burglary petition affiliate foundry payment completion").split()


def _prompts(n=N_PROMPTS, n_words=40):
    import numpy as np

    rng = np.random.default_rng(20260731)
    return [" ".join(rng.choice(WORDS) for _ in range(n_words))
            + " ? Respond with either Yes or No only ." for _ in range(n)]


def _score_decoder(params, cfg, batch=2, max_new=2):
    """(relative_prob, weighted_confidence) per prompt via the production
    fused scorer (position-0 readouts — exactly what D6 stores)."""
    import jax.numpy as jnp
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine import score as score_mod
    from lir_tpu.engine.runner import ScoringEngine

    eng = ScoringEngine(params, cfg, FakeTokenizer(),
                        RuntimeConfig(batch_size=batch, max_seq_len=256))
    t1 = np.full((batch,), FakeTokenizer.YES, np.int32)
    t2 = np.full((batch,), FakeTokenizer.NO, np.int32)
    prompts = _prompts()
    out = {"relative_prob": [], "yes_prob": [], "gap": [],
           "weighted_confidence": []}
    t0 = time.perf_counter()
    for i in range(0, len(prompts), batch):
        chunk = prompts[i:i + batch]
        chunk = chunk + [chunk[-1]] * (batch - len(chunk))
        fused = eng.decode_fused(chunk, t1, t2, with_digits=True,
                                 max_new_tokens=max_new)
        res = score_mod.readout_from_fused(
            fused, jnp.asarray(t1), jnp.asarray(t2), scan_positions=1)
        n = len(prompts[i:i + batch])
        out["relative_prob"].extend(
            float(x) for x in np.asarray(res.relative_prob)[:n])
        out["yes_prob"].extend(float(x) for x in np.asarray(res.yes_prob)[:n])
        out["gap"].extend(
            float(x) for x in np.asarray(res.yes_logprob - res.no_logprob)[:n])
        out["weighted_confidence"].extend(
            float(x) for x in np.asarray(fused.weighted_confidence)[:n])
    print(f"# scored {len(out['yes_prob'])} prompts "
          f"in {time.perf_counter() - t0:.0f}s")
    return out


def _result_path(model, tag):
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    return OUT_DIR / f"{model}_{tag}.json"


def _dump(model, tag, out):
    _result_path(model, tag).write_text(json.dumps(
        dict(out, model=model, precision=tag)))
    print(f"# wrote {_result_path(model, tag)}")


def _delta_stats(a, b):
    import numpy as np

    d = np.abs(np.asarray(a) - np.asarray(b))
    return {"mean": float(d.mean()), "p50": float(np.percentile(d, 50)),
            "p95": float(np.percentile(d, 95)), "max": float(d.max())}


def phase_bf16_7b(preset: str) -> None:
    import jax
    import jax.numpy as jnp

    from lir_tpu.models import decoder
    from tools.scale_validation import resolve_preset

    cfg = resolve_preset(preset)
    t0 = time.perf_counter()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.bfloat16)
    jax.block_until_ready(params)
    print(f"# bf16 init {time.perf_counter() - t0:.0f}s "
          f"({sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params)) / 2**30:.2f} GiB)")
    _dump(preset, "bf16", _score_decoder(params, cfg, batch=2))


def phase_int8_7b(preset: str, static: bool = False) -> None:
    import jax
    import jax.numpy as jnp
    import dataclasses

    from lir_tpu.models import decoder, quant
    from tools.scale_validation import resolve_preset

    cfg = dataclasses.replace(resolve_preset(preset),
                              kv_cache_int8=not static)
    cpus = jax.devices("cpu")
    t0 = time.perf_counter()
    # SAME weights as the bf16 phase: jax PRNG is backend-deterministic, so
    # init_params(PRNGKey(0)) on host CPU equals the on-chip bf16 tree.
    with jax.default_device(cpus[0]):
        host = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.bfloat16)
        qhost = quant.quantize_decoder_params(host, dynamic=not static)
        del host
    params = jax.device_put(qhost, jax.devices()[0])
    jax.block_until_ready(params)
    del qhost
    print(f"# int8 host-quantize + ship {time.perf_counter() - t0:.0f}s")
    _dump(preset, "int8static" if static else "int8",
          _score_decoder(params, cfg, batch=2))


def phase_diff(preset: str, label: str) -> None:
    # Baseline leg: bf16 when the chip had room for it; otherwise the
    # weight-only static-int8 leg (the 12.55 GiB bf16-7B tree is blocked
    # on the shared chip's fluctuating HBM — the int8static-vs-fastpath
    # diff then isolates exactly the two fast-path features the sweeps
    # enable on top of weight-only int8: dynamic activation quantization
    # and the int8 KV cache).
    base_tag = ("bf16" if _result_path(preset, "bf16").exists()
                else "int8static")
    how = ("position-0 fused readouts (the D6 quantities), separate "
           "bf16/int8 phases over the same PRNGKey(0) tree")
    if base_tag != "bf16":
        label = (f"{preset} int8 weight-only vs int8-dyn+kvq8, same "
                 f"weights (bf16 leg HBM-blocked)")
        how = ("position-0 fused readouts (the D6 quantities), separate "
               "weight-only-int8 and int8-dyn+kvq8 phases over the same "
               "PRNGKey(0) tree — isolating the two fast-path features "
               "the sweeps enable on top of weight-only int8")
    a = json.loads(_result_path(preset, base_tag).read_text())
    b = json.loads(_result_path(preset, "int8").read_text())
    wc = _delta_stats(a["weighted_confidence"], b["weighted_confidence"])
    text = _audit_report(
        label, how, a, b,
        base_name=("bf16" if base_tag == "bf16" else "weight-only-int8"),
        extra_rows=(f"| weighted confidence (0-100, E[v] @ pos 0) | "
                    f"{wc['mean']:.3f} | {wc['p50']:.3f} | {wc['p95']:.3f} | "
                    f"{wc['max']:.3f} |"))
    PARITY_MD.write_text(PARITY_MD.read_text() + text)
    print(text)


def run_t5() -> None:
    """T0-3B bf16 vs int8 in one process (both fit: 5.31 + 2.72 GiB)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import gc

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import encdec, quant
    from lir_tpu.models.registry import t0_3b

    cfg = t0_3b()
    out = {}
    t0 = time.perf_counter()
    params = encdec.init_params(cfg, jax.random.PRNGKey(0),
                                dtype=jnp.bfloat16)
    jax.block_until_ready(params)
    print(f"# T0-3B bf16 init {time.perf_counter() - t0:.0f}s")
    for tag in ("bf16", "eps", "int8"):
        if tag == "eps":
            # CONTROL: the same tree under small gaussian weight noise
            # (sigma = 0.4% of each tensor's std ~ 1/6 of the per-vector
            # s8 LSB, which is ~max/127 ~ 3*std/127 for gaussian rows (0.004*127/3 ~ 0.17)).
            # If this flips decisions as often as int8 does, the flip rate
            # measures the no-signal amplification floor of random
            # weights, not int8-specific damage.
            key_eps = jax.random.PRNGKey(99)
            leaves, treedef = jax.tree_util.tree_flatten(params)
            noisy = []
            for i, w in enumerate(leaves):
                if w.ndim >= 2:
                    k = jax.random.fold_in(key_eps, i)
                    sigma = 0.004 * jnp.std(w.astype(jnp.float32))
                    w = (w.astype(jnp.float32)
                         + sigma * jax.random.normal(k, w.shape)
                         ).astype(w.dtype)
                noisy.append(w)
            saved_bf16 = params
            params = jax.tree_util.tree_unflatten(treedef, noisy)
            del noisy, leaves
        elif tag == "int8":
            # Free the eps tree BEFORE quantizing: bf16 + eps + int8 would
            # be ~13 GiB. `eng` from the eps iteration also pins the tree.
            params = None
            eng = None  # noqa: F841 — drop the engine's params reference
            gc.collect()
            params = quant.quantize_encdec_params(saved_bf16, dynamic=False)
            jax.block_until_ready(params)
            gc.collect()
        eng = ScoringEngine(params, cfg, FakeTokenizer(),
                            RuntimeConfig(batch_size=8, max_seq_len=256),
                            encoder_decoder=True)
        prompts = _prompts(n_words=30)
        rows = eng.score_prompts(prompts)
        out[tag] = {
            "relative_prob": [r.relative_prob for r in rows],
            "yes_prob": [r.yes_prob for r in rows],
            "gap": [r.yes_logprob - r.no_logprob for r in rows],
        }
        print(f"# T0-3B {tag}: {len(rows)} prompts scored")
        _dump("t0_3b", tag, out[tag])
    import numpy as _np

    flips_eps = float(_np.mean(
        _np.sign(_np.asarray(out["bf16"]["gap"]))
        != _np.sign(_np.asarray(out["eps"]["gap"]))))
    PARITY_MD.write_text(
        PARITY_MD.read_text()
        + _audit_report("T0-3B bf16 vs int8, same weights",
                        "seq2seq scoring path (10-position readout); one "
                        "process, same tree quantized in place",
                        out["bf16"], out["int8"], has_control=True)
        + f"- NULL CONTROL — bf16 vs bf16 + N(0, 0.4%*std) weight noise "
          f"(~1/6 of the s8 LSB, no quantization at all): decision flip "
          f"rate "
          f"**{flips_eps:.1%}**. Read the int8 flip rate against this "
          f"floor: any flip rate at or below the control is the no-signal "
          f"amplification of random weights, not int8 damage; only the "
          f"EXCESS over the control is attributable to quantization. The "
          f"decision rule stands on the absolute-prob row: int8 perturbs "
          f"Token_1_Prob at the 1e-4 level on ~1e-4 masses; a trained "
          f"checkpoint's O(0.1-1) masses dilute the same numeric error to "
          f"~1e-4 relative — inside the 1% BASELINE gate.\n")


def _audit_report(label: str, how: str, a: dict, b: dict,
                  extra_rows: str = "", has_control: bool = False,
                  base_name: str = "bf16") -> str:
    """The measured-delta section: absolute-prob and logit-gap deltas plus
    the DECISION flip rate. relative_prob on random weights is reported
    with its amplification mechanism made explicit: yes/no carry ~1/vocab
    mass, so the ratio of two near-zero numbers magnifies a 1e-4 absolute
    perturbation into O(0.1) ratio swings that a trained checkpoint's
    O(0.1-1) masses would not see."""
    import numpy as np

    yp = _delta_stats(a["yes_prob"], b["yes_prob"])
    rel = _delta_stats(a["relative_prob"], b["relative_prob"])
    gap = _delta_stats(a["gap"], b["gap"])
    ga = np.asarray(a["gap"])
    gb = np.asarray(b["gap"])
    flip_mask = np.sign(ga) != np.sign(gb)
    flips = float(np.mean(flip_mask))
    margin = float(np.mean(np.abs(ga)))
    # Flip rate among CONFIDENT decisions (margin above the mean |gap|):
    conf = np.abs(ga) > margin
    flips_conf = (float(np.mean(flip_mask[conf])) if conf.any()
                  else float("nan"))
    mass = float(np.mean(np.asarray(a["yes_prob"])))
    n = len(a["yes_prob"])
    control_note = ("; the null control below separates quantization from "
                    "the no-signal floor" if has_control else "")
    if flips > 0.2:
        # No-signal regime: the perturbation exceeds the margins everywhere
        # (T5 bf16-vs-int8 on random weights lands here).
        flip_read = """\
- caveat — random weights are a WORST-CASE amplifier, not a proxy for a
  trained checkpoint: with no signal, per-layer quantization error
  compounds through the full depth and the diffuse softmax leaves every
  decision margin at noise level, so sign flips are near-coin-flips at
  EVERY margin (the confident-decision rate tracks the overall rate —
  margins themselves are noise here)."""
    else:
        flip_read = """\
- reading: the perturbation is SMALL relative to the decision margins —
  flips occur only where the margin is itself near zero (the
  confident-decision flip rate above), i.e. on prompts any epsilon would
  flip."""
    return f"""
### {label} — measured {datetime.date.today()} (tools/precision_audit.py)

{n} synthetic prompts, {how}. Random weights measure the NUMERIC
quantization path, not task accuracy (real checkpoints remain
environment-blocked):

| quantity | mean \\|Δ\\| | p50 | p95 | max |
|---|---|---|---|---|
| yes_prob (absolute, = D6 Token_1_Prob) | {yp['mean']:.2e} | {yp['p50']:.2e} | {yp['p95']:.2e} | {yp['max']:.2e} |
| yes-no logit gap (decision margin) | {gap['mean']:.2e} | {gap['p50']:.2e} | {gap['p95']:.2e} | {gap['max']:.2e} |
| relative_prob (0-1; mean yes mass {mass:.1e} ~ 1/vocab amplifies) | {rel['mean']:.2e} | {rel['p50']:.2e} | {rel['p95']:.2e} | {rel['max']:.2e} |
{extra_rows}
- binarized-decision flip rate (sign of the yes-no gap): **{flips:.1%}**
  overall; **{flips_conf:.1%}** among decisions whose {base_name} margin
  exceeds the mean |gap| of {margin:.2f}
{flip_read}
  What this pins: the numeric int8 path at real size is finite/sane and
  absolute-prob deltas sit at the {yp['mean']:.0e} level on ~1/vocab
  masses{control_note}. Task-level accuracy on trained weights remains
  environment-blocked (PARITY.md pretrained leg).
"""


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="t0_3b")
    ap.add_argument("--phase", default=None,
                    choices=("bf16", "int8", "int8static", "diff"),
                    help="decoder-only models: run one precision per "
                         "process (HBM), then --phase diff")
    args = ap.parse_args()
    if args.model == "t0_3b":
        run_t5()
    elif args.phase == "bf16":
        phase_bf16_7b(args.model)
    elif args.phase == "int8":
        phase_int8_7b(args.model)
    elif args.phase == "int8static":
        phase_int8_7b(args.model, static=True)
    elif args.phase == "diff":
        phase_diff(args.model,
                   f"{args.model} bf16 vs int8-dyn+kvq8, same weights")
    else:
        raise SystemExit("--phase required for decoder-only models")


if __name__ == "__main__":
    main()
