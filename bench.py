"""Benchmark: prompts/sec/chip on the perturbation-sweep scoring path.

BASELINE.json's metric, measured honestly:

- **Real-size model.** On an accelerator the bench scores through
  ``llama2_7b()`` at full size (6.74B params) with DYNAMIC int8 — per-token
  activation quantization + s8 x s8 MXU dots, the TPU-native analogue of
  the 8-bit mode the reference runs (compare_base_vs_instruct.py:431-435,
  BitsAndBytesConfig(load_in_8bit) = LLM.int8() vector-wise quantization).
  Random weights; throughput does not depend on weight values. On CPU
  (smoke runs, no real chip) a 136M-param flagship config keeps the bench
  runnable; the JSON labels which config ran.

- **Verified timing.** Under the tunneled-axon dispatch path,
  ``jax.block_until_ready`` returns before the device finishes (measured:
  it "timed" 4096³ matmuls at 7,883 TFLOPS on a 197-TFLOP chip). The only
  trustworthy sync is a host-side read. So the bench runs R scoring
  iterations inside ONE jitted ``lax.scan`` (single dispatch, no per-iter
  tunnel latency) and times dispatch -> ``float(checksum)``, where the
  checksum sums every iteration's yes-probabilities — XLA cannot elide any
  iteration's forward, and the float() forces full completion.

- **MFU sanity gate.** Implied matmul FLOPS (utils/profiling.scoring_step_
  flops) divided by the chip's published peak for the mode's dot dtype
  (int8 peak = 2x bf16 for the dynamic mode) must be <= 100%; the bench
  ABORTS (exit 1) on a physically impossible number instead of reporting
  it.

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# First recorded value of this benchmark definition (llama-2-7b shapes,
# int8, seq 256, 10-token readout window, single v5e chip, in-scan timing
# with host-side checksum sync; measured 2026-07-30 in the original
# weight-only mode at batch 16: 26.247 prompts/s = 91.4 implied TFLOPS =
# 46.4% MFU of the v5e bf16 peak). vs_baseline tracks framework
# improvement since this first honest recording (dynamic int8 + batch 24
# later raised the measured value ~1.2x). Update deliberately, never
# silently.
BENCH_NOMINAL_7B = 26.247  # prompts/sec/chip

# CPU smoke nominal (flagship 136M config, fp32, batch 8) — only used when
# no accelerator is present so the JSON stays comparable run-to-run.
BENCH_NOMINAL_CPU = 2.0

SEQ = 256
NEW_TOKENS = 10  # MAX_LOOK_AHEAD: the positions the C13 readout consumes

# (batch, n_iters) candidates, largest batch first; on HBM exhaustion the
# bench falls back down the list. 7B int8 on v5e-1 (16 GB): params 6.3 GiB;
# the int8 KV cache (~70 MiB/row incl. XLA's while-loop layout copy)
# admits batch 48, the measured throughput knee; 64 OOMs (SCALE.md,
# 2026-07-30).
TPU_CANDIDATES = ((48, 4), (32, 6), (24, 6), (16, 8), (8, 8))
CPU_CANDIDATES = ((8, 2), (4, 2))


def _is_oom(err: Exception) -> bool:
    msg = str(err)
    return ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            or "out of memory" in msg.lower())


def main() -> None:
    from lir_tpu.engine import generate, score
    from lir_tpu.models import decoder, quant
    from lir_tpu.utils import profiling

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"

    if on_accel:
        import dataclasses

        from lir_tpu.models.registry import llama2_7b
        # int8 KV cache: half the cache HBM -> batch 48 fits (the knee);
        # decode attention runs s8 dots like the dynamic weight mode.
        cfg = dataclasses.replace(llama2_7b(), kv_cache_int8=True)
        params = quant.random_quantized_params(cfg, jax.random.PRNGKey(0),
                                               dtype=jnp.bfloat16,
                                               dynamic=True)
        candidates = TPU_CANDIDATES
        nominal = BENCH_NOMINAL_7B
        mode = "int8-dyn+kvq8"
    else:
        from __graft_entry__ import _flagship_cfg
        cfg = _flagship_cfg()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        candidates = CPU_CANDIDATES
        nominal = BENCH_NOMINAL_CPU
        mode = "fp32"

    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, quant.QuantTensor))
        if not isinstance(l, quant.QuantTensor)
    ) + sum(
        int(np.prod(l.q.shape)) for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, quant.QuantTensor))
        if isinstance(l, quant.QuantTensor)
    )

    rng = np.random.default_rng(0)
    digit_ids = jnp.arange(10, 110, dtype=jnp.int32)
    digit_vals = jnp.arange(0, 100, dtype=jnp.float32)

    def build_program(batch: int, n_iters: int):
        """R scoring iterations in one jitted scan; returns a checksum that
        depends on every iteration's readout (nothing can be elided)."""
        toks = jnp.asarray(
            rng.integers(3, cfg.vocab_size, (n_iters, batch, SEQ)), jnp.int32)
        mask = jnp.ones((batch, SEQ), jnp.int32)
        yes_ids = jnp.full((batch,), 1, jnp.int32)
        no_ids = jnp.full((batch,), 2, jnp.int32)

        def one_iter(params, acc, iter_toks):
            fused = generate.greedy_decode_fused(
                params, cfg, iter_toks, mask, yes_ids, no_ids, digit_ids,
                digit_vals, max_new_tokens=NEW_TOKENS)
            res = score.readout_from_fused(fused, yes_ids, no_ids)
            acc = acc + jnp.sum(res.yes_prob) + jnp.sum(res.no_prob)
            return acc, None

        # params MUST be a traced argument: closing over a 7B tree would
        # constant-fold the weights into the HLO and stall compilation.
        def program(params, toks):
            acc, _ = jax.lax.scan(
                lambda a, t: one_iter(params, a, t), jnp.float32(0.0), toks)
            return acc

        return jax.jit(program), toks

    value = 0.0
    batch_used = candidates[-1][0]
    implied_tflops = 0.0
    mfu = None
    peak = (profiling.chip_peak_flops(dev, int8=mode.startswith("int8-dyn"))
            if on_accel else None)

    last_oom = None
    for batch, n_iters in candidates:
        program, toks = build_program(batch, n_iters)
        try:
            t_c = time.perf_counter()
            chk = float(program(params, toks))  # compile+warmup, host-read sync
            print(f"# bench: batch={batch} compile+first run "
                  f"{time.perf_counter() - t_c:.1f}s", file=sys.stderr)
            if not np.isfinite(chk):
                raise RuntimeError(f"non-finite bench checksum: {chk}")
            best_dt = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                chk = float(program(params, toks))  # dispatch -> host read
                best_dt = min(best_dt, time.perf_counter() - t0)
            if not np.isfinite(chk):
                raise RuntimeError(f"non-finite bench checksum: {chk}")
        except Exception as err:  # noqa: BLE001 — OOM falls back, rest aborts
            if _is_oom(err):
                last_oom = err
                continue
            raise
        value = batch * n_iters / best_dt
        batch_used = batch
        step_flops = profiling.scoring_step_flops(cfg, batch, SEQ, NEW_TOKENS)
        implied_tflops = step_flops * n_iters / best_dt / 1e12
        if peak is not None:
            mfu = implied_tflops * 1e12 / peak
            if mfu > 1.0:
                print(
                    f"BENCH ABORT: implied {implied_tflops:.1f} TFLOPS is "
                    f"{mfu:.0%} of the {dev.device_kind} peak "
                    f"({peak / 1e12:.0f} TFLOPS) — timing is not syncing with "
                    f"the device; refusing to report an impossible number.",
                    file=sys.stderr)
                sys.exit(1)
        break
    else:
        print(f"BENCH ABORT: every batch candidate OOMed; last: {last_oom}",
              file=sys.stderr)
        sys.exit(1)

    if mfu is not None:
        mfu_str = f"{mfu:.1%} MFU"
    elif on_accel:
        mfu_str = "MFU n/a (unknown chip)"   # gate could not run; say so
    else:
        mfu_str = "MFU n/a (cpu)"
    print(json.dumps({
        "metric": "prompts_per_sec_per_chip",
        "value": round(value, 3),
        "unit": (f"prompts/s ({cfg.name} {n_params / 1e9:.2f}B {mode}, "
                 f"seq={SEQ}, {NEW_TOKENS} gen, batch={batch_used}, "
                 f"{implied_tflops:.1f} TFLOPS impl, {mfu_str}, "
                 f"{dev.platform})"),
        "vs_baseline": round(value / nominal, 3),
    }))


if __name__ == "__main__":
    main()
